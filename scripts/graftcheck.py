#!/usr/bin/env python
"""graftcheck: the repo's static-analysis gate (AST lint + program invariants).

Tier A (default, milliseconds, no jax import) lints the package for TPU
footguns (rules GC001-GC005; ``eventstreamgpt_tpu/analysis/lint.py``),
suppressing pre-existing findings via ``eventstreamgpt_tpu/analysis/
baseline.json``. Tier B AOT-lowers the canonical pretrain / fine-tune /
generation step programs on an 8-device virtual CPU mesh and gates static
program invariants: f64-free, host-transfer-free, collective payload within
tolerance of ``COLLECTIVES.json``. Tier C runs the whole-fleet program
census (``analysis/program_census.py``): every registered ``aot_programs``
provider's compiled programs — toy AND scaled shapes — audited for peak
HBM vs ``MEMORY.json``, donation-aliasing completeness, implicit
resharding, and kind-resolved collective inventories (the scaled fsdp8
backward must show reduce-scatter). Tier D runs the serving control-plane
model checker (``analysis/model_check.py``): every schedule of enabled
control-plane actions (admit, issue, resolve, fork, deadline, evict,
promote) over the REAL engine/service/fleet objects up to a depth bound,
with sleep-set partial-order reduction, checking the block-ledger /
FIFO-boundary / zero-drop / determinism oracles at every state. Schedule
counts pin byte-reproducibly in ``MODELCHECK.json`` (the MEMORY.json
discipline) and every scenario must clear 500 post-POR interleavings.

Usage:
    python scripts/graftcheck.py                 # Tier A over the repo
    python scripts/graftcheck.py --tier all      # what CI runs (A+B+C+D)
    python scripts/graftcheck.py --tier c --report-json report.json
    python scripts/graftcheck.py --tier d --modelcheck-report report.json
    python scripts/graftcheck.py --write-baseline  # re-key the lint baseline
    python scripts/graftcheck.py --write-memory    # regenerate MEMORY.json
    python scripts/graftcheck.py --write-modelcheck  # regenerate MODELCHECK.json
    python scripts/graftcheck.py baseline --prune  # drop stale baseline entries
    python scripts/graftcheck.py baseline --prune --check  # exit 1 if stale
    python scripts/graftcheck.py --list-rules
    python scripts/graftcheck.py path/to/file.py # lint specific files

Exit codes: 0 clean, 1 new lint findings (or stale baseline under
``baseline --prune --check``), 2 program-invariant or model-check
violations. See docs/analysis.md for the rule catalog, baseline workflow,
the Tier C census contract, and the Tier D action alphabet + POR
soundness argument.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

BASELINE_FP = REPO_ROOT / "eventstreamgpt_tpu" / "analysis" / "baseline.json"
MODELCHECK_FP = REPO_ROOT / "MODELCHECK.json"

# The ISSUE-level floor: every scenario must clear this many post-POR
# interleavings or the exploration isn't meaningfully exhaustive.
MIN_SCHEDULES_PER_SCENARIO = 500


def run_tier_a(paths: list[Path], write_baseline: bool, no_baseline: bool) -> int:
    from eventstreamgpt_tpu.analysis.lint import (
        RULES,
        apply_baseline,
        default_targets,
        lint_paths,
        load_baseline,
        save_baseline,
    )

    targets = paths or default_targets(REPO_ROOT)
    findings = lint_paths(targets, REPO_ROOT)

    if write_baseline:
        save_baseline(findings, BASELINE_FP)
        print(f"graftcheck[A]: wrote {len(findings)} finding(s) to {BASELINE_FP}")
        return 0

    baseline = {} if no_baseline else load_baseline(BASELINE_FP)
    new, suppressed = apply_baseline(findings, baseline)
    print(
        f"graftcheck[A]: {len(targets)} file(s), {len(findings)} finding(s), "
        f"{suppressed} baselined, {len(new)} new"
    )
    for f in new:
        print(f.render())
    if new:
        counts: dict[str, int] = {}
        for f in new:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        summary = ", ".join(f"{r} ({RULES[r]}): {n}" for r, n in sorted(counts.items()))
        print(f"graftcheck[A]: FAIL — {summary}")
        return 1
    print("graftcheck[A]: OK")
    return 0


def run_baseline_maintenance(prune: bool, check: bool) -> int:
    """``graftcheck baseline --prune [--check]``: drop stale suppression.

    A baseline entry whose (path, rule, snippet) key matches no current
    finding is dead budget: the finding was fixed, but a future regression
    at the same key would be silently suppressed. ``--prune`` rewrites the
    file without the stale budget; ``--check`` only reports and exits 1 if
    any exists (the CI mode — the baseline must stay tight at HEAD).
    """
    from eventstreamgpt_tpu.analysis.lint import (
        _write_baseline_file,
        default_targets,
        lint_paths,
        load_baseline,
        prune_baseline,
    )

    if not prune and not check:
        print("graftcheck[baseline]: nothing to do (pass --prune and/or --check)")
        return 0
    findings = lint_paths(default_targets(REPO_ROOT), REPO_ROOT)
    baseline = load_baseline(BASELINE_FP)
    pruned, stale = prune_baseline(findings, baseline)
    kept = sum(pruned.values())
    print(
        f"graftcheck[baseline]: {len(baseline)} entrie(s) "
        f"({sum(baseline.values())} suppression budget), {stale} stale, {kept} kept"
    )
    if check:
        if stale:
            print(
                "graftcheck[baseline]: FAIL — stale entries present; run "
                "`python scripts/graftcheck.py baseline --prune`"
            )
            return 1
        print("graftcheck[baseline]: OK (no stale entries)")
        return 0
    if stale:
        _write_baseline_file(pruned, BASELINE_FP)
        print(f"graftcheck[baseline]: pruned {stale} stale suppression(s) -> {BASELINE_FP}")
    else:
        print("graftcheck[baseline]: no stale entries, file unchanged")
    return 0


def _provision_mesh() -> None:
    # The virtual CPU mesh must exist before the jax backend initializes.
    from __graft_entry__ import _provision_cpu_devices

    _provision_cpu_devices(8)


def run_tier_b(rel_tol: float, skip_compile: bool) -> int:
    _provision_mesh()

    from eventstreamgpt_tpu.analysis.program_checks import run_program_checks

    problems = run_program_checks(
        rel_tol=rel_tol, compile_collectives=not skip_compile
    )
    for p in problems:
        print(f"graftcheck[B]: {p}")
    if problems:
        print(f"graftcheck[B]: FAIL — {len(problems)} violation(s)")
        return 2
    gates = "f64-free, host-transfer-free" + (
        ", collectives budget SKIPPED (--skip-compile)"
        if skip_compile
        else ", collectives within budget"
    )
    print(f"graftcheck[B]: OK ({gates})")
    return 0


def run_tier_c(report_json: Path | None, regen_memory: Path | None) -> int:
    _provision_mesh()

    from eventstreamgpt_tpu.analysis.program_census import run_census

    problems, report = run_census(regen_path=regen_memory)
    if regen_memory is not None:
        print(f"graftcheck[C]: wrote regenerated memory budgets to {regen_memory}")
    if report_json is not None:
        report_json.write_text(json.dumps(report, indent=1, sort_keys=True) + "\n")
        print(f"graftcheck[C]: wrote per-program report to {report_json}")
    for p in problems:
        print(f"graftcheck[C]: {p}")
    if problems:
        print(f"graftcheck[C]: FAIL — {len(problems)} violation(s)")
        return 2
    print(
        f"graftcheck[C]: OK ({len(report)} programs: peak HBM within MEMORY.json, "
        "donation aliasing complete, no implicit resharding, scaled fsdp8 "
        "reduce-scatter visible)"
    )
    return 0


def _modelcheck_payload(report: dict) -> dict:
    return {
        "note": (
            "graftcheck Tier D schedule-count pins: per-scenario post-POR "
            "interleaving counts from analysis/model_check.py. Deterministic "
            "(sorted DFS) — a diff means the scenario set, depths, or the "
            "explored control-plane behavior changed. Regenerate with "
            "scripts/graftcheck.py --write-modelcheck."
        ),
        "scenarios": {
            name: {
                "depth": rep["depth"],
                "schedules": rep["schedules"],
                "truncated": rep["truncated"],
                "actions": rep["actions"],
            }
            for name, rep in sorted(report["scenarios"].items())
        },
        "total_schedules": report["total_schedules"],
    }


def run_tier_d(
    report_json: Path | None,
    regen_modelcheck: Path | None,
    max_schedules: int | None = None,
) -> int:
    _provision_mesh()

    from eventstreamgpt_tpu.analysis.model_check import run_all

    problems, report = run_all(max_schedules=max_schedules)
    payload = _modelcheck_payload(report)
    if max_schedules is None:
        for name, rep in sorted(report["scenarios"].items()):
            if rep["schedules"] < MIN_SCHEDULES_PER_SCENARIO and not rep["violations"]:
                problems.append(
                    f"scenario {name!r} explored only {rep['schedules']} "
                    f"schedule(s) (floor: {MIN_SCHEDULES_PER_SCENARIO}) — "
                    "widen the scenario or raise its depth"
                )
    if regen_modelcheck is not None:
        regen_modelcheck.write_text(json.dumps(payload, indent=1) + "\n")
        print(f"graftcheck[D]: wrote regenerated schedule pins to {regen_modelcheck}")
    if report_json is not None:
        report_json.write_text(json.dumps(report, indent=1, sort_keys=True) + "\n")
        print(f"graftcheck[D]: wrote per-scenario schedule report to {report_json}")
    for p in problems:
        print(f"graftcheck[D]: {p}")
    if problems:
        print(f"graftcheck[D]: FAIL — {len(problems)} violation(s)")
        return 2
    counts = ", ".join(
        f"{name}={rep['schedules']}" for name, rep in sorted(report["scenarios"].items())
    )
    print(
        f"graftcheck[D]: OK ({report['total_schedules']} post-POR schedules, "
        f"all oracles clean: {counts})"
    )
    return 0


def run_write_modelcheck() -> int:
    _provision_mesh()

    from eventstreamgpt_tpu.analysis.model_check import run_all

    problems, report = run_all()
    MODELCHECK_FP.write_text(json.dumps(_modelcheck_payload(report), indent=1) + "\n")
    for p in problems:
        print(f"graftcheck[D]: {p}")
    print(f"graftcheck[D]: wrote schedule pins to {MODELCHECK_FP}")
    if problems:
        # A pin refresh must not paper over an oracle violation: the file is
        # written (so diffs are inspectable) but the run fails.
        print(f"graftcheck[D]: FAIL — {len(problems)} violation(s)")
        return 2
    return 0


def run_write_memory() -> int:
    _provision_mesh()

    from eventstreamgpt_tpu.analysis.program_census import write_memory_budgets

    path, problems = write_memory_budgets()
    for p in problems:
        print(f"graftcheck[C]: {p}")
    print(f"graftcheck[C]: wrote memory budgets to {path}")
    if problems:
        # A budget refresh must not paper over broken donation/resharding:
        # the file is written (so diffs are inspectable) but the run fails.
        print(f"graftcheck[C]: FAIL — {len(problems)} budget-independent violation(s)")
        return 2
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "baseline":
        bp = argparse.ArgumentParser(
            prog="graftcheck baseline", description="lint-baseline maintenance"
        )
        bp.add_argument(
            "--prune",
            action="store_true",
            help="drop baseline entries whose path+rule+snippet matches no current finding",
        )
        bp.add_argument(
            "--check",
            action="store_true",
            help="with --prune: report only, exit 1 if stale entries exist (CI mode)",
        )
        bargs = bp.parse_args(argv[1:])
        return run_baseline_maintenance(bargs.prune, bargs.check)

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--tier",
        choices=("a", "b", "c", "d", "all"),
        default="a",
        help="a: AST lint (default, fast); b: lowered-program gates; "
        "c: whole-fleet census (memory/donation/resharding); d: serving "
        "control-plane model checker; all: a+b+c+d (CI)",
    )
    ap.add_argument("paths", nargs="*", type=Path, help="lint these files only (Tier A)")
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="re-key analysis/baseline.json from the current findings and exit",
    )
    ap.add_argument(
        "--no-baseline", action="store_true", help="report all findings, ignore the baseline"
    )
    ap.add_argument(
        "--tolerance",
        type=float,
        default=0.25,
        help="relative slack on the COLLECTIVES.json byte budget (default 0.25)",
    )
    ap.add_argument(
        "--skip-compile",
        action="store_true",
        help="Tier B: only the fast lowered-text gates, skip the compiled collective audit",
    )
    ap.add_argument(
        "--write-memory",
        action="store_true",
        help="regenerate MEMORY.json from a fresh Tier C census and exit",
    )
    ap.add_argument(
        "--report-json",
        type=Path,
        default=None,
        help="Tier C: write the per-program memory/collective report here (CI artifact)",
    )
    ap.add_argument(
        "--regen-memory",
        type=Path,
        default=None,
        help="Tier C: also write the regenerated MEMORY.json from the same census "
        "pass (CI diffs it against the committed file without a second census)",
    )
    ap.add_argument(
        "--write-modelcheck",
        action="store_true",
        help="regenerate MODELCHECK.json from a fresh Tier D exploration and exit",
    )
    ap.add_argument(
        "--modelcheck-report",
        type=Path,
        default=None,
        help="Tier D: write the per-scenario schedule-count report here (CI artifact)",
    )
    ap.add_argument(
        "--regen-modelcheck",
        type=Path,
        default=None,
        help="Tier D: also write the regenerated MODELCHECK.json from the same "
        "exploration (CI diffs it against the committed file without a second run)",
    )
    ap.add_argument(
        "--max-schedules",
        type=int,
        default=None,
        help="Tier D: cap schedules per scenario (quick local runs; disables "
        "the per-scenario floor check and the pin regen should not be committed)",
    )
    ap.add_argument("--list-rules", action="store_true", help="print the rule catalog")
    args = ap.parse_args(argv)

    if args.write_baseline and args.paths:
        # A partial lint must never overwrite the whole-repo baseline: the
        # next full run would report every other pre-existing finding as new.
        ap.error("--write-baseline re-keys the full-repo baseline; it cannot be combined with explicit paths")
    if args.write_baseline and args.tier != "a":
        ap.error("--write-baseline is a Tier A operation; drop --tier (or pass --tier a)")

    if args.list_rules:
        from eventstreamgpt_tpu.analysis.lint import RULES

        for rule, desc in RULES.items():
            print(f"{rule}: {desc}")
        return 0

    if args.write_memory:
        return run_write_memory()
    if args.write_modelcheck:
        return run_write_modelcheck()

    rc = 0
    if args.tier in ("a", "all"):
        rc = run_tier_a(args.paths, args.write_baseline, args.no_baseline)
        if args.write_baseline:
            return rc
    if rc == 0 and args.tier in ("b", "all"):
        rc = run_tier_b(args.tolerance, args.skip_compile)
    if rc == 0 and args.tier in ("c", "all"):
        rc = run_tier_c(args.report_json, args.regen_memory)
    if rc == 0 and args.tier in ("d", "all"):
        rc = run_tier_d(
            args.modelcheck_report, args.regen_modelcheck, args.max_schedules
        )
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
