"""Hyperparameter sweep launcher (random/TPE search + ASHA early stopping).

Rebuild of ``/root/reference/scripts/launch_wandb_hp_sweep.py``: the same
sweep-config dialect (nested parameter groups with ``value`` / ``values`` /
``min``+``max`` [+ ``distribution: log_uniform_values``] leaves, collapsed to
hydra dotted-override syntax by ``collapse_cfg``), but executed locally —
this environment has no W&B service, so instead of registering a remote
bayes sweep the launcher samples ``n_trials`` configurations and either
writes the pretrain command list (default) or runs them in-process
(``--run``). The sweep objective name (``tuning_loss``) is preserved so
result ranking works the same way. ``method: bayes`` runs local **TPE**
(Tree-structured Parzen Estimators) under ``--run``: after a random startup,
each trial is proposed from density models of the good/bad observations —
the adaptive-search capability the reference delegates to the W&B service.

The reference sweep's hyperband ``early_terminate`` block
(``/root/reference/configs/hyperparameter_sweep_base.yaml``) is implemented
as **ASHA** over epochs: with ``early_terminate: {type: hyperband, min_iter,
eta}`` present, ``--run`` executes trials rung by rung (``min_iter * eta^k``
epochs), keeping only the top ``1/eta`` of surviving trials after each rung.
Rungs resume from the orbax step checkpoints (the trial's LR schedule is
pinned to its full horizon up front, so a promoted trial is bitwise the run
it would have been without early stopping).

Usage::

    python -m scripts.launch_hp_sweep --config configs/hyperparameter_sweep_base.yaml \
        n_trials=10 sweep_dir=./exp/sweep
"""

from __future__ import annotations

import json
import shlex
import sys
from pathlib import Path
from typing import Any

import numpy as np

from eventstreamgpt_tpu.utils.config_tool import (
    deep_merge,
    parse_overrides,
    resolve_interpolations,
    split_config_arg,
)

from .build_dataset import CONFIGS_DIR, load_yaml_with_defaults

WANDB_SWEEP_KEYS = {"value", "values", "min", "max", "distribution"}


def collapse_cfg(k: str, v: dict[str, Any]) -> dict[str, Any]:
    """Collapses nested parameter groups to dotted keys (reference ``:24-71``).

    Examples:
        >>> collapse_cfg("bar", {"values": "vals"})
        {'bar': {'values': 'vals'}}
        >>> collapse_cfg("foo", {"bar": {"baz": {"values": "vals"}}, "biz": {"max": "MX"}})
        {'foo.bar.baz': {'values': 'vals'}, 'foo.biz': {'max': 'MX'}}
        >>> collapse_cfg("foo", {"bar": {"value": None}})
        {}
        >>> collapse_cfg("foo", None)
        Traceback (most recent call last):
            ...
        TypeError: Misconfigured @ foo: None (<class 'NoneType'>) is not a dict!
    """
    if type(v) is not dict:
        raise TypeError(f"Misconfigured @ {k}: {v} ({type(v)}) is not a dict!")
    if WANDB_SWEEP_KEYS.intersection(v.keys()):
        if set(v.keys()) == {"value"} and v["value"] is None:
            return {}
        return {k: v}

    out: dict[str, Any] = {}
    for kk, vv in v.items():
        out.update(collapse_cfg(f"{k}.{kk}" if k else kk, vv))
    return out


def sample_param(spec: dict[str, Any], rng: np.random.Generator) -> Any:
    """Draws one value from a W&B-dialect parameter spec."""
    if "value" in spec:
        v = spec["value"]
        return None if v == "null" else v
    if "values" in spec:
        return spec["values"][int(rng.integers(len(spec["values"])))]
    lo, hi = spec["min"], spec["max"]
    if spec.get("distribution") == "log_uniform_values":
        return float(np.exp(rng.uniform(np.log(lo), np.log(hi))))
    if isinstance(lo, int) and isinstance(hi, int):
        return int(rng.integers(lo, hi + 1))
    return float(rng.uniform(lo, hi))


def sample_trial(parameters: dict[str, dict], rng: np.random.Generator) -> dict[str, Any]:
    """One random configuration as a dotted-key → value mapping."""
    return {k: sample_param(spec, rng) for k, spec in parameters.items()}


# ------------------------------------------------------------- bayes (TPE)
TPE_STARTUP_TRIALS = 4
TPE_GAMMA = 0.25
TPE_CANDIDATES = 24


def _tpe_numeric(spec, good_vals, bad_vals, rng):
    """Propose a numeric value maximizing the TPE density ratio l(x)/g(x).

    Kernel density over observed values (bandwidth = range / sqrt(n)), in log
    space for log-uniform specs; candidates are drawn from the good-KDE and
    scored against the bad-KDE — the standard Bergstra et al. (2011) TPE
    recipe with independent per-parameter models.
    """
    lo, hi = spec["min"], spec["max"]
    log_space = spec.get("distribution") == "log_uniform_values"
    tf = np.log if log_space else (lambda x: np.asarray(x, dtype=float))
    inv = np.exp if log_space else (lambda x: x)
    lo_t, hi_t = float(tf(lo)), float(tf(hi))
    span = hi_t - lo_t
    if span <= 0:
        # Degenerate (min == max) pins the parameter; legal in the dialect.
        return sample_param(spec, rng)

    # Both densities carry a uniform floor (a fraction of the uniform
    # density over the range): where neither side has observations — e.g.
    # at the boundaries, where clipping piles candidate mass — the ratio
    # damps toward 1 instead of exploding and dragging proposals to the
    # range edges.
    eps = 0.25 / span

    def bandwidth(n_obs):
        # Cap at span/4: with one observation an uncapped span-wide kernel
        # clips nearly every candidate onto the range boundaries.
        return float(np.clip(span / np.sqrt(n_obs), span * 1e-3, span / 4.0))

    def kde(obs, x):
        obs = np.asarray(obs, dtype=float)
        bw = bandwidth(len(obs))
        d = (x[:, None] - obs[None, :]) / bw
        return np.exp(-0.5 * d * d).sum(axis=1) / (len(obs) * bw) + eps

    g_obs = tf(np.asarray(good_vals, dtype=float))
    # Half the candidates come from the good KDE (exploitation), half
    # uniform over the range (exploration + no boundary pileup from clips).
    n_kde = TPE_CANDIDATES // 2
    centers = g_obs[rng.integers(len(g_obs), size=n_kde)]
    bw = bandwidth(len(g_obs))
    cands = np.concatenate(
        [
            np.clip(centers + rng.normal(0.0, bw, size=n_kde), lo_t, hi_t),
            rng.uniform(lo_t, hi_t, size=TPE_CANDIDATES - n_kde),
        ]
    )
    score = kde(g_obs, cands) / kde(tf(np.asarray(bad_vals, dtype=float)), cands)
    best = float(inv(cands[int(np.argmax(score))]))
    if isinstance(lo, int) and isinstance(hi, int) and not log_space:
        return int(round(np.clip(best, lo, hi)))
    return float(np.clip(best, lo, hi))


def _tpe_categorical(spec, good_vals, bad_vals, rng):
    """Propose the category maximizing smoothed good/bad frequency ratio."""
    choices = spec["values"]

    def freq(vals):
        counts = np.array([sum(1 for v in vals if v == c) for c in choices], dtype=float)
        return (counts + 1.0) / (counts.sum() + len(choices))

    ratio = freq(good_vals) / freq(bad_vals)
    return choices[int(np.argmax(ratio))]


def propose_tpe(
    parameters: dict[str, dict],
    history: list[tuple[dict[str, Any], float]],
    rng: np.random.Generator,
) -> dict[str, Any]:
    """One configuration proposed by Tree-structured Parzen Estimators.

    ``history`` is ``[(trial, loss), ...]`` with lower losses better (the
    caller negates maximize-goal metrics). Falls back to random sampling
    until ``TPE_STARTUP_TRIALS`` observations exist — the local stand-in for
    the reference sweep's W&B ``method: bayes`` service.
    """
    done = [(t, l) for t, l in history if l is not None and np.isfinite(l)]
    if len(done) < TPE_STARTUP_TRIALS:
        return sample_trial(parameters, rng)
    done.sort(key=lambda tl: tl[1])
    # n_good < len(done) always holds for len >= 2, so bad is never empty.
    n_good = max(int(np.ceil(TPE_GAMMA * len(done))), 1)
    good, bad = done[:n_good], done[n_good:]

    out = {}
    for k, spec in parameters.items():
        if "value" in spec:
            out[k] = sample_param(spec, rng)
            continue
        g = [t.get(k) for t, _ in good if t.get(k) is not None]
        b = [t.get(k) for t, _ in bad if t.get(k) is not None]
        if not g or not b:
            out[k] = sample_param(spec, rng)
        elif "values" in spec:
            out[k] = _tpe_categorical(spec, g, b, rng)
        else:
            out[k] = _tpe_numeric(spec, g, b, rng)
    return out


def _trial_args(trial: dict[str, Any], extra: dict[str, Any] | None = None) -> list[str]:
    merged = {**trial, **(extra or {})}
    return [
        f"{k}={json.dumps(v) if not isinstance(v, str) else v}"
        for k, v in merged.items()
        if v is not None
    ]


def _full_horizon(trial: dict[str, Any]) -> tuple[int, int]:
    """(full max_epochs, full max_training_steps) for a trial.

    The LR schedule must see the trial's *full* horizon at every rung —
    otherwise a promoted trial's warmup/decay would differ from the
    uninterrupted run and rung losses would not be comparable. A
    trial-specified ``optimization_config.max_training_steps`` is honored
    as-is; otherwise the horizon replicates
    ``OptimizationConfig.set_to_dataset``: ``ceil(len/batch) * max_epochs``
    for padded epochs, or the packed-batch count (same seed/seq-len defaults
    as ``pretrain.train``) when the trial enables packed batches or context
    parallelism — the padded count would over-pin the schedule by the packing
    factor.
    """
    import math

    from eventstreamgpt_tpu.data import JaxDataset, PytorchDatasetConfig
    from eventstreamgpt_tpu.models.config import OptimizationConfig

    oc_defaults = OptimizationConfig()
    max_epochs = int(trial.get("optimization_config.max_epochs", oc_defaults.max_epochs))

    explicit_steps = trial.get("optimization_config.max_training_steps")
    if explicit_steps is not None:
        return max_epochs, int(explicit_steps)

    batch_size = int(trial.get("optimization_config.batch_size", oc_defaults.batch_size))

    dc_kwargs = {
        k.split(".", 1)[1]: v for k, v in trial.items() if k.startswith("data_config.")
    }
    ds = JaxDataset(PytorchDatasetConfig(**dc_kwargs), "train")

    n_cp = int(trial.get("trainer_config.context_parallel_shards") or 1)
    use_packed = bool(trial.get("trainer_config.use_packed_batches")) or n_cp > 1
    if use_packed:
        # Mirror pretrain.train's packed row-length default: an explicit
        # trainer_config.packed_seq_len, else the larger of the configured
        # model context (the trial's value, or the
        # StructuredTransformerConfig class default pretrain would see) and
        # the dataset's per-subject cap.
        from eventstreamgpt_tpu.models.config import StructuredTransformerConfig

        configured_msl = int(
            trial.get("config.max_seq_len") or StructuredTransformerConfig().max_seq_len
        )
        packed_L = int(
            trial.get("trainer_config.packed_seq_len") or max(configured_msl, ds.max_seq_len)
        )
        seed = int(trial.get("seed", 1))
        steps_per_epoch = ds.packed_batch_count(batch_size, seq_len=packed_L, seed=seed)
    else:
        steps_per_epoch = int(math.ceil(len(ds) / batch_size))
    return max_epochs, steps_per_epoch * max_epochs


def run_asha(
    trials: list[dict[str, Any]],
    cfg: dict[str, Any],
    sweep_dir: Path,
    pretrain_main,
) -> list[dict[str, Any]]:
    """ASHA over epochs: run rungs, keep top 1/eta, resume survivors."""
    et = cfg["early_terminate"]
    if et.get("type") != "hyperband":
        raise ValueError(f"Unsupported early_terminate type: {et.get('type')}")
    eta = int(et.get("eta", 3))
    min_iter = max(int(et.get("min_iter", 1)), 1)
    metric_name = cfg["metric"]["name"]
    # goal: minimize (default) or maximize — promotion must follow it.
    goal = cfg["metric"].get("goal", "minimize")
    if goal not in ("minimize", "maximize"):
        raise ValueError(f"Unsupported metric goal: {goal}")
    sign = 1.0 if goal == "minimize" else -1.0

    def rank_key(t):
        v = state[t][metric_name]
        # None and NaN (diverged trial) both rank last.
        return sign * v if v is not None and np.isfinite(v) else float("inf")

    state = [
        {
            "trial": t,
            **trial,
            metric_name: None,
            "epochs_trained": 0,
            "status": "alive",
            "rungs": [],
        }
        for t, trial in enumerate(trials)
    ]
    horizons = [_full_horizon(trial) for trial in trials]

    alive = list(range(len(trials)))
    rung = 0
    while alive:
        target_epochs = min_iter * eta**rung
        for t in alive:
            full_epochs, full_steps = horizons[t]
            run_to = min(target_epochs, full_epochs)
            print(f"--- ASHA rung {rung}: trial {t} -> epoch {run_to}/{full_epochs} ---")
            tuning_loss, _, _ = pretrain_main(
                _trial_args(
                    trials[t],
                    {
                        "optimization_config.max_epochs": run_to,
                        "optimization_config.max_training_steps": full_steps,
                        "do_resume_from_checkpoint": True,
                        "do_overwrite": True,
                    },
                )
            )
            state[t][metric_name] = tuning_loss
            state[t]["epochs_trained"] = run_to
            state[t]["rungs"].append({"rung": rung, "epochs": run_to, metric_name: tuning_loss})
            if run_to >= full_epochs:
                state[t]["status"] = "completed"

        alive = [t for t in alive if state[t]["status"] == "alive"]
        if not alive:
            break
        # Promote the top ceil(len/eta) by the metric; kill the rest.
        order = sorted(alive, key=rank_key)
        n_keep = max((len(order) + eta - 1) // eta, 1)
        for t in order[n_keep:]:
            state[t]["status"] = f"stopped_rung_{rung}"
        alive = order[:n_keep]
        rung += 1

    results = sorted(
        state,
        key=lambda r: (
            sign * r[metric_name]
            if r[metric_name] is not None and np.isfinite(r[metric_name])
            else float("inf")
        ),
    )
    (sweep_dir / "sweep_results.json").write_text(json.dumps(results, indent=2))
    print(f"Best trial: {results[0]}")
    return results


def main(argv: list[str] | None = None):
    argv = list(sys.argv[1:] if argv is None else argv)
    do_run = "--run" in argv
    if do_run:
        argv.remove("--run")
    yaml_fp, argv = split_config_arg(argv)
    if yaml_fp is None:
        yaml_fp = CONFIGS_DIR / "hyperparameter_sweep_base.yaml"

    cfg = load_yaml_with_defaults(yaml_fp)
    deep_merge(cfg, parse_overrides(argv))
    cfg = resolve_interpolations(cfg)

    n_trials = int(cfg.get("n_trials", 10))
    seed = int(cfg.get("seed", 1))
    sweep_dir = Path(cfg.get("sweep_dir", "./sweep"))
    sweep_dir.mkdir(parents=True, exist_ok=True)

    parameters = collapse_cfg("", cfg["parameters"])
    rng = np.random.default_rng(seed)
    use_tpe = do_run and cfg.get("method") == "bayes" and not cfg.get("early_terminate")

    commands = []
    trials = []
    if not use_tpe:
        # TPE proposes trials adaptively inside the run loop — pre-sampled
        # configs would be written but never executed, which is worse than
        # writing nothing; the executed trials land in sweep_trials.json
        # after the run instead.
        for t in range(n_trials):
            trial = sample_trial(parameters, rng)
            trial["save_dir"] = str(sweep_dir / f"trial_{t}")
            trials.append(trial)
            args = " ".join(f"{k}={shlex.quote(json.dumps(v) if not isinstance(v, str) else v)}"
                            for k, v in trial.items() if v is not None)
            commands.append(f"python -m scripts.pretrain {args}")

        (sweep_dir / "sweep_trials.json").write_text(json.dumps(trials, indent=2))
        (sweep_dir / "sweep_commands.sh").write_text("\n".join(commands) + "\n")
        print(f"Wrote {n_trials} trial commands to {sweep_dir / 'sweep_commands.sh'}")

    if do_run:
        from .pretrain import main as pretrain_main

        if cfg.get("early_terminate"):
            # Rungs need batches of comparable trials, so ASHA keeps random
            # proposals; bayes (TPE) applies to the sequential path below.
            return run_asha(trials, cfg, sweep_dir, pretrain_main)

        metric_name = cfg["metric"]["name"]
        goal = cfg["metric"].get("goal", "minimize")
        sign = 1.0 if goal == "minimize" else -1.0
        history: list[tuple[dict[str, Any], float | None]] = []

        def rank(r):
            v = r.get(metric_name)
            # Diverged (NaN) trials rank last, like missing ones — nan would
            # otherwise poison the sort and could print as "Best trial".
            return sign * v if v is not None and np.isfinite(v) else float("inf")

        results = []
        for t in range(n_trials):
            if use_tpe:
                # Adaptive search (the W&B bayes analog): propose from TPE
                # fitted to the observed objective values so far.
                trial = propose_tpe(parameters, history, rng)
                trial["save_dir"] = str(sweep_dir / f"trial_{t}")
                trials.append(trial)
            else:
                trial = trials[t]
            print(f"--- sweep trial {t} ({cfg.get('method', 'random')}) ---")
            tuning_loss, _, _ = pretrain_main(_trial_args(trial))
            history.append((trial, sign * tuning_loss if tuning_loss is not None else None))
            results.append({"trial": t, metric_name: tuning_loss, **trial})
        if use_tpe:
            (sweep_dir / "sweep_trials.json").write_text(json.dumps(trials, indent=2))
        results.sort(key=rank)
        (sweep_dir / "sweep_results.json").write_text(json.dumps(results, indent=2))
        print(f"Best trial: {results[0]}")
        return results

    return commands


if __name__ == "__main__":
    main()
