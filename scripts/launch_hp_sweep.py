"""Hyperparameter sweep launcher (local random search).

Rebuild of ``/root/reference/scripts/launch_wandb_hp_sweep.py``: the same
sweep-config dialect (nested parameter groups with ``value`` / ``values`` /
``min``+``max`` [+ ``distribution: log_uniform_values``] leaves, collapsed to
hydra dotted-override syntax by ``collapse_cfg``), but executed locally —
this environment has no W&B service, so instead of registering a remote
bayes sweep the launcher samples ``n_trials`` random configurations and
either writes the pretrain command list (default) or runs them in-process
(``--run``). The sweep objective name (``tuning_loss``) is preserved so
result ranking works the same way.

Usage::

    python -m scripts.launch_hp_sweep --config configs/hyperparameter_sweep_base.yaml \
        n_trials=10 sweep_dir=./exp/sweep
"""

from __future__ import annotations

import json
import shlex
import sys
from pathlib import Path
from typing import Any

import numpy as np

from eventstreamgpt_tpu.utils.config_tool import (
    deep_merge,
    parse_overrides,
    resolve_interpolations,
    split_config_arg,
)

from .build_dataset import CONFIGS_DIR, load_yaml_with_defaults

WANDB_SWEEP_KEYS = {"value", "values", "min", "max", "distribution"}


def collapse_cfg(k: str, v: dict[str, Any]) -> dict[str, Any]:
    """Collapses nested parameter groups to dotted keys (reference ``:24-71``).

    Examples:
        >>> collapse_cfg("bar", {"values": "vals"})
        {'bar': {'values': 'vals'}}
        >>> collapse_cfg("foo", {"bar": {"baz": {"values": "vals"}}, "biz": {"max": "MX"}})
        {'foo.bar.baz': {'values': 'vals'}, 'foo.biz': {'max': 'MX'}}
        >>> collapse_cfg("foo", {"bar": {"value": None}})
        {}
        >>> collapse_cfg("foo", None)
        Traceback (most recent call last):
            ...
        TypeError: Misconfigured @ foo: None (<class 'NoneType'>) is not a dict!
    """
    if type(v) is not dict:
        raise TypeError(f"Misconfigured @ {k}: {v} ({type(v)}) is not a dict!")
    if WANDB_SWEEP_KEYS.intersection(v.keys()):
        if set(v.keys()) == {"value"} and v["value"] is None:
            return {}
        return {k: v}

    out: dict[str, Any] = {}
    for kk, vv in v.items():
        out.update(collapse_cfg(f"{k}.{kk}" if k else kk, vv))
    return out


def sample_param(spec: dict[str, Any], rng: np.random.Generator) -> Any:
    """Draws one value from a W&B-dialect parameter spec."""
    if "value" in spec:
        v = spec["value"]
        return None if v == "null" else v
    if "values" in spec:
        return spec["values"][int(rng.integers(len(spec["values"])))]
    lo, hi = spec["min"], spec["max"]
    if spec.get("distribution") == "log_uniform_values":
        return float(np.exp(rng.uniform(np.log(lo), np.log(hi))))
    if isinstance(lo, int) and isinstance(hi, int):
        return int(rng.integers(lo, hi + 1))
    return float(rng.uniform(lo, hi))


def sample_trial(parameters: dict[str, dict], rng: np.random.Generator) -> dict[str, Any]:
    """One random configuration as a dotted-key → value mapping."""
    return {k: sample_param(spec, rng) for k, spec in parameters.items()}


def main(argv: list[str] | None = None):
    argv = list(sys.argv[1:] if argv is None else argv)
    do_run = "--run" in argv
    if do_run:
        argv.remove("--run")
    yaml_fp, argv = split_config_arg(argv)
    if yaml_fp is None:
        yaml_fp = CONFIGS_DIR / "hyperparameter_sweep_base.yaml"

    cfg = load_yaml_with_defaults(yaml_fp)
    deep_merge(cfg, parse_overrides(argv))
    cfg = resolve_interpolations(cfg)

    n_trials = int(cfg.get("n_trials", 10))
    seed = int(cfg.get("seed", 1))
    sweep_dir = Path(cfg.get("sweep_dir", "./sweep"))
    sweep_dir.mkdir(parents=True, exist_ok=True)

    parameters = collapse_cfg("", cfg["parameters"])
    rng = np.random.default_rng(seed)

    commands = []
    trials = []
    for t in range(n_trials):
        trial = sample_trial(parameters, rng)
        trial["save_dir"] = str(sweep_dir / f"trial_{t}")
        trials.append(trial)
        args = " ".join(f"{k}={shlex.quote(json.dumps(v) if not isinstance(v, str) else v)}"
                        for k, v in trial.items() if v is not None)
        commands.append(f"python -m scripts.pretrain {args}")

    (sweep_dir / "sweep_trials.json").write_text(json.dumps(trials, indent=2))
    (sweep_dir / "sweep_commands.sh").write_text("\n".join(commands) + "\n")
    print(f"Wrote {n_trials} trial commands to {sweep_dir / 'sweep_commands.sh'}")

    if do_run:
        from .pretrain import main as pretrain_main

        results = []
        for t, trial in enumerate(trials):
            print(f"--- sweep trial {t} ---")
            trial_args = [f"{k}={json.dumps(v) if not isinstance(v, str) else v}"
                          for k, v in trial.items() if v is not None]
            tuning_loss, _, _ = pretrain_main(trial_args)
            results.append({"trial": t, cfg["metric"]["name"]: tuning_loss, **trial})
        results.sort(key=lambda r: r.get(cfg["metric"]["name"]) or float("inf"))
        (sweep_dir / "sweep_results.json").write_text(json.dumps(results, indent=2))
        print(f"Best trial: {results[0]}")
        return results

    return commands


if __name__ == "__main__":
    main()
