"""Device op-level profile of the production train step at a given width.

Traces a few pipelined steps of the packed bf16+Pallas train step through
``jax.profiler`` and prints the top HLO ops by device self-time (parsed from
the xplane with ``xprof``). This is the tool that produced the "remaining
hot spots" table in BASELINE.md.

    PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \
        python scripts/profile_width.py \
        [--hidden 1024 --layers 12 --head-dim 128 --policy save_attention]

``--policy`` selects the rematerialization policy the step compiles under
(default: ``save_attention``, the r06 production-width candidate) — the
backward's recompute mix is policy-dependent, so attributions must name
the policy they were taken under (VERDICT r05 weak #6).

(The pure-python protobuf flag is needed because the installed
tensorflow/xprof protobuf generations disagree; parsing is slow but the
trace itself is unaffected.)
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

PACKED_BATCH, PACKED_SEQ_LEN = 8, 1024


def build_step(hidden: int, layers: int, head_dim: int, policy: str = "save_attention"):
    import jax
    import jax.numpy as jnp

    from eventstreamgpt_tpu.data import JaxDataset, PytorchDatasetConfig
    from eventstreamgpt_tpu.data.synthetic import write_synthetic_dataset
    from eventstreamgpt_tpu.models.config import OptimizationConfig, StructuredTransformerConfig
    from eventstreamgpt_tpu.training import (
        TrainState,
        build_model,
        build_optimizer,
        data_parallel_mesh,
        make_train_step,
        replicate,
        shard_batch,
    )

    data_dir = Path(tempfile.mkdtemp(prefix="esgpt_profile_"))
    write_synthetic_dataset(
        data_dir,
        n_subjects_per_split={"train": 128, "tuning": 16},
        n_event_types=40,
        n_labs=3500,
        n_meds=500,
        mean_seq_len=200,
        max_seq_len=512,
        seed=0,
    )
    train_ds = JaxDataset(
        PytorchDatasetConfig(save_dir=data_dir, max_seq_len=256, min_seq_len=4), "train"
    )
    packed = next(
        b
        for b in train_ds.packed_batches(PACKED_BATCH, seq_len=PACKED_SEQ_LEN, seed=1)
        if b.event_mask.shape[0] == PACKED_BATCH
    )
    config = StructuredTransformerConfig(
        hidden_size=hidden,
        head_dim=head_dim,
        num_attention_heads=hidden // head_dim,
        num_hidden_layers=layers,
        seq_attention_types=["local", "global"],
        seq_window_size=32,
        intermediate_size=hidden * 4,
        TTE_generation_layer_type="log_normal_mixture",
        TTE_lognormal_generation_num_components=3,
        attention_implementation="pallas_flash",
        attention_dropout=0.0,
        gradient_checkpointing=policy,
        precision="bf16",
    )
    config.set_to_dataset(train_ds)
    config.max_seq_len = PACKED_SEQ_LEN

    model = build_model(config)
    oc = OptimizationConfig(
        init_lr=1e-3, batch_size=PACKED_BATCH, max_training_steps=10,
        lr_num_warmup_steps=1, lr_frac_warmup_steps=None,
    )
    tx, _ = build_optimizer(oc)
    params = model.init(jax.random.PRNGKey(0), packed)
    mesh = data_parallel_mesh(PACKED_BATCH)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params))
    state = replicate(state, mesh)
    resident = shard_batch(packed, mesh)
    return make_train_step(model, tx), state, resident


def top_ops_from_trace(trace_dir: str, top_n: int = 30):
    """Parses the xplane and returns [(self_time_us, occurrences, op name)]."""
    from xprof.convert import raw_to_tool_data

    paths = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    for tool in ("hlo_stats", "hlo_op_stats", "op_profile"):
        try:
            data, _ = raw_to_tool_data.xspace_to_tool_data(paths, tool, {})
        except Exception:
            continue
        if tool in ("hlo_stats", "hlo_op_stats"):
            rows = json.loads(data) if isinstance(data, (str, bytes)) else data
            return tool, rows
        return tool, data
    raise RuntimeError("no usable xprof tool produced data")


def summarize_categories(rows, top=25):
    """hlo_stats table ({cols, rows} gviz-style) -> [(category, self_us)].

    The per-category rollup that produced BASELINE.md's head-stack tables
    (dense matmuls vs attention custom-calls vs scatter/gather vs loop
    fusions); re-run this under each remat policy (``--policy``) to see what
    the backward actually recomputes.
    """
    cols = [c["label"] if isinstance(c, dict) else c for c in rows["cols"]]
    i_cat = cols.index("HLO op category")
    i_self = cols.index("Total self time (us)")
    agg: dict = {}
    for r in rows["rows"]:
        c = r["c"] if isinstance(r, dict) else r
        vals = [x.get("v") if isinstance(x, dict) else x for x in c]
        agg[vals[i_cat]] = agg.get(vals[i_cat], 0.0) + float(vals[i_self] or 0)
    return sorted(agg.items(), key=lambda kv: -kv[1])[:top]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument(
        "--policy",
        default="save_attention",
        help="gradient_checkpointing policy to profile under "
        "(none|block|dots|dots_no_batch|save_attention)",
    )
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--trace-dir", default=None)
    args = ap.parse_args(argv)

    import jax

    from eventstreamgpt_tpu.utils.benchmarking import drain, wait_for_quiet

    step, state, resident = build_step(args.hidden, args.layers, args.head_dim, args.policy)
    rng = jax.random.PRNGKey(0)
    state, loss = step(state, resident, rng)  # compile
    drain(loss)
    echo, contended = wait_for_quiet()
    print(f"quiet gate: echo {echo:.2f} ms, contended={contended}", file=sys.stderr)

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="esgpt_trace_")
    jax.profiler.start_trace(trace_dir)
    for _ in range(args.steps):
        state, loss = step(state, resident, rng)
    drain(loss)
    jax.profiler.stop_trace()
    print(f"trace written to {trace_dir}", file=sys.stderr)

    tool, rows = top_ops_from_trace(trace_dir)
    print(f"parsed with tool={tool} (policy={args.policy})")
    if tool in ("hlo_stats", "hlo_op_stats") and isinstance(rows, dict):
        print("-- by HLO op category (device self us over traced steps) --")
        for k, v in summarize_categories(rows):
            print(f"  {v:10.0f}  {k}")
    print(json.dumps(rows)[:20000] if not isinstance(rows, list) else rows[:40])


if __name__ == "__main__":
    main()
