"""Extracts pooled per-subject embeddings from a pretrained model.

Rebuild of ``/root/reference/scripts/get_embeddings.py``: thin entry over
``eventstreamgpt_tpu.training.embedding.get_embeddings``.

Usage::

    python -m scripts.get_embeddings load_from_model_dir=./exp/pretrain \
        task_df_name=in_hosp_mort
"""

from __future__ import annotations

import sys

from eventstreamgpt_tpu.training.embedding import get_embeddings
from eventstreamgpt_tpu.training.fine_tuning import FinetuneConfig
from eventstreamgpt_tpu.utils.config_tool import load_config, split_config_arg


def main(argv: list[str] | None = None):
    argv = list(sys.argv[1:] if argv is None else argv)
    yaml_fp, argv = split_config_arg(argv)
    cfg = load_config(FinetuneConfig, yaml_file=yaml_fp, overrides=argv)
    return get_embeddings(cfg)


if __name__ == "__main__":
    main()
