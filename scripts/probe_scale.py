"""Standalone quiet-window scale sweep: step time / MFU vs width x depth.

Evidence for "MFU at production width" (VERDICT r03 #2): the bench's toy
shape (hidden 256, 2 layers, ~5.5M params) is dispatch-dominated, so its MFU
says nothing about realistic widths. This script probes the full production
train step (fwd+bwd+AdamW, bf16 + Pallas flash/splash kernels, packed
seq-1024 segment-ID batches) across hidden {256, 512, 1024} x layers
{2, 6, 12}, with a tunnel quiet-gate before each point and the
sustained-pipeline step probe (k dependent steps + one true readback − the
measured RTT; ``utils/benchmarking.py`` — ``block_until_ready`` returns
before compute completes on this tunnel, so naive per-step timing reads
dispatch latency, not compute).

Each point prints one JSON line immediately (a contended tail must not
erase earlier quiet points); the final line is a summary table. Run it
directly on the TPU host:

    python -m scripts.probe_scale [--points 256x2,1024x12]

MFU here is the standard dense estimate (6 * n_params FLOPs per event,
fwd+bwd; attention FLOPs excluded) against the v5e bf16 peak of 197
TFLOP/s. Attention at seq 1024 adds ~12*L*h FLOPs/event per layer (~10-20%
at these shapes), so the dense MFU is a mild *underestimate* of hardware
utilization.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

PACKED_BATCH, PACKED_SEQ_LEN = 8, 1024
HEAD_DIM = 64
PEAK_BF16_TFLOPS = 197e12

POINTS = [(h, l) for h in (256, 512, 1024) for l in (2, 6, 12)]


def tunnel_probe_ms(n: int = 20) -> float:
    """Dispatch echo: the contention gate (NOT a compute measurement)."""
    from eventstreamgpt_tpu.utils.benchmarking import dispatch_echo_ms

    return dispatch_echo_ms(n)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", default=None, help="comma list like 256x2,1024x12")
    ap.add_argument("--head-dim", type=int, default=HEAD_DIM)
    args = ap.parse_args(argv)
    head_dim = args.head_dim

    points = POINTS
    if args.points:
        points = [
            (int(h), int(l))
            for h, l in (p.lower().split("x") for p in args.points.split(","))
        ]

    import jax
    import jax.numpy as jnp

    from eventstreamgpt_tpu.data import JaxDataset, PytorchDatasetConfig
    from eventstreamgpt_tpu.data.synthetic import write_synthetic_dataset
    from eventstreamgpt_tpu.models.config import OptimizationConfig, StructuredTransformerConfig
    from eventstreamgpt_tpu.training import (
        TrainState,
        build_model,
        build_optimizer,
        data_parallel_mesh,
        make_train_step,
        replicate,
        shard_batch,
    )

    # One shared packed batch at the bench's long-context shape.
    data_dir = Path(tempfile.mkdtemp(prefix="esgpt_probe_scale_"))
    write_synthetic_dataset(
        data_dir,
        n_subjects_per_split={"train": 128, "tuning": 16},
        n_event_types=40,
        n_labs=3500,
        n_meds=500,
        mean_seq_len=200,
        max_seq_len=512,
        seed=0,
    )
    train_ds = JaxDataset(
        PytorchDatasetConfig(save_dir=data_dir, max_seq_len=256, min_seq_len=4), "train"
    )
    packed_init = next(
        b
        for b in train_ds.packed_batches(PACKED_BATCH, seq_len=PACKED_SEQ_LEN, seed=1)
        if b.event_mask.shape[0] == PACKED_BATCH
    )
    probe_events = int(np.asarray(packed_init.event_mask).sum())

    mesh = data_parallel_mesh(PACKED_BATCH)
    n_devices = int(mesh.devices.size)
    resident = shard_batch(packed_init, mesh)
    rng = jax.random.PRNGKey(0)
    oc = OptimizationConfig(
        init_lr=1e-3, batch_size=PACKED_BATCH, max_training_steps=10,
        lr_num_warmup_steps=1, lr_frac_warmup_steps=None,
    )

    rows = []
    for hidden, layers in points:
        config = StructuredTransformerConfig(
            hidden_size=hidden,
            head_dim=head_dim,
            num_attention_heads=hidden // head_dim,
            num_hidden_layers=layers,
            seq_attention_types=["local", "global"],
            seq_window_size=32,
            intermediate_size=hidden * 4,
            TTE_generation_layer_type="log_normal_mixture",
            TTE_lognormal_generation_num_components=3,
            attention_implementation="pallas_flash",
            attention_dropout=0.0,
            precision="bf16",
        )
        config.set_to_dataset(train_ds)
        config.max_seq_len = PACKED_SEQ_LEN
        model = build_model(config)
        tx, _ = build_optimizer(oc)
        params = model.init(jax.random.PRNGKey(0), packed_init)
        n_params = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
        state = TrainState(
            step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params)
        )
        state = replicate(state, mesh)
        step = make_train_step(model, tx)

        from eventstreamgpt_tpu.utils.benchmarking import drain, sustained_step_ms

        t_c = time.perf_counter()
        state, loss = step(state, resident, rng)  # compile + warmup
        drain(loss)
        compile_s = time.perf_counter() - t_c

        # Quiet-gate (dispatch echo; one shared definition of "quiet" —
        # utils/benchmarking.py), then the sustained-pipeline probe: step
        # time = (k pipelined steps + one readback − RTT) / k.
        from eventstreamgpt_tpu.utils.benchmarking import wait_for_quiet

        probe, contended = wait_for_quiet(retries=4)

        step_ms, state, info = sustained_step_ms(step, state, resident, rng)
        ev_per_s = probe_events / (step_ms / 1000.0) / n_devices
        mfu = ev_per_s * 6 * n_params / PEAK_BF16_TFLOPS

        row = {
            "hidden": hidden,
            "layers": layers,
            "n_params": n_params,
            "step_ms": round(step_ms, 3),
            "events_per_sec_per_chip": round(ev_per_s, 1),
            "mfu_dense_vs_197tflops": round(mfu, 4),
            "tunnel_probe_ms": round(probe, 3),
            "contended": contended,
            "compile_s": round(compile_s, 1),
            "probe_k": info["k"],
            "readback_rtt_ms": info["readback_rtt_ms"],
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

        # Free this point's state before the next (larger) one compiles.
        del state, params, step, loss

    print(json.dumps({"scale_sweep": rows, "batch": PACKED_BATCH, "seq_len": PACKED_SEQ_LEN,
                      "events_per_batch": probe_events, "n_devices": n_devices,
                      "precision": "bf16", "kernels": "pallas flash+splash"}))
    return rows


if __name__ == "__main__":
    sys.exit(0 if main() else 1)
