"""Probe: where does the padded-epoch wall time go (collate / transfer / dispatch)?

The r04 artifact shows ~78 ms wall per step vs 13.5 ms device compute on the
padded CI section. Candidate sinks: host collation (~4 ms measured), the
per-batch ``device_put`` transfer through the tunnel, or per-step dispatch on
a contended control plane. This script measures each in isolation:

  A. collate-only: time ``JaxDataset.batches`` drained on the host.
  B. transfer-only: ``shard_batch`` (device_put) of pre-collated batches,
     one readback at the end, RTT-subtracted.
  C. resident-step epoch: step dispatch loop on ONE resident batch (no
     transfers) — same count as a real epoch, one drain.
  D. full epoch (prefetch pipeline, as bench.py ran it through round 4).
  E. device-resident epoch (`DeviceDataset`: CSR in HBM, on-device collate,
     ~100-byte plans on the wire) — the round-5 fix.

Host-only host timings are exact; device-involved ones use the sustained
protocol (pipelined dispatches + one readback − RTT).
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np


def main():
    import tempfile

    import jax
    import jax.numpy as jnp

    from eventstreamgpt_tpu.data import JaxDataset, PytorchDatasetConfig, prefetch_to_device
    from eventstreamgpt_tpu.data.synthetic import write_synthetic_dataset
    from eventstreamgpt_tpu.models.config import OptimizationConfig, StructuredTransformerConfig
    from eventstreamgpt_tpu.training import (
        TrainState,
        build_model,
        build_optimizer,
        data_parallel_mesh,
        make_train_step,
        replicate,
        shard_batch,
    )
    from eventstreamgpt_tpu.utils.benchmarking import (
        dispatch_echo_ms,
        drain,
        readback_echo_ms,
    )

    N_TRAIN = 512
    BATCH, SEQ_LEN, HIDDEN = 32, 256, 256

    data_dir = Path(tempfile.mkdtemp(prefix="esgpt_feed_probe_"))
    write_synthetic_dataset(
        data_dir,
        n_subjects_per_split={"train": N_TRAIN},
        n_event_types=40,
        n_labs=3500,
        n_meds=500,
        mean_seq_len=200,
        max_seq_len=512,
        seed=0,
    )
    data_config = PytorchDatasetConfig(save_dir=data_dir, max_seq_len=SEQ_LEN, min_seq_len=4)
    ds = JaxDataset(data_config, "train")
    print(f"n_subjects={len(ds)} max_n_dynamic={ds.max_n_dynamic}", flush=True)

    config = StructuredTransformerConfig(
        hidden_size=HIDDEN,
        head_dim=HIDDEN // 4,
        num_attention_heads=4,
        num_hidden_layers=2,
        seq_attention_types=["local", "global"],
        seq_window_size=32,
        intermediate_size=HIDDEN * 4,
        TTE_generation_layer_type="log_normal_mixture",
        TTE_lognormal_generation_num_components=3,
        precision="bf16",
    )
    config.set_to_dataset(ds)
    oc = OptimizationConfig(init_lr=1e-3, batch_size=BATCH, max_epochs=1)
    oc.set_to_dataset(ds)

    model = build_model(config)
    tx, _ = build_optimizer(oc)
    mesh = data_parallel_mesh(BATCH)
    init_batch = next(ds.batches(BATCH, shuffle=True, seed=0))
    params = model.init(jax.random.PRNGKey(0), init_batch)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params))
    state = replicate(state, mesh)
    train_step = make_train_step(model, tx)
    rng = jax.random.PRNGKey(0)

    resident = shard_batch(init_batch, mesh)
    state, loss = train_step(state, resident, rng)
    drain(loss)

    echo = dispatch_echo_ms()
    rtt = readback_echo_ms()
    print(f"dispatch_echo_ms={echo:.2f} readback_rtt_ms={rtt:.1f}", flush=True)

    # Batch wire size
    nbytes = sum(
        np.asarray(v).nbytes
        for v in jax.tree_util.tree_leaves(init_batch)
    )
    print(f"batch_wire_bytes={nbytes} ({nbytes/1e6:.2f} MB)", flush=True)

    # A. collate-only
    t0 = time.perf_counter()
    n_batches = 0
    for b in ds.batches(BATCH, shuffle=True, seed=1):
        n_batches += 1
    t_collate = time.perf_counter() - t0
    print(f"A collate-only: {1000*t_collate/n_batches:.2f} ms/batch ({n_batches} batches)", flush=True)

    # B. transfer-only: pre-collate, then device_put all + one readback
    host_batches = list(ds.batches(BATCH, shuffle=True, seed=2))
    for rep in range(2):
        rtt_i = readback_echo_ms()
        t0 = time.perf_counter()
        dev = [shard_batch(b, mesh) for b in host_batches]
        drain(dev[-1].time_delta)  # readback forces all transfers complete? only last...
        # force ALL: sum a scalar touching each
        s = sum(jnp.sum(d.time_delta) for d in dev)
        drain(s)
        t = 1000 * (time.perf_counter() - t0) - rtt_i
        print(f"B transfer-only rep{rep}: {t/len(host_batches):.2f} ms/batch", flush=True)
        del dev

    # C. resident-step loop: n_batches steps on one resident batch, one drain
    for rep in range(2):
        rtt_i = readback_echo_ms()
        t0 = time.perf_counter()
        for _ in range(n_batches):
            state, loss = train_step(state, resident, rng)
        drain(loss)
        t = 1000 * (time.perf_counter() - t0) - rtt_i
        print(f"C resident-steps rep{rep}: {t/n_batches:.2f} ms/step", flush=True)

    # C2. steps on alternating prefetched device batches (transfer + step, no collate)
    dev_batches = [shard_batch(b, mesh) for b in host_batches]
    s = sum(jnp.sum(d.time_delta) for d in dev_batches)
    drain(s)
    for rep in range(2):
        rtt_i = readback_echo_ms()
        t0 = time.perf_counter()
        for d in dev_batches:
            state, loss = train_step(state, d, rng)
        drain(loss)
        t = 1000 * (time.perf_counter() - t0) - rtt_i
        print(f"C2 steps-over-resident-batches rep{rep}: {t/len(dev_batches):.2f} ms/step", flush=True)
    del dev_batches

    # D. full epoch as bench runs it
    for rep in range(2):
        t0 = time.perf_counter()
        it = prefetch_to_device(
            ds.batches(BATCH, shuffle=True, seed=3 + rep),
            lambda b: shard_batch(b, mesh),
            host_stats_fn=lambda b: int(b.event_mask.sum()),
        )
        ev = 0
        nb = 0
        for d, n in it:
            ev += n
            state, loss = train_step(state, d, rng)
            nb += 1
        drain(loss)
        dt = time.perf_counter() - t0
        print(
            f"D full-epoch rep{rep}: {1000*dt/nb:.2f} ms/step, {ev/dt:.0f} ev/s",
            flush=True,
        )

    # E. device-resident epoch: upload once, per-step wire = the plan.
    from eventstreamgpt_tpu.data import DeviceDataset

    t0 = time.perf_counter()
    dd = DeviceDataset(ds, mesh=mesh)
    drain(dd.arrays["time_delta"])
    t_upload = time.perf_counter() - t0
    print(f"E upload: {dd.nbytes/1e6:.1f} MB in {1000*t_upload:.0f} ms", flush=True)
    for rep in range(3):
        t0 = time.perf_counter()
        ev = 0
        nb = 0
        for d, n in dd.batches(BATCH, shuffle=True, seed=3 + rep, with_counts=True):
            ev += n
            state, loss = train_step(state, d, rng)
            nb += 1
        drain(loss)
        dt = time.perf_counter() - t0
        print(
            f"E device-resident epoch rep{rep}: {1000*dt/nb:.2f} ms/step, {ev/dt:.0f} ev/s",
            flush=True,
        )

    # F. chunked-scan epochs: k collate+step iterations per dispatch.
    from eventstreamgpt_tpu.training import make_chunked_train_step

    for chunk in (4, 8, 16):
        chunk_step = make_chunked_train_step(model, tx, dd)
        # compile outside the timing
        plans0, _ = next(iter(dd.plan_chunks(BATCH, chunk, shuffle=True, seed=0)))
        state, _ = chunk_step(state, dd.arrays, plans0, rng)
        drain(_)
        for rep in range(2):
            t0 = time.perf_counter()
            ev = 0
            nb = 0
            for plans, n in dd.plan_chunks(BATCH, chunk, shuffle=True, seed=30 + rep):
                ev += n
                state, losses = chunk_step(state, dd.arrays, plans, rng)
                nb += plans["starts"].shape[0]
            drain(losses)
            dt = time.perf_counter() - t0
            print(
                f"F chunk={chunk} rep{rep}: {1000*dt/nb:.2f} ms/step, {ev/dt:.0f} ev/s",
                flush=True,
            )


if __name__ == "__main__":
    main()
