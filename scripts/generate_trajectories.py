"""Generates future-trajectory samples and writes them as parquet.

Rebuild of ``/root/reference/scripts/generate_trajectories.py``: thin entry
over ``eventstreamgpt_tpu.evaluation.generate_trajectories``.

Usage::

    python -m scripts.generate_trajectories load_from_model_dir=./exp/pretrain \
        task_specific_params.num_samples=4 task_specific_params.max_new_events=32
"""

from __future__ import annotations

import sys

from eventstreamgpt_tpu.evaluation import GenerateConfig, generate_trajectories
from eventstreamgpt_tpu.utils.config_tool import load_config, split_config_arg


def main(argv: list[str] | None = None):
    argv = list(sys.argv[1:] if argv is None else argv)
    yaml_fp, argv = split_config_arg(argv)
    cfg = load_config(GenerateConfig, yaml_file=yaml_fp, overrides=argv)
    return generate_trajectories(cfg)


if __name__ == "__main__":
    main()
