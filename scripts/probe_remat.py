"""Rematerialization-policy A/B at the production-width probe shape.

VERDICT r05 #3 / r06 #2: measure whole-block remat and the
`jax.checkpoint` selective policies — including ``save_attention``
(dots_no_batch + checkpoint-named attention outputs, so the backward
never re-executes the flash/splash/band custom-calls) — against no-remat
at hidden-1024/12L (both head_dims), sustained protocol.

    python scripts/probe_remat.py [--head-dim 128]

Microbenches pick candidates; ``bench.py``'s width section A/Bs
``dots_no_batch`` vs ``save_attention`` at the step level every run and
reports both (``width1024_remat_ab_ms``) — the artifact picks the default.
"""

from __future__ import annotations

import argparse
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

PACKED_BATCH, PACKED_SEQ_LEN = 8, 1024


def build(head_dim: int, policy: str):
    import jax
    import jax.numpy as jnp

    from eventstreamgpt_tpu.data import JaxDataset, PytorchDatasetConfig
    from eventstreamgpt_tpu.data.synthetic import write_synthetic_dataset
    from eventstreamgpt_tpu.models.config import OptimizationConfig, StructuredTransformerConfig
    from eventstreamgpt_tpu.training import (
        TrainState,
        build_model,
        build_optimizer,
        data_parallel_mesh,
        make_train_step,
        replicate,
        shard_batch,
    )

    if not hasattr(build, "_data"):
        data_dir = Path(tempfile.mkdtemp(prefix="esgpt_remat_"))
        write_synthetic_dataset(
            data_dir,
            n_subjects_per_split={"train": 128},
            n_event_types=40,
            n_labs=3500,
            n_meds=500,
            mean_seq_len=200,
            max_seq_len=512,
            seed=0,
        )
        ds = JaxDataset(
            PytorchDatasetConfig(save_dir=data_dir, max_seq_len=256, min_seq_len=4), "train"
        )
        packed = next(
            b
            for b in ds.packed_batches(PACKED_BATCH, seq_len=PACKED_SEQ_LEN, seed=1)
            if b.event_mask.shape[0] == PACKED_BATCH
        )
        build._data = (ds, packed)
    ds, packed = build._data

    hidden = 1024
    config = StructuredTransformerConfig(
        hidden_size=hidden,
        head_dim=head_dim,
        num_attention_heads=hidden // head_dim,
        num_hidden_layers=12,
        seq_attention_types=["local", "global"],
        seq_window_size=32,
        intermediate_size=hidden * 4,
        TTE_generation_layer_type="log_normal_mixture",
        TTE_lognormal_generation_num_components=3,
        attention_implementation="pallas_flash",
        attention_dropout=0.0,
        gradient_checkpointing=policy,
        precision="bf16",
    )
    config.set_to_dataset(ds)
    config.max_seq_len = PACKED_SEQ_LEN
    model = build_model(config)
    oc = OptimizationConfig(
        init_lr=1e-3, batch_size=PACKED_BATCH, max_training_steps=10,
        lr_num_warmup_steps=1, lr_frac_warmup_steps=None,
    )
    tx, _ = build_optimizer(oc)
    params = model.init(jax.random.PRNGKey(0), packed)
    mesh = data_parallel_mesh(PACKED_BATCH)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params))
    state = replicate(state, mesh)
    resident = shard_batch(packed, mesh)
    return make_train_step(model, tx), state, resident


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument(
        "--policies",
        nargs="*",
        default=["none", "dots_no_batch", "save_attention", "dots", "block"],
    )
    args = ap.parse_args(argv)

    import jax

    from eventstreamgpt_tpu.utils.benchmarking import drain, sustained_step_ms, wait_for_quiet

    rng = jax.random.PRNGKey(0)
    for policy in args.policies:
        step, state, resident = build(args.head_dim, policy)
        try:
            lowered = jax.jit(step).lower(state, resident, rng) if False else None
            state, loss = step(state, resident, rng)
            drain(loss)
        except Exception as e:  # noqa: BLE001 — report OOM/compile failures per policy
            print(f"{policy}: FAILED ({type(e).__name__}: {str(e)[:120]})", flush=True)
            continue
        echo, contended = wait_for_quiet()
        ms, state, info = sustained_step_ms(step, state, resident, rng)
        print(
            f"{policy}: {ms:.2f} ms/step windows={info['window_estimates_ms']} "
            f"contended={contended}",
            flush=True,
        )


if __name__ == "__main__":
    main()
