"""Block-size tuning probe for the Pallas splash-attention (local) kernel.

The local layers use a 32-wide sliding window (reference default), yet a
width-shape device profile showed them costing nearly as much as the global
flash layers (~1.6 ms/layer fwd+bwd) on the kernel's default 128x128 blocks
— the band is narrow, so the cost is small-block grid overhead, not FLOPs.
This sweeps q/kv block shapes (and the fused backward kernel) at the
production-width shape. Run on the real chip:

    python scripts/probe_splash_blocks.py
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

from eventstreamgpt_tpu.utils.benchmarking import (  # noqa: E402
    drain,
    readback_echo_ms,
    wait_for_quiet,
)

WINDOW = 32


def make_inputs(B, H, L, D, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, H, L, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, H, L, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, H, L, D), jnp.bfloat16)
    seg = jnp.zeros((B, L), jnp.int32).at[:, L // 2 :].set(1)
    return q, k, v, seg


def layer_cost_ms(q, k, v, seg, block_sizes, n_pipeline=20, repeats=2):
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as splash_kernel,
    )
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_mask as splash_mask,
    )

    B, H, L, D = q.shape
    mask = splash_mask.MultiHeadMask(
        [splash_mask.LocalMask((L, L), (WINDOW - 1, 0), 0) for _ in range(H)]
    )
    kernel = splash_kernel.make_splash_mha(
        mask, head_shards=1, q_seq_shards=1, block_sizes=block_sizes
    )

    def fwd(q, k, v):
        out = jax.vmap(
            lambda qq, kk, vv, s: kernel(
                qq, kk, vv, segment_ids=splash_kernel.SegmentIds(q=s, kv=s)
            )
        )(q, k, v, seg)
        return (out.astype(jnp.float32) ** 2).sum()

    grad_fn = jax.jit(jax.value_and_grad(fwd, argnums=(0, 1, 2)))
    loss, grads = grad_fn(q, k, v)
    drain(loss)

    best = float("inf")
    for _ in range(repeats):
        rtt = readback_echo_ms()
        qq = q
        t0 = time.perf_counter()
        for _ in range(n_pipeline):
            loss, (dq, dk, dv) = grad_fn(qq, k, v)
            qq = qq + 0.0 * dq
        drain(loss)
        window = 1000.0 * (time.perf_counter() - t0) - rtt
        best = min(best, max(window, 0.0) / n_pipeline)
    return best


def main():
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
    )

    def bs(bq, bkv, fused=False):
        kw = dict(
            block_q=bq, block_kv=bkv, block_kv_compute=bkv,
            block_q_dkv=bq, block_kv_dkv=bkv, block_kv_dkv_compute=bkv,
            use_fused_bwd_kernel=fused,
        )
        if not fused:
            kw.update(block_q_dq=bq, block_kv_dq=bkv)
        return sk.BlockSizes(**kw)

    configs = [
        ("default(128x128)", None),
        ("q256_kv128", bs(256, 128)),
        ("q512_kv128", bs(512, 128)),
        ("q1024_kv128", bs(1024, 128)),
        ("q512_kv256", bs(512, 256)),
        ("q1024_kv256", bs(1024, 256)),
        ("q512_kv128_fused", bs(512, 128, fused=True)),
        ("q1024_kv128_fused", bs(1024, 128, fused=True)),
    ]
    for shape_name, B, H, L, D in [("h1024_hd128", 8, 8, 1024, 128),
                                   ("h1024_hd64", 8, 16, 1024, 64)]:
        q, k, v, seg = make_inputs(B, H, L, D)
        echo, contended = wait_for_quiet()
        print(f"== {shape_name} B={B} H={H} L={L} D={D} window={WINDOW} "
              f"(echo {echo:.2f} ms, contended={contended})", flush=True)
        for name, blocks in configs:
            try:
                ms = layer_cost_ms(q, k, v, seg, blocks)
            except Exception as e:
                print(f"  {name:>18}: FAILED ({type(e).__name__}: {str(e)[:80]})",
                      flush=True)
                continue
            print(f"  {name:>18}: {ms:7.3f} ms/layer fwd+bwd", flush=True)


if __name__ == "__main__":
    main()
