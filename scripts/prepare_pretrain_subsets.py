"""Prepares config dirs + command lists for pretraining-subset experiments.

Rebuild of ``/root/reference/scripts/prepare_pretrain_subsets.py``: given an
initial pretrain run directory (holding ``pretrain_config.yaml``), generates
per-subset-size × per-seed run directories with modified pretrain configs and
writes shell command lists for pretraining, few-shot fine-tuning, zero-shot
evaluation, and embedding extraction over those runs.

Usage::

    python -m scripts.prepare_pretrain_subsets \
        initial_model_path=./exp/pretrain subset_sizes='[100, 1000]' \
        experiment_name=subset_experiments seeds=2
"""

from __future__ import annotations

import copy
import json
import sys
from collections import defaultdict
from pathlib import Path

import yaml

from eventstreamgpt_tpu.utils.config_tool import (
    deep_merge,
    parse_overrides,
    resolve_interpolations,
    split_config_arg,
)

from .build_dataset import CONFIGS_DIR, load_yaml_with_defaults


def main(argv: list[str] | None = None):
    argv = list(sys.argv[1:] if argv is None else argv)
    yaml_fp, argv = split_config_arg(argv)
    if yaml_fp is None:
        yaml_fp = CONFIGS_DIR / "pretrain_subsets_base.yaml"

    cfg = load_yaml_with_defaults(yaml_fp)
    deep_merge(cfg, parse_overrides(argv))
    cfg = resolve_interpolations(cfg)

    initial_model_path = Path(cfg["initial_model_path"])
    initial_config_path = initial_model_path / "pretrain_config.yaml"
    if not initial_config_path.is_file():
        raise FileNotFoundError(f"{initial_config_path} does not exist!")

    subset_sizes = cfg["subset_sizes"]
    if not isinstance(subset_sizes, list):
        raise TypeError(f"subset_sizes must be a list, got {subset_sizes}!")

    seeds = cfg["seeds"]
    if isinstance(seeds, int):
        seeds = [seeds for _ in subset_sizes]
    elif isinstance(seeds, list) and len(seeds) == len(subset_sizes):
        pass
    elif isinstance(seeds, dict) and all(s in seeds for s in subset_sizes):
        seeds = [seeds[s] for s in subset_sizes]
    else:
        raise TypeError(
            f"seeds must be an int or a list/dict matching {subset_sizes}, got {seeds}!"
        )

    with open(initial_config_path) as f:
        initial_config = yaml.safe_load(f)

    experiment_dir = cfg.get("experiment_dir") or initial_config.get("experiment_dir")
    experiment_dir = Path(experiment_dir)
    runs_dir = experiment_dir / cfg["experiment_name"]
    runs_dir.mkdir(parents=True, exist_ok=True)

    ft_tasks = (cfg.get("few_shot_commands") or {}).get("fine_tuning_task_names", [])
    zs_tasks = (cfg.get("zero_shot_commands") or {}).get("fine_tuning_task_names", [])
    emb_tasks = (cfg.get("get_embeddings_commands") or {}).get("fine_tuning_task_names", [])

    commands = defaultdict(list)
    for n_seeds, subset_size in zip(seeds, subset_sizes):
        for seed in range(n_seeds):
            seed_runs_dir = runs_dir / f"subset_{subset_size}" / f"seed_{seed}"
            seed_runs_dir.mkdir(parents=True, exist_ok=True)

            if cfg.get("do_include_PT_commands", True):
                new_config = copy.deepcopy(initial_config)
                new_config["experiment_dir"] = str(experiment_dir)
                new_config.setdefault("data_config", {})["train_subset_size"] = subset_size
                new_config["data_config"]["train_subset_seed"] = seed
                new_config["save_dir"] = str(seed_runs_dir)

                new_config_path = seed_runs_dir / "pretrain_config_source.yaml"
                with open(new_config_path, "w") as f:
                    yaml.safe_dump(new_config, f)

                commands["pretrain"].append(
                    f"python -m scripts.pretrain --config {new_config_path}"
                )

            for task in ft_tasks:
                for ft_subset in (cfg["few_shot_commands"].get("fine_tuning_subset_sizes") or ["FULL"]):
                    commands["finetune"].append(
                        f"python -m scripts.finetune load_from_model_dir={seed_runs_dir} "
                        f"task_df_name={task} "
                        f"data_config_overrides.train_subset_size={ft_subset}"
                    )
            for task in zs_tasks:
                num_samples = (cfg["zero_shot_commands"] or {}).get("num_samples", 10)
                commands["zeroshot"].append(
                    f"python -m scripts.zeroshot load_from_model_dir={seed_runs_dir} "
                    f"task_df_name={task} task_specific_params.num_samples={num_samples}"
                )
            for task in emb_tasks:
                commands["get_embeddings"].append(
                    f"python -m scripts.get_embeddings load_from_model_dir={seed_runs_dir} "
                    f"task_df_name={task}"
                )

    for name, cmds in commands.items():
        fp = runs_dir / f"{name}_commands.sh"
        fp.write_text("\n".join(cmds) + "\n")
        print(f"Wrote {len(cmds)} {name} commands to {fp}")

    (runs_dir / "subset_manifest.json").write_text(
        json.dumps({"subset_sizes": subset_sizes, "seeds": seeds}, indent=2)
    )
    return dict(commands)


if __name__ == "__main__":
    main()
