"""Builds a processed event-stream dataset from a compact YAML spec.

Rebuild of ``/root/reference/scripts/build_dataset.py`` — the same YAML
dialect (``inputs:`` sources + ``measurements:`` by temporality/modality; see
``/root/reference/sample_data/dataset.yaml``) translated into
``DatasetSchema`` / ``InputDFSchema`` / ``MeasurementConfig`` objects, then
``Dataset`` → ``split`` → ``preprocess`` → ``save`` →
``cache_deep_learning_representation``. Hydra is replaced by the repo's
``utils.config_tool`` (``${...}`` interpolation + ``key=value`` overrides);
the hydra ``defaults:`` list is resolved against the shipped ``configs/``
directory.

Usage::

    python -m scripts.build_dataset --config sample_data/dataset.yaml \
        save_dir=./processed cohort_name=sample
"""

from __future__ import annotations

import dataclasses
import sys
from collections import defaultdict
from pathlib import Path
from typing import Any

import yaml

from eventstreamgpt_tpu.data import (
    Dataset,
    DatasetConfig,
    DatasetSchema,
    InputDataType,
    InputDFSchema,
    InputDFType,
    MeasurementConfig,
    TemporalityType,
)
from eventstreamgpt_tpu.data.dataset_pandas import Query
from eventstreamgpt_tpu.data.types import DataModality
from eventstreamgpt_tpu.utils.config_tool import (
    deep_merge,
    parse_overrides,
    resolve_interpolations,
    split_config_arg,
)

CONFIGS_DIR = Path(__file__).resolve().parent.parent / "configs"


def _singular(name: str) -> str:
    """Best-effort singularization for default event types (the reference uses
    ``inflect``, which is not installed here): strips a plural 's' with the
    usual '-ies'/'-ses' special cases."""
    if name.endswith("ies"):
        return name[:-3] + "y"
    if name.endswith("ses"):
        return name[:-2]
    if name.endswith("s") and not name.endswith("ss"):
        return name[:-1]
    return name


def add_to_container(key: str, val: Any, container: dict[str, Any]) -> None:
    """Adds key→val, erroring on conflicting re-specification (reference ``:66``)."""
    if key in container:
        if container[key] == val:
            print(f"WARNING: {key} is specified twice with value {val}.")
        else:
            raise ValueError(f"{key} is specified twice ({container[key]} v. {val})")
    else:
        container[key] = val


def load_yaml_with_defaults(yaml_fp: Path | str, configs_dir: Path = CONFIGS_DIR) -> dict:
    """Loads a YAML config, resolving its hydra-style ``defaults:`` list.

    Supported entries: a bare config name (merged from
    ``configs/<name>.yaml``, recursively), ``{group: name}`` (merged into key
    ``group`` from ``configs/<group>/<name>.yaml``), and ``_self_`` (the
    file's own values take precedence from that point).
    """
    with open(yaml_fp) as f:
        raw = yaml.safe_load(f) or {}

    defaults = raw.pop("defaults", [])
    raw.pop("hydra", None)
    merged: dict[str, Any] = {}

    for entry in defaults:
        if entry == "_self_":
            deep_merge(merged, raw)
            raw = {}
        elif isinstance(entry, str):
            deep_merge(merged, load_yaml_with_defaults(configs_dir / f"{entry}.yaml", configs_dir))
        elif isinstance(entry, dict):
            for group, name in entry.items():
                group_cfg = load_yaml_with_defaults(
                    configs_dir / group / f"{name}.yaml", configs_dir
                )
                merged[group] = group_cfg
        else:
            raise ValueError(f"Can't resolve defaults entry {entry!r}")
    deep_merge(merged, raw)
    return merged


def build_dataset(cfg: dict[str, Any]) -> Dataset:
    """Translates the YAML dict into configs and runs the ETL (reference ``:76-360``)."""
    cfg = dict(cfg)

    # 1. Build measurement_configs and track input schemas.
    subject_id_col = cfg.pop("subject_id_col")
    measurements_by_temporality = cfg.pop("measurements")

    static_sources: dict[str, dict] = defaultdict(dict)
    dynamic_sources: dict[str, dict] = defaultdict(dict)
    measurement_configs: dict[str, MeasurementConfig] = {}

    time_dep_measurements = measurements_by_temporality.pop(
        str(TemporalityType.FUNCTIONAL_TIME_DEPENDENT), {}
    )

    for temporality, measurements_by_modality in measurements_by_temporality.items():
        schema_source = (
            static_sources if temporality == str(TemporalityType.STATIC) else dynamic_sources
        )
        for modality, measurements_by_source in (measurements_by_modality or {}).items():
            if not measurements_by_source:
                continue
            for source_name, measurements in measurements_by_source.items():
                data_schema = schema_source[source_name]

                if isinstance(measurements, str):
                    measurements = [measurements]
                for m in measurements:
                    measurement_config_kwargs: dict[str, Any] = {
                        "name": m,
                        "temporality": temporality,
                        "modality": modality,
                    }
                    if isinstance(m, dict):
                        m_dict = dict(m)
                        if m_dict.get("values_column", None):
                            values_column = m_dict.pop("values_column")
                            m = [m_dict.pop("name"), values_column]
                        else:
                            m = m_dict.pop("name")
                        measurement_config_kwargs.update(m_dict)

                    if isinstance(m, str) and modality == str(DataModality.UNIVARIATE_REGRESSION):
                        add_to_container(m, InputDataType.FLOAT, data_schema)
                    elif (
                        isinstance(m, (list, tuple))
                        and len(m) == 2
                        and modality == str(DataModality.MULTIVARIATE_REGRESSION)
                    ):
                        m, v = m
                        add_to_container(m, InputDataType.CATEGORICAL, data_schema)
                        add_to_container(v, InputDataType.FLOAT, data_schema)
                        measurement_config_kwargs["values_column"] = v
                        measurement_config_kwargs["name"] = m
                    elif isinstance(m, str) and modality in (
                        str(DataModality.SINGLE_LABEL_CLASSIFICATION),
                        str(DataModality.MULTI_LABEL_CLASSIFICATION),
                    ):
                        add_to_container(m, InputDataType.CATEGORICAL, data_schema)
                    else:
                        raise ValueError(
                            f"{m}, {modality} invalid! Must be in {DataModality.values()}!"
                        )

                    if m in measurement_configs:
                        old = {
                            k: v
                            for k, v in measurement_configs[m].to_dict().items()
                            if v is not None
                        }
                        if old != measurement_config_kwargs:
                            raise ValueError(
                                f"{m} differs across input sources!\n{old}\nvs.\n"
                                f"{measurement_config_kwargs}"
                            )
                    else:
                        measurement_configs[m] = MeasurementConfig(**measurement_config_kwargs)

    if len(static_sources) > 1:
        raise NotImplementedError(
            f"Currently, only 1 static source can be specified -- you have {static_sources}"
        )

    static_key = list(static_sources.keys())[0]
    static_col_schema = static_sources[static_key]

    for m, config in (time_dep_measurements or {}).items():
        config = dict(config)
        if not isinstance(m, str):
            raise ValueError(f"{m} must be a string for time-dep measurement!")
        functor_class = config.pop("functor")
        functor_kwargs = config.pop("kwargs", {})

        measurement_config_kwargs = {
            "name": m,
            "temporality": TemporalityType.FUNCTIONAL_TIME_DEPENDENT,
            "functor": MeasurementConfig.FUNCTORS[functor_class](**functor_kwargs),
        }

        for in_col, in_fmt in (config.pop("necessary_static_measurements", None) or {}).items():
            if isinstance(in_fmt, (list, tuple)) and in_fmt[0] == "timestamp":
                schema_val = (InputDataType.TIMESTAMP, in_fmt[1])
            else:
                schema_val = in_fmt
            if in_col in static_col_schema and static_col_schema[in_col] != schema_val:
                raise ValueError(
                    f"Schema Collision! {in_col}, {schema_val} v. {static_col_schema[in_col]}"
                )
            static_col_schema[in_col] = schema_val

        measurement_configs[m] = MeasurementConfig(**measurement_config_kwargs)

    # 2. Build DatasetSchema.
    connection_uri = cfg.pop("connection_uri", None)
    cfg.pop("raw_data_dir", None)

    def build_schema(
        col_schema: dict[str, Any],
        source_schema: dict[str, Any],
        schema_name: str,
        **extra_kwargs,
    ) -> InputDFSchema:
        input_schema_kwargs: dict[str, Any] = {}

        if "query" in source_schema:
            if "input_df" in source_schema:
                raise ValueError(
                    f"Can't specify both query {source_schema['query']} "
                    f"and input_df {source_schema['input_df']} at once!"
                )
            q = source_schema["query"]
            if isinstance(q, (str, list)):
                if not connection_uri:
                    raise ValueError("If providing a query string, must provide a connection_uri!")
                input_schema_kwargs["input_df"] = Query(
                    query=tuple(q) if isinstance(q, list) else q, connection_uri=connection_uri
                )
            elif isinstance(q, dict):
                q = dict(q)
                q.setdefault("connection_uri", connection_uri)
                input_schema_kwargs["input_df"] = Query(**q)
            else:
                raise ValueError(f"Cannot parse query {q}!")
        elif "input_df" in source_schema:
            input_schema_kwargs["input_df"] = source_schema["input_df"]
        else:
            raise ValueError("Must specify either a query or an input dataframe!")

        for param in (
            "start_ts_col",
            "end_ts_col",
            "ts_col",
            "event_type",
            "start_ts_format",
            "end_ts_format",
            "ts_format",
        ):
            if param in source_schema:
                input_schema_kwargs[param] = source_schema[param]

        if source_schema.get("start_ts_col", None):
            input_schema_kwargs["type"] = InputDFType.RANGE
        elif source_schema.get("ts_col", None):
            input_schema_kwargs["type"] = InputDFType.EVENT
        else:
            input_schema_kwargs["type"] = InputDFType.STATIC

        if input_schema_kwargs["type"] != InputDFType.STATIC and "event_type" not in input_schema_kwargs:
            input_schema_kwargs["event_type"] = _singular(schema_name).upper()

        if (
            input_schema_kwargs["type"] == InputDFType.RANGE
            and isinstance(input_schema_kwargs.get("event_type"), list)
        ):
            input_schema_kwargs["event_type"] = tuple(input_schema_kwargs["event_type"])

        cols_covered = []
        any_schemas_present = False
        for n, cols_n in (
            ("start_data_schema", "start_columns"),
            ("end_data_schema", "end_columns"),
            ("data_schema", "columns"),
        ):
            if cols_n not in source_schema:
                continue
            cols = source_schema[cols_n]
            data_schema: dict[str, Any] = {}

            et = source_schema.get("event_type", None)
            et_list = et if isinstance(et, list) else ([et] if isinstance(et, str) else [])
            for et_entry in et_list:
                if isinstance(et_entry, str) and et_entry.startswith("COL:"):
                    event_type_col = et_entry[len("COL:"):]
                    data_schema[event_type_col] = (event_type_col, InputDataType.CATEGORICAL)

            if isinstance(cols, dict):
                cols = [list(t) for t in cols.items()]

            for col in cols:
                if (
                    isinstance(col, (list, tuple))
                    and len(col) == 2
                    and col[1] in col_schema
                ):
                    schema_key = col[0]
                    schema_val = (col[1], col_schema[col[1]])
                elif isinstance(col, str) and col in col_schema:
                    schema_key = col
                    schema_val = (col, col_schema[col])
                else:
                    raise ValueError(f"{col} unprocessable! Col schema: {col_schema}")

                cols_covered.append(schema_val[0])
                add_to_container(schema_key, schema_val, data_schema)
            input_schema_kwargs[n] = data_schema
            any_schemas_present = True

        if not any_schemas_present and (len(col_schema) > len(cols_covered)):
            input_schema_kwargs["data_schema"] = {}

        for col, dt in col_schema.items():
            if col in cols_covered:
                continue
            for schema in ("start_data_schema", "end_data_schema", "data_schema"):
                if schema in input_schema_kwargs:
                    input_schema_kwargs[schema][col] = dt

        must_have = source_schema.get("must_have", None)
        if must_have is None:
            pass
        elif isinstance(must_have, list):
            input_schema_kwargs["must_have"] = must_have
        elif isinstance(must_have, dict):
            mh = []
            for k, v in must_have.items():
                if v is True:
                    mh.append(k)
                elif isinstance(v, list):
                    mh.append((k, v))
                else:
                    raise ValueError(f"{v} invalid for `must_have`")
            input_schema_kwargs["must_have"] = mh

        return InputDFSchema(**input_schema_kwargs, **extra_kwargs)

    inputs = dict(cfg.pop("inputs"))
    dataset_schema = DatasetSchema(
        static=build_schema(
            col_schema=static_col_schema,
            source_schema=inputs.pop(static_key),
            subject_id_col=subject_id_col,
            schema_name=static_key,
        ),
        dynamic=[
            build_schema(
                col_schema=dynamic_sources.get(dynamic_key, {}),
                source_schema=source_schema,
                schema_name=dynamic_key,
            )
            for dynamic_key, source_schema in inputs.items()
        ],
    )

    # 3. Build DatasetConfig + run the pipeline.
    split = cfg.pop("split", (0.8, 0.1))
    seed = cfg.pop("seed", 1)
    do_overwrite = cfg.pop("do_overwrite", False)
    cfg.pop("cohort_name", None)
    DL_chunk_size = cfg.pop("DL_chunk_size", 20000)
    # Subject/measurement-sharded process parallelism for the transform and
    # DL-cache phases (byte-identical outputs at any worker count; the
    # reference gets the analogous parallelism from Polars' Rust threadpool).
    n_workers = int(cfg.pop("n_workers", 1) or 1)

    valid_config_kwargs = {f.name for f in dataclasses.fields(DatasetConfig)}
    extra_kwargs = {k: v for k, v in cfg.items() if k not in valid_config_kwargs}
    config_kwargs = {k: v for k, v in cfg.items() if k in valid_config_kwargs}

    if extra_kwargs:
        print(f"Omitting {extra_kwargs} from config!")

    config = DatasetConfig(measurement_configs=measurement_configs, **config_kwargs)

    if config.save_dir is not None:
        Path(config.save_dir).mkdir(parents=True, exist_ok=True)

    ESD = Dataset(config=config, input_schema=dataset_schema, n_workers=n_workers)
    ESD.split(split, seed=seed)
    ESD.preprocess(n_workers=n_workers)
    ESD.save(do_overwrite=do_overwrite)
    ESD.cache_deep_learning_representation(
        DL_chunk_size, do_overwrite=do_overwrite, n_workers=n_workers
    )
    print("\nETL phase timings:")
    print(ESD.timing_summary())
    return ESD


def main(argv: list[str] | None = None) -> Dataset:
    argv = list(sys.argv[1:] if argv is None else argv)
    yaml_fp, argv = split_config_arg(argv)
    if yaml_fp is None:
        yaml_fp = CONFIGS_DIR / "dataset_base.yaml"

    cfg = load_yaml_with_defaults(yaml_fp)
    deep_merge(cfg, parse_overrides(argv))
    cfg = resolve_interpolations(cfg)
    return build_dataset(cfg)


if __name__ == "__main__":
    main()
