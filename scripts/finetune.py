"""Fine-tunes a pretrained model on a stream classification task.

Rebuild of ``/root/reference/scripts/finetune.py``: thin entry over
``eventstreamgpt_tpu.training.fine_tuning.train``.

Usage::

    python -m scripts.finetune load_from_model_dir=./exp/pretrain \
        task_df_name=in_hosp_mort optimization_config.batch_size=32
"""

from __future__ import annotations

import sys

from eventstreamgpt_tpu.training.fine_tuning import FinetuneConfig
from eventstreamgpt_tpu.training.fine_tuning import train as finetune_train
from eventstreamgpt_tpu.utils.config_tool import load_config, split_config_arg


def main(argv: list[str] | None = None):
    argv = list(sys.argv[1:] if argv is None else argv)
    yaml_fp, argv = split_config_arg(argv)
    cfg = load_config(FinetuneConfig, yaml_file=yaml_fp, overrides=argv)
    return finetune_train(cfg)


if __name__ == "__main__":
    from eventstreamgpt_tpu.reliability import EXIT_PREEMPTED, Preempted

    try:
        main()
    except Preempted as e:
        # Same reschedule contract as scripts/pretrain.py (docs/reliability.md).
        print(f"Preempted cleanly at step {e.step}; exiting {EXIT_PREEMPTED} for reschedule.")
        sys.exit(EXIT_PREEMPTED)
