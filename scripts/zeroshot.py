"""Zero-shot evaluation via generation with a user task labeler.

Rebuild of ``/root/reference/scripts/zeroshot.py``: thin entry over
``eventstreamgpt_tpu.training.zero_shot_evaluator.zero_shot_evaluation``.

Usage::

    python -m scripts.zeroshot load_from_model_dir=./exp/pretrain \
        task_df_name=in_hosp_mort task_specific_params.num_samples=8
"""

from __future__ import annotations

import sys

from eventstreamgpt_tpu.training.fine_tuning import FinetuneConfig
from eventstreamgpt_tpu.training.zero_shot_evaluator import zero_shot_evaluation
from eventstreamgpt_tpu.utils.config_tool import load_config, split_config_arg


def main(argv: list[str] | None = None):
    argv = list(sys.argv[1:] if argv is None else argv)
    yaml_fp, argv = split_config_arg(argv)
    cfg = load_config(FinetuneConfig, yaml_file=yaml_fp, overrides=argv)
    return zero_shot_evaluation(cfg)


if __name__ == "__main__":
    main()
