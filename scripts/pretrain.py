"""Pretrains a generative event-stream transformer.

Rebuild of ``/root/reference/scripts/pretrain.py``: a thin entry point over
``eventstreamgpt_tpu.training.pretrain.train`` with hydra-style
``key.sub=value`` overrides (``utils.config_tool``). An optional
``--config <yaml>`` supplies base values.

Usage::

    python -m scripts.pretrain data_config.save_dir=./processed/sample \
        optimization_config.batch_size=32 save_dir=./exp/pretrain
"""

from __future__ import annotations

import sys
from pathlib import Path

import yaml

from eventstreamgpt_tpu.training import PretrainConfig
from eventstreamgpt_tpu.training import train as pretrain_train
from eventstreamgpt_tpu.utils.config_tool import load_config, split_config_arg


def main(argv: list[str] | None = None):
    argv = list(sys.argv[1:] if argv is None else argv)
    yaml_fp, argv = split_config_arg(argv)

    cfg = load_config(PretrainConfig, yaml_file=yaml_fp, overrides=argv)

    # Dump the resolved config next to the run (reference pretrain.py:34-41).
    save_dir = Path(cfg.save_dir)
    save_dir.mkdir(parents=True, exist_ok=True)
    import json

    from eventstreamgpt_tpu.utils.config_tool import unstructure

    with open(save_dir / "pretrain_config.yaml", "w") as f:
        # json round-trip coerces non-YAML-native leaves (Paths, enums) to str.
        yaml.safe_dump(json.loads(json.dumps(unstructure(cfg), default=str)), f)

    return pretrain_train(cfg)


if __name__ == "__main__":
    from eventstreamgpt_tpu.reliability import EXIT_PREEMPTED, Preempted

    try:
        main()
    except Preempted as e:
        # The orchestrator contract (docs/reliability.md): a graceful
        # SIGTERM/SIGINT drain wrote a final mid-epoch checkpoint; exit with
        # the distinct "reschedule me" status instead of a failure code.
        print(f"Preempted cleanly at step {e.step}; exiting {EXIT_PREEMPTED} for reschedule.")
        sys.exit(EXIT_PREEMPTED)
