"""graftcheck: JAX-aware static analysis for the TPU-native ESGPT stack.

Three tiers, one CLI (``scripts/graftcheck.py``):

* Tier A — ``lint``: custom AST rules (GC001-GC005) over the package for the
  TPU footguns runtime tests only catch after they've burned a pod-hour:
  host syncs reachable from traced scopes or jitted-dispatch loops, f64
  dtype creep, PRNG key reuse, Python control flow on traced values, and
  undonated train-step jits.
* Tier B — ``program_checks``: AOT-lower the canonical pretrain / fine-tune /
  generation step programs and assert static facts of the lowered module:
  no f64 element types, no host transfers, collective payload bytes within
  tolerance of the committed ``COLLECTIVES.json`` budget.
* ``compile_guard``: a recompilation sentinel (context manager over the jit
  trace caches / ``jax.monitoring`` compile events) used by tests and by
  ``training/pretrain.py`` to fail fast if the step recompiles mid-epoch.

``lint`` is pure stdlib (no jax import) so Tier A runs anywhere in
milliseconds; the jax-importing tiers are deferred to submodule imports.
"""

from .lint import (  # noqa: F401
    Finding,
    RULES,
    apply_baseline,
    default_targets,
    lint_paths,
    lint_source,
    load_baseline,
    save_baseline,
)

__all__ = [
    "Finding",
    "RULES",
    "apply_baseline",
    "default_targets",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "save_baseline",
]
