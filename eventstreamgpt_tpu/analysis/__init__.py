"""graftcheck: JAX-aware static analysis for the TPU-native ESGPT stack.

Three tiers, one CLI (``scripts/graftcheck.py``):

* Tier A — ``lint``: custom AST rules (GC001-GC005) over the package for the
  TPU footguns runtime tests only catch after they've burned a pod-hour:
  host syncs reachable from traced scopes or jitted-dispatch loops, f64
  dtype creep, PRNG key reuse, Python control flow on traced values, and
  undonated state-updating jits (train/fine-tune steps and the serving
  decode/prefill/dispatch programs).
* Tier B — ``program_checks``: AOT-lower the canonical pretrain / fine-tune /
  generation step programs and assert static facts of the lowered module:
  no f64 element types, no host transfers, collective payload bytes within
  per-kind tolerance of the committed ``COLLECTIVES.json`` budget.
* Tier C — ``program_census`` + ``memory_checks``: the whole-fleet census.
  Every ``aot_programs`` provider registers its compiled-program factories;
  each program is AOT-compiled at toy AND scaled (width >= 2048) shapes and
  audited from its buffer assignment: peak HBM vs ``MEMORY.json`` (the
  width-4096 replicated rung must FAIL the 16 GB chip budget, fsdp8 must
  fit), donation-aliasing completeness, implicit resharding, and
  kind-resolved collective inventories (the scaled fsdp8 backward must
  show reduce-scatter).
* ``compile_guard``: a recompilation sentinel (context manager over the jit
  trace caches / ``jax.monitoring`` compile events) used by tests and by
  ``training/pretrain.py`` to fail fast if the step recompiles mid-epoch.

``lint`` and the ``program_census`` registry are pure stdlib (no jax
import) so Tier A and provider registration run anywhere in milliseconds;
the jax-importing tiers are deferred to submodule imports.
"""

from .lint import (  # noqa: F401
    Finding,
    RULES,
    apply_baseline,
    default_targets,
    lint_paths,
    lint_source,
    load_baseline,
    save_baseline,
)

__all__ = [
    "Finding",
    "RULES",
    "apply_baseline",
    "default_targets",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "save_baseline",
]
