"""graftcheck: JAX-aware static analysis for the TPU-native ESGPT stack.

Four tiers, one CLI (``scripts/graftcheck.py``):

* Tier A — ``lint``: custom AST rules (GC001-GC008) over the package for the
  TPU footguns runtime tests only catch after they've burned a pod-hour:
  host syncs reachable from traced scopes or jitted-dispatch loops, f64
  dtype creep, PRNG key reuse, Python control flow on traced values, and
  undonated state-updating jits (train/fine-tune steps and the serving
  decode/prefill/dispatch programs). GC006-GC008 are the serving-scoped
  determinism lint: unordered-set iteration in decision paths,
  nondeterministic sources (salted ``hash()``, wall clocks, ``random``,
  uuid), and block-ledger mutation outside the sanctioned owners.
* Tier B — ``program_checks``: AOT-lower the canonical pretrain / fine-tune /
  generation step programs and assert static facts of the lowered module:
  no f64 element types, no host transfers, collective payload bytes within
  per-kind tolerance of the committed ``COLLECTIVES.json`` budget.
* Tier C — ``program_census`` + ``memory_checks``: the whole-fleet census.
  Every ``aot_programs`` provider registers its compiled-program factories;
  each program is AOT-compiled at toy AND scaled (width >= 2048) shapes and
  audited from its buffer assignment: peak HBM vs ``MEMORY.json`` (the
  width-4096 replicated rung must FAIL the 16 GB chip budget, fsdp8 must
  fit), donation-aliasing completeness, implicit resharding, and
  kind-resolved collective inventories (the scaled fsdp8 backward must
  show reduce-scatter).
* Tier D — ``model_check``: the serving control-plane model checker. A
  bounded exhaustive-interleaving explorer with sleep-set partial-order
  reduction drives the REAL Scheduler / GenerationEngine / ServingService /
  ServingFleet (tiny widths, virtual CPU mesh) through every post-POR
  schedule of enabled control-plane actions, checking the
  ``serving/sanitizer.py`` oracles (block-pool refcount conservation,
  zero-drop ledger, slot-epoch stale-boundary guard, strict-FIFO boundary
  resolution, one-time admission binding, session affinity) at every state
  and outcome determinism vs a canonical reference drain at every leaf;
  violations shrink to a minimal failing schedule, and per-scenario
  schedule counts pin byte-reproducibly in ``MODELCHECK.json``.
* ``compile_guard``: a recompilation sentinel (context manager over the jit
  trace caches / ``jax.monitoring`` compile events) used by tests and by
  ``training/pretrain.py`` to fail fast if the step recompiles mid-epoch.

``lint`` and the ``program_census`` registry are pure stdlib (no jax
import) so Tier A and provider registration run anywhere in milliseconds;
the jax-importing tiers are deferred to submodule imports.
"""

from .lint import (  # noqa: F401
    Finding,
    RULES,
    apply_baseline,
    default_targets,
    lint_paths,
    lint_source,
    load_baseline,
    save_baseline,
)

__all__ = [
    "Finding",
    "RULES",
    "apply_baseline",
    "default_targets",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "save_baseline",
]
