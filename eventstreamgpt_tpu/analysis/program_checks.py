"""Tier B of graftcheck: jaxpr/HLO invariant gates over canonical programs.

PR 1 proved "no table-sized collectives" and "device-resident hot loop" via
runtime tests; these properties are static facts of the lowered program, so
this module gates them on every PR with no hardware and no epoch runs. It
AOT-lowers the canonical step programs — the pretrain train step on the
``dp8`` and ``dp4_tp2`` virtual-mesh layouts (compiled under the r06
production-width remat policy, ``save_attention``), the NestedAttention
flagship step (fused dep-graph attention + narrow head projections), the
fine-tuning train step, and the single-dispatch generation program — and
statically asserts:

* **no f64** element types anywhere in the module (TPUs emulate f64; one
  stray weak-typed ``np.float64`` constant doubles a table),
* **no host transfers** in the step (outfeed/infeed/send/recv and
  host-callback custom-calls — a ``jax.debug.print`` or ``pure_callback``
  smuggled into the hot loop),
* **collective payload bytes within tolerance** of the committed
  ``COLLECTIVES.json`` budget (``parallel.collectives_audit
  .compare_inventory``) — an accidental full-table all-gather is a byte
  blowup here long before it is a pod-hour.

The f64 / host-transfer checks run on the *unoptimized* lowering (fast — no
XLA compile); the collective budget needs the optimized HLO, so those
layouts compile (CPU, tiny shapes, ~a minute each). Requires the 8-device
virtual CPU mesh (``__graft_entry__._provision_cpu_devices(8)`` before jax
backend init — the graftcheck CLI and tests/conftest.py both do this).
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

__all__ = [
    "REPO_ROOT",
    "canonical_pretrain_step",
    "canonical_finetune_step",
    "canonical_generation_program",
    "canonical_engine_programs",
    "canonical_kvq_engine_programs",
    "canonical_nohealth_engine_programs",
    "canonical_paged_engine_programs",
    "canonical_sampling_engine_program",
    "canonical_spec_engine_programs",
    "canonical_spec_engine_na_programs",
    "canonical_service_programs",
    "canonical_tp_engine_programs",
    "canonical_swap_engine_programs",
    "check_no_f64",
    "check_no_host_transfers",
    "check_collective_budget",
    "run_program_checks",
]

REPO_ROOT = Path(__file__).resolve().parents[2]

# f64 element types in HLO ("f64[...]") or StableHLO ("tensor<2x3xf64>",
# "tensor<f64>") syntax. Substring-only matching would false-positive on
# hex-ish identifiers, so anchor to the type syntax.
_F64_RE = re.compile(r"f64\[|x\s*f64>|<f64>|tensor<f64")

_HOST_OP_RE = re.compile(
    r"=\s*(?:\([^)]*\)|\S+)\s+(outfeed|infeed|send|send-done|recv|recv-done)\("
)
_CUSTOM_CALL_TARGET_RE = re.compile(r'custom_call_target\s*=\s*"([^"]+)"')
_STABLEHLO_CUSTOM_RE = re.compile(r"stablehlo\.custom_call\s+@(\S+?)[(\s]")
_HOST_CALLBACK_RE = re.compile(r"callback|host|outfeed|infeed|debug_print", re.IGNORECASE)


def _graft_entry():
    """Imports ``__graft_entry__`` (model/batch builders live beside it)."""
    if str(REPO_ROOT) not in sys.path:
        sys.path.insert(0, str(REPO_ROOT))
    import __graft_entry__

    return __graft_entry__


def _require_devices(n: int) -> None:
    import jax

    have = len(jax.devices())
    if have < n:
        raise RuntimeError(
            f"program checks need an {n}-device mesh but jax reports {have}; "
            "provision the virtual CPU platform before importing jax "
            "(__graft_entry__._provision_cpu_devices) — the graftcheck CLI and "
            "tests/conftest.py both do."
        )


# ----------------------------------------------------------- canonical steps
def canonical_pretrain_step(
    n_data: int,
    n_model: int,
    with_health: bool = False,
    na: bool = False,
    na_impl: str | None = None,
    scan: bool = False,
    n_fsdp: int = 1,
):
    """The production pretrain train step on a ``data×model`` mesh — the
    exact construction ``dryrun_multichip`` audits into ``COLLECTIVES.json``
    (same tiny shapes, so inventories are directly comparable).

    ``with_health`` builds the divergence-sentinel-instrumented variant,
    which is what ``train()`` jits by default since the reliability
    subsystem landed (sentinel_enabled defaults to true). ``na`` builds the
    NestedAttention flagship (fused dep-graph attention + narrow head
    projections — the r06 NA production defaults); ``na_impl`` pins the
    dep-graph attention implementation (``"pallas_interpret"`` builds the
    r09 Pallas-kernel program in interpreter mode, which lowers and
    compiles on the virtual CPU mesh — the TPU production program differs
    only in the kernel's Mosaic body). CI programs compile under
    ``gradient_checkpointing="save_attention"`` (the r06 production-width
    remat policy), matching the dry run.

    ``scan`` builds the r10 scan-over-layers variant (``scan_layers=True``:
    one pattern-period block body scanned over stacked params); ``n_fsdp``
    > 1 puts an ``fsdp`` axis on the mesh — parameters and Adam moments
    shard their largest dimension over it, the batch shards over
    ``(data, fsdp)`` jointly, and GSPMD's gather-on-use /
    reduce-scatter-on-grad schedule lands in the collective inventory
    (the ``fsdp8`` budget — the one layout whose bytes are all-gather +
    reduce-scatter dominated by design)."""
    import jax
    import jax.numpy as jnp

    from ..models.config import OptimizationConfig
    from ..training import TrainState, build_optimizer, make_train_step, shard_batch
    from ..training.sharding import make_mesh, make_state_shardings

    ge = _graft_entry()
    _require_devices(n_data * n_model * n_fsdp)
    mesh = make_mesh(n_data, n_model, n_fsdp=n_fsdp)
    overrides = {"scan_layers": True} if scan else {}
    if na:
        if na_impl:
            overrides["dep_graph_attention_impl"] = na_impl
        model, batch = ge._make_model_and_batch(
            batch_size=2 * n_data * n_fsdp, na=True, **overrides
        )
    else:
        model, batch = ge._make_model_and_batch(
            batch_size=2 * n_data * n_fsdp,
            gradient_checkpointing="save_attention",
            **overrides,
        )
    params = model.init(jax.random.PRNGKey(0), batch)
    oc = OptimizationConfig(
        init_lr=1e-3,
        batch_size=2 * n_data * n_fsdp,
        max_training_steps=10,
        lr_num_warmup_steps=1,
        lr_frac_warmup_steps=None,
    )
    tx, _ = build_optimizer(oc)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params))
    shardings = make_state_shardings(state, mesh)
    state = jax.device_put(state, shardings)
    batch = shard_batch(batch, mesh)
    # Parameter-sharding layouts (tp/fsdp) pin the output state to the input
    # layout: without the pin GSPMD propagation reshards small replicated
    # leaves over `model`, silently dropping their donation (the Tier C
    # donation audit's dp4_tp2 finding) and forcing a reshard-per-dispatch.
    pin = shardings if (n_model > 1 or n_fsdp > 1) else None
    step = make_train_step(model, tx, with_health=with_health, out_state_shardings=pin)
    return step, (state, batch, jax.random.PRNGKey(0))


def canonical_finetune_step(n_data: int = 8, with_health: bool = False):
    """The fine-tuning (stream classification) train step, data-parallel.
    ``with_health``: the sentinel-instrumented production default (see
    `canonical_pretrain_step`)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.config import OptimizationConfig, StructuredTransformerConfig
    from ..models.fine_tuning_model import ESTForStreamClassification
    from ..training import TrainState, build_optimizer, make_train_step, shard_batch
    from ..training.sharding import make_mesh, shard_state

    ge = _graft_entry()
    _require_devices(n_data)
    mesh = make_mesh(n_data, 1)
    base_model, batch = ge._make_model_and_batch(batch_size=2 * n_data)
    config = StructuredTransformerConfig.from_dict(
        {
            **base_model.config.to_dict(),
            "finetuning_task": "label",
            "id2label": {0: False, 1: True},
            "num_labels": 2,
            "problem_type": "single_label_classification",
            "task_specific_params": {"pooling_method": "last"},
        }
    )
    model = ESTForStreamClassification(config)
    labels = np.arange(2 * n_data, dtype=np.int64) % 2
    batch = batch.replace(stream_labels={"label": jnp.asarray(labels)})
    params = model.init(jax.random.PRNGKey(0), batch)
    oc = OptimizationConfig(
        init_lr=1e-3,
        batch_size=2 * n_data,
        max_training_steps=10,
        lr_num_warmup_steps=1,
        lr_frac_warmup_steps=None,
    )
    tx, _ = build_optimizer(oc)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params))
    state = shard_state(state, mesh)
    batch = shard_batch(batch, mesh)
    step = make_train_step(model, tx, with_health=with_health)
    return step, (state, batch, jax.random.PRNGKey(0))


def canonical_generation_program(max_new_events: int = 4):
    """The single-dispatch cached generation program (``generate_program``)."""
    import jax

    from ..generation.generation_utils import _build_ci_steps

    ge = _graft_entry()
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)
    steps = _build_ci_steps(
        model, model.config, B=2, input_len=8, max_new_events=max_new_events
    )
    return steps["generate_program"], (params, batch, jax.random.PRNGKey(0))


def canonical_engine_programs(n_data: int = 8) -> dict:
    """The serving engine's prefill + decode-slot programs, slots sharded
    data-parallel over the virtual mesh (``serving/engine.py``).

    The decode-slot program is the serving hot loop: it must stay free of
    host transfers (per-row stopping is judged ON DEVICE — a smuggled
    callback would resurrect the per-event host sync the engine exists to
    remove) and within the committed ``engine_dp8`` collective budget
    (slot-sharded decode with replicated params is collective-free by
    construction; the budget gate keeps it that way). Returns the engine's
    ``aot_programs()`` dict: label -> (jitted fn, example args).
    """
    import jax

    from ..serving import GenerationEngine
    from ..training.sharding import make_mesh

    ge = _graft_entry()
    _require_devices(n_data)
    mesh = make_mesh(n_data, 1)
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)
    engine = GenerationEngine(
        model,
        params,
        model.config,
        template=batch,
        n_slots=2 * n_data,
        max_len=12,
        decode_chunk=2,
        min_bucket=8,
        mesh=mesh,
    )
    return engine.aot_programs(bucket_len=8, group=2)


def canonical_kvq_engine_programs(n_data: int = 8) -> dict:
    """The r09 quantized-decode engine programs on the dp8 mesh: int8 KV
    caches — quantize-on-write at the per-row cursor, dequantize-on-read in
    the attention contraction, quantize-on-admission in prefill's admit
    scatter — through the same f64-free / host-transfer-free /
    collective-budget gates as the float engine. The ``engine_kvq_dp8``
    budget pins the contract that quantization adds (near-)zero
    communication: scales live beside the planes and every new op is
    slot-local. Sampling rides the fused tail on its mesh-auto impl (XLA
    on multi-device meshes — the kernel grid would otherwise all-gather
    the slot-sharded logits plane; see `GenerationEngine`); the Pallas
    sampling kernel itself is gated by
    `canonical_sampling_engine_program`."""
    import jax

    from ..serving import GenerationEngine
    from ..training.sharding import make_mesh

    ge = _graft_entry()
    _require_devices(n_data)
    mesh = make_mesh(n_data, 1)
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)
    engine = GenerationEngine(
        model,
        params,
        model.config,
        template=batch,
        n_slots=2 * n_data,
        max_len=12,
        decode_chunk=2,
        min_bucket=8,
        mesh=mesh,
        kv_cache_dtype="int8",
    )
    return engine.aot_programs(bucket_len=8, group=2)


def canonical_paged_engine_programs(n_data: int = 8) -> dict:
    """The r16 paged copy-on-write engine programs on the dp8 mesh: the
    block-pool decode (attention reads through per-slot block tables, one
    gather per layer), the paged prefill (block-scatter admit), and the
    fork prefill (ONE batch-1 forward admitting a whole CoW branch group).

    The collective contract: the pool is replicated over the mesh (its
    leading dim is num_blocks, not n_slots), so decode's pool updates
    all-gather from the slot-sharded chunk — an all-gather is already in
    the engine_dp8 kind set, so the block gather adds ZERO new collective
    kinds on dp8 (the ``engine_paged_dp8`` budget pins the inventory).
    ``block_size=4`` divides the canonical ``max_len=12`` (3 blocks/slot).
    """
    import jax

    from ..serving import GenerationEngine
    from ..training.sharding import make_mesh

    ge = _graft_entry()
    _require_devices(n_data)
    mesh = make_mesh(n_data, 1)
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)
    engine = GenerationEngine(
        model,
        params,
        model.config,
        template=batch,
        n_slots=2 * n_data,
        max_len=12,
        decode_chunk=2,
        min_bucket=8,
        mesh=mesh,
        paged_kv=True,
        block_size=4,
    )
    return engine.aot_programs(bucket_len=8, group=2)


def canonical_nohealth_engine_programs(n_data: int = 8) -> dict:
    """The engine with the decode health sentinel OFF — the uninstrumented
    counterpart of `canonical_engine_programs` (whose engine carries the
    production default ``health_sentinel=True``). Both register against
    the SAME committed ``engine_dp8`` / ``engine_prefill_dp8`` collective
    budgets: the sentinel must add **zero collectives and zero host
    transfers** (its detection is row-local elementwise work and its
    health row rides the existing packed boundary readback) — the serving
    mirror of PR 3's ``pretrain:dp8`` vs ``pretrain:dp8_health`` contract.
    A sentinel implementation that gathered across slots or smuggled a
    callback would break the byte-identical-budget gate here."""
    import jax

    from ..serving import GenerationEngine
    from ..training.sharding import make_mesh

    ge = _graft_entry()
    _require_devices(n_data)
    mesh = make_mesh(n_data, 1)
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)
    engine = GenerationEngine(
        model,
        params,
        model.config,
        template=batch,
        n_slots=2 * n_data,
        max_len=12,
        decode_chunk=2,
        min_bucket=8,
        mesh=mesh,
        health_sentinel=False,
    )
    return engine.aot_programs(bucket_len=8, group=2)


def canonical_sampling_engine_program() -> dict:
    """The fused-sampling engine programs, unsharded (one device, the
    single-replica serving topology the kernel targets): int8 cache +
    the Pallas sampling kernel in interpreter mode. The decode program is
    gated f64-free and host-transfer-free — the kernel's
    masked-fill/gumbel/argmax epilogue must not smuggle callbacks into the
    decode hot loop — and against a zero-collective budget (single device
    ⇒ any collective is a bug). Returns the engine's full ``aot_programs``
    dict (prefill + boundary pack included) so the Tier C census covers
    every program this topology can compile, not just the budget-gated
    decode."""
    import jax

    from ..serving import GenerationEngine

    ge = _graft_entry()
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)
    engine = GenerationEngine(
        model,
        params,
        model.config,
        template=batch,
        n_slots=4,
        max_len=12,
        decode_chunk=2,
        min_bucket=8,
        kv_cache_dtype="int8",
        sampling_impl="pallas_interpret",
    )
    return engine.aot_programs(bucket_len=8, group=2)


def canonical_sharded_sampling_engine_programs(n_data: int = 8) -> dict:
    """The r20 sharded fused-sampling engine: the Pallas sampling kernel on
    a MULTI-DEVICE data mesh, run under `shard_map` over the slot axis —
    each device sweeps its own ``(n_slots/dp, V)`` logits shard, so the
    grid never crosses the mesh axis. This retires the r09 mesh rule
    (auto → fused-XLA tail on any mesh): the committed
    ``engine_sampling_shard_dp8`` budget pins that the decode program
    carries NO slot-plane logits gather — its collective inventory must
    stay within the baseline ``engine_dp8`` kind set."""
    import jax

    from ..serving import GenerationEngine
    from ..training.sharding import make_mesh

    ge = _graft_entry()
    _require_devices(n_data)
    mesh = make_mesh(n_data, 1)
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)
    engine = GenerationEngine(
        model,
        params,
        model.config,
        template=batch,
        n_slots=2 * n_data,
        max_len=12,
        decode_chunk=2,
        min_bucket=8,
        mesh=mesh,
        kv_cache_dtype="int8",
        sampling_impl="pallas_interpret",
    )
    assert engine._shard_sampling, "dp8 + kernel tail must take the shard_map path"
    return engine.aot_programs(bucket_len=8, group=2)


def canonical_composed_engine_programs(n_data: int = 4, n_model: int = 2) -> dict:
    """THE composed production configuration (r20 tentpole): speculative
    decoding × int8 KV cache × serve-time tensor parallelism behind one
    engine, with the dedicated-prefill split halves included. Every
    capacity multiplier at once: spec's ~K× events per target forward,
    int8's ~2× slots per chip, TP's width-past-one-chip — the
    configuration the composition matrix exists to license. The committed
    ``engine_composed_*_dp4_tp2`` budgets pin the contract that
    composition pays exactly the per-layer TP reduce pattern the plain TP
    engine already pays (zero NEW collective kinds vs ``engine_dp8``
    beyond the documented TP reduces), and the donation audit keeps the
    spec state's donation from being dropped by a layout reshard (the
    out_shardings pin, Tier C fix)."""
    import jax

    from ..serving import GenerationEngine, SpecConfig, truncated_draft
    from ..training.sharding import make_mesh

    ge = _graft_entry()
    _require_devices(n_data * n_model)
    mesh = make_mesh(n_data, n_model)
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)
    dcfg, dparams = truncated_draft(model.config, params, 1)
    draft_model = type(model)(dcfg)
    engine = GenerationEngine(
        model,
        params,
        model.config,
        template=batch,
        n_slots=2 * n_data,
        max_len=12,
        decode_chunk=2,
        min_bucket=8,
        mesh=mesh,
        kv_cache_dtype="int8",
        spec=SpecConfig(model=draft_model, params=dparams, config=dcfg, k=2),
    )
    assert engine.tensor_parallel and engine._kv_quantized
    return engine.aot_programs(bucket_len=8, group=2, include_prefill_stream=True)


def canonical_megakernel_engine_program() -> dict:
    """The r20 fused decode megakernel engine, unsharded (one device — the
    single-replica topology the persistent kernel targets):
    ``decode_step_impl="pallas_interpret"`` routes the CI decode inner step
    through ``ops/pallas_decode_step.py`` — the whole layer stack (LN →
    qkv → cursor write → attention → MLP → event-mask zeroing) as ONE
    Pallas grid, in interpreter mode on CPU (same program structure as the
    TPU Mosaic compile modulo the kernel body). The decode program is
    gated f64-free and host-transfer-free — the kernel must not smuggle
    callbacks into the serving hot loop — and against a zero-collective
    budget (``engine_megakernel_1dev``: single device ⇒ any collective is
    a bug). Returns the full ``aot_programs`` dict so the Tier C census
    covers every program this topology compiles."""
    import jax

    from ..serving import GenerationEngine

    ge = _graft_entry()
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)
    engine = GenerationEngine(
        model,
        params,
        model.config,
        template=batch,
        n_slots=4,
        max_len=12,
        decode_chunk=2,
        min_bucket=8,
        decode_step_impl="pallas_interpret",
    )
    assert engine._decode_step_resolved == "pallas_interpret"
    return engine.aot_programs(bucket_len=8, group=2)


def canonical_tp_engine_programs(n_data: int = 4, n_model: int = 2) -> dict:
    """The serve-time tensor-parallel engine programs on a
    ``data×model`` mesh (``serving/engine.py`` with a ``model`` axis): the
    params shard with the training TP rules (`training/sharding.TP_RULES`)
    and the decode/prefill programs carry the per-layer all-reduces GSPMD
    inserts — the serving fleet's widths-past-one-chip leg. The committed
    ``engine_tp_dp4_tp2`` / ``engine_tp_prefill_dp4_tp2`` budgets pin the
    contract that TP serving pays exactly the per-layer reduce pattern and
    nothing more: an accidental re-replication (or a slot-axis gather
    smuggled in by the sampling tail) is a byte blowup here long before it
    is a latency cliff on a pod."""
    import jax

    from ..serving import GenerationEngine
    from ..training.sharding import make_mesh

    ge = _graft_entry()
    _require_devices(n_data * n_model)
    mesh = make_mesh(n_data, n_model)
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)
    engine = GenerationEngine(
        model,
        params,
        model.config,
        template=batch,
        n_slots=2 * n_data,
        max_len=12,
        decode_chunk=2,
        min_bucket=8,
        mesh=mesh,
    )
    assert engine.tensor_parallel
    # include_prefill_stream: the dedicated-prefill split halves are hot-path
    # programs on a prefill-tier fleet (the compute forward runs per
    # admission group, the donating admit scatter per handoff) — they get
    # the same gates as the fused prefill instead of escaping the census.
    return engine.aot_programs(bucket_len=8, group=2, include_prefill_stream=True)


def canonical_swap_engine_programs() -> dict:
    """The hot-swap engine's programs, unsharded (the zero-downtime weight
    swap leg of the serving fleet): the ordinary decode/prefill/boundary
    set plus ``swap_reshard`` — the shadow-load program that pins a
    host-loaded checkpoint to the live weights' layout so the flip is a
    pure pointer swap. The reshard is gated f64-free, host-transfer-free,
    and against a zero-collective budget (``engine_swap_reshard_1dev``):
    a collective or callback here would stall live decode for the whole
    swap window."""
    import jax

    from ..serving import GenerationEngine

    ge = _graft_entry()
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)
    engine = GenerationEngine(
        model,
        params,
        model.config,
        template=batch,
        n_slots=4,
        max_len=12,
        decode_chunk=2,
        min_bucket=8,
        hot_swap=True,
    )
    # The split prefill halves ride the swap engine's set too (unsharded:
    # zero-collective by construction, f64/host-transfer gated like the
    # rest — a callback smuggled into prefill_compute or admit would stall
    # the handoff exactly like one in decode).
    return engine.aot_programs(bucket_len=8, group=2, include_prefill_stream=True)


def canonical_spec_engine_programs(n_data: int = 8) -> dict:
    """The r13 speculative-decoding engine programs, slots sharded
    data-parallel over the virtual mesh: the draft-chunk program (K
    one-event draft forwards + proposal recording), the verify program (ONE
    K+1-event target forward on the vector-length cache branch + the
    accept/commit math), the fused target+draft prefill, and the widened
    boundary pack. The verify program is the serving hot loop's new center
    of mass: it must stay f64-free, host-transfer-free, and show **zero new
    collective kinds vs the baseline decode** (``engine_dp8``) — the
    fused-sampling mesh rule (auto → XLA tail on multi-device meshes, no
    all-gather of the slot-sharded logits plane) must keep holding inside
    the K-event verify forward, which the ``engine_spec_verify_dp8`` budget
    pins."""
    import jax

    from ..serving import GenerationEngine, SpecConfig, truncated_draft
    from ..training.sharding import make_mesh

    ge = _graft_entry()
    _require_devices(n_data)
    mesh = make_mesh(n_data, 1)
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)
    dcfg, dparams = truncated_draft(model.config, params, 1)
    draft_model = type(model)(dcfg)
    engine = GenerationEngine(
        model,
        params,
        model.config,
        template=batch,
        n_slots=2 * n_data,
        max_len=12,
        decode_chunk=2,
        min_bucket=8,
        mesh=mesh,
        spec=SpecConfig(model=draft_model, params=dparams, config=dcfg, k=2),
    )
    return engine.aot_programs(bucket_len=8, group=2)


def canonical_spec_engine_na_programs() -> dict:
    """The NA speculative-decoding variant, unsharded: the draft chunk runs
    the full per-event dep-graph level walk on the truncated draft, the
    verify scores the whole proposed measurement chain teacher-forced in one
    fused pass (partial-content level embeddings + the per-layer history
    head) and finishes the correction event's walk. Gated f64-free and
    host-transfer-free with zero-collective budgets (single device)."""
    import jax

    from ..data.config import MeasurementConfig
    from ..serving import GenerationEngine, SpecConfig, truncated_draft

    ge = _graft_entry()
    # The canonical NA model is a training artifact; generation-side fill
    # paths additionally need per-measurement configs for the dep-graph
    # levels' measurements.
    model, batch = ge._make_model_and_batch(
        batch_size=2,
        seq_len=8,
        na=True,
        measurement_configs={
            "lab": MeasurementConfig(
                name="lab",
                temporality="dynamic",
                modality="multivariate_regression",
                values_column="v",
            )
        },
    )
    params = model.init(jax.random.PRNGKey(0), batch)
    dcfg, dparams = truncated_draft(model.config, params, 1)
    draft_model = type(model)(dcfg)
    engine = GenerationEngine(
        model,
        params,
        model.config,
        template=batch,
        n_slots=4,
        max_len=12,
        decode_chunk=2,
        min_bucket=8,
        spec=SpecConfig(model=draft_model, params=dparams, config=dcfg, k=2),
    )
    programs = engine.aot_programs(bucket_len=8, group=2)
    # The NA prefill/boundary are structurally the CI spec set's; the NA
    # census rows gate the two programs with new machinery (the fused
    # teacher-forced verify and the level-walking draft chunk).
    return {k: v for k, v in programs.items() if k in ("draft_chunk", "verify")}


def canonical_service_programs(n_data: int = 8) -> dict:
    """The online serving service's dispatch programs on the dp8 mesh
    (``serving/service.py``): a 2-replica service whose replicas shard
    their slots data-parallel over the virtual mesh.

    The service dispatches exactly the engine's compiled programs — the
    slot-decode chunk, bucketed prefill, and the boundary pack (the packed
    done-mask/accounting array whose host copy is the ONLY device->host
    traffic of the serving loop, started async at dispatch), plus replica
    1's differently-chunked decode program (``decode_r1`` — both replicas'
    hot loops get the f64/host-transfer gates; replica 0's additionally
    gates against the committed ``service_dp8`` collective budget). Pins
    the service hot path f64-free and host-transfer-free beyond that one
    designed fetch. Returns label -> (jitted fn, args).
    """
    import jax

    from ..serving import GenerationEngine, ServingService
    from ..training.sharding import make_mesh

    ge = _graft_entry()
    _require_devices(n_data)
    mesh = make_mesh(n_data, 1)
    model, batch = ge._make_model_and_batch(batch_size=2, seq_len=8)
    params = model.init(jax.random.PRNGKey(0), batch)

    def replica(chunk):
        return GenerationEngine(
            model,
            params,
            model.config,
            template=batch,
            n_slots=2 * n_data,
            max_len=12,
            decode_chunk=chunk,
            dispatch_depth=2,
            min_bucket=8,
            mesh=mesh,
        )

    # Replica 0 uses a distinct decode_chunk from the engine canonical so
    # the gated program is a genuinely different compile, not a cache hit.
    service = ServingService(
        [replica(4), replica(2)], prefill_budget_events=32
    )
    return service.aot_programs(bucket_len=8, group=2)


# ------------------------------------------------------------------- checks
def check_no_f64(program_text: str, label: str = "program") -> list[str]:
    """No f64 element types anywhere in the lowered/compiled module."""
    problems = []
    for i, line in enumerate(program_text.splitlines(), start=1):
        if _F64_RE.search(line):
            problems.append(f"{label}: f64 element type at module line {i}: {line.strip()[:160]}")
    return problems


def check_no_host_transfers(program_text: str, label: str = "program") -> list[str]:
    """No outfeed/infeed/send/recv and no host-callback custom-calls."""
    problems = []
    for i, line in enumerate(program_text.splitlines(), start=1):
        m = _HOST_OP_RE.search(line)
        if m:
            problems.append(
                f"{label}: host transfer op `{m.group(1)}` at module line {i}: "
                f"{line.strip()[:160]}"
            )
            continue
        for target_m in _CUSTOM_CALL_TARGET_RE.finditer(line):
            if _HOST_CALLBACK_RE.search(target_m.group(1)):
                problems.append(
                    f"{label}: host-callback custom-call `{target_m.group(1)}` "
                    f"at module line {i}"
                )
        sm = _STABLEHLO_CUSTOM_RE.search(line)
        if sm and _HOST_CALLBACK_RE.search(sm.group(1)):
            problems.append(
                f"{label}: host-callback custom-call `{sm.group(1)}` at module line {i}"
            )
    return problems


def check_collective_budget(
    inventory: dict, layout: str, budget_path: Path, rel_tol: float = 0.25
) -> list[str]:
    """Inventory vs the committed per-layout budget in ``COLLECTIVES.json``."""
    from ..parallel import compare_inventory

    budgets = json.loads(Path(budget_path).read_text())["layouts"]
    if layout not in budgets:
        return [f"{layout}: no budget entry in {budget_path}"]
    return [f"{layout}: {p}" for p in compare_inventory(inventory, budgets[layout], rel_tol)]


# ------------------------------------------------------------------- runner
def run_program_checks(
    budget_path: Path | None = None,
    rel_tol: float = 0.25,
    compile_collectives: bool = True,
    verbose: bool = True,
) -> list[str]:
    """Runs every Tier-B gate; returns violations (empty ⇒ all gates pass).

    Fast gates (f64-free, host-transfer-free) run on the unoptimized
    lowering of all canonical programs. With ``compile_collectives`` the
    ``dp8`` / ``dp4_tp2`` pretrain layouts also compile and gate their
    collective inventories against ``COLLECTIVES.json``.
    """
    from ..parallel import collective_inventory

    if budget_path is None:
        budget_path = REPO_ROOT / "COLLECTIVES.json"
    problems: list[str] = []

    def log(msg: str) -> None:
        if verbose:
            print(f"graftcheck[B]: {msg}", flush=True)

    layouts = {"dp8": (8, 1), "dp4_tp2": (4, 2)}
    programs: dict[str, tuple] = {}
    for name, (n_data, n_model) in layouts.items():
        programs[f"pretrain:{name}"] = canonical_pretrain_step(n_data, n_model)
    # The sentinel-instrumented variants are the PRODUCTION default (train()
    # jits with_health=True unless sentinel_enabled is false), so they must
    # pass the same static gates as the bare step — and the dp8 health
    # variant is additionally held to the bare dp8 collective budget below:
    # the divergence sentinel's contract is that it adds no collectives and
    # no host traffic to the step.
    programs["pretrain:dp8_health"] = canonical_pretrain_step(8, 1, with_health=True)
    # The NA flagship (r06): fused dep-graph attention + narrow head
    # projections are production defaults, so the lowered NA program is held
    # to the same f64-free/host-transfer-free gates and its own committed
    # collective budget — the fused walk must not smuggle host callbacks or
    # unbudgeted collectives into the step.
    programs["pretrain:na_dp8"] = canonical_pretrain_step(8, 1, na=True)
    # The r09 Pallas dep-graph kernel variant (interpreter mode on the CPU
    # mesh — same program structure as the TPU production compile modulo
    # the Mosaic kernel body): the hand kernel's custom_vjp must not
    # smuggle callbacks, f64, or unbudgeted collectives into the step.
    programs["pretrain:na_pallas_dp8"] = canonical_pretrain_step(
        8, 1, na=True, na_impl="pallas_interpret"
    )
    # The r10 scale-up programs: the scan-over-layers step on the pure-dp
    # mesh (stacked params, one scanned body — its budget differs from dp8
    # only in gradient-sweep *shape*, not magnitude) and the FSDP step
    # (scan + parameter/optimizer sharding over an 8-way fsdp axis — the
    # one layout whose budget is all-gather/reduce-scatter dominated; an
    # accidental re-replication or a per-step full-state gather is a byte
    # blowup here long before it is an HBM OOM at width 4096).
    programs["pretrain:scan_dp8"] = canonical_pretrain_step(8, 1, scan=True)
    programs["pretrain:fsdp8"] = canonical_pretrain_step(1, 1, scan=True, n_fsdp=8)
    programs["finetune:dp8"] = canonical_finetune_step(8)
    programs["finetune:dp8_health"] = canonical_finetune_step(8, with_health=True)
    programs["generation:ci"] = canonical_generation_program()
    # The serving engine's programs (slot-sharded over dp8): the decode-slot
    # program is the serving hot loop and additionally gates against its own
    # committed collective budget below.
    for label, (fn, args) in canonical_engine_programs(8).items():
        programs[f"engine:{label}"] = (fn, args)
    # The health-sentinel contract (ISSUE 15, the serving mirror of the
    # dp8-vs-dp8_health pretrain gate): the engine above carries the
    # production default health_sentinel=True; this uninstrumented variant
    # is held to the SAME committed budgets below — the sentinel must add
    # zero collectives and zero host transfers.
    for label, (fn, args) in canonical_nohealth_engine_programs(8).items():
        programs[f"engine_nohealth:{label}"] = (fn, args)
    # The r09 quantized-decode engine (int8 cache, fused-XLA sampling on
    # the sharded mesh): the decode hot loop with quantize-on-write /
    # dequantize-on-read gates against its own committed budget.
    for label, (fn, args) in canonical_kvq_engine_programs(8).items():
        programs[f"engine_kvq:{label}"] = (fn, args)
    # The r16 paged copy-on-write engine: block-pool decode, paged-admit
    # prefill, and the fork (CoW branch group) prefill, each against its
    # own committed budget — the decode budget pins "zero new collective
    # kinds vs engine_dp8" for the block gather.
    for label, (fn, args) in canonical_paged_engine_programs(8).items():
        programs[f"engine_paged:{label}"] = (fn, args)
    # The Pallas fused-sampling decode program (unsharded single-replica
    # topology): zero-collective by construction, and the kernel epilogue
    # must stay callback-free.
    for label, (fn, args) in canonical_sampling_engine_program().items():
        programs[f"engine_sampling:{label}"] = (fn, args)
    # The r13 speculative-decoding programs: the dp8 CI spec engine's
    # draft-chunk/verify/prefill/boundary set (the verify budget pins "zero
    # new collective kinds vs the baseline decode") and the NA variant's
    # draft-chunk/verify pair.
    for label, (fn, args) in canonical_spec_engine_programs(8).items():
        programs[f"engine_spec:{label}"] = (fn, args)
    for label, (fn, args) in canonical_spec_engine_na_programs().items():
        programs[f"engine_spec_na:{label}"] = (fn, args)
    # The online service's dispatch programs (2-replica service over dp8,
    # deeper decode chunk): the service hot path must stay host-transfer-
    # free beyond the one async boundary fetch — a callback smuggled into
    # decode, prefill, or the boundary pack would re-serialize the
    # double-buffered pipeline.
    for label, (fn, args) in canonical_service_programs(8).items():
        programs[f"service:{label}"] = (fn, args)
    # The serving fleet's r12 programs: the tensor-parallel engine on the
    # dp4×tp2 mesh (decode/prefill must carry exactly the per-layer TP
    # all-reduces, budgeted below) and the hot-swap engine with its
    # shadow-load reshard (collective- and callback-free by contract).
    for label, (fn, args) in canonical_tp_engine_programs(4, 2).items():
        programs[f"engine_tp:{label}"] = (fn, args)
    for label, (fn, args) in canonical_swap_engine_programs().items():
        programs[f"engine_swap:{label}"] = (fn, args)
    # The r20 composition-closure programs: the slot-sharded fused-sampling
    # engine on dp8 (the Pallas sampling grid runs on each slot shard — its
    # decode budget pins "no slot-plane gather", retiring the r09 mesh
    # fallback rule) and the composed spec × int8-cache × serve-time-TP
    # engine on dp4×tp2 with the prefill-stream split — ONE engine carrying
    # all three capacity multipliers; each program's budget pins "the
    # per-layer TP reduce pattern and nothing more" over the spec budgets.
    for label, (fn, args) in canonical_sharded_sampling_engine_programs(8).items():
        programs[f"engine_sampling_shard:{label}"] = (fn, args)
    for label, (fn, args) in canonical_composed_engine_programs(4, 2).items():
        programs[f"engine_composed:{label}"] = (fn, args)
    # The r20 fused decode megakernel (single-replica topology, interpreter
    # mode): the persistent Pallas layer-stack kernel must stay callback-
    # free inside the decode hot loop and zero-collective by construction.
    for label, (fn, args) in canonical_megakernel_engine_program().items():
        programs[f"engine_megakernel:{label}"] = (fn, args)

    lowered = {}
    for label, (fn, args) in programs.items():
        log(f"lowering {label}")
        lowered[label] = fn.lower(*args)
        text = lowered[label].as_text()
        problems += check_no_f64(text, label)
        problems += check_no_host_transfers(text, label)

    if compile_collectives:
        # label -> COLLECTIVES.json budget key; the health variant reuses the
        # bare dp8 budget (the sentinel must live within it), the NA program
        # has its own committed budget (na_dp8).
        budget_keys = {f"pretrain:{name}": name for name in layouts}
        budget_keys["pretrain:dp8_health"] = "dp8"
        budget_keys["pretrain:scan_dp8"] = "scan_dp8"
        budget_keys["pretrain:fsdp8"] = "fsdp8"
        budget_keys["pretrain:na_dp8"] = "na_dp8"
        budget_keys["pretrain:na_pallas_dp8"] = "na_pallas_dp8"
        budget_keys["engine:decode"] = "engine_dp8"
        budget_keys["engine:prefill_b8"] = "engine_prefill_dp8"
        # Uninstrumented vs instrumented: byte-identical budgets, per the
        # health-sentinel zero-collective/zero-transfer contract.
        budget_keys["engine_nohealth:decode"] = "engine_dp8"
        budget_keys["engine_nohealth:prefill_b8"] = "engine_prefill_dp8"
        budget_keys["engine_kvq:decode"] = "engine_kvq_dp8"
        budget_keys["engine_kvq:prefill_b8"] = "engine_kvq_prefill_dp8"
        budget_keys["engine_paged:decode"] = "engine_paged_dp8"
        budget_keys["engine_paged:prefill_b8"] = "engine_paged_prefill_dp8"
        budget_keys["engine_paged:prefill_fork_fwd_b8"] = (
            "engine_paged_fork_prefill_dp8"
        )
        budget_keys["engine_paged:prefill_fork_admit"] = (
            "engine_paged_fork_admit_dp8"
        )
        budget_keys["engine_sampling:decode"] = "engine_sampling_1dev"
        budget_keys["engine_spec:draft_chunk"] = "engine_spec_draft_dp8"
        budget_keys["engine_spec:verify"] = "engine_spec_verify_dp8"
        budget_keys["engine_spec:prefill_b8"] = "engine_spec_prefill_dp8"
        budget_keys["engine_spec_na:draft_chunk"] = "engine_spec_na_draft_1dev"
        budget_keys["engine_spec_na:verify"] = "engine_spec_na_verify_1dev"
        budget_keys["service:decode"] = "service_dp8"
        budget_keys["service:prefill_b8"] = "service_prefill_dp8"
        budget_keys["service:boundary_pack"] = "service_boundary_dp8"
        budget_keys["service:decode_r1"] = "service_r1_dp8"
        budget_keys["engine_tp:decode"] = "engine_tp_dp4_tp2"
        budget_keys["engine_tp:prefill_b8"] = "engine_tp_prefill_dp4_tp2"
        budget_keys["engine_tp:prefill_compute_b8"] = "engine_tp_prefill_compute_dp4_tp2"
        budget_keys["engine_tp:admit"] = "engine_tp_admit_dp4_tp2"
        budget_keys["engine_swap:swap_reshard"] = "engine_swap_reshard_1dev"
        budget_keys["engine_sampling_shard:decode"] = "engine_sampling_shard_dp8"
        budget_keys["engine_composed:draft_chunk"] = "engine_composed_draft_dp4_tp2"
        budget_keys["engine_composed:verify"] = "engine_composed_verify_dp4_tp2"
        budget_keys["engine_composed:prefill_b8"] = "engine_composed_prefill_dp4_tp2"
        budget_keys["engine_composed:prefill_compute_b8"] = (
            "engine_composed_prefill_compute_dp4_tp2"
        )
        budget_keys["engine_composed:admit"] = "engine_composed_admit_dp4_tp2"
        budget_keys["engine_megakernel:decode"] = "engine_megakernel_1dev"
        for label, budget_key in budget_keys.items():
            log(f"compiling {label} for the collective budget gate")
            compiled = lowered[label].compile()
            text = compiled.as_text()
            problems += check_no_f64(text, f"{label} (optimized)")
            problems += check_no_host_transfers(text, f"{label} (optimized)")
            inv = collective_inventory(text)
            log(
                f"{label}: {inv['total_count']} collectives, "
                f"{inv['total_bytes']} payload bytes"
            )
            problems += check_collective_budget(inv, budget_key, budget_path, rel_tol)
    return problems
