"""graftcheck Tier D — the serving control-plane model checker.

A bounded exhaustive-interleaving explorer that drives the REAL
`Scheduler`/`GenerationEngine`/`ServingService`/`ServingFleet` objects
(tiny widths, CPU) through every schedule of enabled control-plane
actions — admit, plan, issue_chunk, resolve_chunk, fork, deadline fire,
evict+replay, promote arm/advance — up to a depth bound, checking the
serving invariant oracles at every reached state:

* block-pool refcount conservation (no leak, no double-free, the zero
  block never freed, pool empty after reset) — `serving.sanitizer`
* the fleet's zero-drop physical ledger and session-affinity stability
* the slot-epoch stale-boundary guard and harvest-once
* strict-FIFO boundary resolution and contiguous chunk issue
* one-time, monotonic `fold_in` admission-index binding
* **determinism**: every explored schedule, canonically drained, must
  produce results bitwise identical per admission index to the reference
  serial drain — the repo's placement/chunking/depth/eviction/fork
  invariance contract, checked across ALL interleavings instead of the
  handful the e2e suites pick.

Tractability comes from sleep-set partial-order reduction: each action
declares a *resource set*, two actions are independent iff their
resource sets are disjoint, and a schedule that only reorders independent
actions is explored once. Soundness (docs/analysis.md "Tier D") rests on
the declared-disjoint pairs genuinely commuting on every reachable state
of the scenario — resource sets here are deliberately coarse (whole
engine, whole service) except where the commutation argument is written
down.

Violations shrink to a minimal failing schedule by greedy delta
debugging (drop one action at a time, keep the shortest still-failing
schedule) before being reported — the reproduction a human debugs.

Exploration is replay-based: `Scenario.build()` constructs the engines
ONCE (their jit caches survive `reset()`, so replays never recompile)
and `Scenario.reset()` rewinds the control-plane state — rebuilding the
cheap service/fleet wrappers around the same engines — before each
schedule prefix is re-applied. Counts are deterministic: `enabled()`
returns actions in sorted order, the DFS visits them in that order, and
the committed `MODELCHECK.json` pins the per-scenario schedule counts
byte-reproducibly (the MEMORY.json discipline).

The caller provisions the CPU mesh (tests/conftest.py or graftcheck's
`_provision_mesh`) before anything here touches jax.
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Iterable, Optional

import numpy as np

__all__ = [
    "Action",
    "Scenario",
    "Explorer",
    "ScenarioReport",
    "SCENARIOS",
    "run_scenario",
    "run_all",
]


# --------------------------------------------------------------------------
# Explorer core (pure Python — unit-testable without jax)
# --------------------------------------------------------------------------


class Action:
    """One enabled control-plane action: a stable name (the schedule
    alphabet) plus the resource set the POR independence relation reads —
    two actions commute iff their resources are disjoint."""

    __slots__ = ("name", "resources")

    def __init__(self, name: str, resources: Iterable[str]):
        self.name = name
        self.resources = frozenset(resources)

    def __repr__(self):
        return f"Action({self.name!r}, {sorted(self.resources)})"


class Scenario:
    """One model-checking scenario over real serving objects.

    Subclasses implement:

    * ``build()`` — construct engines/params ONCE (jit caches persist).
    * ``reset()`` — rewind to the initial control-plane state; called
      before every schedule replay. Wrappers (service/fleet) are cheap
      and rebuilt here; engines are `engine.reset()`.
    * ``enabled()`` — the currently enabled actions. MUST be
      deterministic, and every action that binds a PRNG key (admit,
      submit, fork) MUST be sequentially enabled in one fixed order so
      the admitted set's keys are schedule-invariant — interleaving
      freedom lives in WHERE the bindings fall relative to dispatch, not
      in their order.
    * ``apply(name)`` — perform one action.
    * ``invariants()`` — violation messages for the CURRENT state
      (sanitizer logs + conservation/ledger checks); checked by the
      explorer after reset, after every action, and after the drain.
    * ``drain()`` — run the canonical serial completion from the current
      state and return ``{key: outcome}`` where outcome is
      ``("ok", ...content digest...)`` or ``("error:<Type>",)``. Must be
      a deterministic function of the applied schedule.

    ``allowed_errors`` names error outcomes the determinism oracle
    accepts instead of content (e.g. ``DeadlineExceeded`` — an expired
    request returns no content by contract; its index stays burned).
    """

    name: str = "scenario"
    depth: int = 8
    max_schedules: Optional[int] = None
    allowed_errors: frozenset = frozenset()

    def build(self) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def enabled(self) -> list[Action]:
        raise NotImplementedError

    def apply(self, name: str) -> None:
        raise NotImplementedError

    def invariants(self) -> list[str]:
        return []

    def drain(self) -> dict:
        raise NotImplementedError


class _InvalidSchedule(Exception):
    """A shrink candidate replayed an action that was not enabled at its
    point — the candidate is discarded, not a violation."""


class ScenarioReport:
    """Result of exploring one scenario."""

    def __init__(self, name: str, depth: int):
        self.name = name
        self.depth = depth
        self.schedules = 0
        self.actions: set[str] = set()
        self.violations: list[dict] = []
        self.truncated = False

    def to_dict(self) -> dict:
        return {
            "scenario": self.name,
            "depth": self.depth,
            "schedules": self.schedules,
            "actions": sorted(self.actions),
            "truncated": self.truncated,
            "violations": self.violations,
        }


class Explorer:
    """Sleep-set DFS over a scenario's schedules.

    Stops at the FIRST violation (after shrinking it to a minimal failing
    schedule) — one actionable reproduction beats a thousand duplicates
    of the same bug. ``max_schedules`` caps the leaf count; with the cap
    hit the count is still deterministic (sorted DFS order)."""

    def __init__(self, scenario: Scenario, max_schedules: Optional[int] = None):
        self.scenario = scenario
        self.max_schedules = (
            max_schedules if max_schedules is not None else scenario.max_schedules
        )
        self.report = ScenarioReport(scenario.name, scenario.depth)
        self._stop = False
        self._reference: Optional[dict] = None

    # ------------------------------------------------------------ plumbing
    def _apply_checked(self, name: str) -> list[str]:
        """Applies one action; any exception or invariant breach is the
        violation message list (empty = clean)."""
        try:
            self.scenario.apply(name)
        except Exception as e:  # noqa: BLE001 — every escape IS the finding
            return [f"{type(e).__name__} applying {name!r}: {e}"]
        return self.scenario.invariants()

    def _safe_drain(self) -> tuple[Optional[dict], list[str]]:
        try:
            outcome = self.scenario.drain()
        except Exception as e:  # noqa: BLE001
            return None, [f"{type(e).__name__} during canonical drain: {e}"]
        msgs = self.scenario.invariants()
        return outcome, msgs

    def _reset_checked(self) -> list[str]:
        self.scenario.reset()
        return self.scenario.invariants()

    def _compare(self, outcome: dict) -> list[str]:
        """The determinism oracle: per-key outcomes vs the reference
        serial drain. ``allowed_errors`` outcomes pass without content
        (their keys must still be present — a silent drop never passes)."""
        ref = self._reference
        msgs = []
        for k in sorted(set(ref) | set(outcome), key=repr):
            a, b = ref.get(k), outcome.get(k)
            if b is None:
                msgs.append(f"request {k!r} completed in the reference but "
                            "not in this schedule (dropped)")
                continue
            if a is None:
                msgs.append(f"request {k!r} completed in this schedule but "
                            "not in the reference")
                continue
            if b[0] != "ok":
                kind = b[0].split(":", 1)[1] if ":" in b[0] else b[0]
                if kind not in self.scenario.allowed_errors:
                    msgs.append(f"request {k!r} failed with {kind} "
                                "(not an allowed outcome for this scenario)")
                continue
            if a != b:
                msgs.append(
                    f"request {k!r} diverged from the reference drain: "
                    f"{a} != {b} — results must be bitwise invariant to "
                    "the control-plane schedule"
                )
        return msgs

    def _replay(self, schedule: list[str]) -> None:
        """Rewinds and re-applies ``schedule`` (known-clean prefix)."""
        self.scenario.reset()
        for name in schedule:
            self.scenario.apply(name)

    def _fails(self, schedule: list[str]) -> bool:
        """Shrink predicate: does ``schedule`` (replayed from reset, then
        canonically drained) produce a violation? Invalid schedules (an
        action not enabled at its point) are not failures."""
        msgs = self._reset_checked()
        if msgs:
            return True
        for name in schedule:
            if name not in {a.name for a in self.scenario.enabled()}:
                raise _InvalidSchedule(name)
            msgs = self._apply_checked(name)
            if msgs:
                return True
        outcome, msgs = self._safe_drain()
        if msgs:
            return True
        return bool(self._reference is not None and self._compare(outcome))

    def _shrink(self, schedule: list[str]) -> list[str]:
        cur = list(schedule)
        changed = True
        while changed:
            changed = False
            for i in range(len(cur)):
                cand = cur[:i] + cur[i + 1 :]
                try:
                    if self._fails(cand):
                        cur = cand
                        changed = True
                        break
                except _InvalidSchedule:
                    continue
        return cur

    def _violate(self, schedule: list[str], messages: list[str]) -> None:
        minimal = self._shrink(schedule)
        self.report.violations.append(
            {
                "schedule": list(schedule),
                "minimal": minimal,
                "messages": list(messages),
            }
        )
        self._stop = True

    # ---------------------------------------------------------- exploration
    def run(self) -> ScenarioReport:
        msgs = self._reset_checked()
        if msgs:
            self._violate([], msgs)
            return self.report
        self._reference, msgs = self._safe_drain()
        if msgs:
            self._reference = None
            self._violate([], msgs)
            return self.report
        self._replay([])
        self._dfs([], {})
        return self.report

    def _leaf(self, schedule: list[str]) -> None:
        self.report.schedules += 1
        outcome, msgs = self._safe_drain()
        if msgs:
            self._violate(schedule, msgs)
            return
        msgs = self._compare(outcome)
        if msgs:
            self._violate(schedule, msgs)
            return
        if (
            self.max_schedules is not None
            and self.report.schedules >= self.max_schedules
        ):
            self.report.truncated = True
            self._stop = True

    def _dfs(self, schedule: list[str], sleep: dict[str, frozenset]) -> None:
        """``schedule`` is applied to the live state on entry. ``sleep``
        maps action name -> resources for actions whose exploration here
        would only commute into an already-explored schedule."""
        if self._stop:
            return
        enabled = sorted(self.scenario.enabled(), key=lambda a: a.name)
        self.report.actions.update(a.name for a in enabled)
        candidates = [a for a in enabled if a.name not in sleep]
        if len(schedule) >= self.scenario.depth or not candidates:
            self._leaf(schedule)
            return
        done: list[Action] = []
        for act in candidates:
            if self._stop:
                return
            self._replay(schedule)
            msgs = self._apply_checked(act.name)
            if msgs:
                self._violate(schedule + [act.name], msgs)
                return
            carried = {**sleep, **{d.name: d.resources for d in done}}
            child_sleep = {
                n: r for n, r in carried.items() if not (act.resources & r)
            }
            self._dfs(schedule + [act.name], child_sleep)
            done.append(act)


# --------------------------------------------------------------------------
# The tiny CI model (in-package replica of the test-suite recipe)
# --------------------------------------------------------------------------

_CI_SETUP = None


def _tiny_config():
    from ..data.config import MeasurementConfig
    from ..models.config import StructuredTransformerConfig

    # Vocab: event_type [1, 4), multi_lab [4, 8), lab_vals [8, 12) — the
    # CI-width config the fast serving suites build (tests/test_generation).
    measurement_configs = {
        "multi_lab": MeasurementConfig(
            name="multi_lab",
            temporality="dynamic",
            modality="multi_label_classification",
        ),
        "lab_vals": MeasurementConfig(
            name="lab_vals",
            temporality="dynamic",
            modality="multivariate_regression",
            values_column="v",
        ),
    }
    return StructuredTransformerConfig(
        measurement_configs=measurement_configs,
        vocab_sizes_by_measurement={"event_type": 3, "multi_lab": 4, "lab_vals": 4},
        vocab_offsets_by_measurement={"event_type": 1, "multi_lab": 4, "lab_vals": 8},
        measurements_idxmap={"event_type": 1, "multi_lab": 2, "lab_vals": 3},
        measurements_per_generative_mode={
            "single_label_classification": ["event_type"],
            "multi_label_classification": ["multi_lab", "lab_vals"],
            "multivariate_regression": ["lab_vals"],
        },
        max_seq_len=12,
        hidden_size=16,
        head_dim=4,
        num_attention_heads=4,
        num_hidden_layers=2,
        intermediate_size=16,
        seq_attention_types="global",
    )


def _make_prompt(B=2, L=3, M=6, seed=0):
    import jax.numpy as jnp

    from ..data.types import EventStreamBatch

    rng = np.random.default_rng(seed)
    dyn_meas = np.zeros((B, L, M), dtype=np.int64)
    dyn_idx = np.zeros((B, L, M), dtype=np.int64)
    dyn_vals = np.zeros((B, L, M), dtype=np.float32)
    dyn_vmask = np.zeros((B, L, M), dtype=bool)
    for b in range(B):
        for l in range(L):
            dyn_meas[b, l, 0] = 1
            dyn_idx[b, l, 0] = rng.integers(1, 4)
            dyn_meas[b, l, 1] = 2
            dyn_idx[b, l, 1] = rng.integers(4, 8)
            dyn_meas[b, l, 2] = 3
            dyn_idx[b, l, 2] = rng.integers(8, 12)
            dyn_vals[b, l, 2] = rng.normal()
            dyn_vmask[b, l, 2] = True
    return EventStreamBatch(
        event_mask=jnp.ones((B, L), dtype=bool),
        time_delta=jnp.asarray(rng.uniform(0.5, 10.0, size=(B, L)).astype(np.float32)),
        start_time=jnp.zeros((B,), dtype=jnp.float32),
        static_indices=jnp.asarray(rng.integers(1, 12, size=(B, 2))),
        static_measurement_indices=jnp.asarray(np.ones((B, 2), dtype=np.int64)),
        dynamic_indices=jnp.asarray(dyn_idx),
        dynamic_measurement_indices=jnp.asarray(dyn_meas),
        dynamic_values=jnp.asarray(dyn_vals),
        dynamic_values_mask=jnp.asarray(dyn_vmask),
    )


def _ci_setup():
    """(config, model, params, template) — built once per process; every
    scenario's engines share the weights, so compile caches amortize."""
    global _CI_SETUP
    if _CI_SETUP is None:
        import jax

        from ..models.ci_model import CIPPTForGenerativeSequenceModeling

        config = _tiny_config()
        template = _make_prompt(B=4, L=4)
        model = CIPPTForGenerativeSequenceModeling(config)
        params = model.init(jax.random.PRNGKey(0), template)
        _CI_SETUP = (config, model, params, template)
    return _CI_SETUP


def _build_engine(**kw):
    import jax

    from ..serving.engine import GenerationEngine

    config, model, params, template = _ci_setup()
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_len", 8)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("min_bucket", 2)
    kw.setdefault("base_key", jax.random.PRNGKey(7))
    return GenerationEngine(model, params, config, template=template, **kw)


def _digest(batch) -> Optional[str]:
    """Stable content digest of a result batch (bitwise: raw array bytes)."""
    if batch is None:
        return None
    import jax

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(batch):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()[:16]


def _outcome(result) -> tuple:
    """Schedule-invariant summary of one Engine/Service/FleetResult."""
    err = getattr(result, "error", None)
    if err is not None:
        return (f"error:{type(err).__name__}",)
    return (
        "ok",
        result.prompt_len,
        result.n_events,
        result.n_generated,
        _digest(result.batch),
    )


# --------------------------------------------------------------------------
# Engine-level scenarios
# --------------------------------------------------------------------------


class _EngineScenario(Scenario):
    """Shared machinery: one paged engine, N sequentially-admitted
    requests, the admit/plan/issue/resolve action alphabet.

    Resource sets (the commutation argument, docs/analysis.md):
    ``admit`` touches only the scheduler queue; ``plan`` consumes the
    queue AND admits into slots/device state; ``issue`` advances device
    state and appends to the in-flight deque; ``resolve`` pops the deque
    and harvests slots. ``admit`` therefore commutes with ``issue`` and
    ``resolve`` (disjoint state; harvest never touches the queue in
    these scenarios — the health sentinel cannot fire on finite CI
    weights), and every other pair conflicts."""

    n_requests = 3
    max_new = 3
    engine_kw: dict = {}

    def build(self) -> None:
        self.eng = _build_engine(paged_kv=True, block_size=4, **self.engine_kw)
        from ..serving.sanitizer import attach_sanitizer

        self.san = attach_sanitizer(self.eng)
        self._prompts = [_make_prompt(B=1, L=2, seed=10 + i) for i in range(self.n_requests)]

    def _fresh_requests(self) -> list:
        from ..serving.scheduler import Request

        return [
            Request(prompt=p, max_new_events=self.max_new, request_id=f"r{i}")
            for i, p in enumerate(self._prompts)
        ]

    def reset(self) -> None:
        self.eng.reset()
        self.requests = self._fresh_requests()
        self.submitted = 0
        self.results: dict[int, tuple] = {}

    def enabled(self) -> list[Action]:
        acts = []
        if self.submitted < len(self.requests):
            acts.append(Action(f"admit{self.submitted}", {"queue"}))
        if self.eng.scheduler.pending and self.eng.free_slots():
            acts.append(Action("plan", {"queue", "slots", "device"}))
        if self.eng.occupied and self.eng.inflight_chunks < self.eng.dispatch_depth:
            acts.append(Action("issue", {"device", "inflight"}))
        if self.eng.inflight_chunks:
            acts.append(Action("resolve", {"slots", "device", "inflight"}))
        return acts

    def apply(self, name: str) -> None:
        if name.startswith("admit"):
            self.eng.submit(self.requests[self.submitted])
            self.submitted += 1
        elif name == "plan":
            self.eng.plan_and_dispatch()
        elif name == "issue":
            self.eng.issue_chunk()
        elif name == "resolve":
            self._record(self.eng.resolve_chunk(0.0, True))
        else:
            raise KeyError(name)

    def _record(self, results) -> None:
        for r in results:
            key = r.admission_index
            if key in self.results:
                raise AssertionError(
                    f"admission index {key} completed twice (stale-boundary "
                    "double harvest)"
                )
            self.results[key] = _outcome(r)

    def invariants(self) -> list[str]:
        msgs = list(self.san.violations)
        msgs += self.san.check()
        if (
            self.eng._dispatched_chunks - self.eng._resolved_chunks
            != self.eng.inflight_chunks
        ):
            msgs.append(
                "in-flight accounting desynced: dispatched "
                f"{self.eng._dispatched_chunks} - resolved "
                f"{self.eng._resolved_chunks} != {self.eng.inflight_chunks} queued"
            )
        return msgs

    def drain(self) -> dict:
        guard = 0
        while (
            self.submitted < len(self.requests)
            or self.eng.scheduler.pending
            or self.eng.occupied
            or self.eng.inflight_chunks
        ):
            guard += 1
            if guard > 500:
                raise RuntimeError("drain did not converge in 500 rounds")
            while self.submitted < len(self.requests):
                self.eng.submit(self.requests[self.submitted])
                self.submitted += 1
            if self.eng.scheduler.pending and self.eng.free_slots():
                self.eng.plan_and_dispatch()
            if self.eng.occupied and self.eng.inflight_chunks < self.eng.dispatch_depth:
                self.eng.issue_chunk()
            elif self.eng.inflight_chunks:
                self._record(self.eng.resolve_chunk(0.0, True))
        return dict(self.results)


class EnginePipelineScenario(_EngineScenario):
    """Continuous batching under pipelined dispatch: 4 requests through a
    2-slot paged engine at dispatch depth 2 — every interleaving of
    admission, group prefill, chunk issue, and boundary resolution."""

    name = "engine_pipeline"
    depth = 14
    max_schedules = 800
    n_requests = 4
    max_new = 4
    engine_kw = dict(n_slots=2, dispatch_depth=2)


class EngineRecycleScenario(_EngineScenario):
    """Slot recycling under stale pipelined boundaries: 1 slot, depth-2
    pipelining, 4 tenants in sequence — the scenario whose boundaries
    predate re-admissions, exercising the `_slot_epoch` guard."""

    name = "engine_recycle"
    depth = 16
    max_schedules = 800
    n_requests = 5
    max_new = 4
    engine_kw = dict(n_slots=1, dispatch_depth=2)


class ForkCowScenario(_EngineScenario):
    """Copy-on-write fork vs plain traffic: a 2-branch fork group (one
    prefill, shared refcounted prefix blocks) interleaved with two plain
    requests on a 3-slot paged engine. Key bindings stay schedule-
    invariant by sequential enabling: fork first, then the plain admits."""

    name = "fork_cow"
    depth = 14
    max_schedules = 800
    n_plain = 2
    engine_kw = dict(n_slots=3, dispatch_depth=2)

    def build(self) -> None:
        self.eng = _build_engine(paged_kv=True, block_size=4, **self.engine_kw)
        from ..serving.sanitizer import attach_sanitizer

        self.san = attach_sanitizer(self.eng)
        self._fork_prompt = _make_prompt(B=1, L=4, seed=21)
        self._plain_prompts = [
            _make_prompt(B=1, L=2, seed=22 + i) for i in range(self.n_plain)
        ]

    def reset(self) -> None:
        self.eng.reset()
        from ..serving.scheduler import Request

        self._plain = [
            Request(prompt=p, max_new_events=4, request_id=f"plain{i}")
            for i, p in enumerate(self._plain_prompts)
        ]
        self.forked = False
        self.admitted_plain = 0
        self.results = {}

    def enabled(self) -> list[Action]:
        acts = []
        if not self.forked:
            acts.append(Action("fork", {"queue"}))
        elif self.admitted_plain < len(self._plain):
            acts.append(Action(f"admit_plain{self.admitted_plain}", {"queue"}))
        if self.eng.scheduler.pending and self.eng.free_slots():
            acts.append(Action("plan", {"queue", "slots", "device"}))
        if self.eng.occupied and self.eng.inflight_chunks < self.eng.dispatch_depth:
            acts.append(Action("issue", {"device", "inflight"}))
        if self.eng.inflight_chunks:
            acts.append(Action("resolve", {"slots", "device", "inflight"}))
        return acts

    def apply(self, name: str) -> None:
        if name == "fork":
            self.eng.fork(self._fork_prompt, 2, 3, request_id="branch")
            self.forked = True
        elif name.startswith("admit_plain"):
            self.eng.submit(self._plain[self.admitted_plain])
            self.admitted_plain += 1
        else:
            super().apply(name)

    def drain(self) -> dict:
        guard = 0
        while (
            not self.forked
            or self.admitted_plain < len(self._plain)
            or self.eng.scheduler.pending
            or self.eng.occupied
            or self.eng.inflight_chunks
        ):
            guard += 1
            if guard > 500:
                raise RuntimeError("drain did not converge in 500 rounds")
            if not self.forked:
                self.apply("fork")
            while self.admitted_plain < len(self._plain):
                self.apply(f"admit_plain{self.admitted_plain}")
            if self.eng.scheduler.pending and self.eng.free_slots():
                self.eng.plan_and_dispatch()
            if self.eng.occupied and self.eng.inflight_chunks < self.eng.dispatch_depth:
                self.eng.issue_chunk()
            elif self.eng.inflight_chunks:
                self._record(self.eng.resolve_chunk(0.0, True))
        return dict(self.results)


# --------------------------------------------------------------------------
# Service-level scenario (deadline lanes, per-replica pump)
# --------------------------------------------------------------------------


class ServiceDeadlineScenario(Scenario):
    """A 2-replica service with a deadline lane, decomposed to per-replica
    granularity: submit/place/tick/harvest plus a logical-clock jump that
    fires the lane deadline on whatever is still queued.

    Resource sets: ``submit``/``expire`` own the lanes; ``place`` owns
    lanes + both replicas (it may place onto either, keyed by outstanding
    budget a harvest changes); ``tick{r}``/``harvest{r}`` own replica r
    only — rounds on distinct replicas commute (disjoint engines, result
    records keyed by admission index, `_outstanding` entries disjoint)."""

    name = "service_deadline"
    depth = 14
    max_schedules = 800
    n_requests = 4
    allowed_errors = frozenset({"DeadlineExceeded"})

    def build(self) -> None:
        from ..serving.sanitizer import attach_sanitizer

        self.engines = [
            _build_engine(paged_kv=True, block_size=4, n_slots=1, dispatch_depth=1)
            for _ in range(2)
        ]
        self.sans = [attach_sanitizer(e) for e in self.engines]
        self._prompts = [_make_prompt(B=1, L=2, seed=30 + i) for i in range(self.n_requests)]

    def reset(self) -> None:
        import jax

        from ..serving.scheduler import Request
        from ..serving.service import ServingService
        from ..serving.slo import LaneConfig

        for e in self.engines:
            e.reset()
        self.svc = ServingService(
            self.engines,
            lanes=(LaneConfig("rt", deadline_s=5.0),),
            default_lane="rt",
            base_key=jax.random.PRNGKey(11),
        )
        self.requests = [
            Request(prompt=p, max_new_events=3, request_id=f"q{i}")
            for i, p in enumerate(self._prompts)
        ]
        self.submitted = 0
        self.now = 0.0
        self.expired_fired = False
        self.results: dict[int, tuple] = {}

    def enabled(self) -> list[Action]:
        acts = []
        if self.submitted < len(self.requests):
            acts.append(Action(f"submit{self.submitted}", {"lanes"}))
        if self.svc.lanes.pending:
            acts.append(Action("place", {"lanes", "r0", "r1"}))
            if not self.expired_fired:
                acts.append(Action("expire", {"lanes"}))
        for ri, eng in enumerate(self.engines):
            if (eng.scheduler.pending and eng.free_slots()) or (
                eng.occupied and eng.inflight_chunks < eng.dispatch_depth
            ):
                acts.append(Action(f"tick{ri}", {f"r{ri}"}))
            if eng.inflight_chunks:
                acts.append(Action(f"harvest{ri}", {f"r{ri}"}))
        return acts

    def _record(self, service_results) -> None:
        for sr in service_results:
            key = sr.admission_index
            if key in self.results:
                raise AssertionError(f"service index {key} completed twice")
            self.results[key] = _outcome(sr)

    def apply(self, name: str) -> None:
        if name.startswith("submit"):
            accepted = self.svc.submit(self.requests[self.submitted])
            assert accepted  # the lane is unbounded in this scenario
            self.submitted += 1
        elif name == "place":
            self.svc._place()
        elif name == "expire":
            # The logical clock jumps past the lane deadline; everything
            # still QUEUED cancels with a typed DeadlineExceeded. Placed
            # and resident work is exempt by contract.
            self.now = 11.0
            self.expired_fired = True
            self._record(self.svc._expire(self.now))
        elif name.startswith("tick"):
            eng = self.engines[int(name[4:])]
            if eng.scheduler.pending and eng.free_slots():
                eng.plan_and_dispatch()
            if eng.occupied and eng.inflight_chunks < eng.dispatch_depth:
                eng.issue_chunk()
        elif name.startswith("harvest"):
            ri = int(name[7:])
            eng = self.engines[ri]
            self._record(
                self.svc._wrap(er, ri) for er in eng.resolve_chunk(self.now, True)
            )
        else:
            raise KeyError(name)

    def invariants(self) -> list[str]:
        from ..serving.sanitizer import check_block_pool

        msgs = []
        for ri, (eng, san) in enumerate(zip(self.engines, self.sans)):
            msgs += [f"replica {ri}: {m}" for m in san.violations]
            msgs += [f"replica {ri}: {m}" for m in check_block_pool(eng)]
        # The service-level zero-drop scoreboard: accepted == returned +
        # still physically somewhere (lane, engine queue, or resident).
        if self.svc._next_index != len(self.results) + len(self.svc._meta):
            msgs.append(
                f"service ledger desynced: {self.svc._next_index} accepted != "
                f"{len(self.results)} returned + {len(self.svc._meta)} in flight"
            )
        return msgs

    def drain(self) -> dict:
        guard = 0
        while self.submitted < len(self.requests) or self.svc._meta:
            guard += 1
            if guard > 500:
                raise RuntimeError("drain did not converge in 500 rounds")
            while self.submitted < len(self.requests):
                self.apply(f"submit{self.submitted}")
            self._record(self.svc._expire(self.now))
            self.svc._place()
            for ri, eng in enumerate(self.engines):
                if eng.scheduler.pending and eng.free_slots():
                    eng.plan_and_dispatch()
                if eng.occupied and eng.inflight_chunks < eng.dispatch_depth:
                    eng.issue_chunk()
                if eng.inflight_chunks and (
                    eng.inflight_chunks >= eng.dispatch_depth or not eng.occupied
                ):
                    self._record(
                        self.svc._wrap(er, ri)
                        for er in eng.resolve_chunk(self.now, True)
                    )
        return dict(self.results)


# --------------------------------------------------------------------------
# Fleet-level scenarios (eviction + replay, promotion hold/flip)
# --------------------------------------------------------------------------


class _FleetScenario(Scenario):
    """Shared machinery: a 2-service fleet (1 paged replica each), traffic
    from subjects chosen so BOTH services own sessions, per-service
    `step` actions at the granularity `ServingFleet.run` uses.

    Resource sets: ``step{sid}`` owns service sid only — steps of
    distinct services commute (disjoint engines and `_meta` keys; the
    shared accepted/completed counters only ever increment, and results
    are recorded by fleet index, not arrival order). ``submit`` owns its
    routed service plus the ring ("router"); eviction and promotion
    advancement own everything they might touch."""

    engine_kw: dict = {}
    n_requests = 4

    def build(self) -> None:
        from ..serving.router import ConsistentHashRouter
        from ..serving.sanitizer import attach_sanitizer

        self.engines = {
            sid: _build_engine(
                paged_kv=True, block_size=4, n_slots=2, dispatch_depth=1,
                **self.engine_kw,
            )
            for sid in ("s0", "s1")
        }
        self.sans = {sid: attach_sanitizer(e) for sid, e in self.engines.items()}
        # Subjects picked off the real ring so each service owns two.
        ring = ConsistentHashRouter(["s0", "s1"])
        per_sid: dict[str, list[str]] = {"s0": [], "s1": []}
        for i in range(64):
            sub = f"u{i}"
            sid = ring.route(sub)
            if len(per_sid[sid]) < self.n_requests // 2:
                per_sid[sid].append(sub)
            if all(len(v) >= self.n_requests // 2 for v in per_sid.values()):
                break
        # Interleave ownership so submission order alternates services.
        self.subjects = [
            s for pair in zip(per_sid["s0"], per_sid["s1"]) for s in pair
        ]
        self._prompts = [
            _make_prompt(B=1, L=2, seed=40 + i) for i in range(self.n_requests)
        ]

    def _build_fleet(self):
        import jax

        from ..serving.fleet import ServingFleet
        from ..serving.service import ServingService

        for e in self.engines.values():
            e.reset()
        self.services = {
            sid: ServingService([e], base_key=jax.random.PRNGKey(13))
            for sid, e in self.engines.items()
        }
        self.fleet = ServingFleet(
            dict(self.services), base_key=jax.random.PRNGKey(17)
        )

    def reset(self) -> None:
        from ..serving.scheduler import Request

        self._build_fleet()
        self.requests = [
            Request(prompt=p, max_new_events=3, request_id=f"f{i}")
            for i, p in enumerate(self._prompts)
        ]
        self.submitted = 0
        self.results: dict[int, tuple] = {}

    # --------------------------------------------------------- shared ops
    def _submit_next(self) -> None:
        sub = self.subjects[self.submitted]
        accepted = self.fleet.submit(sub, self.requests[self.submitted])
        assert accepted  # default lanes are unbounded
        self.submitted += 1

    def _step(self, sid: str) -> None:
        svc = self.fleet.services[sid]
        for sr in svc.step(lambda: 0.0, True, place=True):
            fr = self.fleet._wrap(sr, sid)
            key = fr.fleet_index
            if key in self.results:
                raise AssertionError(f"fleet index {key} completed twice")
            self.results[key] = _outcome(fr)

    def _route_of_next(self) -> str:
        return self.fleet.route(self.subjects[self.submitted])

    def invariants(self) -> list[str]:
        from ..serving.sanitizer import check_block_pool, check_fleet_ledger

        msgs = []
        for sid, san in self.sans.items():
            live = sid in self.fleet.services
            # An evicted service's engine is parked mid-flight — its pool
            # legitimately holds abandoned residents; skip it.
            if live:
                msgs += [f"{sid}: {m}" for m in san.violations]
                msgs += [f"{sid}: {m}" for m in check_block_pool(self.engines[sid])]
        msgs += check_fleet_ledger(self.fleet)
        return msgs

    def drain(self) -> dict:
        guard = 0
        while (
            self.submitted < len(self.requests)
            or self.fleet._any_busy()
            or self.fleet._promotion is not None
            or self.fleet._meta
        ):
            guard += 1
            if guard > 500:
                raise RuntimeError("drain did not converge in 500 rounds")
            while self.submitted < len(self.requests):
                self._submit_next()
            if self.fleet._promotion is not None:
                self.fleet._advance_promotion()
            for sid in sorted(self.fleet.services):
                self._step(sid)
        return dict(self.results)


class FleetEvictScenario(_FleetScenario):
    """Replica eviction with session replay, interleaved with live
    traffic: at any point while both services stand, `s0` can be evicted
    — its vnodes fall to `s1`, its in-flight sessions replay there from
    their bound keys, and every result must stay bitwise identical to
    the no-eviction reference (the PR 14 replay contract, checked across
    every admission/dispatch interleaving instead of one)."""

    name = "fleet_evict"
    depth = 18
    max_schedules = 800
    n_requests = 8

    def reset(self) -> None:
        super().reset()
        self.evicted = False

    def enabled(self) -> list[Action]:
        acts = []
        if self.submitted < len(self.requests):
            acts.append(
                Action(f"submit{self.submitted}", {"router", self._route_of_next()})
            )
        for sid in sorted(self.fleet.services):
            eng = self.engines[sid]
            svc = self.fleet.services[sid]
            if svc.lanes.pending or eng.scheduler.pending or eng.occupied or eng.inflight_chunks:
                acts.append(Action(f"step_{sid}", {sid}))
        if not self.evicted and len(self.fleet.services) > 1:
            acts.append(Action("evict", {"router", "s0", "s1"}))
        return acts

    def apply(self, name: str) -> None:
        if name.startswith("submit"):
            self._submit_next()
        elif name.startswith("step_"):
            self._step(name[5:])
        elif name == "evict":
            self.fleet.evict_service("s0", reason="model-check eviction")
            self.evicted = True
        else:
            raise KeyError(name)


class FleetPromoteScenario(_FleetScenario):
    """Verified promotion under traffic: arm a hot swap (to a checkpoint
    byte-identical to the live one, so content is flip-invariant), then
    interleave its state machine — shadow load, fleet-wide probe, per-
    service drain + hold + flip + held release — with submissions and
    service rounds. The zero-drop ledger must hold at EVERY state: a
    request accepted into a swap window is held and released, never
    dropped."""

    name = "fleet_promote"
    depth = 12
    # Promotion schedules are the most expensive to replay (the drain runs
    # the full shadow-load → probe → drain/hold/flip/release state machine
    # per schedule), so this cap sits closer to the 500-schedule floor
    # than the ~50 ms/schedule engine scenarios' 800.
    max_schedules = 560
    engine_kw = dict(hot_swap=True)

    def reset(self) -> None:
        super().reset()
        self.armed = False

    def enabled(self) -> list[Action]:
        acts = []
        if self.submitted < len(self.requests):
            acts.append(
                Action(
                    f"submit{self.submitted}",
                    {"router", "hold", self._route_of_next()},
                )
            )
        for sid in sorted(self.fleet.services):
            eng = self.engines[sid]
            svc = self.fleet.services[sid]
            if svc.lanes.pending or eng.scheduler.pending or eng.occupied or eng.inflight_chunks:
                acts.append(Action(f"step_{sid}", {sid}))
        if not self.armed:
            acts.append(Action("promote_arm", {"promo"}))
        elif self.fleet._promotion is not None:
            acts.append(
                Action("promote_advance", {"promo", "hold", "s0", "s1"})
            )
        return acts

    def apply(self, name: str) -> None:
        if name.startswith("submit"):
            self._submit_next()
        elif name.startswith("step_"):
            self._step(name[5:])
        elif name == "promote_arm":
            # at_time=0.0 arms the state machine without running it
            # synchronously — promote_advance drives each phase as an
            # explored action.
            _, _, params, _ = _ci_setup()
            self.fleet.promote(params, at_time=0.0)
            self.armed = True
        elif name == "promote_advance":
            self.fleet._advance_promotion()
        else:
            raise KeyError(name)


# --------------------------------------------------------------------------
# Registry + entry points
# --------------------------------------------------------------------------

SCENARIOS: dict[str, Callable[[], Scenario]] = {
    cls.name: cls
    for cls in (
        EnginePipelineScenario,
        EngineRecycleScenario,
        ForkCowScenario,
        ServiceDeadlineScenario,
        FleetEvictScenario,
        FleetPromoteScenario,
    )
}


def run_scenario(
    name: str,
    max_schedules: Optional[int] = None,
    depth: Optional[int] = None,
) -> dict:
    """Builds and explores one scenario; returns its report dict."""
    scenario = SCENARIOS[name]()
    if depth is not None:
        scenario.depth = depth
    scenario.build()
    report = Explorer(scenario, max_schedules=max_schedules).run()
    return report.to_dict()


def run_all(
    max_schedules: Optional[int] = None,
    scenarios: Optional[Iterable[str]] = None,
) -> tuple[list[str], dict]:
    """Explores every scenario. Returns ``(problems, report)`` — problems
    is the graftcheck gate's flat message list (empty = clean)."""
    problems: list[str] = []
    reports: dict[str, dict] = {}
    for name in scenarios if scenarios is not None else sorted(SCENARIOS):
        rep = run_scenario(name, max_schedules=max_schedules)
        reports[name] = rep
        for v in rep["violations"]:
            problems.append(
                f"model_check[{name}]: {v['messages'][0]} "
                f"(minimal failing schedule: {v['minimal']})"
            )
    report = {
        "scenarios": reports,
        "total_schedules": sum(r["schedules"] for r in reports.values()),
    }
    return problems, report
