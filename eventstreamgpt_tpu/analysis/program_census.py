"""Tier C of graftcheck: the whole-fleet compiled-program census.

The stack emits dozens of distinct compiled programs — pretrain layouts
(dp/tp/scan/fsdp), the serving engine's decode + per-bucket prefill +
boundary pack (float, quantized-cache, and fused-sampling variants), the
online service's per-replica programs, and the bench width-ladder rungs.
Tier B gates a hand-picked canonical list at toy shapes; Tier C is the
**census**: every ``aot_programs`` provider registers its program factories
here (`register_aot_provider` — the hooks live in ``training/sharding.py``,
``serving/engine.py``, ``serving/service.py``, plus this module's own
generation and width-ladder providers), so a compiled program nobody
registered is itself a failure, and every registered program is AOT-lowered
and compiled on the 8-device virtual mesh and statically audited:

* **peak HBM** per program from XLA's buffer assignment
  (``analysis/memory_checks.py``), gated against the committed
  ``MEMORY.json``; the width-4096 replicated ladder rung is the negative
  control (it must FAIL the 16 GB/chip budget) and the fsdp8 rung the
  positive one (it must fit).
* **kind-resolved collective inventories** at BOTH toy and scaled shapes
  (width >= 2048): the scaled fsdp8 backward must show reduce-scatter —
  not just all-reduce — once folded AR+slice pairs are resolved
  (``parallel.collectives_audit.resolve_folded_reduce_scatters``); toy
  inventories re-gate against ``COLLECTIVES.json``, scaled ones against
  their ``MEMORY.json`` entry.
* **donation completeness**: every donated argument leaf actually aliased
  in the compiled output (an undonated-in-practice buffer double-buffers
  HBM even when GC005 passes at the AST level).
* **implicit resharding**: declared input shardings diffed against the
  compiled executable's expected layouts.

Module-level code is stdlib-only (like ``lint``); jax and the model stack
load lazily inside the factories, so importing the registry costs nothing.

Regenerate budgets with ``python scripts/graftcheck.py --write-memory``
(byte-reproducible; CI diffs the regenerated file against the committed
one). See docs/analysis.md "Tier C".
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Callable

__all__ = [
    "CensusProgram",
    "register_aot_provider",
    "registered_providers",
    "census_programs",
    "aot_surface",
    "collect_census",
    "run_census",
    "write_memory_budgets",
    "MEMORY_PATH",
    "HBM_BUDGET_GB",
    "SCALED_WIDTHS",
    "SCALED_LAYERS",
]

REPO_ROOT = Path(__file__).resolve().parents[2]
MEMORY_PATH = REPO_ROOT / "MEMORY.json"
COLLECTIVES_PATH = REPO_ROOT / "COLLECTIVES.json"

# The documented serving/training chip budget (docs/scaling.md, bench.py).
HBM_BUDGET_GB = 16.0
# Scaled-shape policy: width >= 2048 is where HBM-fit reasoning becomes
# real (the replicated 4096 train state cannot fit a 16 GB chip) and where
# the FSDP gradient sweep's reduce-scatter must be visible in the
# kind-resolved inventory. 12 layers matches the bench ladder geometry.
SCALED_WIDTHS = (2048, 4096)
SCALED_LAYERS = 12


@dataclasses.dataclass
class CensusProgram:
    """One registered compiled program and its Tier-C gate metadata.

    ``fn``/``args`` are what ``jax.jit(...).lower(*args)`` needs — args may
    be concrete arrays (toy shapes) or ``jax.ShapeDtypeStruct`` trees with
    shardings (scaled shapes, where materializing the state would not fit
    host RAM, let alone a chip). ``budget_key`` names the raw-inventory
    COLLECTIVES.json layout this program re-gates against (None: no
    committed toy budget). ``scaled`` programs commit their kind-resolved
    inventory to MEMORY.json instead. ``hbm_expect`` is "fit"/"oom"/None
    against `HBM_BUDGET_GB`; ``require_kinds`` must appear in the resolved
    inventory with count >= 1.
    """

    label: str
    fn: Any
    args: tuple
    donate_argnums: tuple = ()
    budget_key: str | None = None
    scaled: bool = False
    hbm_expect: str | None = None
    require_kinds: tuple = ()


_PROVIDERS: dict[str, Callable[[], dict[str, CensusProgram]]] = {}


def register_aot_provider(
    name: str, factory: Callable[[], dict[str, CensusProgram]]
) -> None:
    """Registers a subsystem's program factory under ``name``.

    The factory is lazy: it builds the subsystem's canonical instances and
    returns ``{label: CensusProgram}`` only when the census actually runs.
    Re-registering a name replaces the factory (idempotent module reload).
    """
    _PROVIDERS[name] = factory


def _import_provider_hooks() -> None:
    """Imports the modules whose bottom-of-module hooks register providers.

    Keeping the hook in each provider module (rather than a central list
    here) is what makes an unregistered provider loud: a new subsystem that
    grows an ``aot_programs`` without a hook fails the census-completeness
    test, not a code review.
    """
    from ..serving import engine as _engine  # noqa: F401
    from ..serving import fleet as _fleet  # noqa: F401
    from ..serving import service as _service  # noqa: F401
    from ..training import sharding as _sharding  # noqa: F401


def registered_providers() -> dict[str, Callable[[], dict[str, CensusProgram]]]:
    _import_provider_hooks()
    return dict(_PROVIDERS)


def census_programs() -> dict[str, CensusProgram]:
    """Builds every registered provider's programs (no lowering yet)."""
    programs: dict[str, CensusProgram] = {}
    for provider, factory in sorted(registered_providers().items()):
        for label, prog in factory().items():
            if label in programs:
                raise ValueError(
                    f"census label collision: provider {provider!r} re-registers "
                    f"{label!r}"
                )
            programs[label] = prog
    return programs


# --------------------------------------------------- built-in providers
def _generation_programs() -> dict[str, CensusProgram]:
    """The single-dispatch cached generation program (Tier B's
    ``generation:ci``): no donation (params are reused across calls), no
    committed collective budget (single-program, collective-free)."""
    from . import program_checks as pc

    fn, args = pc.canonical_generation_program()
    return {"generation:ci": CensusProgram("generation:ci", fn, args)}


def _scaled_model_and_batch(width: int, layers: int, batch_size: int = 8, seq_len: int = 8):
    """The width-ladder rung geometry at census scale: proper proportions
    (head_dim 128, 4x MLP, scan-over-layers, the production remat policy)
    on the toy vocabulary — parameter bytes, not dataset width, are what
    the HBM analysis measures."""
    import numpy as np

    from ..data.types import EventStreamBatch
    from ..models.ci_model import CIPPTForGenerativeSequenceModeling
    from ..models.config import StructuredTransformerConfig

    vocab = 32
    cfg = StructuredTransformerConfig(
        vocab_sizes_by_measurement={"event_type": vocab // 2, "lab": vocab // 2 - 1},
        vocab_offsets_by_measurement={"event_type": 1, "lab": vocab // 2 + 1},
        measurements_idxmap={"event_type": 1, "lab": 2},
        measurements_per_generative_mode={
            "single_label_classification": ["event_type"],
            "multi_label_classification": ["lab"],
            "multivariate_regression": ["lab"],
        },
        max_seq_len=seq_len,
        hidden_size=width,
        head_dim=128,
        num_attention_heads=width // 128,
        num_hidden_layers=layers,
        intermediate_size=4 * width,
        TTE_generation_layer_type="log_normal_mixture",
        TTE_lognormal_generation_num_components=2,
        scan_layers=True,
        gradient_checkpointing="save_attention",
        attention_dropout=0.0,
    )
    rng = np.random.default_rng(0)
    n_data = 4
    em = np.ones((batch_size, seq_len), dtype=bool)
    dm = np.full((batch_size, seq_len, n_data), 2, dtype=np.int64)
    dm[:, :, 0] = 1
    di = np.where(
        dm == 1,
        rng.integers(1, vocab // 2 + 1, size=dm.shape),
        rng.integers(vocab // 2 + 1, vocab, size=dm.shape),
    )
    batch = EventStreamBatch(
        event_mask=em,
        time_delta=rng.uniform(0.5, 10.0, size=em.shape).astype(np.float32),
        static_indices=rng.integers(1, vocab, size=(batch_size, 2)),
        static_measurement_indices=np.ones((batch_size, 2), dtype=np.int64),
        dynamic_indices=di,
        dynamic_measurement_indices=dm,
        dynamic_values=rng.normal(size=dm.shape).astype(np.float32),
        dynamic_values_mask=(dm == 2) & (rng.random(dm.shape) < 0.5),
    )
    return CIPPTForGenerativeSequenceModeling(cfg), batch


def _scaled_train_program(width: int, layers: int, layout: str):
    """``(fn, abstract args)`` for a scaled train step — abstract because a
    2.4B-parameter replicated tree must never materialize on this host; the
    compile (and every gate) only needs shapes + declared shardings."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models.config import OptimizationConfig
    from ..training import TrainState, build_optimizer, make_train_step
    from ..training.sharding import (
        batch_partition_axes,
        make_mesh,
        make_state_shardings,
    )

    mesh = make_mesh(1, 1, n_fsdp=8) if layout == "fsdp8" else make_mesh(8, 1)
    model, batch = _scaled_model_and_batch(width, layers)
    oc = OptimizationConfig(
        init_lr=1e-3,
        batch_size=8,
        max_training_steps=10,
        lr_num_warmup_steps=1,
        lr_frac_warmup_steps=None,
    )
    tx, _ = build_optimizer(oc)

    def init_fn(key):
        p = model.init(key, jax.tree_util.tree_map(jnp.asarray, batch))
        return TrainState(step=jnp.zeros((), jnp.int32), params=p, opt_state=tx.init(p))

    shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    if layout == "fsdp8":
        shardings = make_state_shardings(shapes, mesh)
    else:
        shardings = jax.tree_util.tree_map(lambda _: NamedSharding(mesh, P()), shapes)
    state_sds = jax.tree_util.tree_map(
        lambda s, h: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=h), shapes, shardings
    )
    axes = batch_partition_axes(mesh)
    dim0 = axes if len(axes) > 1 else axes[0]

    def batch_sds(x):
        x = np.asarray(x)
        sharding = NamedSharding(mesh, P(dim0, *([None] * (x.ndim - 1))))
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    args = (
        state_sds,
        jax.tree_util.tree_map(batch_sds, batch),
        jax.ShapeDtypeStruct((2,), np.uint32),
    )
    # The fsdp rungs pin the output state to the declared layout (see
    # make_train_step) — the donation audit requires in/out layouts to match.
    pin = shardings if layout == "fsdp8" else None
    return make_train_step(model, tx, out_state_shardings=pin), args


def _ladder_programs() -> dict[str, CensusProgram]:
    """The width-ladder rungs as census programs: scaled shapes where the
    HBM-fit verdicts and the reduce-scatter visibility are real, not toy
    artifacts. The replicated width-4096 rung is the committed negative
    control for the 16 GB budget gate."""
    out: dict[str, CensusProgram] = {}
    specs = [
        # (label, width, layout, hbm_expect, require_kinds)
        ("ladder:fsdp8@w2048", 2048, "fsdp8", "fit", ("reduce-scatter",)),
        ("ladder:fsdp8@w4096", 4096, "fsdp8", "fit", ("reduce-scatter",)),
        ("ladder:replicated_dp8@w4096", 4096, "replicated", "oom", ()),
    ]
    for label, width, layout, expect, kinds in specs:
        fn, args = _scaled_train_program(width, SCALED_LAYERS, layout)
        out[label] = CensusProgram(
            label,
            fn,
            args,
            donate_argnums=(0,),
            scaled=True,
            hbm_expect=expect,
            require_kinds=kinds,
        )
    return out


register_aot_provider("generation", _generation_programs)
register_aot_provider("ladder", _ladder_programs)


# --------------------------------------------------------- the census run
def aot_surface() -> dict[str, set[str]]:
    """Every program label the canonical ``aot_programs`` surfaces expose.

    Enumerated independently of the registry (straight from the engine /
    service / training canonical constructions), so the completeness test
    can assert census ∪ Tier B covers it with no self-reference.
    """
    from . import program_checks as pc

    surface: dict[str, set[str]] = {
        "training": {
            "pretrain:dp8",
            "pretrain:dp4_tp2",
            "pretrain:dp8_health",
            "pretrain:na_dp8",
            "pretrain:na_pallas_dp8",
            "pretrain:scan_dp8",
            "pretrain:fsdp8",
            "finetune:dp8",
            "finetune:dp8_health",
        },
        "generation": {"generation:ci"},
        "engine": {f"engine:{k}" for k in pc.canonical_engine_programs(8)}
        | {f"engine_nohealth:{k}" for k in pc.canonical_nohealth_engine_programs(8)}
        | {f"engine_kvq:{k}" for k in pc.canonical_kvq_engine_programs(8)}
        | {f"engine_sampling:{k}" for k in pc.canonical_sampling_engine_program()}
        | {f"engine_spec:{k}" for k in pc.canonical_spec_engine_programs(8)}
        | {f"engine_spec_na:{k}" for k in pc.canonical_spec_engine_na_programs()}
        | {f"engine_paged:{k}" for k in pc.canonical_paged_engine_programs(8)}
        | {
            f"engine_sampling_shard:{k}"
            for k in pc.canonical_sharded_sampling_engine_programs(8)
        }
        | {f"engine_megakernel:{k}" for k in pc.canonical_megakernel_engine_program()},
        "service": {f"service:{k}" for k in pc.canonical_service_programs(8)},
        "fleet": {f"engine_tp:{k}" for k in pc.canonical_tp_engine_programs(4, 2)}
        | {f"engine_swap:{k}" for k in pc.canonical_swap_engine_programs()}
        | {f"engine_composed:{k}" for k in pc.canonical_composed_engine_programs(4, 2)},
        "ladder": {
            "ladder:fsdp8@w2048",
            "ladder:fsdp8@w4096",
            "ladder:replicated_dp8@w4096",
        },
    }
    return surface


def collect_census(
    programs: dict[str, CensusProgram] | None = None, verbose: bool = True
) -> tuple[dict[str, dict], list[str]]:
    """Lowers + compiles every registered program and extracts the facts.

    ``programs`` lets callers that already built the registry (for budget
    metadata) pass it in — the factories construct real models, engines,
    and the 2-replica service, so rebuilding the fleet is the expensive
    half of census setup.

    Returns ``(per-label report, budget-independent violations)``: the
    report carries each program's memory breakdown, donation audit,
    resharding audit, and collective inventories (raw always, kind-resolved
    for scaled programs); the violations are the gates that need no
    committed budget — donation completeness, implicit resharding,
    HBM-fit expectations, required collective kinds, and (for the scaled
    programs Tier B never sees) f64/host-transfer cleanliness.
    """
    from ..parallel import collective_inventory
    from . import program_checks as pc
    from .memory_checks import (
        check_hbm_fit,
        donation_report,
        memory_report,
        resharding_report,
    )

    def log(msg: str) -> None:
        if verbose:
            print(f"graftcheck[C]: {msg}", flush=True)

    if programs is None:
        programs = census_programs()
    report: dict[str, dict] = {}
    problems: list[str] = []
    for label, prog in programs.items():
        log(f"lowering + compiling {label}")
        lowered = prog.fn.lower(*prog.args)
        if prog.scaled:
            # Tier B's text gates only see toy shapes; the scaled programs
            # get the same f64/host-transfer cleanliness here.
            text = lowered.as_text()
            problems += pc.check_no_f64(text, label)
            problems += pc.check_no_host_transfers(text, label)
        compiled = lowered.compile()
        hlo = compiled.as_text()
        entry: dict[str, Any] = {"memory": memory_report(compiled)}

        if prog.donate_argnums:
            d = donation_report(compiled, prog.args, prog.donate_argnums, hlo_text=hlo)
            entry["donation"] = {
                "n_donated": d["n_donated"],
                "n_aliased": d["n_aliased"],
                "n_pruned": d["n_pruned"],
            }
            for u in d["undonated"]:
                problems.append(
                    f"{label}: donated-but-unaliased buffer ({u}) — the donation "
                    "is a no-op in the compiled program and the buffer "
                    "double-buffers HBM"
                )

        reshard = resharding_report(compiled, prog.args)
        entry["resharding_ok"] = not reshard
        problems += [f"{label}: {p}" for p in reshard]

        entry["collectives"] = collective_inventory(hlo)
        if prog.scaled:
            entry["collectives_resolved"] = collective_inventory(hlo, resolve_folded=True)
            for kind in prog.require_kinds:
                if entry["collectives_resolved"].get(kind, {}).get("count", 0) == 0:
                    problems.append(
                        f"{label}: kind-resolved inventory shows no {kind} — the "
                        "scaled-shape sweep this layout exists for is not being "
                        "scattered"
                    )
        if prog.hbm_expect is not None:
            problems += check_hbm_fit(
                entry["memory"], HBM_BUDGET_GB, prog.hbm_expect == "fit", label
            )
        mem = entry["memory"]
        log(
            f"{label}: peak {mem['peak_hbm_bytes'] / 1e9:.3f} GB/device, "
            f"{entry['collectives']['total_count']} collectives"
        )
        report[label] = entry
    return report, problems


def _memory_budget_entry(label: str, prog_report: dict, prog: CensusProgram) -> dict:
    entry = {"peak_hbm_bytes": prog_report["memory"]["peak_hbm_bytes"]}
    entry.update(
        {k: v for k, v in prog_report["memory"].items() if k != "peak_hbm_bytes"}
    )
    if "donation" in prog_report:
        entry["n_donated"] = prog_report["donation"]["n_donated"]
        entry["n_aliased"] = prog_report["donation"]["n_aliased"]
        # jit-pruned donated leaves hold no buffer (nothing to alias, nothing
        # double-buffered); committed only when present so the clean contract
        # n_donated == n_aliased + n_pruned stays checkable from the file.
        if prog_report["donation"]["n_pruned"]:
            entry["n_pruned"] = prog_report["donation"]["n_pruned"]
    if prog.scaled:
        entry["collectives"] = prog_report["collectives_resolved"]
        entry["hbm_expect"] = prog.hbm_expect
    return entry


def run_census(
    memory_path: Path | None = None,
    collectives_path: Path | None = None,
    rel_tol: float = 0.10,
    verbose: bool = True,
    regen_path: Path | None = None,
) -> tuple[list[str], dict]:
    """Runs every Tier-C gate; returns ``(violations, per-program report)``.

    On top of `collect_census`'s budget-free gates: every program's peak
    HBM against its committed ``MEMORY.json`` entry (a registered program
    with no entry is a violation — run ``--write-memory``), toy-shape raw
    inventories re-gated against ``COLLECTIVES.json``, and scaled-shape
    kind-resolved inventories against their ``MEMORY.json`` entry.

    ``regen_path`` additionally writes the regenerated budget file from the
    SAME census pass — what CI diffs against the committed ``MEMORY.json``
    without paying a second whole-fleet compile.
    """
    from ..parallel import compare_inventory
    from .memory_checks import compare_memory

    memory_path = memory_path or MEMORY_PATH
    collectives_path = collectives_path or COLLECTIVES_PATH
    budgets = (
        json.loads(Path(memory_path).read_text())["programs"]
        if Path(memory_path).exists()
        else {}
    )
    coll_budgets = json.loads(Path(collectives_path).read_text())["layouts"]

    programs = census_programs()
    report, problems = collect_census(programs, verbose=verbose)
    if regen_path is not None:
        _write_budget_file(programs, report, Path(regen_path))
    for label, entry in report.items():
        prog = programs[label]
        if label not in budgets:
            problems.append(
                f"{label}: registered program has no committed MEMORY.json entry — "
                "regenerate with `python scripts/graftcheck.py --write-memory`"
            )
            continue
        problems += [
            f"{label}: {p}" for p in compare_memory(entry["memory"], budgets[label], rel_tol)
        ]
        if prog.budget_key is not None:
            if prog.budget_key not in coll_budgets:
                # Same graceful path as a missing MEMORY.json entry: a typo'd
                # or not-yet-committed key must be a reported violation, not a
                # KeyError traceback after minutes of fleet compilation.
                problems.append(
                    f"{label}: budget key {prog.budget_key!r} has no entry in "
                    "COLLECTIVES.json — regenerate with dryrun_multichip(8) or "
                    "fix the registered key"
                )
            else:
                problems += [
                    f"{label}: {p}"
                    for p in compare_inventory(
                        entry["collectives"], coll_budgets[prog.budget_key]
                    )
                ]
        if prog.scaled and "collectives" in budgets[label]:
            # The scaled rungs pin all-reduce tighter than the default bound:
            # a PARTIAL reduce-scatter→all-reduce substitution leaves the rs
            # kind present (the presence rule passes) and at these budgets
            # +25% of the all-reduce bytes could hide most of a re-routed
            # sweep; +10% cannot.
            problems += [
                f"{label} (resolved): {p}"
                for p in compare_inventory(
                    entry["collectives_resolved"],
                    budgets[label]["collectives"],
                    per_kind_tol={"all-reduce": (0.10, 64 * 1024)},
                )
            ]
    return problems, report


def _write_budget_file(
    programs: dict[str, CensusProgram], report: dict[str, dict], path: Path
) -> None:
    out = {
        "note": (
            "graftcheck Tier C memory budgets: per-compiled-program peak HBM "
            "(bytes/device, from XLA buffer assignment on the 8-device virtual "
            "mesh), donation-aliasing counts, and kind-resolved collective "
            "inventories for the scaled-shape ladder rungs. Regenerate with "
            "`python scripts/graftcheck.py --write-memory`; see docs/analysis.md."
        ),
        "n_devices": 8,
        "hbm_budget_gb": HBM_BUDGET_GB,
        "programs": {
            label: _memory_budget_entry(label, report[label], programs[label])
            for label in sorted(report)
        },
    }
    Path(path).write_text(json.dumps(out, indent=1) + "\n")


def write_memory_budgets(
    memory_path: Path | None = None, verbose: bool = True
) -> tuple[Path, list[str]]:
    """Regenerates ``MEMORY.json`` from a fresh census run.

    Byte-reproducible on a fixed jax/jaxlib (sorted labels, stable key
    order, indent 1, trailing newline) — CI regenerates and diffs against
    the committed file, the same discipline COLLECTIVES.json gets from the
    multichip dry run. Budget-free violations (donation, resharding,
    HBM-fit expectations) are returned, not suppressed: a budget refresh
    must never paper over a broken donation.
    """
    memory_path = Path(memory_path or MEMORY_PATH)
    programs = census_programs()
    report, problems = collect_census(programs, verbose=verbose)
    _write_budget_file(programs, report, memory_path)
    return memory_path, problems
