"""Recompilation sentinel: fail fast when a step function recompiles.

A mid-epoch recompile is the silent TPU killer: a drifting batch shape or a
weak-typed constant retraces the step, XLA spends tens of seconds per
recompile, and the run "works" at a tenth of its throughput. The compile is
a static event, so it can be *gated*, not profiled:

* ``CompileGuard(watch=[step_fn])`` snapshots each watched jitted function's
  trace-cache size (``PjitFunction._cache_size``) when armed and raises
  `RecompileError` from :meth:`check` / ``__exit__`` if any watched function
  grew a new executable. Per-function and noise-free: eager helper ops
  compiling elsewhere don't trip it.
* ``CompileGuard()`` (no watch) falls back to a process-global backend
  compile counter fed by a ``jax.monitoring`` duration listener — coarser
  (any compile in the window trips it) but works for "this region must
  dispatch only cached programs" assertions in tests.

Used by ``training/pretrain.py`` (armed from the second epoch, checked after
every full-shape dispatch; ``trainer_config.guard_recompiles=False`` opts
out) and by ``tests/training/test_compile_guard.py`` to pin the
compile-exactly-once contract across epoch boundaries and mid-epoch resume.
"""

from __future__ import annotations

import warnings
from typing import Callable, Sequence

__all__ = ["CompileGuard", "RecompileError", "backend_compile_count"]

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# Process-global backend-compile counter. jax.monitoring has no listener
# de-registration, so register exactly one module-level listener lazily and
# let guards snapshot/diff the counter.
_compile_count = 0
_listener_installed = False


def _install_listener() -> None:
    global _listener_installed
    if _listener_installed:
        return
    import jax

    def _on_event(event: str, duration: float, **kwargs) -> None:
        global _compile_count
        if event == _COMPILE_EVENT:
            _compile_count += 1

    jax.monitoring.register_event_duration_secs_listener(_on_event)
    _listener_installed = True


def backend_compile_count() -> int:
    """Backend compiles observed process-wide since the listener installed."""
    _install_listener()
    return _compile_count


def _cache_size(fn) -> int | None:
    getter = getattr(fn, "_cache_size", None)
    if getter is None:
        return None
    try:
        return int(getter())
    except Exception:
        return None


class RecompileError(RuntimeError):
    """A guarded region compiled more executables than its budget allows."""


class CompileGuard:
    """Context manager / armable sentinel over jit compile activity.

    Args:
        watch: jitted callables whose trace caches are monitored. Empty ⇒
            fall back to the process-global backend-compile counter.
        max_compiles: new executables tolerated inside the guarded region.
        label: names the guarded region in the error message.
        on_violation: ``"raise"`` (default) or ``"warn"``.
    """

    def __init__(
        self,
        watch: Sequence[Callable] = (),
        max_compiles: int = 0,
        label: str = "guarded region",
        on_violation: str = "raise",
    ):
        if on_violation not in ("raise", "warn"):
            raise ValueError(f"on_violation must be 'raise' or 'warn', got {on_violation!r}")
        self.watch = list(watch)
        self.max_compiles = int(max_compiles)
        self.label = label
        self.on_violation = on_violation
        self.armed = False
        self._baseline_caches: list[int | None] = []
        self._baseline_global = 0
        # Watched fns without a cache-size probe (API drift) degrade to the
        # global counter rather than silently guarding nothing.
        self._use_global = not self.watch or any(
            _cache_size(fn) is None for fn in self.watch
        )
        if self._use_global:
            _install_listener()

    # ------------------------------------------------------------- lifecycle
    def arm(self) -> "CompileGuard":
        """Snapshots compile state; subsequent ``check()`` diffs against it."""
        if self._use_global:
            self._baseline_global = backend_compile_count()
        else:
            self._baseline_caches = [_cache_size(fn) for fn in self.watch]
        self.armed = True
        return self

    @property
    def compiles(self) -> int:
        """New executables since ``arm()`` (0 when unarmed)."""
        if not self.armed:
            return 0
        if self._use_global:
            return backend_compile_count() - self._baseline_global
        total = 0
        for fn, base in zip(self.watch, self._baseline_caches):
            now = _cache_size(fn)
            if now is not None and base is not None:
                total += max(now - base, 0)
        return total

    def check(self) -> None:
        """Raises (or warns) if the region exceeded its compile budget."""
        if not self.armed:
            return
        n = self.compiles
        if n > self.max_compiles:
            what = (
                ", ".join(getattr(f, "__name__", str(f)) for f in self.watch)
                if self.watch and not self._use_global
                else "the process"
            )
            msg = (
                f"{self.label}: {n} new compile(s) of {what} "
                f"(budget {self.max_compiles}). A steady-state step recompiled — "
                "look for drifting batch shapes, weak-typed constants, or python "
                "scalars captured as tracers."
            )
            if self.on_violation == "warn":
                warnings.warn(msg, RuntimeWarning, stacklevel=2)
                # re-baseline so one drift doesn't warn on every later check
                self.arm()
            else:
                raise RecompileError(msg)

    def disarm(self) -> None:
        self.armed = False

    def __enter__(self) -> "CompileGuard":
        return self.arm()

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.check()
        self.disarm()
