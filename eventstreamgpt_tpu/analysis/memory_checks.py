"""Static HBM, donation, and resharding analysis of compiled programs.

Tier C of graftcheck extracts three classes of facts from an AOT-compiled
executable — no execution, no hardware:

* **peak HBM** from XLA's buffer assignment (``compiled.memory_analysis()``):
  per-device argument + output + temp + generated-code bytes, net of
  donation aliasing. This is the number that decides whether a layout fits
  a 16 GB chip *before* a single device step — the pjit-era playbook for
  catching OOMs at compile time.
* **donation completeness** from the module's ``input_output_alias`` map:
  every leaf of a donated argument must actually be aliased to an output
  buffer in the compiled program. A donated-but-unaliased buffer
  double-buffers silently — GC005 passing at the AST level only proves the
  ``donate_argnums`` was *written*, not that XLA could honor it (dtype or
  sharding mismatches between the donated input and its output make the
  donation a no-op, with a warning nobody reads).
* **implicit resharding** by diffing the shardings the caller declared on
  the arguments against the shardings the compiled executable expects.
  With sharding propagation to parameters disabled (jax's default) these
  match; a mismatch means every dispatch silently device_puts — a
  per-step resharding tax invisible in the program text.

All helpers take the compiled object (``jitted.lower(...).compile()``) and
stay pure-analysis: nothing here allocates device buffers beyond what
lowering itself does.
"""

from __future__ import annotations

import re
from typing import Any

__all__ = [
    "memory_report",
    "peak_hbm_bytes",
    "donation_report",
    "resharding_report",
    "compare_memory",
    "check_hbm_fit",
]

# input_output_alias entries: "{out_index}: (param_number, {param_index}, kind)"
_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\((\d+),\s*\{[\d,\s]*\}")


def peak_hbm_bytes(mem_stats: Any) -> int:
    """Per-device peak HBM of a compiled executable's buffer assignment.

    ``arguments + outputs - aliased + temps + generated code``: donated
    (aliased) outputs reuse their input buffers, everything else is live at
    peak. Activations the schedule materializes land in ``temp``; this is
    the static floor a real step cannot go below.
    """
    return int(
        mem_stats.argument_size_in_bytes
        + mem_stats.output_size_in_bytes
        - mem_stats.alias_size_in_bytes
        + mem_stats.temp_size_in_bytes
        + mem_stats.generated_code_size_in_bytes
    )


def memory_report(compiled: Any) -> dict:
    """The committed-to-``MEMORY.json`` memory facts of one executable."""
    ms = compiled.memory_analysis()
    return {
        "peak_hbm_bytes": peak_hbm_bytes(ms),
        "argument_bytes": int(ms.argument_size_in_bytes),
        "output_bytes": int(ms.output_size_in_bytes),
        "alias_bytes": int(ms.alias_size_in_bytes),
        "temp_bytes": int(ms.temp_size_in_bytes),
        "generated_code_bytes": int(ms.generated_code_size_in_bytes),
    }


def _kept_flat_indices(compiled: Any, n_leaves: int) -> list[int]:
    """The flat argument-leaf indices the compiled executable kept.

    jit prunes unused arguments by default, so the compiled module's
    parameter numbers index the *kept* leaves, not the caller's flat
    leaves. Falls back to the identity when the executable doesn't expose
    the kept set (analysis must degrade, never crash)."""
    ex = getattr(compiled, "_executable", None)
    kept = getattr(ex, "_kept_var_idx", None)
    if kept is None:
        kept = getattr(ex, "kept_var_idx", None)
    if kept is None:
        return list(range(n_leaves))
    return sorted(kept)


def donation_report(
    compiled: Any, args: tuple, donate_argnums: tuple, hlo_text: str | None = None
) -> dict:
    """Donated-leaf vs actually-aliased audit of one compiled program.

    Flattens ``args`` the way jit does (donated argument *leaves* occupy a
    contiguous range of flat parameter numbers per argument), maps through
    the executable's kept-argument set (pruned leaves hold no buffer and
    cannot double-buffer), parses the compiled module's
    ``input_output_alias`` header, and reports every donated leaf whose
    compiled parameter number is not aliased to any output. Returns
    ``{"n_donated", "n_aliased", "n_pruned", "undonated"}`` where
    ``undonated`` names each unaliased leaf by argument index and flat
    offset — an undonated-in-practice buffer is exactly the
    double-buffering GC005's AST check cannot see. ``n_donated ==
    n_aliased + n_pruned`` when the audit is clean. ``hlo_text`` lets
    callers that already serialized the optimized module pass it in
    (``compiled.as_text()`` is not cheap at fleet scale).
    """
    import jax

    if hlo_text is None:
        hlo_text = compiled.as_text()
    header = next(
        (l for l in hlo_text.splitlines() if "input_output_alias=" in l),
        "",
    )
    aliased_params = {int(m.group(1)) for m in _ALIAS_ENTRY_RE.finditer(header)}

    flat_ranges: list[tuple[int, int]] = []  # per-arg (start, stop) flat leaf range
    pos = 0
    for a in args:
        n = len(jax.tree_util.tree_leaves(a))
        flat_ranges.append((pos, pos + n))
        pos += n
    kept = _kept_flat_indices(compiled, pos)
    kept_pos = {flat: i for i, flat in enumerate(kept)}  # flat -> compiled param no.

    donated_leaves = 0
    pruned = 0
    undonated: list[str] = []
    for argnum in donate_argnums:
        start, stop = flat_ranges[argnum]
        for flat in range(start, stop):
            donated_leaves += 1
            if flat not in kept_pos:
                pruned += 1
                continue
            if kept_pos[flat] not in aliased_params:
                undonated.append(
                    f"arg {argnum} leaf {flat - start} (compiled parameter {kept_pos[flat]})"
                )
    return {
        "n_donated": donated_leaves,
        "n_aliased": donated_leaves - pruned - len(undonated),
        "n_pruned": pruned,
        "undonated": undonated,
    }


def _normalized_spec(sharding: Any) -> tuple | None:
    """A NamedSharding's PartitionSpec as a trailing-None-free tuple, or
    ``None`` for shardings without a spec (single-device, GSPMD opaque)."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    out = list(spec)
    while out and out[-1] is None:
        out.pop()
    return tuple(out)


def resharding_report(compiled: Any, args: tuple) -> list[str]:
    """Declared argument shardings vs the compiled executable's layouts.

    Walks the flattened arguments beside ``compiled.input_shardings``; every
    leaf whose declared ``NamedSharding`` spec differs from the spec the
    executable expects is an implicit reshard: jax will silently copy that
    argument to the compiled layout on every dispatch. Leaves without a
    declared NamedSharding (host numpy, single-device arrays) are skipped —
    there is nothing declared to diff against.
    """
    import jax

    leaves = jax.tree_util.tree_leaves(args)
    kept = _kept_flat_indices(compiled, len(leaves))
    leaves = [leaves[i] for i in kept if i < len(leaves)]
    compiled_in = jax.tree_util.tree_leaves(compiled.input_shardings[0])
    problems: list[str] = []
    if len(leaves) != len(compiled_in):
        return [
            f"argument flattening mismatch: {len(leaves)} kept leaves vs "
            f"{len(compiled_in)} compiled input shardings (analyzer skew)"
        ]
    for i, (leaf, got) in enumerate(zip(leaves, compiled_in)):
        declared = _normalized_spec(getattr(leaf, "sharding", None))
        actual = _normalized_spec(got)
        if declared is None or actual is None:
            continue
        if declared != actual:
            problems.append(
                f"flat arg {i}: declared PartitionSpec{declared} but the "
                f"compiled program expects PartitionSpec{actual} — every "
                "dispatch reshards this argument"
            )
    return problems


def compare_memory(
    report: dict,
    budget: dict,
    rel_tol: float = 0.10,
    abs_slack: int = 1 << 20,
) -> list[str]:
    """Gates a `memory_report` against its committed ``MEMORY.json`` entry.

    ``peak_hbm_bytes`` must stay within ``budget * (1 + rel_tol) +
    abs_slack``; shrinking never fails (refresh the budget). The breakdown
    fields are informational — temp bytes move with XLA scheduling choices,
    but the peak is the number serving capacity is planned against.
    """
    problems: list[str] = []
    have = int(report.get("peak_hbm_bytes", 0))
    want = int(budget.get("peak_hbm_bytes", 0))
    if have > want * (1.0 + rel_tol) + abs_slack:
        problems.append(
            f"peak HBM {have}B exceeds committed budget {want}B "
            f"(+{rel_tol:.0%} + {abs_slack}B slack)"
        )
    return problems


def check_hbm_fit(report: dict, hbm_budget_gb: float, expect_fit: bool, label: str) -> list[str]:
    """Asserts a program's peak HBM lands on the expected side of the chip
    budget. ``expect_fit=False`` is the negative control: the width-4096
    replicated layout MUST fail a 16 GB chip — if it suddenly "fits", the
    analyzer (or the layout) broke, and trusting it would OOM real silicon.
    """
    peak = int(report.get("peak_hbm_bytes", 0))
    budget = int(hbm_budget_gb * 1e9)
    fits = peak <= budget
    if fits and not expect_fit:
        return [
            f"{label}: peak HBM {peak / 1e9:.2f} GB unexpectedly fits the "
            f"{hbm_budget_gb:g} GB budget — this layout is the analyzer's "
            "negative control and must exceed it"
        ]
    if not fits and expect_fit:
        return [
            f"{label}: peak HBM {peak / 1e9:.2f} GB exceeds the "
            f"{hbm_budget_gb:g} GB chip budget"
        ]
    return []
