"""Tier A of graftcheck: JAX-aware AST lint rules (GC001-GC005).

Pure stdlib — no jax import — so the whole package lints in well under a
second. The rules encode the TPU footguns that runtime tests only catch
after they've burned real accelerator time:

* **GC001** host-sync calls (``.item()``, ``float()``, ``np.asarray``,
  ``jax.device_get``, ``.block_until_ready()``) reachable from traced scopes
  (jit/scan/vmap bodies) or lexically inside a loop that dispatches a known
  jitted callable (the epoch hot loop). Inside a trace these are a
  ``ConcretizationTypeError`` waiting to happen or a silent callback; inside
  the dispatch loop they stall the pipeline on a device round trip per step.
* **GC002** float64 dtype creep outside the host-side preprocessing
  allowlist. TPUs emulate f64 at a many-fold slowdown; one stray
  ``np.float64`` in a traced constant silently doubles a table's HBM.
* **GC003** PRNG key reuse: a key variable consumed twice (or consumed in a
  loop without an intervening ``split``/``fold_in`` reassignment) produces
  correlated randomness — the classic silent-statistics bug.
* **GC004** Python ``if``/``while`` on traced values in traced scopes:
  either a tracer-boolean error at runtime or, with shape-dependent values,
  a recompile per distinct value.
* **GC005** a train-step ``jax.jit`` without ``donate_argnums``: the
  optimizer state is double-buffered and peak HBM nearly doubles.

Three rules (the Tier D determinism lint) are scoped to ``serving/`` —
the control plane whose contract is bitwise schedule-invariance, so ANY
nondeterminism in a decision path is a results bug, not a style nit:

* **GC006** iteration over a set/frozenset feeding serving decisions
  (placement, admission, eviction order). Set iteration order varies
  per process (``PYTHONHASHSEED``); wrap in ``sorted(...)``. Membership
  tests are fine — only iteration is flagged.
* **GC007** nondeterministic sources in serving code: builtin ``hash()``
  (process-salted), wall-clock reads (``time.time``/``time_ns``,
  ``datetime.now``/``utcnow``), the global ``random`` module,
  ``os.urandom``, ``uuid.uuid4``. Use ``router.stable_hash``, injected
  logical clocks, and derived PRNG keys. ``time.perf_counter`` /
  ``time.monotonic`` are sanctioned (latency measurement, never a
  decision input).
* **GC008** block-ledger discipline: ``.alloc``/``.incref``/``.decref``/
  ``.reset_occupancy`` on a ``_block_alloc`` (or touching its ``_free``/
  ``_rc`` internals) outside the sanctioned owners — the allocator class
  itself, ``_plan_admission_tables``, ``_free_slot_blocks``, ``reset``.
  Unpaired alloc/free scattered through the control plane is how
  double-frees are born; `serving.sanitizer` catches them at runtime,
  this rule catches the call site at review time.

Scope analysis is intentionally heuristic (module-local call graph +
lexical nesting + simple local-variable dataflow); precision comes from the
checked-in baseline (``analysis/baseline.json`` suppresses pre-existing
findings while new ones fail) and inline waivers::

    x = float(loss)  # graftcheck: allow GC001 -- epoch-end flush, pipeline already drained

See ``docs/analysis.md`` for the rule catalog and fix patterns.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path

__all__ = [
    "Finding",
    "RULES",
    "lint_source",
    "lint_paths",
    "default_targets",
    "load_baseline",
    "save_baseline",
    "apply_baseline",
    "prune_baseline",
]

RULES: dict[str, str] = {
    "GC001": "host-sync call reachable from a traced scope or jitted-dispatch loop",
    "GC002": "float64 dtype outside the host-side preprocessing allowlist",
    "GC003": "PRNG key consumed twice without an intervening split/fold_in",
    "GC004": "Python if/while on a traced value inside a traced scope",
    "GC005": "state-updating jit (train/fine-tune step, decode/prefill/dispatch) without donate_argnums",
    "GC006": "iteration over an unordered set in a serving decision path",
    "GC007": "nondeterministic source (hash/wall-clock/random/uuid) in serving code",
    "GC008": "block alloc/free outside the sanctioned ledger owners",
}

# GC006-GC008 only run on the serving control plane (the code whose
# contract is bitwise schedule-invariance).
_SERVING_PATH_RE = re.compile(r"(^|/)serving/")

# GC007 vocabulary. Dotted prefixes are matched against the full chain;
# `perf_counter`/`monotonic` are deliberately absent (latency measurement
# is sanctioned — it must never feed a decision, which GC006/Tier D catch).
_NONDET_DOTTED = {
    "time.time": "wall-clock read — serving decisions take an injected logical clock",
    "time.time_ns": "wall-clock read — serving decisions take an injected logical clock",
    "datetime.now": "wall-clock read — serving decisions take an injected logical clock",
    "datetime.utcnow": "wall-clock read — serving decisions take an injected logical clock",
    "datetime.datetime.now": "wall-clock read — serving decisions take an injected logical clock",
    "datetime.datetime.utcnow": "wall-clock read — serving decisions take an injected logical clock",
    "os.urandom": "OS entropy — derive from the engine's PRNG key instead",
    "uuid.uuid4": "random UUID — derive ids from admission indices or stable_hash",
}
_NONDET_MODULE_ROOTS = {
    "random": "the global `random` module is seeded per process — use numpy "
    "Generator with a fixed seed or a derived jax PRNG key",
}

# GC008: the ledger mutators, and the scopes allowed to call them.
_LEDGER_METHODS = {"alloc", "incref", "decref", "reset_occupancy"}
_LEDGER_INTERNALS = {"_free", "_rc"}
_LEDGER_OWNER_FUNCS = {"_plan_admission_tables", "_free_slot_blocks", "reset"}

# GC005 trigger vocabulary: jits of state-updating steps. "train" covers the
# pretrain AND fine-tune step factories (both jit `*train_step*` bodies);
# decode/prefill/dispatch cover the serving engine's and service's hot-loop
# jits, whose undonated state would double-buffer every slot's KV cache.
_GC005_NAME_RE = re.compile(r"train|decode|prefill|dispatch|finetune|fine_tune")

# Paths where f64 is the *point* (pandas/preprocessing fit statistics run
# host-side at full precision; synthetic data generation is host-only).
F64_ALLOWLIST_DIRS = ("data/preprocessing/",)
# serving/ingest.py is the online-admission TRANSFORM — the same host-side
# numpy/pandas preprocessing the batch ETL runs (and must stay bit-identical
# to it, f64 timestamps included); it never enters a traced scope (gated by
# TestIngestPathGate).
F64_ALLOWLIST_FILES = ("dataset_pandas.py", "synthetic.py", "ingest.py")

# jax transforms whose function arguments execute under a trace.
_TRACING_TRANSFORMS = {
    "jit", "vmap", "pmap", "grad", "value_and_grad", "checkpoint", "remat",
    "custom_vjp", "custom_jvp", "named_call",
    "scan", "while_loop", "cond", "switch", "map", "fori_loop",
    "associative_scan",
}
_JIT_NAMES = {"jit"}  # jax.jit / nn.jit / plain jit

_SYNC_ATTR_METHODS = {
    "item": "`.item()` blocks on a device->host readback",
    "block_until_ready": "`.block_until_ready()` blocks the host on the device stream",
}
_SYNC_DOTTED = {
    "np.asarray": "`np.asarray` on a device array forces a host transfer",
    "np.array": "`np.array` on a device array forces a host transfer",
    "numpy.asarray": "`numpy.asarray` on a device array forces a host transfer",
    "numpy.array": "`numpy.array` on a device array forces a host transfer",
    "jax.device_get": "`jax.device_get` is an explicit device->host transfer",
    "jax.block_until_ready": "`jax.block_until_ready` blocks the host on the device stream",
}
_SYNC_BUILTINS = {
    "float": "`float()` on a device value blocks on a host readback",
}

# Attribute accesses that yield static (trace-time) metadata, not values.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding", "weak_type"}
_STATIC_BUILTIN_CALLS = {"len", "isinstance", "getattr", "hasattr", "type", "id", "callable"}

_KEY_PRODUCERS = {"PRNGKey", "key", "split", "fold_in", "clone", "wrap_key_data"}
_KEY_PARAM_RE = re.compile(r"(^|_)(rng|key|prng_key)s?$")

_ALLOW_RE = re.compile(r"graftcheck:\s*allow\s*(?P<rules>GC\d{3}(?:\s*,\s*GC\d{3})*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, keyed for baselining by (path, rule, snippet)."""

    path: str  # repo-relative posix path
    line: int
    col: int
    rule: str
    message: str
    hint: str
    snippet: str  # stripped source line, the line-number-stable baseline key

    def key(self) -> tuple[str, str, str]:
        return (self.path, self.rule, self.snippet)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}\n    fix: {self.hint}"


def _dotted(node: ast.AST) -> str | None:
    """``jax.lax.scan`` -> "jax.lax.scan" for Name/Attribute chains."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _tail(dotted: str | None) -> str | None:
    return dotted.rsplit(".", 1)[-1] if dotted else None


class _Func:
    """A function scope: AST node + lexical parent + analysis state."""

    def __init__(self, node, name: str, parent: "_Func | None"):
        self.node = node
        self.name = name
        self.parent = parent
        self.children: list[_Func] = []  # lexically nested defs
        self.traced = False
        self.returned_funcs: list[_Func] = []  # nested defs this factory returns
        self.returns_jitted = False  # returns jax.jit(...) directly
        # local name -> _Func whose returned_funcs the value aliases
        self.factory_vars: dict[str, "_Func"] = {}
        # local names bound to jitted callables (jax.jit(...) results or
        # calls of factories that return one)
        self.jitted_vars: set[str] = set()
        self.call_targets: list["_Func"] = []  # resolved same-module callees

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"_Func({self.name}, traced={self.traced})"


def _own_walk(func_node: ast.AST):
    """Walks a function's *own* statements, not nested function bodies."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _walk_shallow(root: ast.AST):
    """Walks a subtree (root included) without descending into nested
    function bodies — a callback defined inside a loop only executes if
    called, and calls are what the loop scan follows."""
    yield root
    yield from _own_walk(root)


class _Module:
    """Module-level index: function scopes, traced-set, jitted locals."""

    def __init__(self, tree: ast.Module, path: str, src_lines: list[str]):
        self.tree = tree
        self.path = path
        self.src_lines = src_lines
        self.funcs: list[_Func] = []
        self.by_node: dict[ast.AST, _Func] = {}
        self.module_jitted: set[str] = set()
        self._index(tree, parent=None)
        for f in self.funcs:
            self._analyze_locals(f)
        self._mark_traced()

    # ---------------------------------------------------------------- index
    def _index(self, node: ast.AST, parent: _Func | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                name = getattr(child, "name", "<lambda>")
                f = _Func(child, name, parent)
                self.funcs.append(f)
                self.by_node[child] = f
                if parent is not None:
                    parent.children.append(f)
                self._index(child, f)
            elif isinstance(child, ast.ClassDef):
                # methods belong to no enclosing function scope
                self._index(child, None)
            else:
                self._index(child, parent)

    def resolve(self, scope: _Func | None, name: str) -> _Func | None:
        """Lexical lookup of ``name`` among nested/module-level defs."""
        f = scope
        while f is not None:
            for c in f.children:
                if c.name == name:
                    return c
            if f.name == name:
                return f
            f = f.parent
        for c in self.funcs:
            if c.parent is None and c.name == name:
                return c
        return None

    # --------------------------------------------------- local var dataflow
    def _is_jit_call(self, call: ast.Call) -> bool:
        return _tail(_dotted(call.func)) in _JIT_NAMES

    def _factory_for_value(self, scope: _Func, value: ast.AST) -> _Func | None:
        """The _Func whose returned functions ``value`` evaluates to."""
        if isinstance(value, ast.Call):
            g = None
            if isinstance(value.func, ast.Name):
                g = self.resolve(scope, value.func.id)
            if g is not None and g.returned_funcs:
                return g
        return None

    def _value_is_jitted(self, scope: _Func | None, value: ast.AST) -> bool:
        if isinstance(value, ast.Call):
            if self._is_jit_call(value):
                return True
            if isinstance(value.func, ast.Name):
                g = self.resolve(scope, value.func.id)
                if g is not None and g.returns_jitted:
                    return True
        if isinstance(value, ast.Name) and scope is not None:
            f = scope
            while f is not None:
                if value.id in f.jitted_vars:
                    return True
                f = f.parent
            return value.id in self.module_jitted
        return False

    def _analyze_locals(self, f: _Func) -> None:
        # returned funcs / returns_jitted
        for node in _own_walk(f.node):
            if isinstance(node, ast.Return) and node.value is not None:
                if isinstance(node.value, ast.Name):
                    g = self.resolve(f, node.value.id)
                    if g is not None and g in f.children:
                        f.returned_funcs.append(g)
                if isinstance(node.value, ast.Call) and self._is_jit_call(node.value):
                    f.returns_jitted = True
        for node in _own_walk(f.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                tname = node.targets[0].id
                if self._value_is_jitted(f, node.value):
                    f.jitted_vars.add(tname)
                g = self._factory_for_value(f, node.value)
                if g is not None:
                    f.factory_vars[tname] = g
        # call edges from f's own code
        for node in _own_walk(f.node):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                g = self.resolve(f, node.func.id)
                if g is not None and g is not f:
                    f.call_targets.append(g)
                fac = f.factory_vars.get(node.func.id)
                if fac is not None:
                    f.call_targets.extend(fac.returned_funcs)

    def module_own_walk(self):
        """Walks module-level code, not descending into function bodies."""
        stack = list(ast.iter_child_nodes(self.tree))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _module_scope_jitted(self) -> None:
        for node in ast.iter_child_nodes(self.tree):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and self._value_is_jitted(None, node.value)
            ):
                self.module_jitted.add(node.targets[0].id)

    # ------------------------------------------------------------ traced set
    def _transform_fn_args(self, call: ast.Call) -> list[ast.AST]:
        """Function-valued arguments of a tracing-transform call."""
        name = _tail(_dotted(call.func))
        if name not in _TRACING_TRANSFORMS:
            # partial(jax.jit, ...)(f) style: treat partial over a transform
            # as the transform itself.
            if (
                isinstance(call.func, ast.Call)
                and _tail(_dotted(call.func.func)) in ("partial",)
                and call.func.args
                and _tail(_dotted(call.func.args[0])) in _TRACING_TRANSFORMS
            ):
                return list(call.args)
            return []
        return list(call.args) + [kw.value for kw in call.keywords]

    def _mark_traced(self) -> None:
        self._module_scope_jitted()
        roots: list[_Func] = []

        def mark_value(scope: _Func | None, value: ast.AST) -> None:
            if isinstance(value, ast.Name):
                g = self.resolve(scope, value.id)
                if g is not None:
                    roots.append(g)
                elif scope is not None:
                    fac = scope.factory_vars.get(value.id)
                    if fac is not None:
                        roots.extend(fac.returned_funcs)
            elif isinstance(value, ast.Lambda):
                g = self.by_node.get(value)
                if g is not None:
                    roots.append(g)
            elif isinstance(value, ast.Call):
                fac = None
                if isinstance(value.func, ast.Name) and scope is not None:
                    fac = self.resolve(scope, value.func.id)
                elif isinstance(value.func, ast.Name):
                    fac = self.resolve(None, value.func.id)
                if fac is not None:
                    roots.extend(fac.returned_funcs)

        # decorator roots
        for f in self.funcs:
            for dec in getattr(f.node, "decorator_list", []):
                d = dec.func if isinstance(dec, ast.Call) else dec
                names = {_tail(_dotted(d))}
                if isinstance(dec, ast.Call):
                    names |= {_tail(_dotted(a)) for a in dec.args}
                if names & _TRACING_TRANSFORMS:
                    roots.append(f)
        # transform-call roots (module and function scopes)
        for node in self.module_own_walk():
            if isinstance(node, ast.Call):
                for arg in self._transform_fn_args(node):
                    mark_value(None, arg)
        for f in self.funcs:
            for node in _own_walk(f.node):
                if isinstance(node, ast.Call):
                    for arg in self._transform_fn_args(node):
                        mark_value(f, arg)

        # propagate: traced f => nested defs + same-module callees traced
        work = list(roots)
        while work:
            f = work.pop()
            if f.traced:
                continue
            f.traced = True
            work.extend(f.children)
            work.extend(f.call_targets)


# ---------------------------------------------------------------- rule checks
class _Linter:
    def __init__(self, src: str, path: str):
        self.src = src
        self.path = path
        self.lines = src.splitlines()
        self.findings: list[Finding] = []
        self.tree = ast.parse(src, filename=path)
        _annotate_assign_names(self.tree)
        self.mod = _Module(self.tree, path, self.lines)
        self.allowed = self._parse_allows()

    def _parse_allows(self) -> dict[int, set[str]]:
        allows: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _ALLOW_RE.search(line)
            if m:
                allows[i] = {r.strip() for r in m.group("rules").split(",")}
        return allows

    def add(self, node: ast.AST, rule: str, message: str, hint: str) -> None:
        line = getattr(node, "lineno", 1)
        if rule in self.allowed.get(line, ()):
            return
        snippet = self.lines[line - 1].strip() if line - 1 < len(self.lines) else ""
        self.findings.append(
            Finding(self.path, line, getattr(node, "col_offset", 0), rule, message, hint, snippet)
        )

    def run(self) -> list[Finding]:
        self.check_gc001()
        self.check_gc002()
        self.check_gc003()
        self.check_gc004()
        self.check_gc005()
        if _SERVING_PATH_RE.search(self.path.replace("\\", "/")):
            self.check_gc006()
            self.check_gc007()
            self.check_gc008()
        # The loop scan can reach one site via several paths (direct + shared
        # helpers) — one site, one finding.
        seen: set[tuple[int, int, str]] = set()
        unique: list[Finding] = []
        for f in sorted(self.findings, key=lambda f: (f.line, f.col, f.rule)):
            if (f.line, f.col, f.rule) not in seen:
                seen.add((f.line, f.col, f.rule))
                unique.append(f)
        self.findings = unique
        return self.findings

    # ------------------------------------------------------------- GC001
    def _sync_call(self, node: ast.Call) -> str | None:
        if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_ATTR_METHODS:
            return _SYNC_ATTR_METHODS[node.func.attr]
        dotted = _dotted(node.func)
        if dotted in _SYNC_DOTTED:
            return _SYNC_DOTTED[dotted]
        if isinstance(node.func, ast.Name) and node.func.id in _SYNC_BUILTINS:
            # float(CONSTANT) / float("inf") are host-only literals, not syncs.
            if node.args and isinstance(node.args[0], ast.Constant):
                return None
            return _SYNC_BUILTINS[node.func.id]
        return None

    def check_gc001(self) -> None:
        hint_traced = (
            "keep values on device inside traced code; compute reductions with jnp and "
            "read results back outside the jitted scope"
        )
        hint_loop = (
            "buffer device scalars (e.g. losses) and convert once per epoch/window flush "
            "after the dispatch queue drains; see training/pretrain.py pending-log pattern"
        )
        for f in self.mod.funcs:
            if not f.traced:
                continue
            for node in _own_walk(f.node):
                if isinstance(node, ast.Call):
                    why = self._sync_call(node)
                    if why:
                        self.add(
                            node, "GC001",
                            f"host sync in traced scope `{f.name}`: {why}",
                            hint_traced,
                        )
        # dispatch-loop scan: loops that call a known jitted callable
        for f in self.mod.funcs:
            if f.traced:
                continue
            jitted = set(self.mod.module_jitted)
            g: _Func | None = f
            while g is not None:
                jitted |= g.jitted_vars
                g = g.parent
            if not jitted:
                continue
            for node in _own_walk(f.node):
                if not isinstance(node, (ast.For, ast.While)):
                    continue
                called, helper_funcs = self._loop_calls(f, node)
                if not (called & jitted):
                    continue
                self._scan_loop_syncs(node, f.name, hint_loop)
                for h in helper_funcs:
                    if not h.traced:
                        self._scan_loop_syncs(h.node, f.name, hint_loop, helper=h.name)

    def _loop_calls(self, f: _Func, loop: ast.AST) -> tuple[set[str], list[_Func]]:
        """Names called in a loop body + local helper funcs reached from it."""
        called: set[str] = set()
        helpers: list[_Func] = []
        seen: set[_Func] = set()
        stack = [loop]
        while stack:
            scope_node = stack.pop()
            walker = (
                _walk_shallow(scope_node) if scope_node is loop else _own_walk(scope_node)
            )
            for node in walker:
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    called.add(node.func.id)
                    h = self.mod.resolve(f, node.func.id)
                    if h is not None and h.parent is not None and h not in seen:
                        seen.add(h)
                        helpers.append(h)
                        stack.append(h.node)
        return called, helpers

    def _scan_loop_syncs(self, scope_node, loop_fn: str, hint: str, helper: str | None = None):
        where = f"jitted-dispatch loop in `{loop_fn}`" + (
            f" (via helper `{helper}`)" if helper else ""
        )
        it = (
            _own_walk(scope_node)
            if isinstance(scope_node, (ast.FunctionDef, ast.AsyncFunctionDef))
            else _walk_shallow(scope_node)
        )
        for node in it:
            if isinstance(node, ast.Call):
                why = self._sync_call(node)
                if why:
                    self.add(node, "GC001", f"host sync inside {where}: {why}", hint)

    # ------------------------------------------------------------- GC002
    def _f64_allowlisted(self) -> bool:
        p = self.path.replace("\\", "/")
        if any(d in p for d in F64_ALLOWLIST_DIRS):
            return True
        return p.rsplit("/", 1)[-1] in F64_ALLOWLIST_FILES

    def check_gc002(self) -> None:
        if self._f64_allowlisted():
            return
        hint = (
            "use float32 (or bf16) on the accelerator path; f64 belongs only in "
            "host-side preprocessing (data/preprocessing/, dataset_pandas.py, "
            "synthetic.py, serving/ingest.py)"
        )
        f64_strs = {"float64", "f8", ">f8", "<f8", "double"}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute) and node.attr in ("float64", "double"):
                root = _dotted(node)
                if root and root.split(".")[0] in ("np", "numpy", "jnp", "jax"):
                    self.add(node, "GC002", f"float64 dtype `{root}`", hint)
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (
                        kw.arg == "dtype"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value in f64_strs
                    ):
                        self.add(node, "GC002", f'float64 dtype string "{kw.value.value}"', hint)
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and node.args
                ):
                    a = node.args[0]
                    if isinstance(a, ast.Constant) and a.value in f64_strs:
                        self.add(node, "GC002", f'astype("{a.value}")', hint)
                if (
                    _dotted(node.func) == "jax.config.update"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value == "jax_enable_x64"
                ):
                    self.add(node, "GC002", "jax_enable_x64 flips every default dtype to f64", hint)

    # ------------------------------------------------------------- GC003
    def check_gc003(self) -> None:
        for f in self.mod.funcs:
            self._scan_keys(f)

    def _scan_keys(self, f: _Func) -> None:
        hint = (
            "split before each consumption: `key, sub = jax.random.split(key)` (or "
            "`fold_in` on a loop counter) so no key is sampled from twice"
        )
        uses_jax_random = any(
            isinstance(n, ast.Call)
            and (_dotted(n.func) or "").startswith(("jax.random.", "jr.", "jrandom."))
            for n in _own_walk(f.node)
        )
        key_vars: dict[str, int] = {}  # name -> uses since last (re)split
        node_ref = f.node
        if uses_jax_random and not isinstance(node_ref, ast.Lambda):
            for arg in list(node_ref.args.args) + list(node_ref.args.kwonlyargs):
                if _KEY_PARAM_RE.search(arg.arg):
                    key_vars[arg.arg] = 0
        reported: set[tuple[int, str]] = set()

        def is_producer(value: ast.AST) -> bool:
            if not isinstance(value, ast.Call):
                return False
            d = _dotted(value.func)
            if d is None:
                return False
            parts = d.split(".")
            return parts[-1] in _KEY_PRODUCERS and (
                len(parts) == 1 or "random" in parts or parts[0] in ("jr", "jrandom")
            )

        def walk_to_calls(node: ast.AST):
            """Yields nodes of an arg subtree, stopping at nested calls and
            function bodies (nested calls count their own args separately)
            and at subscripts (``ks[0]``/``ks[1]`` from one split are
            distinct keys, not reuse of ``ks``)."""
            stack = [node]
            while stack:
                n = stack.pop()
                yield n
                if isinstance(n, (ast.Call, ast.FunctionDef, ast.Lambda, ast.Subscript)):
                    continue
                stack.extend(ast.iter_child_nodes(n))

        def record_uses(expr: ast.AST, state: dict[str, int]) -> None:
            for node in ast.walk(expr):
                if not isinstance(node, ast.Call):
                    continue
                # `fold_in(key, data)` is the sanctioned re-derivation idiom
                # (fresh stream per distinct data) — not a consumption.
                if _tail(_dotted(node.func)) in ("fold_in", "clone"):
                    continue
                for sub in list(node.args) + [kw.value for kw in node.keywords]:
                    for leaf in walk_to_calls(sub):
                        if (
                            isinstance(leaf, ast.Name)
                            and isinstance(leaf.ctx, ast.Load)
                            and leaf.id in state
                        ):
                            state[leaf.id] += 1
                            if state[leaf.id] > 1 and (leaf.lineno, leaf.id) not in reported:
                                reported.add((leaf.lineno, leaf.id))
                                self.add(
                                    leaf, "GC003",
                                    f"PRNG key `{leaf.id}` consumed again without an "
                                    "intervening split/fold_in",
                                    hint,
                                )

        def assign_targets(targets, value, state: dict[str, int]) -> None:
            names: list[str] = []
            for t in targets:
                if isinstance(t, ast.Name):
                    names.append(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    names.extend(e.id for e in t.elts if isinstance(e, ast.Name))
            if is_producer(value):
                for n in names:
                    state[n] = 0
            else:
                for n in names:
                    state.pop(n, None)

        # exec_* return a terminator kind: "return" (function exit, propagates
        # out of branch merges), "break" (absorbed by the enclosing loop), or
        # None. A branch that exits early must not leak its use counts into
        # the fall-through path — `if fast: return f(key)` + later uses of
        # `key` are alternatives, not reuse.
        def exec_stmt(st: ast.stmt, state: dict[str, int]) -> str | None:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                return None
            if isinstance(st, ast.Assign):
                record_uses(st.value, state)
                assign_targets(st.targets, st.value, state)
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                record_uses(st.value, state)
                assign_targets([st.target], st.value, state)
            elif isinstance(st, ast.If):
                record_uses(st.test, state)
                s_body, s_else = dict(state), dict(state)
                t_body = exec_block(st.body, s_body)
                t_else = exec_block(st.orelse, s_else)
                state.clear()
                if t_body and t_else:
                    state.update(s_body)
                    return "return" if "return" in (t_body, t_else) else t_body
                if t_body:
                    state.update(s_else)
                elif t_else:
                    state.update(s_body)
                else:
                    for k in set(s_body) | set(s_else):
                        if k in s_body and k in s_else:
                            state[k] = max(s_body[k], s_else[k])
                        else:
                            state[k] = s_body.get(k, s_else.get(k, 0))
            elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                if isinstance(st, ast.While):
                    record_uses(st.test, state)
                else:
                    record_uses(st.iter, state)
                # two abstract iterations: a key consumed each pass without a
                # split/fold_in reassignment inside the loop is reuse
                t = exec_block(st.body, state)
                if t is None:
                    t = exec_block(st.body, state)
                if t == "return":
                    return "return"
                exec_block(st.orelse, state)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    record_uses(item.context_expr, state)
                return exec_block(st.body, state)
            elif isinstance(st, ast.Try):
                t = exec_block(st.body, state)
                for h in st.handlers:
                    exec_block(h.body, dict(state))
                exec_block(st.orelse, state)
                exec_block(st.finalbody, state)
                return t
            elif isinstance(st, ast.Return):
                if st.value is not None:
                    record_uses(st.value, state)
                return "return"
            elif isinstance(st, ast.Raise):
                return "return"
            elif isinstance(st, (ast.Break, ast.Continue)):
                return "break"
            elif isinstance(st, ast.Expr):
                record_uses(st.value, state)
            elif isinstance(st, ast.AugAssign):
                record_uses(st.value, state)
            return None

        def exec_block(stmts, state: dict[str, int]) -> str | None:
            for st in stmts:
                t = exec_stmt(st, state)
                if t is not None:
                    return t
            return None

        body = f.node.body if not isinstance(f.node, ast.Lambda) else []
        exec_block(body, key_vars)

    # ------------------------------------------------------------- GC004
    def _traced_hits(self, expr: ast.AST, tainted: set[str]) -> list[ast.Name]:
        """Tainted names used as *values* (not static metadata) in ``expr``."""
        hits: list[ast.Name] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load) and node.id in tainted:
                    hits.append(node)
            elif isinstance(node, ast.Attribute):
                if node.attr in _STATIC_ATTRS:
                    return  # x.shape / x.ndim / x.dtype are trace-time facts
                # plain attribute data access (configs, dataclass fields) is
                # treated as static; calls on attributes are handled below
                return
            elif isinstance(node, ast.Call):
                fname = _tail(_dotted(node.func))
                if fname in _STATIC_BUILTIN_CALLS:
                    return
                if isinstance(node.func, ast.Attribute):
                    # x.sum() / x.any(): the receiver is consumed as a value
                    visit_value(node.func.value)
                for a in node.args:
                    visit(a)
                for kw in node.keywords:
                    visit(kw.value)
            elif isinstance(node, ast.Compare):
                if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                    return  # `x is None` identity checks are static
                for child in [node.left, *node.comparators]:
                    visit(child)
            elif isinstance(node, ast.Subscript):
                visit_value(node.value)
                visit(node.slice)
            else:
                for child in ast.iter_child_nodes(node):
                    visit(child)

        def visit_value(node: ast.AST) -> None:
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load) and node.id in tainted:
                    hits.append(node)
            else:
                visit(node)

        visit(expr)
        return hits

    def _has_jax_call(self, expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d and d.split(".")[0] in ("jnp", "jax", "lax", "nn"):
                    return True
        return False

    def _static_jit_params(self, f: _Func) -> set:
        """Params a jit decorator declares static (names and argnums)."""
        static: set = set()
        for dec in getattr(f.node, "decorator_list", []):
            if not isinstance(dec, ast.Call):
                continue
            names = {_tail(_dotted(dec.func))}
            names |= {_tail(_dotted(a)) for a in dec.args}
            if not (names & _JIT_NAMES):
                continue
            for kw in dec.keywords:
                if kw.arg in ("static_argnames", "static_argnums"):
                    vals = (
                        kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value]
                    )
                    for v in vals:
                        if isinstance(v, ast.Constant):
                            static.add(v.value)
        return static

    def check_gc004(self) -> None:
        hint = (
            "branch on trace-time facts only (shapes, dtypes, config flags) or use "
            "jnp.where / jax.lax.cond / jax.lax.while_loop for value-dependent control flow"
        )
        for f in self.mod.funcs:
            if not f.traced or isinstance(f.node, ast.Lambda):
                continue
            static_params = self._static_jit_params(f)
            tainted = set()
            args = (
                list(f.node.args.posonlyargs)
                + list(f.node.args.args)
                + list(f.node.args.kwonlyargs)
            )
            for i, a in enumerate(args):
                if a.arg in ("self", "cls") or a.arg in static_params:
                    continue
                if i in static_params:
                    continue
                # plain-Python annotations are static by construction
                ann = getattr(a.annotation, "id", None)
                if ann in ("str", "bool", "int", "float"):
                    continue
                tainted.add(a.arg)
            for node in _own_walk(f.node):
                if isinstance(node, ast.Assign):
                    is_traced_val = bool(self._traced_hits(node.value, tainted)) or (
                        self._has_jax_call(node.value)
                    )
                    for t in node.targets:
                        names = (
                            [t.id]
                            if isinstance(t, ast.Name)
                            else [e.id for e in getattr(t, "elts", []) if isinstance(e, ast.Name)]
                        )
                        for n in names:
                            (tainted.add if is_traced_val else tainted.discard)(n)
            for node in _own_walk(f.node):
                if isinstance(node, (ast.If, ast.While)):
                    hits = self._traced_hits(node.test, tainted)
                    if hits:
                        kind = "while" if isinstance(node, ast.While) else "if"
                        self.add(
                            node, "GC004",
                            f"Python `{kind}` on traced value `{hits[0].id}` in traced "
                            f"scope `{f.name}`",
                            hint,
                        )

    # ------------------------------------------------------------- GC006
    def check_gc006(self) -> None:
        hint = (
            "set iteration order varies per process (PYTHONHASHSEED); wrap in "
            "sorted(...) so placement/admission order is a pure function of the "
            "request stream"
        )

        def is_set_expr(node: ast.AST, local_sets: set[str]) -> bool:
            if isinstance(node, (ast.Set, ast.SetComp)):
                return True
            if isinstance(node, ast.Call) and _tail(_dotted(node.func)) in (
                "set", "frozenset"
            ):
                return True
            if isinstance(node, ast.Name):
                return node.id in local_sets
            if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
            ):
                return is_set_expr(node.left, local_sets) or is_set_expr(
                    node.right, local_sets
                )
            return False

        def scan(walker) -> None:
            nodes = list(walker)
            local_sets: set[str] = set()
            assigns = [
                node
                for node in nodes
                if isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ]
            # The walkers are stack-based (reverse order); fold assignments
            # in SOURCE order so `ready = sorted(ready)` discards the
            # earlier `ready = set(...)` binding, not the other way round.
            for node in sorted(assigns, key=lambda n: (n.lineno, n.col_offset)):
                if is_set_expr(node.value, local_sets):
                    local_sets.add(node.targets[0].id)
                else:
                    local_sets.discard(node.targets[0].id)
            for node in nodes:
                iters: list[ast.AST] = []
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    iters.append(node.iter)
                elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
                    iters.extend(g.iter for g in node.generators)
                for it in iters:
                    if is_set_expr(it, local_sets):
                        what = (
                            f"`{it.id}`" if isinstance(it, ast.Name) else "a set expression"
                        )
                        self.add(
                            node, "GC006",
                            f"iteration over unordered set {what} in serving code",
                            hint,
                        )

        scan(self.mod.module_own_walk())
        for f in self.mod.funcs:
            scan(_own_walk(f.node))

    # ------------------------------------------------------------- GC007
    def check_gc007(self) -> None:
        hint = (
            "serving results must be bitwise schedule-invariant: use "
            "router.stable_hash for hashing, an injected logical clock for time, "
            "and keys derived from the engine's base_key for randomness"
        )
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name) and node.func.id == "hash":
                self.add(
                    node, "GC007",
                    "builtin `hash()` is salted per process (PYTHONHASHSEED) — "
                    "placement keyed on it reshuffles every restart",
                    hint,
                )
                continue
            dotted = _dotted(node.func)
            if dotted is None:
                continue
            if dotted in _NONDET_DOTTED:
                self.add(node, "GC007", f"`{dotted}`: {_NONDET_DOTTED[dotted]}", hint)
                continue
            root = dotted.split(".")[0]
            if root in _NONDET_MODULE_ROOTS and "." in dotted:
                self.add(node, "GC007", f"`{dotted}`: {_NONDET_MODULE_ROOTS[root]}", hint)

    # ------------------------------------------------------------- GC008
    def check_gc008(self) -> None:
        hint = (
            "route block alloc/free through the sanctioned owners "
            "(_plan_admission_tables, _free_slot_blocks, reset) so every alloc "
            "has exactly one paired release; serving.sanitizer verifies the "
            "pairing at runtime"
        )

        def is_allocator(node: ast.AST, aliases: set[str]) -> bool:
            if isinstance(node, ast.Attribute):
                return node.attr == "_block_alloc"
            if isinstance(node, ast.Name):
                return node.id in aliases or node.id == "_block_alloc"
            return False

        def scan(body: list[ast.stmt], cls_name: str | None, fn_name: str | None) -> None:
            sanctioned = (
                (cls_name is not None and "Allocator" in cls_name)
                or fn_name in _LEDGER_OWNER_FUNCS
            )
            aliases: set[str] = set()
            stack = list(body)
            nodes: list[ast.AST] = []
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scan(n.body, cls_name if fn_name is None else None, n.name)
                    continue
                if isinstance(n, ast.ClassDef):
                    scan(n.body, n.name, None)
                    continue
                nodes.append(n)
                stack.extend(ast.iter_child_nodes(n))
            for n in nodes:
                if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(
                    n.targets[0], ast.Name
                ):
                    if is_allocator(n.value, aliases):
                        aliases.add(n.targets[0].id)
            if sanctioned:
                return
            for n in nodes:
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _LEDGER_METHODS
                    and is_allocator(n.func.value, aliases)
                ):
                    where = f" in `{fn_name}`" if fn_name else ""
                    self.add(
                        n, "GC008",
                        f"block ledger call `.{n.func.attr}()`{where} outside the "
                        "sanctioned owners",
                        hint,
                    )
                elif (
                    isinstance(n, ast.Attribute)
                    and n.attr in _LEDGER_INTERNALS
                    and is_allocator(n.value, aliases)
                ):
                    where = f" in `{fn_name}`" if fn_name else ""
                    self.add(
                        n, "GC008",
                        f"direct touch of allocator internal `.{n.attr}`{where} — "
                        "the free list and refcounts belong to the allocator",
                        hint,
                    )

        scan(list(ast.iter_child_nodes(self.tree)), None, None)

    # ------------------------------------------------------------- GC005
    def check_gc005(self) -> None:
        hint = (
            "donate the mutated state: jax.jit(step, donate_argnums=(0,)) (train "
            "state) / donate_argnums=(1,) (engine decode/prefill state) so the "
            "update happens in place instead of double-buffering HBM"
        )

        def jit_target_names(call: ast.Call, scope: _Func | None) -> set[str]:
            names: set[str] = set()
            if call.args:
                candidates = [call.args[0]]
                # `jax.jit(self._decode_a if na else self._decode_b, ...)`:
                # both branches name the step.
                if isinstance(call.args[0], ast.IfExp):
                    candidates = [call.args[0].body, call.args[0].orelse]
                for a in candidates:
                    if isinstance(a, ast.Name):
                        names.add(a.id)
                    elif isinstance(a, ast.Attribute):
                        # `jax.jit(self._decode_chunk_ci)` — method steps on
                        # the engine/service classes.
                        names.add(a.attr)
                    elif isinstance(a, ast.Call):
                        t = _tail(_dotted(a.func))
                        if t:
                            names.add(t)
            return names

        scopes: list[tuple] = [(self.mod.module_own_walk(), None)]
        scopes += [(_own_walk(f.node), f) for f in self.mod.funcs]
        for walker, scope in scopes:
            for node in walker:
                if not isinstance(node, ast.Call) or _tail(_dotted(node.func)) not in _JIT_NAMES:
                    continue
                kwargs = {kw.arg for kw in node.keywords}
                if kwargs & {"donate_argnums", "donate_argnames"}:
                    continue
                names = jit_target_names(node, scope)
                # the assignment target also names the step
                parent_assign = getattr(node, "_gc_parent_assign", None)
                if parent_assign:
                    names |= parent_assign
                if any(_GC005_NAME_RE.search(n.lower()) for n in names):
                    self.add(
                        node, "GC005",
                        f"state-updating jit of `{'/'.join(sorted(names))}` without donation",
                        hint,
                    )
        # decorator form: @jax.jit on a def whose name says train/decode/...
        for f in self.mod.funcs:
            for dec in getattr(f.node, "decorator_list", []):
                d = dec.func if isinstance(dec, ast.Call) else dec
                if _tail(_dotted(d)) in _JIT_NAMES and _GC005_NAME_RE.search(f.name.lower()):
                    kwargs = (
                        {kw.arg for kw in dec.keywords} if isinstance(dec, ast.Call) else set()
                    )
                    if not (kwargs & {"donate_argnums", "donate_argnames"}):
                        self.add(
                            dec, "GC005",
                            f"state-updating jit of `{f.name}` without donation",
                            hint,
                        )


def _annotate_assign_names(tree: ast.Module) -> None:
    """Tags jit calls with their assignment-target names (for GC005).

    Attribute targets count too: ``self._decode_jit = jax.jit(...)`` names
    the step just as well as a local — the serving engine's dispatch jits
    are all attribute-bound."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            names = set()
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
                elif isinstance(t, ast.Attribute):
                    names.add(t.attr)
            if names:
                node.value._gc_parent_assign = names  # type: ignore[attr-defined]


# ------------------------------------------------------------------ public API
def lint_source(src: str, path: str = "<memory>") -> list[Finding]:
    """Lints one module's source; ``path`` keys findings and the f64 allowlist."""
    return _Linter(src, path).run()


def default_targets(repo_root: Path) -> list[Path]:
    """The lint scope: the package, the scripts, and the driver entry."""
    targets: list[Path] = []
    for rel in ("eventstreamgpt_tpu", "scripts"):
        d = repo_root / rel
        if d.is_dir():
            targets.extend(sorted(d.rglob("*.py")))
    entry = repo_root / "__graft_entry__.py"
    if entry.exists():
        targets.append(entry)
    return targets


def lint_paths(paths: list[Path], repo_root: Path) -> list[Finding]:
    findings: list[Finding] = []
    for p in paths:
        try:
            rel = p.resolve().relative_to(repo_root.resolve()).as_posix()
        except ValueError:  # outside the repo (ad-hoc file): absolute key
            rel = p.resolve().as_posix()
        findings.extend(lint_source(p.read_text(), rel))
    return findings


# ------------------------------------------------------------------- baseline
def load_baseline(fp: Path) -> dict[tuple[str, str, str], int]:
    if not Path(fp).exists():
        return {}
    data = json.loads(Path(fp).read_text())
    out: dict[tuple[str, str, str], int] = {}
    for rec in data.get("findings", []):
        out[(rec["path"], rec["rule"], rec["snippet"])] = int(rec.get("count", 1))
    return out


def _write_baseline_file(counts: dict[tuple[str, str, str], int], fp: Path) -> None:
    recs = [
        {"path": p, "rule": r, "snippet": s, "count": c}
        for (p, r, s), c in sorted(counts.items())
    ]
    Path(fp).write_text(
        json.dumps(
            {
                "note": (
                    "graftcheck lint baseline: pre-existing findings suppressed by key "
                    "(path, rule, snippet). New findings fail; shrink this file, never "
                    "grow it. Regenerate with scripts/graftcheck.py --write-baseline; "
                    "drop stale entries with scripts/graftcheck.py baseline --prune."
                ),
                "findings": recs,
            },
            indent=1,
        )
        + "\n"
    )


def save_baseline(findings: list[Finding], fp: Path) -> None:
    counts: dict[tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    _write_baseline_file(counts, fp)


def prune_baseline(
    findings: list[Finding], baseline: dict[tuple[str, str, str], int]
) -> tuple[dict[tuple[str, str, str], int], int]:
    """Drops baseline budget no current finding consumes.

    Returns ``(pruned baseline, stale count)``: each entry's count shrinks
    to the number of matching findings actually present (entries with no
    match disappear), and the stale count is the total suppression budget
    removed. Fixed findings otherwise leave their entries behind forever —
    dead budget a future regression at the same (path, rule, snippet) key
    would silently spend.
    """
    present: dict[tuple[str, str, str], int] = {}
    for f in findings:
        present[f.key()] = present.get(f.key(), 0) + 1
    pruned: dict[tuple[str, str, str], int] = {}
    stale = 0
    for key, count in baseline.items():
        keep = min(count, present.get(key, 0))
        stale += count - keep
        if keep:
            pruned[key] = keep
    return pruned, stale


def apply_baseline(
    findings: list[Finding], baseline: dict[tuple[str, str, str], int]
) -> tuple[list[Finding], int]:
    """Splits findings into (new, n_suppressed) under the baseline budget."""
    budget = dict(baseline)
    new: list[Finding] = []
    suppressed = 0
    for f in findings:
        if budget.get(f.key(), 0) > 0:
            budget[f.key()] -= 1
            suppressed += 1
        else:
            new.append(f)
    return new, suppressed
