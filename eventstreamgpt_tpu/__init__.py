"""EventStreamGPT-TPU: a TPU-native framework for generative modeling of event streams.

A from-scratch JAX/Flax/Pallas re-design with the full capabilities of the
EventStreamGPT reference (data pipeline, conditionally-independent and
nested-attention point-process transformers, autoregressive generation,
fine-tuning / zero-shot / embedding workflows), built for XLA compilation,
SPMD sharding over device meshes, and MXU-friendly static shapes.
"""

__version__ = "0.1.0"
