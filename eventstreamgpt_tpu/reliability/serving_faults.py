"""Deterministic fault injection for the serving stack's recovery paths.

The serving analogue of `reliability.faults` (PR 3): a `ServingFaultPlan`
scripts faults against **deterministic serving counters** — an engine's
dispatched-chunk index and the fleet's service ids — never the wall clock,
so every recovery path in ``serving/`` (slot quarantine, replica eviction
and session replay, promotion rollback, deadline storms) is exercised on
CPU in CI with the same timeline on every run:

* ``nan_slot`` — poison one slot's row content at a chunk boundary so its
  next forward produces non-finite logits/values, driving the decode
  health sentinel (`SlotState.health`): the slot quarantines, its request
  fails with `SlotHealthError` (or retries from its bound key), and
  co-resident slots stay bit-identical to a clean run.
* ``hang`` — sleep inside the dispatch at a chunk boundary, driving the
  fleet's hung-dispatch watchdog (bounded boundary-readback timeout) into
  an eviction. Combined with deadline lanes (`slo.LaneConfig.deadline_s`)
  this is the **deadline storm**: the stall ages the queued backlog past
  its deadlines, and every expired request must surface as a typed
  `DeadlineExceeded` — zero silent drops.
* ``death`` — every dispatch at or after a chunk boundary raises
  `ReplicaDeadError` (a dead replica stays dead), driving fleet eviction +
  deterministic session replay on survivors.
* ``corrupt_shadow`` — garble a staged hot-swap shadow checkpoint (NaN into
  the first float leaf), driving `ServingFleet.promote`'s finite-output
  verification gate into a rollback.
* ``flip_failure`` — raise from a service's flip during a fleet promotion,
  driving the mid-fleet rollback path (already-flipped services flip back
  onto the old weights still held in their shadow buffers).

Faults are scoped by a **fault scope** string: engines carry a
``fault_scope`` attribute (the fleet stamps each service's engines with the
service id at construction; tests may set it directly), and a fault with
``service=None`` matches every scope. Plans install process-globally
(`install_serving_fault_plan` / the `serving_fault_plan` context manager);
every hook below is a no-op when no plan is active, so production serving
pays a single ``None`` check per dispatch.
"""

from __future__ import annotations

import dataclasses
import time
from contextlib import contextmanager
from typing import Any, Iterator

import numpy as np

__all__ = [
    "ServingFault",
    "ServingFaultPlan",
    "active_serving_fault_plan",
    "clear_serving_fault_plan",
    "corrupt_params_tree",
    "install_serving_fault_plan",
    "maybe_corrupt_shadow",
    "maybe_die",
    "maybe_fail_flip",
    "maybe_hang",
    "poison_slots",
    "serving_fault_plan",
]

SERVING_FAULT_KINDS = frozenset(
    {"nan_slot", "hang", "death", "corrupt_shadow", "flip_failure"}
)


@dataclasses.dataclass(frozen=True)
class ServingFault:
    """One scripted serving fault. Which trigger fields apply depends on
    ``kind``:

    ``nan_slot`` fires at ``(service, chunk_index)`` and poisons ``slot``.
    ``hang`` fires at ``(service, chunk_index)`` and sleeps ``seconds``
    (once). ``death`` fires at every ``(service, chunk >= chunk_index)``
    dispatch — dead replicas stay dead. ``corrupt_shadow`` fires on the
    matching service's next shadow load. ``flip_failure`` fires on the
    matching service's flip during a promotion (once). ``service=None``
    matches any fault scope.
    """

    kind: str
    service: str | None = None  # fault scope (fleet service id); None = any
    slot: int | None = None  # nan_slot: which decode slot
    chunk_index: int | None = None  # chunk-boundary trigger (engine counter)
    seconds: float = 0.0  # hang: stall duration

    def __post_init__(self):
        if self.kind not in SERVING_FAULT_KINDS:
            raise ValueError(
                f"unknown serving fault kind {self.kind!r}; expected one of "
                f"{sorted(SERVING_FAULT_KINDS)}"
            )
        if self.kind == "nan_slot" and (self.slot is None or self.chunk_index is None):
            raise ValueError("nan_slot needs slot and chunk_index")
        if self.kind in ("hang", "death") and self.chunk_index is None:
            raise ValueError(f"{self.kind} needs chunk_index")
        if self.kind == "hang" and self.seconds <= 0:
            raise ValueError("hang needs seconds > 0")

    def _matches_scope(self, scope: str | None) -> bool:
        return self.service is None or self.service == scope


@dataclasses.dataclass
class ServingFaultPlan:
    """A scripted, deterministic serving-fault timeline + a log of firings."""

    faults: list[ServingFault] = dataclasses.field(default_factory=list)
    fired: list[dict] = dataclasses.field(default_factory=list)
    _spent: set = dataclasses.field(default_factory=set)  # one-shot triggers

    def _log(self, fault: ServingFault, **context) -> None:
        self.fired.append({"kind": fault.kind, "service": fault.service, **context})

    def poison_slots(self, scope: str | None, chunk_index: int) -> list[int]:
        """Slot indices to poison before dispatching chunk ``chunk_index``."""
        out = []
        for f in self.faults:
            if (
                f.kind == "nan_slot"
                and f._matches_scope(scope)
                and f.chunk_index == chunk_index
            ):
                self._log(f, scope=scope, chunk_index=chunk_index, slot=f.slot)
                out.append(f.slot)
        return out

    def hang_seconds(self, scope: str | None, chunk_index: int) -> float:
        """One-shot stall duration for this dispatch (0.0 = none)."""
        total = 0.0
        for f in self.faults:
            key = ("hang", f.service, f.chunk_index)
            if (
                f.kind == "hang"
                and f._matches_scope(scope)
                and chunk_index >= f.chunk_index
                and key not in self._spent
            ):
                self._spent.add(key)
                self._log(f, scope=scope, chunk_index=chunk_index, seconds=f.seconds)
                total += f.seconds
        return total

    def is_dead(self, scope: str | None, chunk_index: int) -> bool:
        """True when a ``death`` fault covers this dispatch (sticky: a dead
        replica raises on every dispatch at or after its death boundary)."""
        for f in self.faults:
            if (
                f.kind == "death"
                and f._matches_scope(scope)
                and chunk_index >= f.chunk_index
            ):
                key = ("death", f.service, f.chunk_index, scope)
                if key not in self._spent:
                    self._spent.add(key)
                    self._log(f, scope=scope, chunk_index=chunk_index)
                return True
        return False

    def take_corrupt_shadow(self, scope: str | None) -> bool:
        for f in self.faults:
            key = ("corrupt_shadow", f.service, scope)
            if (
                f.kind == "corrupt_shadow"
                and f._matches_scope(scope)
                and key not in self._spent
            ):
                self._spent.add(key)
                self._log(f, scope=scope)
                return True
        return False

    def take_flip_failure(self, scope: str | None) -> bool:
        for f in self.faults:
            key = ("flip_failure", f.service)
            if (
                f.kind == "flip_failure"
                and f._matches_scope(scope)
                and key not in self._spent
            ):
                self._spent.add(key)
                self._log(f, scope=scope)
                return True
        return False


_ACTIVE: ServingFaultPlan | None = None


def install_serving_fault_plan(plan: ServingFaultPlan) -> ServingFaultPlan:
    global _ACTIVE
    _ACTIVE = plan
    return plan


def clear_serving_fault_plan() -> None:
    global _ACTIVE
    _ACTIVE = None


def active_serving_fault_plan() -> ServingFaultPlan | None:
    return _ACTIVE


@contextmanager
def serving_fault_plan(plan: ServingFaultPlan) -> Iterator[ServingFaultPlan]:
    install_serving_fault_plan(plan)
    try:
        yield plan
    finally:
        clear_serving_fault_plan()


# ------------------------------------------------------------ engine hooks
def poison_slots(scope: str | None, chunk_index: int) -> list[int]:
    """Slots whose row content the engine must poison before this chunk's
    dispatch (their next forward then produces non-finite logits/values —
    the on-device injection point for the decode health sentinel)."""
    plan = _ACTIVE
    if plan is None:
        return []
    return plan.poison_slots(scope, chunk_index)


def maybe_hang(scope: str | None, chunk_index: int) -> None:
    """Stalls the dispatch (the hung-dispatch scenario the fleet watchdog's
    bounded boundary-readback timeout must catch)."""
    plan = _ACTIVE
    if plan is None:
        return
    seconds = plan.hang_seconds(scope, chunk_index)
    if seconds > 0:
        time.sleep(seconds)


def maybe_die(scope: str | None, chunk_index: int) -> None:
    """Raises `ReplicaDeadError` when a death fault covers this dispatch."""
    plan = _ACTIVE
    if plan is None:
        return
    if plan.is_dead(scope, chunk_index):
        from ..serving.errors import ReplicaDeadError

        raise ReplicaDeadError(
            f"injected replica death (scope={scope!r}, chunk={chunk_index})"
        )


# --------------------------------------------------------- promotion hooks
def corrupt_params_tree(params: Any) -> Any:
    """NaN-poisons the first float leaf of a param tree (a torn/garbled
    checkpoint staged for promotion). Also a test utility."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    poisoned = list(leaves)
    for i, leaf in enumerate(leaves):
        if np.issubdtype(np.asarray(leaf).dtype, np.floating):
            arr = np.array(leaf, copy=True)
            arr.reshape(-1)[0] = np.nan
            poisoned[i] = arr.astype(np.asarray(leaf).dtype)
            break
    return jax.tree_util.tree_unflatten(treedef, poisoned)


def maybe_corrupt_shadow(scope: str | None, params: Any) -> Any:
    """Returns the (possibly corrupted) staged shadow checkpoint — the
    injection point `GenerationEngine.load_shadow` passes every staged
    tree through; `ServingFleet.promote`'s verification probe must catch
    the corruption before any flip."""
    plan = _ACTIVE
    if plan is None:
        return params
    if plan.take_corrupt_shadow(scope):
        return corrupt_params_tree(params)
    return params


def maybe_fail_flip(scope: str | None) -> None:
    """Raises `PromotionError` when a flip-failure fault covers ``scope`` —
    the mid-fleet flip failure the promotion rollback path must survive."""
    plan = _ACTIVE
    if plan is None:
        return
    if plan.take_flip_failure(scope):
        from ..serving.errors import PromotionError

        raise PromotionError(f"injected flip failure (scope={scope!r})")
