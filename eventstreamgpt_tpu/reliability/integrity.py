"""Checkpoint integrity: retries with backoff, checksum manifests, walk-back.

Three failure modes of persistent storage under long runs, and their
treatment here:

* **transient errors** (flaky NFS/GCS, momentary quota): every save/restore
  attempt runs under `retry_transient` — exponential backoff on ``OSError``,
  bounded attempts, then the error propagates (it was not transient).
* **silent corruption** (bit rot, torn replication): every committed step
  gets a ``manifest_<step>.json`` sidecar of per-file sha256 digests,
  written atomically after orbax finalizes; `verify` recomputes digests
  before a restore touches the arrays.
* **partial writes** (a kill mid-save): the step exists but is not
  restorable. `restore_latest_verified` walks ``all_steps()`` newest-first,
  skipping steps that fail verification *or* whose restore raises, and
  lands on the newest verifiable checkpoint instead of killing the run.

Steps predating this manager carry no manifest; they are accepted with a
warning (the walk-back still catches them if they fail to restore) so
existing runs resume unchanged.
"""

from __future__ import annotations

import hashlib
import json
import time
import warnings
from pathlib import Path
from typing import Any, Callable

import jax
import orbax.checkpoint as ocp

from ..training.checkpoint import TrainCheckpointManager
from ..utils.misc import atomic_write_json
from . import faults

__all__ = [
    "ReliableCheckpointManager",
    "decode_resume_metadata",
    "resume_training_state",
    "retry_transient",
]


def decode_resume_metadata(meta: dict | None) -> tuple[int, int]:
    """``(resume_epoch, skip_batches)`` from a checkpoint metadata sidecar —
    the one decoding of the resume coordinates (pretrain resume, fine-tune
    resume, and divergence rollback all route through here, so they cannot
    disagree). An epoch-complete checkpoint resumes at the next epoch's
    start; a mid-epoch one re-enters its epoch past the batches already
    trained on."""
    meta = meta or {}
    if meta.get("epoch_complete", True):
        return int(meta.get("epoch", 0)) + 1, 0
    return int(meta.get("epoch", 0)), int(meta.get("step_in_epoch", 0))


def resume_training_state(
    ckpt_mgr: "ReliableCheckpointManager", state: Any, place_state: Callable[[Any], Any]
) -> tuple[Any, int, int, int]:
    """The training loops' shared auto-resume: walk-back restore of the
    newest verifiable checkpoint with readable resume metadata, re-placed on
    the caller's mesh. Returns ``(state, restored_step, start_epoch,
    skip_batches)``."""
    from flax import serialization

    import jax

    template = serialization.to_state_dict(jax.device_get(state))
    restored_sd, step = ckpt_mgr.restore_latest_verified(template, require_metadata=True)
    state = place_state(serialization.from_state_dict(jax.device_get(state), restored_sd))
    start_epoch, skip = decode_resume_metadata(ckpt_mgr.metadata(step))
    print(
        f"Resumed from checkpoint at step {step} "
        f"(epoch {start_epoch}, skipping {skip} batches)"
    )
    return state, step, start_epoch, skip


def retry_transient(
    fn: Callable[[], Any],
    *,
    retries: int = 3,
    backoff_base: float = 0.5,
    backoff_max: float = 8.0,
    sleep: Callable[[float], None] = time.sleep,
    describe: str = "checkpoint I/O",
) -> Any:
    """Runs ``fn`` with exponential backoff on ``OSError``.

    ``retries`` counts *re*-attempts: the operation runs at most
    ``retries + 1`` times, sleeping ``min(backoff_base * 2**attempt,
    backoff_max)`` between attempts. Non-``OSError`` failures propagate
    immediately — only plausibly-transient filesystem errors are retried.
    """
    for attempt in range(retries + 1):
        try:
            return fn()
        except OSError as e:
            if attempt == retries:
                raise
            delay = min(backoff_base * (2.0**attempt), backoff_max)
            warnings.warn(
                f"{describe} failed (attempt {attempt + 1}/{retries + 1}): {e}; "
                f"retrying in {delay:.2f}s",
                RuntimeWarning,
                stacklevel=2,
            )
            sleep(delay)


def _file_sha256(fp: Path, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(fp, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


class ReliableCheckpointManager(TrainCheckpointManager):
    """`TrainCheckpointManager` hardened for pod-scale runs.

    Saves block on orbax finalization so the manifest hashes the *committed*
    files (train loops already save at a drained cadence, so the lost
    async overlap is one checkpoint interval's tail). Restores should go
    through `restore_latest_verified`; the base `restore` stays available
    for explicit-step surgery.
    """

    def __init__(
        self,
        ckpt_dir: Path | str,
        max_to_keep: int = 2,
        save_interval_steps: int = 1,
        *,
        retries: int = 3,
        backoff_base: float = 0.5,
        backoff_max: float = 8.0,
        sleep: Callable[[float], None] = time.sleep,
    ):
        super().__init__(ckpt_dir, max_to_keep=max_to_keep, save_interval_steps=save_interval_steps)
        self._retries = retries
        self._backoff_base = backoff_base
        self._backoff_max = backoff_max
        self._sleep = sleep
        self._save_calls = 0

    # ------------------------------------------------------------------ save
    def save(self, step: int, state: Any, metadata: dict | None = None) -> bool:
        save_index = self._save_calls
        self._save_calls += 1
        attempt_counter = {"n": 0}

        def attempt() -> bool:
            this_attempt = attempt_counter["n"]
            attempt_counter["n"] += 1
            faults.maybe_fail_save(save_index, this_attempt)
            saved_ = super(ReliableCheckpointManager, self).save(step, state, metadata)
            if saved_:
                # Orbax saves are async: a flaky filesystem surfaces its
                # OSError from the background array write HERE, not from the
                # enqueue above — waiting inside the attempt is what makes
                # the real transient-write failure retryable (and the
                # manifest below requires finalized files anyway).
                self.wait_until_finished()
            return saved_

        saved = retry_transient(
            attempt,
            retries=self._retries,
            backoff_base=self._backoff_base,
            backoff_max=self._backoff_max,
            sleep=self._sleep,
            describe=f"checkpoint save (step {step})",
        )
        if saved:
            # The deterministic crash window sits exactly here: arrays
            # committed on disk, manifest not yet written.
            faults.maybe_kill_during_save(self.ckpt_dir, step, save_index)
            retry_transient(
                lambda: self._write_manifest(step),
                retries=self._retries,
                backoff_base=self._backoff_base,
                backoff_max=self._backoff_max,
                sleep=self._sleep,
                describe=f"checkpoint manifest (step {step})",
            )
            faults.maybe_corrupt_after_save(self.ckpt_dir, step, save_index)
        return saved

    # -------------------------------------------------------------- manifest
    def _manifest_fp(self, step: int) -> Path:
        return self.ckpt_dir / f"manifest_{step}.json"

    def _step_dir(self, step: int) -> Path:
        return self.ckpt_dir / str(step)

    def _write_manifest(self, step: int) -> None:
        if jax.process_index() != 0:
            return  # shared-fs sidecar: one writer (see TrainCheckpointManager.save)
        step_dir = self._step_dir(step)
        if not step_dir.is_dir():
            return  # layout without per-step dirs: nothing to attest
        files = {}
        for fp in sorted(p for p in step_dir.rglob("*") if p.is_file()):
            rel = fp.relative_to(step_dir).as_posix()
            files[rel] = {"sha256": _file_sha256(fp), "bytes": fp.stat().st_size}
        atomic_write_json(
            self._manifest_fp(step), {"step": step, "algo": "sha256", "files": files}
        )

    def verify(self, step: int) -> bool:
        """Recomputes the step's digests against its manifest.

        Missing manifest → accepted with a warning (pre-manifest legacy
        steps); present-but-unreadable or mismatching → False.
        """
        return self._verify_status(step) != "failed"

    def _verify_status(self, step: int) -> str:
        """``"verified"`` (manifest matched), ``"legacy"`` (no manifest —
        accepted but unproven), or ``"failed"`` (provably corrupt). The
        distinction drives the walk-back deletion policy: only steps the
        checksums actually vouch for are kept when their restore fails."""
        fp = self._manifest_fp(step)
        if not fp.exists():
            warnings.warn(
                f"checkpoint step {step} has no integrity manifest; accepting unverified",
                RuntimeWarning,
                stacklevel=2,
            )
            return "legacy"
        try:
            with open(fp) as f:
                manifest = json.load(f)
            files = manifest["files"]
        except (OSError, json.JSONDecodeError, KeyError, UnicodeDecodeError) as e:
            warnings.warn(f"unreadable manifest for step {step}: {e}", RuntimeWarning, stacklevel=2)
            return "failed"
        step_dir = self._step_dir(step)
        for rel, meta in files.items():
            f = step_dir / rel
            if not f.is_file():
                warnings.warn(f"step {step}: missing file {rel}", RuntimeWarning, stacklevel=2)
                return "failed"
            if f.stat().st_size != meta["bytes"] or _file_sha256(f) != meta["sha256"]:
                warnings.warn(
                    f"step {step}: checksum mismatch on {rel}", RuntimeWarning, stacklevel=2
                )
                return "failed"
        return "verified"

    # --------------------------------------------------------------- restore
    def restore_latest_verified(
        self, state_template: Any, *, require_metadata: bool = False
    ) -> tuple[Any, int]:
        """Restores the newest checkpoint that passes verification.

        Walks ``all_steps()`` newest-first; a step that fails checksum
        verification, or whose restore raises (truncated/partial writes on
        legacy manifest-less steps), is skipped with a warning instead of
        killing the run. With ``require_metadata`` (the training loops'
        resume paths), a step whose metadata sidecar is missing/undecodable
        is also skipped: its resume coordinates are gone, and silently
        defaulting them would reset the epoch counter under epoch-7 weights.
        Raises ``FileNotFoundError`` when nothing restorable remains.

        Skipped-step disposal: provably-bad newer steps (checksum-failed,
        manifest-less torn writes, lost metadata) are deleted after a
        successful restore — orbax's monotonic-step contract ignores any
        ``save(step <= latest_step)``, so leaving them would silently no-op
        every re-save of the retrained window. A checksum-**verified** step
        whose restore failed is presumed transiently unreadable and kept for
        the next relaunch, at the documented cost that saves below it are
        skipped until training passes it again.
        """
        steps = sorted(self.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"No checkpoints found under {self.ckpt_dir}")
        skipped: dict[int, str] = {}  # step -> why, for the disposal policy
        for step in steps:
            status = self._verify_status(step)
            if status == "failed":
                warnings.warn(
                    f"skipping corrupt/unverifiable checkpoint step {step}; walking back",
                    RuntimeWarning,
                    stacklevel=2,
                )
                skipped[step] = "failed"
                continue
            if require_metadata and self.metadata(step) is None:
                warnings.warn(
                    f"checkpoint step {step} has no readable resume metadata; walking back",
                    RuntimeWarning,
                    stacklevel=2,
                )
                # A checksum-VERIFIED step with an unreadable sidecar is not
                # disposable: the arrays are provably good and the sidecar
                # read may have failed transiently — keep it (same policy as
                # a verified step whose restore raised). Only unproven steps
                # are tagged for deletion.
                skipped[step] = "verified" if status == "verified" else "no-metadata"
                continue
            try:
                state = retry_transient(
                    lambda: self._mgr.restore(
                        step, args=ocp.args.PyTreeRestore(state_template)
                    ),
                    # A torn write (e.g. a kill mid-save on a manifest-less
                    # step) raises OSError too, and no amount of backoff
                    # repairs it — one retry covers the genuinely transient
                    # case without stalling the walk-back on every corrupt
                    # step it passes.
                    retries=min(self._retries, 1),
                    backoff_base=self._backoff_base,
                    backoff_max=self._backoff_max,
                    sleep=self._sleep,
                    describe=f"checkpoint restore (step {step})",
                )
            except Exception as e:  # orbax surfaces corruption as various types
                warnings.warn(
                    f"restore of checkpoint step {step} failed ({type(e).__name__}: {e}); "
                    "walking back to an earlier step",
                    RuntimeWarning,
                    stacklevel=2,
                )
                skipped[step] = status  # "verified" or "legacy"
                continue
            self._dispose_skipped(skipped, restored_step=step)
            return state, step
        raise FileNotFoundError(
            f"No verifiable checkpoint could be restored under {self.ckpt_dir} "
            f"(tried steps {steps})"
        )

    def _dispose_skipped(self, skipped: dict[int, str], restored_step: int) -> None:
        """Applies the walk-back disposal policy (process 0 only — the
        checkpoint store is shared across a pod)."""
        if jax.process_index() != 0:
            return
        for newer, why in sorted(skipped.items()):
            if why == "verified":
                warnings.warn(
                    f"checkpoint step {newer} is checksum-verified but was skipped "
                    f"(restore or sidecar read failed, presumed transient); keeping "
                    f"it — NOTE: re-saves at steps <= {newer} are skipped until "
                    f"training passes it",
                    RuntimeWarning,
                    stacklevel=3,
                )
                continue
            try:
                self._mgr.delete(newer)
                warnings.warn(
                    f"deleted unrestorable checkpoint step {newer} "
                    f"(walked back to {restored_step})",
                    RuntimeWarning,
                    stacklevel=3,
                )
            except Exception as e:  # pragma: no cover - fs-dependent
                warnings.warn(
                    f"could not delete unrestorable checkpoint step {newer}: {e}",
                    RuntimeWarning,
                    stacklevel=3,
                )
        self._prune_metadata()
