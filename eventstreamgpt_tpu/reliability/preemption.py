"""Graceful preemption: drain at a chunk boundary, checkpoint, exit distinct.

TPU-pod schedulers deliver ``SIGTERM`` with a grace window before the hard
kill. `GracefulShutdown` converts the first signal into a flag the training
loops poll at their chunk/flush boundaries (a Python bool read — no device
sync); the loop then drains the dispatch pipeline, writes a final mid-epoch
checkpoint, and raises `Preempted`, which the script entry points convert to
`EXIT_PREEMPTED` so orchestrators can distinguish "reschedule me, resume is
safe" from a real failure. A second signal restores the previous handler and
re-delivers itself — the escape hatch when the drain itself wedges.
"""

from __future__ import annotations

import os
import signal
import threading

__all__ = ["EXIT_PREEMPTED", "GracefulShutdown", "Preempted"]

# The orchestrator contract: this exit status means "preempted after a clean
# final checkpoint — reschedule and the run resumes with at most one chunk of
# progress lost". Distinct from 0 (done), 1 (error), and the 128+signum codes
# of an *unhandled* signal death.
EXIT_PREEMPTED = 85


class Preempted(RuntimeError):
    """Raised by the training loops after a graceful drain + final checkpoint.

    ``step`` is the global step of the final checkpoint; script entry points
    catch this and ``sys.exit(EXIT_PREEMPTED)``.
    """

    def __init__(self, message: str, step: int | None = None):
        super().__init__(message)
        self.step = step


class GracefulShutdown:
    """Context manager turning SIGTERM/SIGINT into a pollable drain flag.

    Handlers install only in the main thread (signal module constraint —
    e.g. ASHA sweep workers call ``train()`` from worker threads); elsewhere
    the object is inert but still usable programmatically via `request`
    (which is also how the deterministic fault-injection path delivers
    preemption in-process). Previous handlers are restored on exit, also on
    error, so nested/sequential in-process runs start clean.
    """

    _SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self._requested = threading.Event()
        self._prev: dict[int, object] = {}
        self._signum: int | None = None

    def __enter__(self) -> "GracefulShutdown":
        if threading.current_thread() is threading.main_thread():
            for sig in self._SIGNALS:
                self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()

    def _handle(self, signum, frame) -> None:
        if self._requested.is_set():
            # Second signal while draining: restore the previous disposition
            # and re-deliver — the operator's hard-stop escape hatch.
            signal.signal(signum, self._prev.get(signum, signal.SIG_DFL))
            os.kill(os.getpid(), signum)
            return
        self._signum = signum
        self._requested.set()

    def request(self) -> None:
        """Programmatic preemption (fault injection, tests, embedders)."""
        self._requested.set()

    @property
    def requested(self) -> bool:
        return self._requested.is_set()
