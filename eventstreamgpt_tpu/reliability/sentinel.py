"""Divergence sentinel: device-resident health flags, host-side verdicts,
and the bounded rollback state machine.

The detection contract is shaped by the GC001 discipline (docs/analysis.md):
the train step computes its own health — ``[loss, grad_global_norm]`` as an
f32 device vector riding the step outputs — and the loop buffers those
vectors exactly like it buffers window losses. Nothing is read back per
step; the buffered flags are inspected only at the existing flush cadence
(checkpoint saves and epoch end), where the pipeline drains anyway. A window
is **bad** when any step in it has a non-finite loss or gradient norm, a
gradient norm above ``grad_norm_max``, or a loss above ``spike_factor`` ×
the running loss EMA (EMA updated from healthy windows only, so a divergent
tail cannot drag the baseline up after it).

After ``bad_windows_to_rollback`` consecutive bad windows the training loop
restores the last good checkpoint (checkpoints are never written from a bad
window — inspection runs before the save at the same cadence), advances
``skip_batches`` past the poisoned window, and retries. `RollbackController`
bounds the run at ``max_rollbacks`` rollbacks; past that (or with no
verifiable checkpoint to return to) it writes a diagnostic dump next to the
run and raises `DivergenceError`.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from pathlib import Path
from typing import Any, Callable

import numpy as np

from ..utils.misc import atomic_write_json
from .preemption import Preempted

__all__ = [
    "DivergenceError",
    "DivergenceSentinel",
    "EpochOutcome",
    "HealthMonitor",
    "RollbackController",
    "SentinelConfig",
    "finish_epoch",
    "rollback_restore",
]


class DivergenceError(RuntimeError):
    """Training diverged beyond what rollback can repair.

    Carries the path of the diagnostic dump written before raising.
    """

    def __init__(self, message: str, diagnostics_fp: Path | None = None):
        super().__init__(message)
        self.diagnostics_fp = diagnostics_fp


@dataclasses.dataclass
class SentinelConfig:
    """Divergence-sentinel thresholds (all host-side; the step only emits
    ``[loss, grad_norm]``). Non-finite checks are always on; the spike and
    gradient-norm ceilings are opt-in."""

    ema_decay: float = 0.9
    spike_factor: float | None = None  # loss > spike_factor * EMA → bad
    grad_norm_max: float | None = None  # grad norm above this → bad
    warmup_windows: int = 1  # healthy windows before spike checks engage
    bad_windows_to_rollback: int = 1  # K consecutive bad windows
    max_rollbacks: int = 3  # M rollbacks before aborting

    @classmethod
    def from_trainer_config(cls, tc: dict) -> "SentinelConfig | None":
        """Parses the ``sentinel_*`` trainer-config keys; ``None`` (sentinel
        off) when ``sentinel_enabled`` is explicitly false."""
        if not tc.get("sentinel_enabled", True):
            return None
        cfg = cls()
        if tc.get("sentinel_ema_decay") is not None:
            cfg.ema_decay = float(tc["sentinel_ema_decay"])
        if tc.get("sentinel_spike_factor") is not None:
            cfg.spike_factor = float(tc["sentinel_spike_factor"])
        if tc.get("sentinel_grad_norm_max") is not None:
            cfg.grad_norm_max = float(tc["sentinel_grad_norm_max"])
        if tc.get("sentinel_warmup_windows") is not None:
            cfg.warmup_windows = int(tc["sentinel_warmup_windows"])
        if tc.get("sentinel_bad_windows") is not None:
            cfg.bad_windows_to_rollback = max(int(tc["sentinel_bad_windows"]), 1)
        if tc.get("sentinel_max_rollbacks") is not None:
            cfg.max_rollbacks = int(tc["sentinel_max_rollbacks"])
        return cfg


class DivergenceSentinel:
    """Classifies inspection windows from buffered ``[loss, grad_norm]``
    health vectors and tracks the consecutive-bad count."""

    def __init__(self, config: SentinelConfig):
        self.config = config
        self.ema: float | None = None
        self.healthy_windows = 0
        self.consecutive_bad = 0
        # Ring buffer of recent window summaries for the diagnostic dump.
        self.history: deque[dict] = deque(maxlen=64)

    def observe_window(self, health: np.ndarray, *, step: int, epoch: int) -> bool:
        """Feeds one inspection window; returns True when it is healthy.

        ``health`` is the stacked per-step vectors, shape ``(n_steps, 2)``
        with columns ``[loss, grad_norm]`` (already host-side: the caller
        reads the buffers back at a cadence where the pipeline drains
        anyway).
        """
        health = np.asarray(health, dtype=np.float64).reshape(-1, 2)  # graftcheck: allow GC002 -- host-side verdict math on already-read-back scalars; never traced
        losses, gnorms = health[:, 0], health[:, 1]
        cfg = self.config

        reasons = []
        if not np.isfinite(losses).all():
            reasons.append("non-finite loss")
        if not np.isfinite(gnorms).all():
            reasons.append("non-finite grad norm")
        if cfg.grad_norm_max is not None and np.isfinite(gnorms).all():
            if (gnorms > cfg.grad_norm_max).any():
                reasons.append(
                    f"grad norm {float(np.nanmax(gnorms)):.3e} > {cfg.grad_norm_max:.3e}"
                )
        if (
            cfg.spike_factor is not None
            and not reasons
            and self.ema is not None
            and self.healthy_windows >= cfg.warmup_windows
        ):
            threshold = cfg.spike_factor * self.ema
            if (losses > threshold).any():
                reasons.append(
                    f"loss spike {float(losses.max()):.4e} > "
                    f"{cfg.spike_factor:g} x EMA ({self.ema:.4e})"
                )

        bad = bool(reasons)

        def finite_stat(arr: np.ndarray, fn) -> float | None:
            finite = arr[np.isfinite(arr)]
            return float(fn(finite)) if finite.size else None

        self.history.append(
            {
                "step": int(step),
                "epoch": int(epoch),
                "n_steps": int(health.shape[0]),
                "n_nonfinite": int((~np.isfinite(health)).any(axis=1).sum()),
                "loss_mean": finite_stat(losses, np.mean),
                "loss_max": finite_stat(losses, np.max),
                "grad_norm_max": finite_stat(gnorms, np.max),
                "ema": self.ema,
                "bad": bad,
                "reasons": reasons,
            }
        )
        if bad:
            self.consecutive_bad += 1
            return False
        self.consecutive_bad = 0
        self.healthy_windows += 1
        for loss in losses:
            self.ema = (
                float(loss)
                if self.ema is None
                else cfg.ema_decay * self.ema + (1.0 - cfg.ema_decay) * float(loss)
            )
        return True

    @property
    def should_rollback(self) -> bool:
        return self.consecutive_bad >= self.config.bad_windows_to_rollback

    def reset_after_rollback(self) -> None:
        """Restored state re-warms from scratch: the poisoned tail must not
        leave a bad streak or a spiked EMA behind."""
        self.consecutive_bad = 0
        self.ema = None
        self.healthy_windows = 0


class HealthMonitor:
    """Per-epoch health-flag buffer + inspection gate, shared verbatim by
    the pretrain and fine-tune loops (the verdict/gating logic is where
    subtle bugs live — one copy only).

    The loops `record` each dispatch's device health arrays (no readback)
    and call `inspect` only at their flush cadence; `inspect` returns the
    window's verdict, and checkpoint saves must gate on it — even a bad
    window below the K-streak must never commit a poisoned rollback target.
    """

    def __init__(self, sentinel: DivergenceSentinel | None):
        self.sentinel = sentinel
        self.pending: list = []
        self.rollback_requested = False
        self.detection_progress = 0

    def record(self, health: Any) -> None:
        """Buffers one dispatch's device health array(s) — shape ``(2,)``
        (per-batch step) or ``(k, 2)`` (scanned chunk)."""
        if self.sentinel is not None:
            self.pending.append(health)

    def inspect(self, *, step: int, epoch: int, progress: int) -> bool:
        """Feeds the buffer to the sentinel; returns the window verdict
        (True = healthy or nothing to inspect). ``progress`` is the
        epoch-order batch index reached — it becomes the poisoned-window
        edge if this window flips the rollback request."""
        if self.sentinel is None or not self.pending:
            return True
        window = np.concatenate([np.asarray(h).reshape(-1, 2) for h in self.pending])
        self.pending.clear()
        healthy = self.sentinel.observe_window(window, step=step, epoch=epoch)
        if self.sentinel.should_rollback and not self.rollback_requested:
            self.rollback_requested = True
            self.detection_progress = progress
        return healthy

    def vetted_save(
        self,
        ckpt_mgr,
        step: int,
        state_dict_fn: Callable[[], Any],
        metadata: dict,
        *,
        epoch: int,
        progress: int,
    ) -> bool:
        """The cadence checkpoint gate both loops share: inspect first, and
        commit only when THIS window vetted healthy and no rollback is
        pending — a bad-but-below-streak window must never become a poisoned
        rollback target. Returns True when the save ran (``state_dict_fn``'s
        device readback drained the pipeline, so callers flush their
        buffered log records on that signal)."""
        healthy = self.inspect(step=step, epoch=epoch, progress=progress)
        if not healthy or self.rollback_requested:
            return False
        ckpt_mgr.save(step, state_dict_fn(), metadata=metadata)
        return True


class RollbackController:
    """Bounds rollbacks at M and owns the poisoned-window excision map.

    ``poisoned`` maps epoch → the epoch-order batch index training must skip
    to when (re-)entering that epoch: the restored checkpoint may predate
    the poisoned window by several cadences, and the batch order within an
    epoch is deterministic, so excising ``[restore point, detection point)``
    is what keeps a data-caused fault from simply re-firing after restore.
    """

    def __init__(self, max_rollbacks: int, diagnostics_fp: Path | str):
        self.max_rollbacks = max_rollbacks
        self.diagnostics_fp = Path(diagnostics_fp)
        self.rollbacks = 0
        self.poisoned: dict[int, int] = {}
        self.events: list[dict] = []

    def epoch_skip(self, epoch: int, skip: int) -> int:
        return max(skip, self.poisoned.get(epoch, 0))

    def request_rollback(
        self, sentinel: DivergenceSentinel, *, epoch: int, step_in_epoch: int, global_step: int
    ) -> None:
        """Registers a rollback attempt; raises `DivergenceError` past M."""
        self.rollbacks += 1
        self.poisoned[epoch] = max(self.poisoned.get(epoch, 0), step_in_epoch)
        self.events.append(
            {
                "rollback": self.rollbacks,
                "epoch": epoch,
                "step_in_epoch": step_in_epoch,
                "global_step": global_step,
            }
        )
        if self.rollbacks > self.max_rollbacks:
            self.abort(
                sentinel,
                reason=f"divergence persisted after {self.max_rollbacks} rollback(s)",
            )

    def abort(self, sentinel: DivergenceSentinel, *, reason: str, **context: Any) -> None:
        """Writes the diagnostic dump and raises `DivergenceError`."""
        dump = {
            "reason": reason,
            "rollbacks": self.rollbacks,
            "max_rollbacks": self.max_rollbacks,
            "poisoned_windows": {str(k): v for k, v in self.poisoned.items()},
            "rollback_events": self.events,
            "sentinel_config": dataclasses.asdict(sentinel.config),
            "window_history": list(sentinel.history),
            **context,
        }
        self.diagnostics_fp.parent.mkdir(parents=True, exist_ok=True)
        atomic_write_json(self.diagnostics_fp, dump, indent=2, default=str)
        raise DivergenceError(
            f"{reason}; diagnostics written to {self.diagnostics_fp}",
            diagnostics_fp=self.diagnostics_fp,
        )


def rollback_restore(
    ckpt_mgr,
    sentinel: DivergenceSentinel,
    controller: RollbackController,
    state_template: Any,
    *,
    epoch: int,
    detection_progress: int,
    global_step: int,
    label: str = "training",
) -> tuple[Any, int, int, int]:
    """Executes one bounded rollback — the recovery core shared verbatim by
    the pretrain and fine-tune loops so the state machine cannot drift.

    Counts the rollback (raising `DivergenceError` past M, or when nothing
    restorable exists), restores the newest verifiable checkpoint, decodes
    its resume metadata, and resets the sentinel. Returns
    ``(restored_state_dict, restored_step, resume_epoch, resume_skip)``; the
    caller re-places the state on its mesh and rewinds its own counters.
    """
    controller.request_rollback(
        sentinel, epoch=epoch, step_in_epoch=detection_progress, global_step=global_step
    )
    try:
        restored_sd, restored_step = ckpt_mgr.restore_latest_verified(
            state_template, require_metadata=True
        )
    except FileNotFoundError:
        controller.abort(
            sentinel,
            reason=f"{label} diverged before any restorable checkpoint existed",
            epoch=epoch,
            global_step=global_step,
        )
    from .integrity import decode_resume_metadata

    resume_epoch, resume_skip = decode_resume_metadata(ckpt_mgr.metadata(restored_step))
    sentinel.reset_after_rollback()
    return restored_sd, restored_step, resume_epoch, resume_skip


@dataclasses.dataclass
class EpochOutcome:
    """What `finish_epoch` decided: ``action`` is ``"proceed"`` (continue to
    eval/epoch-end bookkeeping; ``tail_healthy`` gates the epoch-end save)
    or ``"rollback"`` (re-enter at the returned resume coordinates with the
    re-placed state). Preemption never returns — it raises `Preempted`."""

    action: str
    tail_healthy: bool = True
    state: Any = None
    global_step: int = 0
    resume_epoch: int = 0
    resume_skip: int = 0
    stop: bool = False


def finish_epoch(
    *,
    health_mon: HealthMonitor,
    rollback_ctl: "RollbackController | None",
    ckpt_mgr,
    shutdown,
    state: Any,
    place_state: Callable[[Any], Any],
    log_record: Callable[[dict], None],
    epoch: int,
    epoch_progress: int,
    global_step: int,
    accum: int,
    max_training_steps: int | None,
    label: str,
) -> EpochOutcome:
    """The post-epoch recovery tail shared verbatim by both training loops.

    Vets the tail window (checkpoint saves downstream gate on the verdict),
    then executes whichever recovery path the epoch ended in:

    * **rollback** — restores via `rollback_restore`, re-places the state,
      re-derives the ``stop`` budget from the rewound counter, logs the
      event, and returns ``action="rollback"``; if shutdown arrived
      mid-rollback, raises `Preempted` instead (the restored checkpoint on
      disk IS the resume point — nothing from the poisoned tail persists).
    * **preemption** — writes the final drain checkpoint only when the tail
      window vetted healthy (otherwise the last vetted checkpoint is the
      resume point), closes the manager, and raises `Preempted` carrying
      the step a relaunch will actually restore.
    * **neither** — returns ``action="proceed"`` with the tail verdict.
    """
    import jax
    from flax import serialization

    tail_healthy = True
    if not health_mon.rollback_requested:
        tail_healthy = health_mon.inspect(
            step=global_step, epoch=epoch, progress=epoch_progress
        )

    if health_mon.rollback_requested:
        template = serialization.to_state_dict(jax.device_get(state))
        restored_sd, restored_step, resume_epoch, resume_skip = rollback_restore(
            ckpt_mgr,
            health_mon.sentinel,
            rollback_ctl,
            template,
            epoch=epoch,
            detection_progress=health_mon.detection_progress,
            global_step=global_step,
            label=label,
        )
        state = place_state(serialization.from_state_dict(jax.device_get(state), restored_sd))
        # Re-derive the step budget from the rewound counter: a stop latched
        # inside the poisoned window no longer holds.
        stop = max_training_steps is not None and restored_step // accum >= max_training_steps
        log_record(
            {
                "split": "reliability",
                "event": "rollback",
                "rollback": rollback_ctl.rollbacks,
                "restored_step": restored_step,
                "epoch": epoch,
                "poisoned_through": health_mon.detection_progress,
                "step": restored_step,
            }
        )
        print(
            f"Divergence rollback #{rollback_ctl.rollbacks} ({label}): restored step "
            f"{restored_step}; re-entering epoch {resume_epoch} past the poisoned window"
        )
        if shutdown.requested:
            ckpt_mgr.wait_until_finished()
            ckpt_mgr.close()
            raise Preempted(
                f"preempted during divergence rollback at step {restored_step}",
                step=restored_step,
            )
        return EpochOutcome(
            action="rollback",
            state=state,
            global_step=restored_step,
            resume_epoch=resume_epoch,
            resume_skip=resume_skip,
            stop=stop,
        )

    if shutdown.requested:
        if tail_healthy:
            ckpt_mgr.save(
                global_step,
                serialization.to_state_dict(jax.device_get(state)),
                metadata={
                    "epoch": epoch,
                    "epoch_complete": False,
                    "step_in_epoch": epoch_progress,
                },
            )
            final_step = global_step
        else:
            print(
                f"Preemption drain ({label}): tail window failed divergence vetting; "
                "skipping the final save (resume falls back to the last vetted "
                "checkpoint)."
            )
            final_step = ckpt_mgr.latest_step()
        ckpt_mgr.wait_until_finished()
        ckpt_mgr.close()
        if final_step is None:
            # Nothing restorable exists (preempted before the first vetted
            # checkpoint): the reschedule contract still applies — a
            # relaunch simply starts from scratch, which is everything this
            # run had — but say so explicitly instead of reporting a
            # checkpoint that does not exist.
            print(
                f"Preemption drain complete ({label}): no restorable checkpoint "
                "exists yet; a relaunch restarts from scratch."
            )
        else:
            print(
                f"Preemption drain complete ({label}): resume checkpoint at step "
                f"{final_step}; exiting for reschedule."
            )
        raise Preempted(f"graceful preemption at step {global_step}", step=final_step)

    return EpochOutcome(action="proceed", tail_healthy=tail_healthy)
