"""Fault tolerance for long training runs (SURVEY §5.3/§5.4 hardening).

At pod scale faults are the steady state: preempted workers, flaky
persistent storage, and the occasional divergent step are routine in long
TPU runs. This package makes every one of them a *recoverable* event with a
deterministic test harness, instead of a dead or silently poisoned run:

* `sentinel` — divergence detection (non-finite loss/grad-norm, loss-EMA
  spikes) from device-resident health flags inspected only at the training
  loops' existing flush cadence, plus the bounded rollback state machine.
* `integrity` — checkpoint save/restore hardening: exponential-backoff
  retries for transient ``OSError``s, a checksum manifest sidecar verified
  on restore, and walk-back to the newest verifiable step when the latest
  checkpoint is corrupt or unreadable.
* `preemption` — SIGTERM/SIGINT drain-and-checkpoint with a distinct exit
  code orchestrators can treat as "reschedule me".
* `faults` — a deterministic fault-injection plan so every recovery path
  above is exercised on CPU in CI.
* `serving_faults` — the serving-side plan (slot NaN injection, replica
  hang/death, corrupt shadow checkpoints, flip failures), keyed on chunk
  indices and service ids so the serving recovery paths (`serving/` slot
  quarantine, fleet eviction + replay, promotion rollback) are exercised
  the same deterministic way.

See ``docs/reliability.md`` for the operator-facing contract.
"""

from .faults import (
    Fault,
    FaultPlan,
    active_fault_plan,
    clear_fault_plan,
    corrupt_checkpoint_step,
    fault_plan,
    install_fault_plan,
)
from .integrity import ReliableCheckpointManager, retry_transient
from .preemption import EXIT_PREEMPTED, GracefulShutdown, Preempted
from .serving_faults import (
    ServingFault,
    ServingFaultPlan,
    active_serving_fault_plan,
    clear_serving_fault_plan,
    install_serving_fault_plan,
    serving_fault_plan,
)
from .sentinel import (
    DivergenceError,
    DivergenceSentinel,
    RollbackController,
    SentinelConfig,
    rollback_restore,
)

__all__ = [
    "EXIT_PREEMPTED",
    "DivergenceError",
    "DivergenceSentinel",
    "Fault",
    "FaultPlan",
    "GracefulShutdown",
    "Preempted",
    "ReliableCheckpointManager",
    "RollbackController",
    "SentinelConfig",
    "ServingFault",
    "ServingFaultPlan",
    "active_fault_plan",
    "active_serving_fault_plan",
    "clear_fault_plan",
    "clear_serving_fault_plan",
    "corrupt_checkpoint_step",
    "fault_plan",
    "install_fault_plan",
    "install_serving_fault_plan",
    "retry_transient",
    "rollback_restore",
    "serving_fault_plan",
]
