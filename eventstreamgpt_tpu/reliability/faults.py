"""Deterministic fault injection for the training loops' recovery paths.

A `FaultPlan` scripts faults against deterministic counters — the batch
index within an epoch's (seeded, reproducible) order, the checkpoint-save
call index, the global optimizer-loop step — so every recovery path in this
package is exercised on CPU in CI with the same timeline on every run:

* ``nan_batch`` / ``spike_batch`` — poison the batch at epoch-order index N
  (NaN values, or values scaled by ``scale``), driving a non-finite loss or
  a loss spike through the divergence sentinel.
* ``save_error`` — raise ``OSError`` for the first ``times`` attempts of
  checkpoint-save call N, exercising `integrity.retry_transient`.
* ``corrupt_checkpoint`` — truncate/garble a file of the just-written step
  after save call N (manifest left stale), exercising walk-back restore.
* ``sigterm`` — request graceful shutdown at global step N (delivered as a
  real ``SIGTERM`` when no `GracefulShutdown` is passed), exercising the
  drain-and-checkpoint preemption path.
* ``kill`` — ``SIGKILL`` this process during save call N, *after* the array
  write but before the integrity manifest: the crash-consistency scenario
  (a checkpoint that exists on disk but is not verifiable).

Plans are installed process-globally (`install_fault_plan` / the
`fault_plan` context manager); the harness hooks below are no-ops when no
plan is active, so production runs pay a single ``None`` check. Batch
poisoning keys on the *epoch-order index*, not the global step, so a
post-rollback ``skip_batches`` excision genuinely removes the poisoned
window instead of letting the fault re-fire at the rewound step counter.
"""

from __future__ import annotations

import dataclasses
import os
import signal
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Iterable, Iterator

import numpy as np

__all__ = [
    "Fault",
    "FaultPlan",
    "active_fault_plan",
    "clear_fault_plan",
    "corrupt_checkpoint_step",
    "fault_plan",
    "install_fault_plan",
    "maybe_corrupt_after_save",
    "maybe_fail_save",
    "maybe_kill_during_save",
    "maybe_sigterm",
    "wrap_batches",
]

BATCH_KINDS = frozenset({"nan_batch", "spike_batch"})
SAVE_KINDS = frozenset({"save_error", "corrupt_checkpoint", "kill"})


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scripted fault. Which trigger field applies depends on ``kind``:

    ``nan_batch``/``spike_batch`` fire on ``(epoch, batch_index)`` (epoch
    ``None`` = every epoch; the index counts the epoch's deterministic batch
    order from 0). ``save_error``/``corrupt_checkpoint``/``kill`` fire on
    ``save_index`` (counting checkpoint-save *calls* from 0). ``sigterm``
    fires once at global optimizer-loop step ``step``.
    """

    kind: str
    step: int | None = None  # sigterm: global step
    epoch: int | None = None  # batch faults: restrict to one epoch
    batch_index: int | None = None  # batch faults: 0-based epoch-order index
    save_index: int | None = None  # save faults: 0-based save-call index
    times: int = 1  # save_error: attempts to fail before succeeding
    scale: float = 1e6  # spike_batch: value multiplier

    def __post_init__(self):
        known = BATCH_KINDS | SAVE_KINDS | {"sigterm"}
        if self.kind not in known:
            raise ValueError(f"unknown fault kind {self.kind!r}; expected one of {sorted(known)}")
        if self.kind in BATCH_KINDS and self.batch_index is None:
            raise ValueError(f"{self.kind} needs batch_index")
        if self.kind in SAVE_KINDS and self.save_index is None:
            raise ValueError(f"{self.kind} needs save_index")
        if self.kind == "sigterm" and self.step is None:
            raise ValueError("sigterm needs step")


@dataclasses.dataclass
class FaultPlan:
    """A scripted, deterministic fault timeline plus a log of what fired."""

    faults: list[Fault] = dataclasses.field(default_factory=list)
    fired: list[dict] = dataclasses.field(default_factory=list)
    _spent: set = dataclasses.field(default_factory=set)  # one-shot triggers

    def _log(self, fault: Fault, **context) -> None:
        self.fired.append({"kind": fault.kind, **context})

    # ---- batch faults (re-fire if the same batch is retrained: data-caused)
    def batch_fault(self, epoch: int, batch_index: int) -> Fault | None:
        for f in self.faults:
            if (
                f.kind in BATCH_KINDS
                and f.batch_index == batch_index
                and (f.epoch is None or f.epoch == epoch)
            ):
                return f
        return None

    # ---- save faults (keyed per save call; save_error fails `times` attempts)
    def save_fault(self, kind: str, save_index: int) -> Fault | None:
        for f in self.faults:
            if f.kind == kind and f.save_index == save_index:
                return f
        return None

    # ---- sigterm (one-shot; fires at the first boundary crossing the step,
    # since a scanned chunk can advance the global counter by k at once)
    def take_sigterm(self, step: int) -> Fault | None:
        for f in self.faults:
            key = ("sigterm", f.step)
            if f.kind == "sigterm" and step >= f.step and key not in self._spent:
                self._spent.add(key)
                return f
        return None


_ACTIVE: FaultPlan | None = None


def install_fault_plan(plan: FaultPlan) -> FaultPlan:
    global _ACTIVE
    _ACTIVE = plan
    return plan


def clear_fault_plan() -> None:
    global _ACTIVE
    _ACTIVE = None


def active_fault_plan() -> FaultPlan | None:
    return _ACTIVE


@contextmanager
def fault_plan(plan: FaultPlan):
    install_fault_plan(plan)
    try:
        yield plan
    finally:
        clear_fault_plan()


# --------------------------------------------------------------- batch hooks
def _poison_batch(batch: Any, fault: Fault) -> Any:
    """Returns a poisoned copy of a *host* batch (numpy fields).

    ``nan_batch`` drives the loss non-finite through every head that consumes
    values or inter-event times; ``spike_batch`` scales the same fields so
    the loss spikes but stays finite (the EMA-spike detection path).
    """
    updates: dict[str, Any] = {}
    for name in ("dynamic_values", "time_delta"):
        val = getattr(batch, name, None)
        if val is None:
            continue
        arr = np.array(val, dtype=np.float32, copy=True)
        if fault.kind == "nan_batch":
            arr[...] = np.nan
        else:
            arr *= fault.scale
        updates[name] = arr
    return batch.replace(**updates)


def wrap_batches(batches: Iterable, epoch: int, first_index: int) -> Iterator:
    """Wraps an epoch's host batch stream with the active plan's batch faults.

    ``first_index`` is the epoch-order index of the stream's first batch
    (``skip_batches`` on resume), so triggers stay aligned with the epoch's
    deterministic order no matter where the stream starts. Returns the input
    unchanged when no plan (or no batch fault) is active — zero overhead on
    the production path.
    """
    plan = _ACTIVE
    if plan is None or not any(f.kind in BATCH_KINDS for f in plan.faults):
        return iter(batches)

    def gen():
        for i, batch in enumerate(batches, start=first_index):
            fault = plan.batch_fault(epoch, i)
            if fault is not None:
                plan._log(fault, epoch=epoch, batch_index=i)
                batch = _poison_batch(batch, fault)
            yield batch

    return gen()


# ---------------------------------------------------------------- save hooks
def maybe_fail_save(save_index: int, attempt: int) -> None:
    """Raises the scripted transient ``OSError`` for (save call, attempt)."""
    plan = _ACTIVE
    if plan is None:
        return
    fault = plan.save_fault("save_error", save_index)
    if fault is not None and attempt < fault.times:
        plan._log(fault, save_index=save_index, attempt=attempt)
        raise OSError(
            f"injected transient I/O failure (save {save_index}, attempt {attempt})"
        )


def maybe_kill_during_save(ckpt_dir: Path, step: int, save_index: int) -> None:
    """The crash window: SIGKILL *during* save call N — after orbax began
    writing, before the integrity manifest. Simulated faithfully: the
    just-written step is truncated (the torn write a mid-flight kill leaves)
    and the process dies uncatchably. Hooked before the manifest write."""
    plan = _ACTIVE
    if plan is None:
        return
    fault = plan.save_fault("kill", save_index)
    if fault is not None:
        plan._log(fault, save_index=save_index, step=step)
        corrupt_checkpoint_step(ckpt_dir, step, mode="truncate")
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_corrupt_after_save(ckpt_dir: Path, step: int, save_index: int) -> None:
    """Silent post-save corruption: the step's bytes rot *after* the
    manifest was written (bit rot, torn replication) — the case only the
    checksum verification catches. Hooked after the manifest write."""
    plan = _ACTIVE
    if plan is None:
        return
    fault = plan.save_fault("corrupt_checkpoint", save_index)
    if fault is not None:
        plan._log(fault, save_index=save_index, step=step)
        corrupt_checkpoint_step(ckpt_dir, step, mode="garbage")


# ------------------------------------------------------------- sigterm hook
def maybe_sigterm(step: int, shutdown=None) -> None:
    """Delivers the scripted preemption at global step ``step``.

    With a `GracefulShutdown` in hand the request is set directly (exactly
    what the signal handler would do, minus delivery timing jitter — the
    deterministic in-process path). Without one, a real ``SIGTERM`` is sent
    to this process (the subprocess e2e path).
    """
    plan = _ACTIVE
    if plan is None:
        return
    fault = plan.take_sigterm(step)
    if fault is None:
        return
    plan._log(fault, step=step)
    if shutdown is not None:
        shutdown.request()
    else:
        os.kill(os.getpid(), signal.SIGTERM)


# --------------------------------------------------------------- disk faults
def corrupt_checkpoint_step(ckpt_dir: Path | str, step: int, mode: str = "truncate") -> Path:
    """Corrupts the largest file of checkpoint ``step`` on disk.

    ``truncate`` halves the file (a partial write / torn upload);
    ``garbage`` rewrites its first bytes (silent bit corruption — the case
    only the checksum manifest catches). Returns the corrupted path. Also a
    test utility, usable without any plan installed.
    """
    step_dir = Path(ckpt_dir) / str(step)
    files = sorted(
        (p for p in step_dir.rglob("*") if p.is_file()),
        key=lambda p: p.stat().st_size,
        reverse=True,
    )
    if not files:
        raise FileNotFoundError(f"no files to corrupt under {step_dir}")
    target = files[0]
    if mode == "truncate":
        size = target.stat().st_size
        with open(target, "r+b") as f:
            f.truncate(max(size // 2, 1))
    elif mode == "garbage":
        with open(target, "r+b") as f:
            f.write(b"\xde\xad\xbe\xef" * 8)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    return target
