"""JSON round-tripping for config objects.

Re-designed equivalent of the reference's ``JSONableMixin``
(``/root/reference/EventStream/utils.py:214-363``). Every config object in the
framework serializes to plain JSON so that run artifacts (``config.json``,
``vocabulary_config.json`` etc.) keep the same on-disk contract as the
reference implementation.
"""

from __future__ import annotations

import dataclasses
import enum
import json
from pathlib import Path
from typing import Any, TypeVar

T = TypeVar("T", bound="JSONableMixin")


def _jsonify(obj: Any) -> Any:
    """Recursively converts an object into JSON-compatible primitives."""
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, JSONableMixin):
        return obj.to_dict()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _jsonify(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {k: _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    if isinstance(obj, Path):
        return str(obj)
    return obj


class JSONableMixin:
    """Mixin granting ``to_dict``/``from_dict``/``to_json_file``/``from_json_file``.

    Dataclass subclasses get ``to_dict`` for free; other classes must override.

    Examples:
        >>> import dataclasses
        >>> @dataclasses.dataclass
        ... class MyData(JSONableMixin):
        ...     name: str
        >>> MyData("hi").to_dict()
        {'name': 'hi'}
        >>> MyData.from_dict({'name': 'hi'})
        MyData(name='hi')
    """

    @classmethod
    def from_dict(cls: type[T], as_dict: dict) -> T:
        """Constructs this class from a dictionary of constructor kwargs."""
        return cls(**as_dict)

    def to_dict(self) -> dict[str, Any]:
        """Returns a plain-JSON dictionary representation of this object."""
        if dataclasses.is_dataclass(self):
            out = {}
            for f in dataclasses.fields(self):
                out[f.name] = _jsonify(getattr(self, f.name))
            return out
        raise NotImplementedError("This must be overwritten in non-dataclass derived classes!")

    def to_json_file(self, fp: Path | str, do_overwrite: bool = False) -> None:
        """Writes this object's dict form to ``fp`` as JSON."""
        fp = Path(fp)
        if fp.exists() and not do_overwrite:
            raise FileExistsError(f"{fp} exists and do_overwrite = {do_overwrite}")
        fp.parent.mkdir(parents=True, exist_ok=True)
        fp.write_text(json.dumps(self.to_dict()))

    @classmethod
    def from_json_file(cls: type[T], fp: Path | str) -> T:
        """Reads an object of this class from the JSON file at ``fp``."""
        with open(fp) as f:
            return cls.from_dict(json.load(f))
