"""A minimal structured-config system (Hydra-style, dependency-free).

The reference drives every entry point through Hydra structured configs
registered via its ``hydra_dataclass`` decorator
(``/root/reference/EventStream/utils.py:395-414``) plus YAML files with
``${...}`` interpolations. Hydra/omegaconf are not available in this
environment, so this module re-implements the slice of behavior the framework
needs, keeping YAML configs written for the reference working unchanged:

* ``config_dataclass`` — decorator registering a dataclass in a global store
  under its snake_case name (Hydra ``ConfigStore`` analog).
* ``load_config`` — build a registered config from an optional YAML file plus
  dotted-key command line overrides (``a.b.c=value``), with type coercion
  driven by dataclass annotations.
* ``${key}`` / ``${now:%fmt}`` interpolation on string fields.
"""

from __future__ import annotations

import dataclasses
import datetime
import enum
import re
import sys
import types
import typing
from pathlib import Path
from typing import Any, Callable, TypeVar, Union

import yaml

T = TypeVar("T")

CONFIG_STORE: dict[str, type] = {}


def _snake_case(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


def config_dataclass(cls: type[T]) -> type[T]:
    """Registers ``cls`` (made a dataclass if not already) in the config store.

    The store key is the snake_case class name, mirroring the reference's
    ``hydra_dataclass`` registration contract so e.g. ``PretrainConfig``
    resolves as ``pretrain_config``.
    """
    if not dataclasses.is_dataclass(cls):
        cls = dataclasses.dataclass(cls)
    CONFIG_STORE[_snake_case(cls.__name__)] = cls
    return cls


def _strip_optional(tp: Any) -> Any:
    origin = typing.get_origin(tp)
    if origin in (typing.Union, types.UnionType):
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _coerce(value: Any, tp: Any) -> Any:
    """Coerces a YAML/CLI value to the annotated type where unambiguous."""
    tp = _strip_optional(tp)
    if value is None:
        return None
    origin = typing.get_origin(tp)
    if origin in (typing.Union, types.UnionType):
        return value
    if tp is Any or tp is dataclasses.MISSING:
        return value
    if isinstance(tp, type):
        if issubclass(tp, enum.Enum):
            return tp(value) if not isinstance(value, tp) else value
        if dataclasses.is_dataclass(tp):
            if isinstance(value, tp):
                return value
            if isinstance(value, dict):
                return structure(value, tp)
            return value
        if tp is Path:
            return Path(value)
        if tp is bool and isinstance(value, str):
            return value.lower() in ("true", "1", "yes")
        if tp in (int, float, str) and not isinstance(value, (dict, list)):
            return tp(value)
    if origin in (list, tuple) and isinstance(value, (list, tuple)):
        args = typing.get_args(tp)
        if args:
            return list(_coerce(v, args[0]) for v in value)
        return list(value)
    if origin is dict and isinstance(value, dict):
        args = typing.get_args(tp)
        if len(args) == 2:
            return {k: _coerce(v, args[1]) for k, v in value.items()}
        return value
    return value


def structure(d: dict[str, Any], cls: type[T]) -> T:
    """Builds dataclass ``cls`` from a (possibly nested) plain dictionary."""
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for k, v in d.items():
        if k in fields:
            kwargs[k] = _coerce(v, fields[k].type if not isinstance(fields[k].type, str) else _resolve_annotation(cls, k))
        else:
            kwargs[k] = v
    return cls(**kwargs)


def _resolve_annotation(cls: type, field_name: str) -> Any:
    try:
        hints = typing.get_type_hints(cls)
        return hints.get(field_name, Any)
    except Exception:
        return Any


def unstructure(obj: Any) -> Any:
    """Inverse of `structure`: dataclass tree → plain dict/JSON primitives."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: unstructure(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, dict):
        return {k: unstructure(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [unstructure(v) for v in obj]
    if isinstance(obj, Path):
        return str(obj)
    return obj


_INTERP_RE = re.compile(r"\$\{([^}]+)\}")


def _interpolate_str(s: str, root: dict[str, Any]) -> Any:
    def lookup(expr: str) -> Any:
        if expr.startswith("now:"):
            return datetime.datetime.now().strftime(expr[4:])
        if expr.startswith("oc.env:"):
            import os

            spec = expr[len("oc.env:") :]
            var, _, default = spec.partition(",")
            val = os.environ.get(var)
            if val is not None:
                return val
            if _:
                return default
            raise KeyError(f"Environment variable '{var}' (from ${{{expr}}}) is not set")
        node: Any = root
        for part in expr.split("."):
            if isinstance(node, dict) and part in node:
                node = node[part]
            else:
                return None
        return node

    full = _INTERP_RE.fullmatch(s)
    if full:
        resolved = lookup(full.group(1))
        return s if resolved is None else resolved

    def sub_one(m: re.Match) -> str:
        resolved = lookup(m.group(1))
        return m.group(0) if resolved is None else str(resolved)

    return _INTERP_RE.sub(sub_one, s)


def resolve_interpolations(d: dict[str, Any], root: dict[str, Any] | None = None) -> dict[str, Any]:
    """Resolves ``${...}`` interpolations in all string values, in place-order.

    Repeats until fixpoint (bounded) so chained references resolve.
    """
    root = root if root is not None else d

    def _resolve(node: Any) -> Any:
        if isinstance(node, dict):
            return {k: _resolve(v) for k, v in node.items()}
        if isinstance(node, list):
            return [_resolve(v) for v in node]
        if isinstance(node, str) and "${" in node:
            return _interpolate_str(node, root)
        return node

    for _ in range(5):
        new = _resolve(d)
        if new == d:
            break
        d = new
        root = d
    return d


def set_dotted(d: dict[str, Any], key: str, value: Any) -> None:
    """Sets ``d["a"]["b"] = value`` for dotted key ``"a.b"``, creating levels."""
    parts = key.split(".")
    node = d
    for p in parts[:-1]:
        node = node.setdefault(p, {})
        if not isinstance(node, dict):
            raise ValueError(f"Cannot set {key}: {p} is not a mapping")
    node[parts[-1]] = value


def parse_override_value(raw: str) -> Any:
    """Parses a CLI override value using YAML rules (ints, floats, lists, null)."""
    try:
        return yaml.safe_load(raw)
    except yaml.YAMLError:
        return raw


def deep_merge(dst: dict, src: dict) -> dict:
    """Recursively merges ``src`` into ``dst`` in place (src wins); returns dst."""
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            deep_merge(dst[k], v)
        else:
            dst[k] = v
    return dst


def split_config_arg(argv: list[str]) -> tuple[str | None, list[str]]:
    """Extracts a ``--config <yaml>`` pair from CLI args; returns (path, rest)."""
    argv = list(argv)
    yaml_fp = None
    if "--config" in argv:
        i = argv.index("--config")
        if i + 1 >= len(argv):
            raise ValueError("--config requires a YAML file path argument")
        yaml_fp = argv[i + 1]
        del argv[i : i + 2]
    return yaml_fp, argv


def parse_overrides(argv: list[str]) -> dict[str, Any]:
    """Parses ``key=value`` CLI args (Hydra syntax) into a nested dict.

    Hydra's bare ``~key`` deletion syntax sets the key to None; other
    ``=``-less tokens are rejected loudly rather than silently dropped.
    """
    out: dict[str, Any] = {}
    for arg in argv:
        if "=" not in arg:
            if arg.startswith("~"):
                set_dotted(out, arg[1:], None)
                continue
            raise ValueError(f"Override {arg!r} is not of the form key=value")
        key, _, raw = arg.partition("=")
        key = key.lstrip("+~")  # hydra's +key= / ~key syntax: treat as plain set
        set_dotted(out, key, parse_override_value(raw))
    return out


def load_config(
    config_cls: type[T] | str,
    yaml_file: Path | str | None = None,
    overrides: list[str] | dict[str, Any] | None = None,
    defaults: dict[str, Any] | None = None,
) -> T:
    """Builds a structured config: defaults ← YAML ← CLI overrides.

    Args:
        config_cls: The registered dataclass (or its store name).
        yaml_file: Optional YAML file of base values.
        overrides: Either pre-parsed nested dict or ``key=value`` strings.
        defaults: Optional extra base-layer values below the YAML file.
    """
    if isinstance(config_cls, str):
        config_cls = CONFIG_STORE[config_cls]

    # Seed with *declared* dataclass defaults so ${...} interpolations can
    # reference them even when neither YAML nor CLI set the referenced key.
    # Nested dataclasses seed from their declared field defaults rather than
    # an instantiated object: __post_init__-derived values (e.g.
    # OptimizationConfig.end_lr computed from init_lr) must not be baked in,
    # or overriding one of their inputs later would conflict (hydra's
    # ConfigStore has the same declared-defaults semantics).
    def declared_defaults(cls: type) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for f in dataclasses.fields(cls):
            if f.default is not dataclasses.MISSING:
                v = f.default
            elif f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
                v = f.default_factory()
            else:
                continue
            if dataclasses.is_dataclass(v) and not isinstance(v, type):
                # A factory instance indistinguishable from the plain default
                # seeds from declared field defaults (so __post_init__-derived
                # values don't get baked in); a factory that customized any
                # field keeps its instance state verbatim — structure() will
                # re-run __post_init__ and re-derive consistently.
                try:
                    is_plain_default = unstructure(type(v)()) == unstructure(v)
                except TypeError:
                    is_plain_default = False
                out[f.name] = declared_defaults(type(v)) if is_plain_default else unstructure(v)
            else:
                out[f.name] = unstructure(v)
        return out

    merged: dict[str, Any] = declared_defaults(config_cls)

    def merge(dst: dict, src: dict) -> None:
        for k, v in src.items():
            if isinstance(v, dict) and isinstance(dst.get(k), dict):
                merge(dst[k], v)
            else:
                dst[k] = v

    if defaults:
        merge(merged, defaults)
    if yaml_file is not None:
        with open(yaml_file) as f:
            loaded = yaml.safe_load(f) or {}
        loaded.pop("defaults", None)  # hydra defaults-list: handled by caller
        loaded.pop("hydra", None)  # hydra runtime block: not config values
        merge(merged, loaded)
    if overrides:
        if isinstance(overrides, list):
            overrides = parse_overrides(overrides)
        merge(merged, overrides)

    merged = resolve_interpolations(merged)
    return structure(merged, config_cls)


def main_entry(config_cls: type[T], fn: Callable[[T], Any], yaml_file: Path | str | None = None) -> Any:
    """CLI driver: parse ``sys.argv[1:]`` as overrides and invoke ``fn(cfg)``."""
    cfg = load_config(config_cls, yaml_file=yaml_file, overrides=sys.argv[1:])
    return fn(cfg)
