"""Honest device timing on asynchronous / RPC-tunneled JAX backends.

Measuring step time with ``jax.block_until_ready`` + ``perf_counter`` is
WRONG on RPC-style backends (e.g. a tunneled TPU): ``block_until_ready``
can return as soon as the *dispatch* is acknowledged, ~100x before the
computation finishes (measured on this repo's tunnel: a 166M-param train
step "blocked" in 2.3 ms whose sustained cost is ~204 ms — an implied MFU
of 23x the hardware peak, i.e. physically impossible). Only a **host
readback** of computed data (``float(x)`` / ``np.asarray(x)``) is a true
synchronization barrier.

The readback itself costs a data-plane round trip (measured ~80-120 ms on
the tunnel, even when the dispatch path is quiet), so per-step readbacks
overstate cost as badly as fake blocking understates it. The honest
protocol, implemented here:

1. ``readback_echo_ms`` — measure the constant readback RTT.
2. ``sustained_step_ms`` — dispatch ``k`` dependent steps back-to-back,
   force ONE readback at the end, subtract the RTT, divide by ``k``; size
   ``k`` from a calibration run so the residual RTT jitter is amortized to
   a few percent; repeat and take the minimum (contention only inflates).

``dispatch_echo_ms`` (the fake-block echo) is still useful as a cheap
*contention gate* — control-plane congestion correlates with the tunnel's
slow windows — just never as a step-time measurement.
"""

from __future__ import annotations

import time
from typing import Any, Callable

import numpy as np

__all__ = [
    "dispatch_echo_ms",
    "readback_echo_ms",
    "drain",
    "sustained_step_ms",
    "wait_for_quiet",
]

# One definition of "quiet" for every measurement artifact (bench.py,
# scripts/probe_scale.py): quiet dispatch echo is 0.02-1 ms; sustained
# contention windows measure 10-130+ ms.
QUIET_THRESHOLD_MS = 2.0
QUIET_RETRIES = 2
QUIET_WAIT_S = 20.0


def wait_for_quiet(
    threshold_ms: float = QUIET_THRESHOLD_MS,
    retries: int = QUIET_RETRIES,
    wait_s: float = QUIET_WAIT_S,
) -> tuple[float, bool]:
    """Retries the dispatch echo until quiet (or retries exhausted).

    Returns ``(echo_ms, contended)`` — the final pre-flight echo and
    whether it still exceeded the threshold.
    """
    echo = dispatch_echo_ms()
    for _ in range(retries):
        if echo <= threshold_ms:
            break
        time.sleep(wait_s)
        echo = dispatch_echo_ms()
    return echo, bool(echo > threshold_ms)


def drain(x) -> float:
    """Forces completion of ``x``'s computation via a true host readback.

    Returns the scalar-sum payload (so callers can also use it as a value
    barrier). ``jax.block_until_ready`` is NOT sufficient on RPC backends —
    see module docstring.
    """
    import jax.numpy as jnp

    return float(jnp.asarray(x).sum())


def dispatch_echo_ms(n: int = 20) -> float:
    """Min-of-n *dispatch* round trip (fake-block echo): a contention gate.

    On a quiet tunnel this measures 0.02-1 ms; sustained contention windows
    measure 10-130+ ms. It does NOT measure compute time (the block can
    return before the device runs anything).
    """
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a: a @ a)
    x = jnp.ones((256, 256), jnp.float32)
    jax.block_until_ready(f(x))
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(f(x))  # graftcheck: allow GC001 -- measuring the sync latency is the point
        best = min(best, time.perf_counter() - t0)
    return 1000.0 * best


def readback_echo_ms(n: int = 5) -> float:
    """Min-of-n true data-plane round trip: dispatch + compute + readback of
    a tiny program. The constant ``sustained_step_ms`` subtracts."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a: (a @ a).sum())
    x = jnp.ones((8, 8), jnp.float32)
    float(f(x))
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        float(f(x))  # graftcheck: allow GC001 -- measuring the readback latency is the point
        best = min(best, time.perf_counter() - t0)
    return 1000.0 * best


def sustained_step_ms(
    step_fn: Callable,
    state: Any,
    batch: Any,
    rng,
    target_window_ms: float = 3000.0,
    k_min: int = 8,
    k_max: int = 512,
    repeats: int = 2,
) -> tuple[float, Any, dict]:
    """Sustained per-step time of ``step_fn(state, batch, rng) -> (state, loss)``.

    Dispatches ``k`` dependent steps (the returned state feeds the next
    step, so the device cannot overlap them), forces one readback, and
    subtracts the measured readback RTT. ``k`` is sized so the measured
    window is ~``target_window_ms`` — large enough that RTT jitter
    (~±40 ms observed) contributes only a few percent. The minimum over
    ``repeats`` windows is returned (contention can only inflate a window).

    Returns ``(step_ms, state, info)`` where info carries the chosen ``k``,
    the readback RTT, and each window's raw estimate.
    """

    def run(k: int, st):
        t0 = time.perf_counter()
        loss = None
        for _ in range(k):
            st, loss = step_fn(st, batch, rng)
        drain(loss)
        return 1000.0 * (time.perf_counter() - t0), st

    rtt = readback_echo_ms()
    # Calibration window: small k; its own bias (rtt/k_min) only affects
    # the k chosen, not the reported number.
    t_cal, state = run(k_min, state)
    est = max((t_cal - rtt) / k_min, 0.01)
    k = int(min(max(target_window_ms / est, k_min), k_max))

    estimates = []
    for _ in range(repeats):
        rtt_i = readback_echo_ms()
        t, state = run(k, state)
        estimates.append(max(t - rtt_i, 0.0) / k)
    info = {
        "k": k,
        "readback_rtt_ms": round(rtt, 2),
        "window_estimates_ms": [round(e, 4) for e in estimates],
    }
    return min(estimates), state, info
