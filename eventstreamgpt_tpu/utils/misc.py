"""Small shared helpers: count/proportion cutoffs, seeding, phase timing.

TPU-native rebuild of scattered utilities from
``/root/reference/EventStream/utils.py:24-121`` and the external ``ml-mixins``
package the reference depends on (``SeedableMixin``, ``TimeableMixin``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from collections import defaultdict
from contextlib import contextmanager
from functools import wraps
from pathlib import Path
from typing import Any, Callable, Union

import numpy as np

COUNT_OR_PROPORTION = Union[int, float]


def atomic_write_json(fp: Path | str, obj: Any, **json_kwargs: Any) -> None:
    """Atomically publishes ``obj`` as JSON at ``fp`` (tmp + fsync + rename).

    The one durable-sidecar writer (checkpoint metadata, integrity
    manifests, divergence diagnostics): a crash mid-write must never leave a
    truncated JSON file where a reader expects a valid one, and a crash
    right after must still find the bytes on disk — hence the fsync before
    the rename. The tmp name is per-process unique so concurrent writers on
    a shared filesystem (pod-scale multi-host runs) cannot truncate each
    other's in-flight tmp and publish a torn file through the rename.
    """
    fp = Path(fp)
    tmp = fp.with_name(f"{fp.name}.{os.getpid()}.tmp")
    with open(tmp, "w") as f:
        json.dump(obj, f, **json_kwargs)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, fp)
    # The rename itself lives in the directory entry: without fsyncing the
    # parent, a power loss can make the just-published file vanish (and a
    # vanished integrity manifest silently downgrades verification).
    try:
        dirfd = os.open(fp.parent, os.O_RDONLY)
    except OSError:  # platforms/filesystems without directory opens
        return
    try:
        os.fsync(dirfd)
    except OSError:
        pass
    finally:
        os.close(dirfd)


def count_or_proportion(N: int | None, cnt_or_prop: COUNT_OR_PROPORTION) -> int:
    """Resolves a cutoff that may be an absolute count or a fraction of a whole.

    Equivalent contract to ``/root/reference/EventStream/utils.py:24``.

    Examples:
        >>> count_or_proportion(100, 0.1)
        10
        >>> count_or_proportion(None, 11)
        11
        >>> count_or_proportion(100, 0.116)
        12
    """
    match cnt_or_prop:
        case bool():
            raise TypeError(f"{cnt_or_prop} must be a positive integer or a float between 0 or 1")
        case int() if cnt_or_prop > 0:
            return cnt_or_prop
        case int():
            raise ValueError(f"{cnt_or_prop} must be positive if it is an integer")
        case float() if 0 < cnt_or_prop < 1:
            if not isinstance(N, int):
                raise TypeError(f"{N} must be an integer when cnt_or_prop is a float!")
            return int(round(cnt_or_prop * N))
        case float():
            raise ValueError(f"{cnt_or_prop} must be between 0 and 1 if it is a float")
        case _:
            raise TypeError(f"{cnt_or_prop} must be a positive integer or a float between 0 or 1")


def lt_count_or_proportion(
    N_obs: int, cnt_or_prop: COUNT_OR_PROPORTION | None, N_total: int | None = None
) -> bool:
    """True iff ``N_obs`` falls below the resolved cutoff; ``None`` cutoff → False.

    Examples:
        >>> lt_count_or_proportion(10, 0.1, 100)
        False
        >>> lt_count_or_proportion(10, 0.11, 100)
        True
        >>> lt_count_or_proportion(10, None)
        False
    """
    if cnt_or_prop is None:
        return False
    return N_obs < count_or_proportion(N_total, cnt_or_prop)


def num_initial_spaces(s: str) -> int:
    """Number of leading spaces of ``s``.

    Examples:
        >>> num_initial_spaces("  a")
        2
    """
    return len(s) - len(s.lstrip(" "))


class SeedableMixin:
    """Deterministic seeding support for host-side (numpy) randomness.

    Replaces the external ``ml-mixins`` ``SeedableMixin`` the reference uses
    (imported at ``/root/reference/EventStream/data/dataset_base.py:21``).
    Device-side randomness in this framework always flows through explicit
    ``jax.random`` keys instead.
    """

    def _seed(self, seed: int | None = None, key: str | None = None) -> int:
        if seed is None:
            seed = int(np.random.SeedSequence().entropy % (2**31))
        self._past_seeds = getattr(self, "_past_seeds", [])
        self._past_seeds.append((key, seed))
        np.random.seed(seed)
        return seed

    @staticmethod
    def WithSeed(fn: Callable) -> Callable:
        """Decorator: seeds numpy from the ``seed`` kwarg before running ``fn``."""

        @wraps(fn)
        def wrapped(self, *args, seed: int | None = None, **kwargs):
            self._seed(seed=seed, key=fn.__name__)
            return fn(self, *args, **kwargs)

        return wrapped


class TimeableMixin:
    """Accumulates wall-clock durations for named phases.

    Replaces the external ``ml-mixins`` ``TimeableMixin`` (used pervasively in
    the reference ETL, e.g. ``dataset_base.py:606-1062``); kept first-class per
    SURVEY.md §5.1 so every pipeline phase stays measurable.
    """

    @property
    def _timings(self) -> dict[str, list[float]]:
        if not hasattr(self, "_timings_dict"):
            self._timings_dict = defaultdict(list)
        return self._timings_dict

    @contextmanager
    def _time_as(self, key: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self._timings[key].append(time.perf_counter() - start)

    @staticmethod
    def TimeAs(fn: Callable) -> Callable:
        """Decorator form of `_time_as`, keyed on the function name."""

        @wraps(fn)
        def wrapped(self, *args, **kwargs):
            with self._time_as(fn.__name__):
                return fn(self, *args, **kwargs)

        return wrapped

    def _duration_stats(self) -> dict[str, tuple[float, int]]:
        """Returns ``{phase: (total_seconds, n_calls)}`` for all timed phases."""
        return {k: (sum(v), len(v)) for k, v in self._timings.items()}

    def timing_summary(self) -> str:
        """Formatted per-phase wall-clock table, longest phases first.

        SURVEY §5.1: the reference decorates every ETL phase but never reports
        the timings; this surfaces them (printed by scripts/build_dataset).
        """
        stats = sorted(self._duration_stats().items(), key=lambda kv: -kv[1][0])
        if not stats:
            return "(no timed phases)"
        width = max(len(k) for k, _ in stats)
        lines = [f"{'phase':<{width}}  total_s  calls"]
        for k, (total, n) in stats:
            lines.append(f"{k:<{width}}  {total:7.2f}  {n:5d}")
        return "\n".join(lines)


def to_dict_flat(obj: Any, prefix: str = "") -> dict[str, Any]:
    """Flattens a (possibly nested dataclass/dict) object into dotted keys.

    Used by the sweep launcher to map nested configs onto flat W&B-style
    parameter names (reference analog: ``scripts/launch_wandb_hp_sweep.py:24``).

    Examples:
        >>> to_dict_flat({"a": {"b": 1}, "c": 2})
        {'a.b': 1, 'c': 2}
    """
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        obj = {f.name: getattr(obj, f.name) for f in dataclasses.fields(obj)}
    out: dict[str, Any] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            kk = f"{prefix}.{k}" if prefix else str(k)
            if isinstance(v, dict) or (dataclasses.is_dataclass(v) and not isinstance(v, type)):
                out.update(to_dict_flat(v, kk))
            else:
                out[kk] = v
        return out
    out[prefix] = obj
    return out
