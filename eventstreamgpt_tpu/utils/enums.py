"""String-valued enums used throughout the framework.

TPU-native re-design of the enum utilities the reference keeps in
``EventStream/utils.py:139`` (``StrEnum``). Pure Python; no accelerator
dependence.
"""

from __future__ import annotations

import enum


class StrEnum(str, enum.Enum):
    """An enum whose members are (and serialize as) lowercase strings.

    ``enum.auto()`` resolves to the lowercased member name, matching the
    behavior of the reference's backported ``StrEnum``
    (``/root/reference/EventStream/utils.py:139-213``) so that on-disk JSON
    configs remain interchangeable.

    Examples:
        >>> class Color(StrEnum):
        ...     RED = enum.auto()
        ...     DARK_BLUE = enum.auto()
        >>> Color.RED.value
        'red'
        >>> str(Color.DARK_BLUE)
        'dark_blue'
        >>> Color("red") is Color.RED
        True
    """

    def __str__(self) -> str:
        return str(self.value)

    @staticmethod
    def _generate_next_value_(name, start, count, last_values) -> str:
        return name.lower()

    @classmethod
    def values(cls) -> list[str]:
        """Returns all member values of this enum."""
        return list(map(lambda c: c.value, cls))
