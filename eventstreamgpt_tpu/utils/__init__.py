from .config_tool import (
    CONFIG_STORE,
    config_dataclass,
    load_config,
    main_entry,
    parse_overrides,
    resolve_interpolations,
    structure,
    unstructure,
)
from .enums import StrEnum
from .misc import (
    COUNT_OR_PROPORTION,
    SeedableMixin,
    TimeableMixin,
    atomic_write_json,
    count_or_proportion,
    lt_count_or_proportion,
    num_initial_spaces,
    to_dict_flat,
)
from .serialization import JSONableMixin

__all__ = [
    "CONFIG_STORE",
    "COUNT_OR_PROPORTION",
    "JSONableMixin",
    "SeedableMixin",
    "StrEnum",
    "TimeableMixin",
    "atomic_write_json",
    "config_dataclass",
    "count_or_proportion",
    "load_config",
    "lt_count_or_proportion",
    "main_entry",
    "num_initial_spaces",
    "parse_overrides",
    "resolve_interpolations",
    "structure",
    "to_dict_flat",
    "unstructure",
]
