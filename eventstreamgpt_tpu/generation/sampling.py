"""Sampling from model predictions and fixed-shape batch updates.

Rebuild of the generation plumbing in
``/root/reference/EventStream/transformer/model_output.py`` (``sample``
``:1093``, ``_build_new_batch_element`` ``:279``,
``format_updates_to_last_batch_event`` ``:392``, ``append_to_batch`` ``:862``,
``update_last_event_data`` ``:944``, ``strip_unused_indices`` ``:108``).

The reference grows batches by concatenation and compacts data elements with
data-dependent shapes — neither compiles under XLA. Here the generation batch
is **preallocated** to its final size and a write cursor tracks the number of
real events; sampled content is written with ``.at[]`` updates at static
layouts (one slot per single-label/univariate measurement, ``vocab_size``
slots per multi-label/multivariate measurement, zeros where unsampled —
index 0 is padding so unsampled slots are inert), then compacted with a
stable sort on ``index == 0`` (the static-shape equivalent of
``strip_unused_indices``) and truncated to the batch's data-element width.
"""

from __future__ import annotations

import zlib
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import struct

from ..data.types import DataModality, EventStreamBatch, TemporalityType
from ..distributions import Bernoulli, Categorical
from ..models.config import StructuredTransformerConfig
from ..models.embedding import MeasIndexGroupOptions
from ..ops.tensor_ops import gather_last, take_event
from ..models.model_output import GenerativeSequenceModelPredictions
from ..ops import expand_indexed_regression

Array = Any


@struct.dataclass
class GenerativeSequenceModelSamples:
    """One sampled event (reference ``model_output.py:246``)."""

    event_mask: Array  # (B,)
    time_to_event: Optional[Array] = None  # (B,)
    classification: Optional[dict[str, Array]] = None
    regression: Optional[dict[str, Array]] = None
    regression_indices: Optional[dict[str, Array]] = None


@jax.custom_batching.custom_vmap
def _sampling_barrier(x):
    """`optimization_barrier` with a vmap rule (the stock primitive has none
    in this jax version): barriers pass through row-batching untouched."""
    return jax.lax.optimization_barrier(x)


@_sampling_barrier.def_vmap
def _sampling_barrier_vmap(axis_size, in_batched, x):
    return jax.lax.optimization_barrier(x), in_batched[0]


def _named_key(key: jax.Array, name: str) -> jax.Array:
    """A PRNG key derived stably from ``name``.

    Keys are bound to measurement names (via crc32, which is stable across
    processes, unlike ``hash``) rather than dict position, so a cached decode
    that sees only one level's predictions samples identically to an uncached
    full forward that sees them all.
    """
    return jax.random.fold_in(key, zlib.crc32(name.encode()) & 0x7FFFFFFF)


def _greedy_draw(dist) -> Array:
    """The deterministic (greedy) draw of a head distribution: categorical
    heads take their mode, Bernoulli heads threshold at p = 0.5, continuous
    heads take their mean. Shared by ``sample_predictions(greedy=True)`` and
    the speculative-decoding accept rule (serving/spec.py), whose greedy
    bit-identity contract holds *because* both sides call this one
    function."""
    if isinstance(dist, Categorical):
        return dist.mode
    if isinstance(dist, Bernoulli):
        return (dist.probs >= 0.5).astype(jnp.float32)
    return dist.mean


def sample_head_draws(
    preds: GenerativeSequenceModelPredictions,
    key: jax.Array,
    categorical_sampler=None,
    greedy: bool = False,
) -> dict[str, Array]:
    """The raw per-head draws behind `sample_predictions`, keyed by the
    stable head names the named-key derivation already uses (``cls:<m>``,
    ``cls_obs:<m>``, ``reg:<m>``, ``reg_obs:<m>``, ``tte``).

    Split out so speculative decoding (serving/spec.py) can couple draft
    and target draws through the SAME keys and inspect the pre-assembly
    values (the is-observed bit separately from the categorical draw, the
    raw regression draw before the NaN mask) — with zero drift risk:
    `sample_predictions` is exactly ``assemble_event_sample(preds,
    sample_head_draws(...), event_mask)``. Every head's key derives from
    its name (not draw order), so draw ORDER never affects values.
    """

    # The barrier pins every draw's bits against fusion-context
    # sensitivity: when a head's dense epilogue (ELU rate, mixture params,
    # logits) is visible in the same XLA program as the sampler, it can
    # fuse into the draw and compute the distribution parameters 1 ulp off
    # from a materialized forward. Serving's fork() bit-identity contract
    # (CoW branch == independent submission) samples across a program
    # boundary, so every draw must see "materialized" parameters in every
    # context — engine, generate(), and the evaluator all sample through
    # here, so their relative parity pins move together.
    preds = jax.tree_util.tree_map(_sampling_barrier, preds)

    def _draw_categorical(dist: Categorical, k: jax.Array) -> Array:
        if greedy:
            return _greedy_draw(dist)
        if categorical_sampler is not None:
            return categorical_sampler(dist.logits, k)
        return dist.sample(k)

    def _draw(dist, k: jax.Array) -> Array:
        return _greedy_draw(dist) if greedy else dist.sample(k)

    draws: dict[str, Array] = {}
    if preds.classification is not None:
        for k, (is_obs_dist, dist) in preds.classification.items():
            if is_obs_dist is not None:
                if not isinstance(dist, Categorical):
                    raise ValueError(f"Don't know how to sample classification dist {dist}!")
                draws[f"cls_obs:{k}"] = _draw(is_obs_dist, _named_key(key, f"cls_obs:{k}"))
            if isinstance(dist, Categorical):
                draws[f"cls:{k}"] = _draw_categorical(dist, _named_key(key, f"cls:{k}"))
            else:
                draws[f"cls:{k}"] = _draw(dist, _named_key(key, f"cls:{k}"))
    if preds.regression is not None:
        for k, (is_obs_dist, dist) in preds.regression.items():
            draws[f"reg:{k}"] = _draw(dist, _named_key(key, f"reg:{k}"))
            if is_obs_dist is not None:
                draws[f"reg_obs:{k}"] = _draw(is_obs_dist, _named_key(key, f"reg_obs:{k}"))
    if preds.time_to_event is not None:
        if greedy:
            draws["tte"] = preds.time_to_event.mean
        else:
            draws["tte"] = preds.time_to_event.sample(_named_key(key, "tte"))
    return draws


def assemble_event_sample(
    preds: GenerativeSequenceModelPredictions,
    draws: dict[str, Array],
    event_mask: Array,
) -> GenerativeSequenceModelSamples:
    """Assembles raw head draws (`sample_head_draws`) into an event sample:
    is-observed gating for single-label classification (unobserved → 0) and
    regression (unobserved → NaN), and the reference's +inf→1000 TTE clamp."""
    sampled_classification = None
    if preds.classification is not None:
        sampled_classification = {}
        for k, (is_obs_dist, dist) in preds.classification.items():
            samp = draws[f"cls:{k}"]
            if is_obs_dist is None:
                sampled_classification[k] = samp
            else:
                sampled_classification[k] = jnp.where(draws[f"cls_obs:{k}"] == 1, samp, 0)

    sampled_regression = None
    if preds.regression is not None:
        sampled_regression = {}
        for k, (is_obs_dist, dist) in preds.regression.items():
            samp = draws[f"reg:{k}"]
            if is_obs_dist is None:
                sampled_regression[k] = samp
            else:
                is_obs = jnp.broadcast_to((draws[f"reg_obs:{k}"] == 1)[..., None], samp.shape)
                sampled_regression[k] = jnp.where(is_obs, samp, jnp.nan)

    time_to_event = None
    if preds.time_to_event is not None:
        # Reference clamps +inf to 1000 (noting its own hack; ``:1155``).
        time_to_event = jnp.nan_to_num(draws["tte"], posinf=1000.0)

    return GenerativeSequenceModelSamples(
        event_mask=event_mask,
        time_to_event=time_to_event,
        classification=sampled_classification,
        regression=sampled_regression,
        regression_indices=preds.regression_indices,
    )


def sample_predictions(
    preds: GenerativeSequenceModelPredictions,
    event_mask: Array,
    key: jax.Array,
    categorical_sampler=None,
    greedy: bool = False,
) -> GenerativeSequenceModelSamples:
    """Samples an event from per-head predictions (reference ``:1093``).

    ``preds`` must already be sliced to the source event (trailing sequence
    dim removed). ``event_mask`` is the (B,) mask for the sampled event.

    ``categorical_sampler`` optionally replaces every `Categorical` head's
    draw: a ``(logits, key) -> int32`` callable (the serving engine passes
    `ops.fused_sampling.fused_categorical` here — its fused filter+draw
    tail is bit-exact vs ``Categorical.sample`` when unfiltered, so the
    engine's ``generate()`` parity contract survives the swap). ``None``
    keeps the reference multi-op tail.

    ``greedy`` replaces every draw with the head's deterministic statistic
    (`_greedy_draw`: categorical mode, Bernoulli >= 0.5, continuous mean).
    ``key`` is then unused; the PRNG chain still advances identically in
    callers, so flipping the knob never perturbs neighboring draws.
    """
    draws = sample_head_draws(
        preds, key, categorical_sampler=categorical_sampler, greedy=greedy
    )
    return assemble_event_sample(preds, draws, event_mask)


def compact_data_elements(
    dynamic_indices: Array,
    dynamic_measurement_indices: Array,
    dynamic_values: Array,
    dynamic_values_mask: Array,
    out_width: int,
):
    """Static-shape ``strip_unused_indices`` (reference ``:108``): moves
    nonzero-index elements to the front via stable sort, truncates/pads to
    ``out_width``."""
    order = jnp.argsort(dynamic_indices == 0, axis=-1, stable=True)

    # Only the first out_width permuted slots survive, so truncate the
    # order BEFORE applying it (permute-then-truncate == truncate-the-
    # permutation), and apply it as a one-hot select-reduce rather than
    # take_along_axis: the input width here is the concat of every
    # measurement's candidate elements (~4k with multi-label vocabularies)
    # and XLA's per-element gather lowering measured ~1.3 ms per call per
    # decode event. The truncated one-hot is (out_width, width) per row.
    # The order is injective, so exactly one position contributes per
    # output slot (NaN values at selected slots are preserved).
    cur = dynamic_indices.shape[-1]
    keep = min(cur, out_width)
    kept_order = order[..., :keep]

    def take(x):
        return gather_last(x, kept_order)

    di = take(dynamic_indices)
    dmi = take(dynamic_measurement_indices)
    dv = take(dynamic_values)
    dvm = take(dynamic_values_mask)

    if keep < out_width:
        pad = [(0, 0)] * (di.ndim - 1) + [(0, out_width - keep)]
        di = jnp.pad(di, pad)
        dmi = jnp.pad(dmi, pad)
        dv = jnp.pad(dv, pad)
        dvm = jnp.pad(dvm, pad)
    # Zero out everything tied to padding indices.
    valid = di != 0
    return di, jnp.where(valid, dmi, 0), jnp.where(valid & dvm, dv, 0.0), valid & dvm


def _functor_elements(
    sample: GenerativeSequenceModelSamples,
    batch: EventStreamBatch,
    config: StructuredTransformerConfig,
    cursor: Array,
):
    """Computes FUNCTIONAL_TIME_DEPENDENT elements for the new event.

    Reference ``_build_new_batch_element`` ``:318-358``: one element per
    functor measurement, updated analytically from the prior event.
    """
    B = batch.event_mask.shape[0]
    prior_idx = cursor - 1

    def at_prior(x):
        """Each row's prior-event slice, (B, L, M) -> (B, M) (take_event)."""
        return take_event(x, prior_idx)

    prior_indices_all = at_prior(batch.dynamic_indices)
    prior_meas_all = at_prior(batch.dynamic_measurement_indices)
    prior_vals_all = at_prior(batch.dynamic_values)
    prior_vmask_all = at_prior(batch.dynamic_values_mask)

    # New absolute time (minutes since epoch): start_time + duration-so-far +
    # sampled TTE. Durations exclude the filler delta at the prior event.
    positions = jnp.arange(batch.sequence_length)[None, :]
    prior_cmp = prior_idx[:, None] if getattr(prior_idx, "ndim", 0) == 1 else prior_idx
    deltas_before = jnp.where(
        (positions < prior_cmp) & batch.event_mask, batch.time_delta, 0.0
    ).sum(-1)
    start_time = batch.start_time if batch.start_time is not None else jnp.zeros((B,))
    new_time = jnp.where(
        sample.event_mask, start_time + deltas_before + sample.time_to_event, 0.0
    )

    els_idx, els_meas, els_val, els_vmask = [], [], [], []
    for m, cfg in config.measurement_configs.items():
        if cfg.temporality != TemporalityType.FUNCTIONAL_TIME_DEPENDENT:
            continue
        if cfg.modality == DataModality.DROPPED:
            continue
        meas_idx = config.measurements_idxmap[m]
        offset = config.vocab_offsets_by_measurement[m]

        is_m = prior_meas_all == meas_idx
        indices = jnp.where(is_m, prior_indices_all, 0).sum(-1)
        vals = jnp.where(is_m & prior_vmask_all, prior_vals_all, 0.0).sum(-1)

        new_indices, new_values = cfg.functor.update_from_prior_timepoint(
            prior_indices=indices - offset,
            prior_values=vals,
            new_delta=sample.time_to_event,
            new_time=new_time,
            vocab=cfg.vocabulary,
            measurement_metadata=cfg.measurement_metadata,
        )
        new_indices = new_indices + offset
        els_idx.append(new_indices)
        els_meas.append(jnp.full_like(new_indices, meas_idx))
        els_val.append(jnp.nan_to_num(new_values, nan=0.0, posinf=0.0, neginf=0.0))
        els_vmask.append(~jnp.isnan(new_values))

    if not els_idx:
        z = jnp.zeros((B, 0), dtype=batch.dynamic_indices.dtype)
        return z, z, z.astype(jnp.float32), z.astype(bool), new_time
    return (
        jnp.stack(els_idx, -1),
        jnp.stack(els_meas, -1),
        jnp.stack(els_val, -1),
        jnp.stack(els_vmask, -1),
        new_time,
    )


def append_new_event(
    batch: EventStreamBatch,
    sample: GenerativeSequenceModelSamples,
    config: StructuredTransformerConfig,
    cursor: Array,
) -> EventStreamBatch:
    """Writes the sampled TTE + functor elements as event ``cursor``.

    Equivalent to the reference ``append_to_batch`` (``:862``) on a
    preallocated batch: ``time_delta[cursor-1] = TTE``; the new event gets the
    filler delta 1, the sampled event mask, and functor-computed elements.
    """
    B, L, M = batch.dynamic_indices.shape
    f_idx, f_meas, f_val, f_vmask, _ = _functor_elements(sample, batch, config, cursor)
    nf = f_idx.shape[-1]

    bcols = jnp.arange(B)
    time_delta = batch.time_delta.at[bcols, cursor - 1].set(
        jnp.where(sample.event_mask, sample.time_to_event, batch.time_delta[bcols, cursor - 1])
    )
    time_delta = time_delta.at[bcols, cursor].set(1.0)
    event_mask = batch.event_mask.at[bcols, cursor].set(sample.event_mask)

    new_idx = jnp.zeros((B, M), dtype=batch.dynamic_indices.dtype)
    new_meas = jnp.zeros((B, M), dtype=batch.dynamic_measurement_indices.dtype)
    new_val = jnp.zeros((B, M), dtype=batch.dynamic_values.dtype)
    new_vmask = jnp.zeros((B, M), dtype=bool)
    if nf > 0:
        new_idx = new_idx.at[:, :nf].set(f_idx)
        new_meas = new_meas.at[:, :nf].set(f_meas)
        new_val = new_val.at[:, :nf].set(f_val)
        new_vmask = new_vmask.at[:, :nf].set(f_vmask)

    # Zero content for non-events.
    em = sample.event_mask[:, None]
    new_idx = jnp.where(em, new_idx, 0)
    new_meas = jnp.where(em, new_meas, 0)
    new_val = jnp.where(em, new_val, 0.0)
    new_vmask = new_vmask & em

    return batch.replace(
        time_delta=time_delta,
        event_mask=event_mask,
        dynamic_indices=batch.dynamic_indices.at[bcols, cursor].set(new_idx),
        dynamic_measurement_indices=batch.dynamic_measurement_indices.at[bcols, cursor].set(new_meas),
        dynamic_values=batch.dynamic_values.at[bcols, cursor].set(new_val),
        dynamic_values_mask=batch.dynamic_values_mask.at[bcols, cursor].set(new_vmask),
    )


def _format_new_elements(
    sample: GenerativeSequenceModelSamples,
    batch: EventStreamBatch,
    config: StructuredTransformerConfig,
    measurements_to_fill,
    cursor: Array,
):
    """Fixed-layout content arrays for the sampled measurements.

    Reference ``format_updates_to_last_batch_event`` (``:392``), with zeros in
    unsampled slots instead of dynamic stripping.
    """
    B = batch.event_mask.shape[0]
    idx_parts, meas_parts, val_parts, vmask_parts = [], [], [], []

    def add_single_label(m):
        offset = config.vocab_offsets_by_measurement[m]
        preds = sample.classification[m]
        indices = (offset + preds)[:, None]
        idx_parts.append(indices)
        meas_parts.append(jnp.full_like(indices, config.measurements_idxmap[m]))
        val_parts.append(jnp.zeros_like(indices, dtype=jnp.float32))
        vmask_parts.append(jnp.zeros_like(indices, dtype=bool))

    def add_multi_label(m):
        offset = config.vocab_offsets_by_measurement[m]
        V = config.vocab_sizes_by_measurement[m]
        preds = sample.classification[m]  # (B, V) binary
        indices = jnp.where(preds == 1, jnp.arange(V)[None, :] + offset, 0)
        idx_parts.append(indices)
        meas_parts.append(jnp.where(indices != 0, config.measurements_idxmap[m], 0))
        return indices

    def add_multivariate_regression(m, indices, aligned_to_vocab):
        offset = config.vocab_offsets_by_measurement[m]
        V = config.vocab_sizes_by_measurement[m]
        regressed = sample.regression[m]
        regressed_mask = jnp.ones_like(regressed, dtype=bool)
        if (
            sample.regression_indices is not None
            and m in sample.regression_indices
            and sample.regression_indices[m] is not None
        ):
            ridx = sample.regression_indices[m]
            regressed = expand_indexed_regression(jnp.nan_to_num(regressed, nan=0.0), ridx, V)
            regressed_mask = (
                expand_indexed_regression(regressed_mask.astype(jnp.float32), ridx, V) > 0
            )
        mask = indices >= offset
        if aligned_to_vocab:
            # `indices` from add_multi_label is vocab-parallel: column j is
            # offset+j where sampled, 0 elsewhere — so the gather is the
            # identity on every masked column and the unmasked ones are
            # zeroed below anyway. Skip it: gathering (B, V) from (B, V)
            # was the hottest residual op of the decode scan.
            values = regressed
            values_mask = regressed_mask
        else:
            gather_idx = jnp.where(mask, indices - offset, 0)
            # gather_last, not take_along_axis: gathering a few dozen
            # observed targets from the (B, vocab) regression plane lowers
            # to a per-element gather (~2 ms/event, device profile).
            values = gather_last(regressed, gather_idx)
            values_mask = gather_last(regressed_mask, gather_idx)
        val_parts.append(jnp.where(mask, jnp.nan_to_num(values, nan=0.0), 0.0))
        vmask_parts.append(jnp.where(mask, values_mask & ~jnp.isnan(values), False))

    def add_univariate_regression(m):
        preds = sample.regression[m]
        preds = preds[..., 0] if preds.ndim == 2 else preds
        obs = ~jnp.isnan(preds)
        val_parts.append(jnp.nan_to_num(preds, nan=0.0)[:, None])
        vmask_parts.append(obs[:, None])
        idx_parts.append((config.vocab_offsets_by_measurement[m] * obs.astype(jnp.int32))[:, None])
        meas_parts.append((config.measurements_idxmap[m] * obs.astype(jnp.int32))[:, None])

    if "event_type" in measurements_to_fill:
        add_single_label("event_type")

    for m in measurements_to_fill:
        group_mode = None
        if isinstance(m, (tuple, list)):
            m, group_mode = m
        if m == "event_type":
            continue
        modality = config.measurement_configs[m].modality

        if modality == DataModality.SINGLE_LABEL_CLASSIFICATION and group_mode is None:
            add_single_label(m)
        elif modality == DataModality.MULTI_LABEL_CLASSIFICATION and group_mode is None:
            indices = add_multi_label(m)
            val_parts.append(jnp.zeros_like(indices, dtype=jnp.float32))
            vmask_parts.append(jnp.zeros_like(indices, dtype=bool))
        elif modality == DataModality.UNIVARIATE_REGRESSION and group_mode is None:
            add_univariate_regression(m)
        elif modality == DataModality.MULTIVARIATE_REGRESSION and group_mode in (
            None,
            MeasIndexGroupOptions.CATEGORICAL_AND_NUMERICAL,
        ):
            indices = add_multi_label(m)
            add_multivariate_regression(m, indices, aligned_to_vocab=True)
        elif modality == DataModality.MULTIVARIATE_REGRESSION and group_mode == (
            MeasIndexGroupOptions.CATEGORICAL_ONLY
        ):
            indices = add_multi_label(m)
            val_parts.append(jnp.zeros_like(indices, dtype=jnp.float32))
            vmask_parts.append(jnp.zeros_like(indices, dtype=bool))
        elif modality == DataModality.MULTIVARIATE_REGRESSION and group_mode == (
            MeasIndexGroupOptions.NUMERICAL_ONLY
        ):
            meas_idx = config.measurements_idxmap[m]
            bcols = jnp.arange(B)
            cur_meas = batch.dynamic_measurement_indices[bcols, cursor - 1]
            cur_idx = batch.dynamic_indices[bcols, cursor - 1]
            indices = jnp.where(cur_meas == meas_idx, cur_idx, 0)
            idx_parts.append(indices)
            meas_parts.append(jnp.where(indices != 0, meas_idx, 0))
            add_multivariate_regression(m, indices, aligned_to_vocab=False)
        else:
            raise ValueError(f"{modality}, {group_mode} invalid!")

    new_idx = jnp.concatenate(idx_parts, axis=1)
    new_meas = jnp.concatenate(meas_parts, axis=1)
    new_val = jnp.concatenate(val_parts, axis=1)
    new_vmask = jnp.concatenate(vmask_parts, axis=1)
    return new_idx, new_meas, new_val, new_vmask


def update_last_event_data(
    batch: EventStreamBatch,
    sample: GenerativeSequenceModelSamples,
    config: StructuredTransformerConfig,
    cursor: Array,
    measurements_to_fill=None,
) -> EventStreamBatch:
    """Merges sampled content into event ``cursor - 1``.

    Reference ``update_last_event_data`` (``:944``): existing elements are
    kept (minus categorical duplicates for NUMERICAL_ONLY fills), new sampled
    elements appended, then everything is compacted to the batch's
    data-element width.
    """
    if measurements_to_fill is None:
        measurements_to_fill = ["event_type"]
        for m, cfg in config.measurement_configs.items():
            if not cfg.is_dropped and cfg.temporality == TemporalityType.DYNAMIC:
                measurements_to_fill.append(m)
        measurements_to_fill = set(measurements_to_fill)
    if not measurements_to_fill:
        return batch
    if "time" in measurements_to_fill:
        raise ValueError("You shouldn't ever be trying to fill the 'time' aspect of a batch!")

    B, L, M = batch.dynamic_indices.shape
    bcols = jnp.arange(B)
    prev_idx = batch.dynamic_indices[bcols, cursor - 1]
    prev_meas = batch.dynamic_measurement_indices[bcols, cursor - 1]
    prev_val = batch.dynamic_values[bcols, cursor - 1]
    prev_vmask = batch.dynamic_values_mask[bcols, cursor - 1]

    drop = jnp.zeros_like(prev_idx, dtype=bool)
    for m in measurements_to_fill:
        if isinstance(m, (tuple, list)) and m[1] == MeasIndexGroupOptions.NUMERICAL_ONLY:
            drop = drop | (prev_meas == config.measurements_idxmap[m[0]])
    prev_idx = jnp.where(drop, 0, prev_idx)
    prev_meas = jnp.where(drop, 0, prev_meas)
    prev_val = jnp.where(drop, 0.0, prev_val)
    prev_vmask = jnp.where(drop, False, prev_vmask)

    new_idx, new_meas, new_val, new_vmask = _format_new_elements(
        sample, batch, config, measurements_to_fill, cursor
    )

    # Only fill content for real events.
    em = sample.event_mask[:, None]
    new_idx = jnp.where(em, new_idx, 0)
    new_meas = jnp.where(em, new_meas, 0)
    new_val = jnp.where(em, new_val, 0.0)
    new_vmask = new_vmask & em

    all_idx = jnp.concatenate([prev_idx, new_idx], axis=1)
    all_meas = jnp.concatenate([prev_meas, new_meas], axis=1)
    all_val = jnp.concatenate([prev_val, new_val], axis=1)
    all_vmask = jnp.concatenate([prev_vmask, new_vmask], axis=1)

    di, dmi, dv, dvm = compact_data_elements(all_idx, all_meas, all_val, all_vmask, M)

    return batch.replace(
        dynamic_indices=batch.dynamic_indices.at[bcols, cursor - 1].set(di),
        dynamic_measurement_indices=batch.dynamic_measurement_indices.at[bcols, cursor - 1].set(dmi),
        dynamic_values=batch.dynamic_values.at[bcols, cursor - 1].set(dv),
        dynamic_values_mask=batch.dynamic_values_mask.at[bcols, cursor - 1].set(dvm),
    )
