"""Autoregressive generation: sampling, fixed-shape batch updates, the loop."""

from .sampling import (  # noqa: F401
    GenerativeSequenceModelSamples,
    append_new_event,
    compact_data_elements,
    sample_predictions,
    update_last_event_data,
)
from .generation_utils import GenerationOutput, generate  # noqa: F401
from .stopping_criteria import (  # noqa: F401
    DeadRowCriteria,
    DeviceCriterion,
    MaxLengthCriteria,
    StoppingCriteria,
    StoppingCriteriaList,
)
