"""Stopping criteria for event-stream generation.

Rebuild of
``/root/reference/EventStream/transformer/generation/generation_stopping_criteria.py``:
an ABC judging whole batches on **event count** (not token count), a
max-length criterion, and a list combinator.

Two evaluation protocols coexist:

* the reference's **host protocol** (`StoppingCriteria.__call__`): judge the
  whole batch on host between steps. `generate()` supports it on its slow
  (per-event Python dispatch) path.
* the **device protocol** (`DeviceCriterion.row_done`): judge each row from
  device-resident per-row decode state, inside the jitted decode program —
  no host sync, rows stop independently. The serving engine
  (``serving/engine.py``) consumes these; `MaxLengthCriteria` implements
  both, so one criterion object works on either path.
"""

from __future__ import annotations

import abc
from typing import Any

from ..data.types import EventStreamBatch

Array = Any


class StoppingCriteria(abc.ABC):
    """Decides whether generation should stop for the whole batch."""

    @abc.abstractmethod
    def __call__(self, batch: EventStreamBatch, **kwargs) -> bool: ...


class DeviceCriterion(abc.ABC):
    """Per-row, device-evaluable stopping protocol (the engine's fast path).

    ``row_done`` is traced into the jitted decode step once per engine
    program; it must be a pure jnp function of the given per-row state and
    return a ``(n_slots,)`` bool array (True = row finished). Criteria that
    need host data or whole-batch views stay on the host
    `StoppingCriteria` protocol and the `generate()` slow path.
    """

    @abc.abstractmethod
    def row_done(
        self,
        *,
        big: EventStreamBatch,
        cursor: Array,
        base_len: Array,
        n_generated: Array,
        budget: Array,
    ) -> Array:
        """Per-row done verdicts after a completed decode step.

        Args:
            big: the engine's preallocated content buffer (rows = slots).
            cursor: ``(S,)`` int32 — events held per row (prompt + written).
            base_len: ``(S,)`` int32 — prompt events per row.
            n_generated: ``(S,)`` int32 — REAL generated events per row.
            budget: ``(S,)`` int32 — per-row ``max_new_events``.
        """


class MaxLengthCriteria(StoppingCriteria, DeviceCriterion):
    """Stops once the batch holds ``max_length`` events (reference ``:31``).

    On the device protocol the bound applies per row: a row is done when ITS
    event count reaches ``max_length``, independent of its cohort.
    """

    def __init__(self, max_length: int):
        self.max_length = max_length

    def __call__(self, batch: EventStreamBatch, n_events: int | None = None, **kwargs) -> bool:
        n = n_events if n_events is not None else batch.sequence_length
        return n >= self.max_length

    def row_done(self, *, cursor, **kwargs):
        return cursor >= self.max_length


class DeadRowCriteria(DeviceCriterion):
    """Stops rows whose newest event is a non-event (device protocol only).

    Once a row writes a masked event every later event is masked too
    (``sample.event_mask`` propagates the previous event's bit), so the row
    can never produce another real event: decoding it further is pure waste.
    Semantically loss-free — the skipped steps would have produced only
    masked padding. This is the engine's answer to cohort rows that are
    "already done or unpredictable" burning full-horizon decode in
    ``generate()``.
    """

    def row_done(self, *, big, cursor, base_len, **kwargs):
        import jax.numpy as jnp

        from ..ops.tensor_ops import take_event

        last_real = take_event(big.event_mask, cursor - 1)
        # Only rows that have started generating can be declared dead — the
        # prompt's own final event is judged by the first decode step.
        return (~last_real) & (cursor > base_len)


class StoppingCriteriaList(list, StoppingCriteria):
    """Stops when any member criterion fires (reference ``:50``)."""

    def __call__(self, batch: EventStreamBatch, **kwargs) -> bool:
        return any(criteria(batch, **kwargs) for criteria in self)

    @property
    def max_length(self) -> int | None:
        """The tightest max length across members (any member firing stops
        generation, so the minimum is the binding one)."""
        lengths = [c.max_length for c in self if isinstance(c, MaxLengthCriteria)]
        return min(lengths) if lengths else None
