"""Stopping criteria for event-stream generation.

Rebuild of
``/root/reference/EventStream/transformer/generation/generation_stopping_criteria.py``:
an ABC judging whole batches on **event count** (not token count), a
max-length criterion, and a list combinator.
"""

from __future__ import annotations

import abc

from ..data.types import EventStreamBatch


class StoppingCriteria(abc.ABC):
    """Decides whether generation should stop for the whole batch."""

    @abc.abstractmethod
    def __call__(self, batch: EventStreamBatch, **kwargs) -> bool: ...


class MaxLengthCriteria(StoppingCriteria):
    """Stops once the batch holds ``max_length`` events (reference ``:31``)."""

    def __init__(self, max_length: int):
        self.max_length = max_length

    def __call__(self, batch: EventStreamBatch, n_events: int | None = None, **kwargs) -> bool:
        n = n_events if n_events is not None else batch.sequence_length
        return n >= self.max_length


class StoppingCriteriaList(list, StoppingCriteria):
    """Stops when any member criterion fires (reference ``:50``)."""

    def __call__(self, batch: EventStreamBatch, **kwargs) -> bool:
        return any(criteria(batch, **kwargs) for criteria in self)

    @property
    def max_length(self) -> int | None:
        """The tightest max length across members (any member firing stops
        generation, so the minimum is the binding one)."""
        lengths = [c.max_length for c in self if isinstance(c, MaxLengthCriteria)]
        return min(lengths) if lengths else None
