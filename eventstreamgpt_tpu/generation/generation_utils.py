"""The autoregressive generation loop.

Rebuild of ``/root/reference/EventStream/transformer/generation/generation_utils.py``
(``StructuredGenerationMixin.generate`` ``:124-308`` and the per-mode event
samplers ``:310-416``) as a function over flax models.

Structure under XLA: the output batch is **preallocated** to
``input_len + max_new_events`` events and every step writes through a cursor,
so each step is a fixed-shape jitted computation. On the common path (KV
caches, no data-dependent stopping criteria) everything after the prefix
pass runs **on device inside one ``lax.scan``** — the CI body is one forward
per event, the NA body the full per-event level walk of the three-phase
cache machine of `NestedAttentionPointProcessTransformer` — so the host
dispatches two programs per generate() call regardless of horizon. With
data-dependent stopping criteria (or ``use_cache=False``) the loop falls
back to per-event Python dispatch. Jitted step closures are memoized per
(model, shape) across generate() calls.

Deliberate divergence: the reference's *uncached* NA generation slices input
embeddings per dep-graph target, attending over a smaller key set than the
training forward (``transformer.py:918-927``); here the uncached NA path runs
full forwards (target=None) each step, which provably matches the cached path
and the training-time attention pattern (see
``tests/models/test_na_model.py::test_cached_dep_graph_decode_matches_uncached``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flax import struct

from ..data.types import EventStreamBatch
from ..models.config import StructuredEventProcessingMode, StructuredTransformerConfig
from ..models.transformer import NAPast, init_kv_caches, time_from_deltas
from ..ops.tensor_ops import take_event
from .sampling import append_new_event, sample_predictions, update_last_event_data
from .stopping_criteria import MaxLengthCriteria, StoppingCriteriaList

Array = Any


@struct.dataclass
class GenerationOutput:
    """A completed generation plus per-row accounting.

    ``generate(..., return_output=True)`` wraps its result batch with
    per-row ``n_generated`` — the count of REAL events each row produced
    (rows whose prompts end in padding generate only masked events and
    count 0; a fired stopping criterion shortens every row). Previously
    only whole-batch event totals were observable from the result batch.
    """

    batch: EventStreamBatch
    n_generated: Array  # (B,) int32: real generated events per row
    input_len: int = struct.field(pytree_node=False, default=0)


def _with_accounting(batch: EventStreamBatch, input_len: int) -> GenerationOutput:
    n_gen = batch.event_mask[:, input_len:].sum(axis=1).astype(jnp.int32)
    return GenerationOutput(batch=batch, n_generated=n_gen, input_len=input_len)


@jax.jit
def _batch_nonfinite(batch: EventStreamBatch) -> Array:
    """True if any float tensor in the batch holds a NaN/inf (scalar bool).

    The reference validates every batch tensor between generation steps
    (``generation_utils.py:253-269``); here the checks are fused into one
    jitted reduction so the guard costs one scalar readback per step.
    """
    bad = jnp.asarray(False)
    for x in (batch.time_delta, batch.dynamic_values):
        if x is not None:
            bad = bad | ~jnp.isfinite(x).all()
    return bad


def _preallocate(batch: EventStreamBatch, max_new_events: int) -> EventStreamBatch:
    """Right-pads the sequence axis with ``max_new_events`` empty events."""

    def pad_seq(x, fill=0):
        if x is None:
            return None
        pad = [(0, 0)] * x.ndim
        pad[1] = (0, max_new_events)
        return jnp.pad(x, pad, constant_values=fill)

    return batch.replace(
        event_mask=pad_seq(batch.event_mask, False),
        time_delta=pad_seq(batch.time_delta),
        time=None,  # recomputed from deltas as needed
        dynamic_indices=pad_seq(batch.dynamic_indices),
        dynamic_measurement_indices=pad_seq(batch.dynamic_measurement_indices),
        dynamic_values=pad_seq(batch.dynamic_values),
        dynamic_values_mask=pad_seq(batch.dynamic_values_mask),
    )


def _slice_preds_at(preds, idx: Array):
    """Slices (B, L, ...) prediction pytrees down to event ``idx``: (B, ...)."""

    def take(x):
        if x is None:
            return None
        if x.shape[1] == 1:
            # Decode-scan views are one event long — a static slice; the
            # take_along_axis this replaces measured ~1 ms/leaf/event on TPU.
            return x[:, 0]
        return take_event(x, idx)

    return jax.tree_util.tree_map(take, preds)


def _trim_to_event(batch: EventStreamBatch, idx: Array) -> EventStreamBatch:
    """A one-event view of the batch at event ``idx``, with absolute time set.

    Mirrors ``prepare_inputs_for_generation`` trimming
    (``conditionally_independent_model.py:198-248``).
    """
    B = batch.event_mask.shape[0]
    t_full = time_from_deltas(batch)

    def take2(x):  # (B, L) -> (B, 1); masked-reduce, not gather (take_event)
        return take_event(x, idx)[:, None]

    def take3(x):  # (B, L, M) -> (B, 1, M)
        return take_event(x, idx)[:, None, :]

    return batch.replace(
        event_mask=take2(batch.event_mask),
        time_delta=take2(batch.time_delta),
        time=take2(t_full),
        dynamic_indices=take3(batch.dynamic_indices),
        dynamic_measurement_indices=take3(batch.dynamic_measurement_indices),
        dynamic_values=take3(batch.dynamic_values),
        dynamic_values_mask=take3(batch.dynamic_values_mask),
    )


def _mask_through_cursor(batch: EventStreamBatch, cursor: Array) -> EventStreamBatch:
    """Event mask restricted to positions < cursor (hides preallocated tail).

    ``cursor`` may be a scalar (cohort path) or per-row ``(B,)`` (engine
    slots)."""
    positions = jnp.arange(batch.sequence_length)[None, :]
    cur = cursor[:, None] if getattr(cursor, "ndim", 0) == 1 else cursor
    return batch.replace(event_mask=batch.event_mask & (positions < cur))


def generate(
    model,
    params,
    batch: EventStreamBatch,
    config: StructuredTransformerConfig,
    key: jax.Array,
    max_new_events: int | None = None,
    max_length: int | None = None,
    num_return_sequences: int = 1,
    use_cache: bool = True,
    stopping_criteria: StoppingCriteriaList | None = None,
    do_validate_batch: bool = True,
    mesh: Mesh | None = None,
    return_output: bool = False,
) -> EventStreamBatch | GenerationOutput:
    """Autoregressively samples future events (reference ``generate`` ``:124``).

    Args:
        model: A `CIPPTForGenerativeSequenceModeling` or
            `NAPPTForGenerativeSequenceModeling` module instance.
        params: Model parameters.
        batch: The prompt batch. Every sequence should be **right-aligned
            real events** (no interior padding); the returned batch has the
            prompt in place and generated events appended at the cursor.
        config: The model configuration.
        key: PRNG key for sampling.
        max_new_events: Number of events to generate. Exactly one of this and
            ``max_length`` must be set (or ``max_length`` defaults to
            ``config.max_seq_len`` as in the reference ``:176-207``).
        num_return_sequences: Sample count per prompt element; the batch is
            expanded in-order (reference ``:216``).
        use_cache: Use KV caches (one forward per new event/element) instead
            of full forwards each step.
        stopping_criteria: Optional `StoppingCriteriaList` consulted before
            the loop and after every completed event (reference ``:239,297``);
            a `MaxLengthCriteria` inside it also bounds ``max_new_events``. A
            criterion already satisfied by the prompt returns the prompt
            (expanded by ``num_return_sequences``) unchanged.
        do_validate_batch: Check the prompt for NaN/inf and raise (reference
            ``:253-269`` checks every step; here every value *written* during
            generation is already sanitized at the sampling layer —
            ``sampling.py`` ``nan_to_num``/clamps — so only the prompt can
            carry non-finites and one check suffices). The check's device
            reduction is dispatched up front but its host readback is
            deferred until the generation dispatches are in flight, so it
            costs no serial round trip; a bad prompt still raises before any
            result is returned.
        mesh: Optional device mesh with a ``data`` axis. The (expanded) batch
            is sharded over it with replicated params, so every jitted
            generation step runs data-parallel across the mesh — the
            TPU-native analog of the reference's DDP generation
            (``generation_utils.py:240-247``), minus the per-step all-reduce
            handshake (all shards run the same step count, so no peer can
            finish early). The expanded batch size
            (``batch_size * num_return_sequences``) must be divisible by the
            mesh's ``data`` axis size.

        return_output: Return a `GenerationOutput` (result batch + per-row
            ``n_generated`` real-event counts) instead of the bare batch.

    Returns:
        The completed `EventStreamBatch` of ``input_len + max_new_events``
        events (fewer if a stopping criterion fired) — or a
        `GenerationOutput` wrapping it when ``return_output`` is set.
    """
    if batch.segment_ids is not None:
        raise NotImplementedError(
            "generate() requires padded (one subject per row) prompt batches; packed "
            "segment_ids rows are a training/eval layout. De-pack the prompts first."
        )

    input_len = batch.sequence_length
    if num_return_sequences > 1:
        batch = batch.repeat_batch_elements(num_return_sequences)

    # Prompt validation. Host-array prompts are checked on the host for free
    # (before any device placement). Device-resident prompts need a device
    # reduction whose readback costs a full data-plane round trip on an
    # RPC-tunneled backend (~80-100 ms — comparable to the WHOLE fused
    # generation program): dispatch it, start the async copy, and defer the
    # bool() until the generation program is in flight. Framework-collated
    # resident prompts (DeviceDataset eval paths) are already NaN-clean by
    # construction and every value *written* during generation is sanitized
    # at the sampling layer — latency-sensitive callers pass
    # ``do_validate_batch=False`` there.
    bad_prompt = None
    if do_validate_batch:
        float_leaves = [
            x for x in (batch.time_delta, batch.dynamic_values) if x is not None
        ]
        if all(isinstance(x, np.ndarray) for x in float_leaves):
            if any(not np.isfinite(x).all() for x in float_leaves):
                raise ValueError(
                    "Non-finite values (NaN/inf) in the prompt batch; generation would "
                    "propagate them. Clean the inputs or pass do_validate_batch=False."
                )
        else:
            bad_prompt = _batch_nonfinite(batch)
            # Start the device->host copy of the scalar now: the wire latency
            # (the whole cost on a tunneled backend) overlaps the generation
            # dispatches below, so the bool() in _check_prompt finds the value
            # already on the host instead of paying a serial round trip.
            try:
                bad_prompt.copy_to_host_async()
            except AttributeError:  # non-jax array (e.g. test doubles)
                pass

    if mesh is not None:
        if "data" not in mesh.shape:
            raise ValueError(
                f"generate() shards batches over a 'data' mesh axis; the given mesh has "
                f"axes {tuple(mesh.axis_names)}."
            )
        n_data = int(mesh.shape["data"])
        if batch.batch_size % n_data != 0:
            raise ValueError(
                f"Expanded batch size {batch.batch_size} (batch x num_return_sequences) "
                f"must be divisible by the mesh's 'data' axis size ({n_data})."
            )

        # ONE device_put call for the whole batch: per-leaf puts each pay a
        # control-plane round trip on tunneled backends (~10 ms each — the
        # r05 generation-wall profile showed the wrapper's puts costing 5x
        # the fused generation program itself).
        shardings = jax.tree_util.tree_map(
            lambda x: NamedSharding(mesh, P("data", *([None] * (np.ndim(x) - 1)))), batch
        )
        batch = jax.device_put(batch, shardings)
        params = jax.device_put(params, NamedSharding(mesh, P()))

    def _check_prompt():
        if bad_prompt is not None and bool(bad_prompt):
            raise ValueError(
                "Non-finite values (NaN/inf) in the prompt batch; generation would "
                "propagate them. Clean the inputs or pass do_validate_batch=False."
            )

    bounds = []
    if stopping_criteria is not None:
        if bool(stopping_criteria(batch, n_events=input_len)):
            _check_prompt()
            return _with_accounting(batch, input_len) if return_output else batch
        if stopping_criteria.max_length is not None:
            bounds.append(stopping_criteria.max_length - input_len)
    if max_new_events is not None:
        bounds.append(max_new_events)
    elif max_length is not None:
        bounds.append(max_length - input_len)
    elif not bounds:
        bounds.append(config.max_seq_len - input_len)
    # Every explicit bound applies; a MaxLengthCriteria cannot loosen an
    # explicit max_length/max_new_events argument (or vice versa).
    max_new_events = min(bounds)
    if max_new_events <= 0:
        raise ValueError(f"max_new_events must be positive; got {max_new_events}")

    # Length bounds are fully folded into max_new_events above, so a criteria
    # list containing only MaxLengthCriteria needs no per-event host sync.
    if stopping_criteria is not None and all(
        isinstance(c, MaxLengthCriteria) for c in stopping_criteria
    ):
        stopping_criteria = None

    mode = config.structured_event_processing_mode
    gen = (
        _generate_ci
        if mode == StructuredEventProcessingMode.CONDITIONALLY_INDEPENDENT
        else _generate_na
    )
    try:
        result = gen(
            model,
            params,
            batch,
            config,
            key,
            max_new_events,
            use_cache,
            stopping_criteria=stopping_criteria,
        )
    except Exception:
        # A non-finite prompt can crash generation itself; surface the clear
        # validation error instead of the downstream failure (ADVICE r04).
        _check_prompt()
        raise
    _check_prompt()
    return _with_accounting(result, input_len) if return_output else result


def _should_stop(big, cursor, stopping_criteria) -> bool:
    """Consults stopping criteria after a completed event (reference
    ``generation_utils.py:239,297``). Returns True if generation should stop."""
    if stopping_criteria is None:
        return False
    masked = _mask_through_cursor(big, cursor)
    return bool(stopping_criteria(masked, n_events=int(cursor)))


# ------------------------------------------------------- jitted step caching
# generate() runs per batch inside eval loops; rebuilding its @jax.jit
# closures on every call would give each call a fresh (empty) trace cache and
# re-trace the model each time — seconds of pure overhead per batch. Step
# closures are therefore memoized per (mode, config signature, shape
# signature): a flax module's apply() is a pure function of its config, so
# callers that build a fresh model object per generate() call still hit the
# cache (the cached closures keep the first equivalent instance alive). The
# cache is FIFO-bounded (one entry per distinct generation shape — a handful
# per process).
_STEP_CACHE: dict[tuple, dict] = {}
_STEP_CACHE_MAX = 32


def _config_signature(config: StructuredTransformerConfig) -> str:
    import json

    return json.dumps(config.to_dict(), sort_keys=True, default=str)


# Serializing a realistic config (full measurement metadata + vocab maps)
# costs milliseconds; generate() runs once per eval batch, so the signature
# is memoized per live model object (weakly — a dead model's id can be
# recycled, hence the identity re-check).
_SIG_CACHE: dict[int, tuple[Any, str]] = {}


def _model_config_signature(model, config: StructuredTransformerConfig) -> str:
    import weakref

    key = id(model)
    hit = _SIG_CACHE.get(key)
    if hit is not None and hit[0]() is model:
        return hit[1]
    sig = _config_signature(config)
    try:
        ref = weakref.ref(model)
    except TypeError:
        return sig
    if len(_SIG_CACHE) >= 64:
        # Overflow is almost always dead weakrefs (eval loops building a
        # fresh model per batch): evict those first so live models keep
        # their memoized signatures; a full clear — which forfeits every
        # live memo — is the last resort only.
        for dead in [k for k, (r, _) in _SIG_CACHE.items() if r() is None]:
            del _SIG_CACHE[dead]
        if len(_SIG_CACHE) >= 64:
            _SIG_CACHE.clear()
    _SIG_CACHE[key] = (ref, sig)
    return sig


def _cached_steps(cache_key: tuple, build):
    hit = _STEP_CACHE.pop(cache_key, None)
    if hit is not None:
        # Re-insert on hit: eviction below is LRU, so steady-state shapes
        # (the eval loop's one batch shape) can't be churned out by
        # one-off shapes (VERDICT r04 weak #8).
        _STEP_CACHE[cache_key] = hit
        return hit
    steps = build()
    if len(_STEP_CACHE) >= _STEP_CACHE_MAX:
        _STEP_CACHE.pop(next(iter(_STEP_CACHE)))
    _STEP_CACHE[cache_key] = steps
    return steps


# ------------------------------------------------------------------- CI path
def _build_ci_steps(model, config, B, input_len, max_new_events):
    total_len = input_len + max_new_events

    @jax.jit
    def prefix_step(params, big_batch):
        view = big_batch.slice((slice(None), slice(0, input_len)))
        out = model.apply(
            params,
            view,
            past=init_kv_caches(config, B, max_len=total_len),
            use_cache=True,
            is_generation=True,
        )
        return out.preds, out.past_key_values

    # The caches are consumed and rebound every step (`preds, caches =
    # decode_step(params, big, caches, cursor)`), so they donate: the KV
    # planes update in place instead of double-buffering a second
    # (B, total_len) cache set per dispatch.
    @partial(jax.jit, donate_argnums=(2,))
    def decode_step(params, big_batch, caches, cursor):
        view = _trim_to_event(big_batch, cursor - 1)
        out = model.apply(params, view, past=caches, use_cache=True, is_generation=True)
        return out.preds, out.past_key_values

    @jax.jit
    def full_step(params, big_batch, cursor):
        masked = _mask_through_cursor(big_batch, cursor)
        out = model.apply(params, masked, is_generation=True)
        return out.preds

    def sample_and_write_body(big_batch, preds_last, cursor, key):
        bcols = jnp.arange(B)
        event_mask_last = big_batch.event_mask[bcols, cursor - 1]
        sample = sample_predictions(preds_last, event_mask_last, key)
        new_batch = append_new_event(big_batch, sample, config, cursor)
        return update_last_event_data(new_batch, sample, config, cursor + 1)

    sample_and_write = jax.jit(
        lambda params, big_batch, preds_last, cursor, key: sample_and_write_body(
            big_batch, preds_last, cursor, key
        )
    )

    def decode_scan_body(params, big_batch, caches, cursor, key):
        def body(carry, _):
            big_b, caches_b, cur, k = carry
            k, step_key = jax.random.split(k)
            view = _trim_to_event(big_b, cur - 1)
            out = model.apply(params, view, past=caches_b, use_cache=True, is_generation=True)
            preds_last = _slice_preds_at(out.preds, jnp.asarray(0))
            big_b = sample_and_write_body(big_b, preds_last, cur, step_key)
            return (big_b, out.past_key_values, cur + 1, k), None

        carry, _ = jax.lax.scan(
            body, (big_batch, caches, cursor, key), None, length=max_new_events - 1
        )
        return carry

    # The scan consumes the preallocated batch and the caches and returns
    # their successors in the carry — both donate when dispatched as a
    # standalone program.
    decode_scan = jax.jit(decode_scan_body, donate_argnums=(1, 2))

    @jax.jit
    def generate_program(params, prompt_batch, key):
        """The WHOLE cached generation — tail preallocation, prefix forward,
        first sample, the decode scan, and the final cursor masking — as one
        device program, so `generate()` costs a single dispatch (wall was
        ~93% host dispatch/placement at r04; VERDICT r05 #5: even the eager
        jnp pads of `_preallocate` each cost a control-plane round trip on a
        tunneled backend). Key-split order matches the step-by-step path
        exactly, so all paths sample identical trajectories."""
        big_batch = _preallocate(prompt_batch, max_new_events)
        cursor = jnp.asarray(input_len, jnp.int32)
        key, step_key = jax.random.split(key)
        view = big_batch.slice((slice(None), slice(0, input_len)))
        out = model.apply(
            params,
            view,
            past=init_kv_caches(config, B, max_len=total_len),
            use_cache=True,
            is_generation=True,
        )
        preds_last = _slice_preds_at(out.preds, cursor - 1)
        big_batch = sample_and_write_body(big_batch, preds_last, cursor, step_key)
        cursor = cursor + 1
        if max_new_events > 1:
            big_batch, _, cursor, key = decode_scan_body(
                params, big_batch, out.past_key_values, cursor, key
            )
        return _mask_through_cursor(big_batch, cursor)

    return dict(
        prefix_step=prefix_step,
        decode_step=decode_step,
        full_step=full_step,
        sample_and_write=sample_and_write,
        decode_scan=decode_scan,
        generate_program=generate_program,
    )


def _generate_ci(
    model,
    params,
    batch,
    config,
    key,
    max_new_events,
    use_cache,
    stopping_criteria=None,
):
    B = batch.batch_size
    input_len = batch.sequence_length

    steps = _cached_steps(
        ("ci", _model_config_signature(model, config), B, input_len, max_new_events),
        lambda: _build_ci_steps(model, config, B, input_len, max_new_events),
    )

    # On-device decode loop: with KV caches and no data-dependent stopping
    # criteria (the common path — MaxLength bounds fold into max_new_events),
    # the ENTIRE generation (preallocation, prefix, scan, final masking) is
    # one jitted program — a single dispatch per call (VERDICT r02 weak #6,
    # r05 #5). The per-step key-split sequence matches the Python loop
    # exactly, so both paths sample identical trajectories.
    if use_cache and stopping_criteria is None:
        return steps["generate_program"](params, batch, key)

    prefix_step = steps["prefix_step"]
    decode_step = steps["decode_step"]
    full_step = steps["full_step"]
    sample_and_write = steps["sample_and_write"]

    big = _preallocate(batch, max_new_events)
    cursor = jnp.asarray(input_len, jnp.int32)
    caches = None

    for step in range(max_new_events):
        key, step_key = jax.random.split(key)
        if use_cache:
            if step == 0:
                preds, caches = prefix_step(params, big)
                preds_last = _slice_preds_at(preds, cursor - 1)
            else:
                preds, caches = decode_step(params, big, caches, cursor)
                preds_last = _slice_preds_at(preds, jnp.asarray(0))
        else:
            preds = full_step(params, big, cursor)
            preds_last = _slice_preds_at(preds, cursor - 1)
        big = sample_and_write(params, big, preds_last, cursor, step_key)
        cursor = cursor + 1
        if _should_stop(big, cursor, stopping_criteria):
            break

    return _mask_through_cursor(big, cursor)


# ------------------------------------------------------------------- NA path
def _build_na_steps(model, config, B, input_len, max_new_events):
    total_len = input_len + max_new_events
    measurements_to_fill_list = [{"time"}, *config.measurements_per_dep_graph_level[1:]]
    n_levels = len(measurements_to_fill_list)

    @jax.jit
    def prefix_step(params, big_batch):
        view = big_batch.slice((slice(None), slice(0, input_len)))
        out = model.apply(
            params,
            view,
            past=NAPast(seq_past=init_kv_caches(config, B, max_len=total_len), dep_graph_past=None),
            use_cache=True,
            is_generation=True,
        )
        return out.preds, out.past_key_values

    def make_target_step(target):
        @jax.jit
        def target_step(params, big_batch, past, event_idx):
            view = _trim_to_event(big_batch, event_idx)
            out = model.apply(
                params,
                view,
                past=past,
                use_cache=True,
                is_generation=True,
                dep_graph_el_generation_target=target,
            )
            return out.preds, out.past_key_values

        return target_step

    @jax.jit
    def full_step(params, big_batch, cursor):
        masked = _mask_through_cursor(big_batch, cursor)
        out = model.apply(params, masked, is_generation=True)
        return out.preds

    @jax.jit
    def do_append(params, big_batch, preds_last, cursor, key):
        bcols = jnp.arange(B)
        event_mask_last = big_batch.event_mask[bcols, cursor - 1]
        sample = sample_predictions(preds_last, event_mask_last, key)
        return append_new_event(big_batch, sample, config, cursor)

    def make_do_fill(measurements_to_fill):
        frozen = tuple(sorted(measurements_to_fill, key=str))

        @jax.jit
        def do_fill(params, big_batch, preds_last, cursor, key):
            bcols = jnp.arange(B)
            event_mask_last = big_batch.event_mask[bcols, cursor - 1]
            sample = sample_predictions(preds_last, event_mask_last, key)
            return update_last_event_data(
                big_batch, sample, config, cursor, measurements_to_fill=set(frozen)
            )

        return do_fill

    target_steps = {t: make_target_step(t) for t in range(n_levels)}
    do_fills = [None] + [make_do_fill(m) for m in measurements_to_fill_list[1:]]

    def decode_scan_body(params, big_batch, past, cursor, key):
        """All post-first events decoded on device: one lax.scan whose body
        runs the full per-event level walk (target-0 contextualization + one
        decode/fill per dependency-graph level), mirroring the Python loop's
        key-split order exactly."""

        def body(carry, _):
            big_b, past_b, cur, k = carry
            k, step_key = jax.random.split(k)
            preds, past_b = target_steps[0](params, big_b, past_b, cur - 1)
            preds_last = _slice_preds_at(preds, jnp.asarray(0))
            big_b = do_append(params, big_b, preds_last, cur, step_key)
            for level in range(1, n_levels):
                k, step_key = jax.random.split(k)
                preds, past_b = target_steps[level](params, big_b, past_b, cur)
                preds_last = _slice_preds_at(preds, jnp.asarray(0))
                big_b = do_fills[level](params, big_b, preds_last, cur + 1, step_key)
            return (big_b, past_b, cur + 1, k), None

        carry, _ = jax.lax.scan(
            body, (big_batch, past, cursor, key), None, length=max_new_events - 1
        )
        return carry

    # The scan consumes the preallocated batch and the caches and returns
    # their successors in the carry — both donate when dispatched as a
    # standalone program.
    decode_scan = jax.jit(decode_scan_body, donate_argnums=(1, 2))

    @jax.jit
    def generate_program(params, prompt_batch, key):
        """Whole cached NA generation — tail preallocation, prefix pass,
        first event's level walk, decode scan, final masking — as ONE device
        program (one dispatch per `generate()` call; VERDICT r05 #5).
        Key-split order matches the step-by-step path exactly."""
        cursor = jnp.asarray(input_len, jnp.int32)
        past = None
        big_b = _preallocate(prompt_batch, max_new_events)
        for level in range(n_levels):
            key, step_key = jax.random.split(key)
            if level == 0:
                view = big_b.slice((slice(None), slice(0, input_len)))
                out = model.apply(
                    params,
                    view,
                    past=NAPast(
                        seq_past=init_kv_caches(config, B, max_len=total_len),
                        dep_graph_past=None,
                    ),
                    use_cache=True,
                    is_generation=True,
                )
                preds, past = out.preds, out.past_key_values
                preds_last = _slice_preds_at(preds, cursor - 1)
                big_b = do_append(params, big_b, preds_last, cursor, step_key)
            else:
                view = _trim_to_event(big_b, cursor)
                out = model.apply(
                    params,
                    view,
                    past=past,
                    use_cache=True,
                    is_generation=True,
                    dep_graph_el_generation_target=level,
                )
                preds, past = out.preds, out.past_key_values
                preds_last = _slice_preds_at(preds, jnp.asarray(0))
                big_b = do_fills[level](params, big_b, preds_last, cursor + 1, step_key)
        cursor = cursor + 1
        if max_new_events > 1:
            big_b, past, cursor, key = decode_scan_body(params, big_b, past, cursor, key)
        return _mask_through_cursor(big_b, cursor)

    return dict(
        measurements_to_fill_list=measurements_to_fill_list,
        prefix_step=prefix_step,
        target_steps=target_steps,
        full_step=full_step,
        do_append=do_append,
        do_fills=do_fills,
        decode_scan=decode_scan,
        generate_program=generate_program,
    )


def _generate_na(
    model,
    params,
    batch,
    config,
    key,
    max_new_events,
    use_cache,
    stopping_criteria=None,
):
    B = batch.batch_size
    input_len = batch.sequence_length

    steps = _cached_steps(
        ("na", _model_config_signature(model, config), B, input_len, max_new_events),
        lambda: _build_na_steps(model, config, B, input_len, max_new_events),
    )
    measurements_to_fill_list = steps["measurements_to_fill_list"]
    prefix_step = steps["prefix_step"]
    target_steps = steps["target_steps"]
    full_step = steps["full_step"]
    do_append = steps["do_append"]
    do_fills = steps["do_fills"]

    # On-device NA decode: with caches and no data-dependent stopping
    # criteria, the whole generation (preallocation, prefix, every event's
    # level walk, final masking) is one jitted program — a single dispatch
    # per call (VERDICT r02 weak #6, r05 #5). The key-split sequence matches
    # the Python path exactly.
    if use_cache and stopping_criteria is None:
        return steps["generate_program"](params, batch, key)

    big = _preallocate(batch, max_new_events)
    cursor = jnp.asarray(input_len, jnp.int32)

    past = None
    for step in range(max_new_events):
        for level, measurements_to_fill in enumerate(measurements_to_fill_list):
            key, step_key = jax.random.split(key)
            is_first = step == 0

            if use_cache:
                if is_first and level == 0:
                    preds, past = prefix_step(params, big)
                    preds_last = _slice_preds_at(preds, cursor - 1)
                elif level == 0:
                    # Contextualize the just-completed event (target=0).
                    preds, past = target_steps[0](params, big, past, cursor - 1)
                    preds_last = _slice_preds_at(preds, jnp.asarray(0))
                else:
                    # Decode one new graph element of the in-progress event.
                    preds, past = target_steps[level](params, big, past, cursor)
                    preds_last = _slice_preds_at(preds, jnp.asarray(0))
            else:
                if level == 0:
                    preds = full_step(params, big, cursor)
                    preds_last = _slice_preds_at(preds, cursor - 1)
                else:
                    preds = full_step(params, big, cursor + 1)
                    preds_last = _slice_preds_at(preds, cursor)

            if measurements_to_fill == {"time"}:
                big = do_append(params, big, preds_last, cursor, step_key)
            else:
                big = do_fills[level](params, big, preds_last, cursor + 1, step_key)
        cursor = cursor + 1
        if _should_stop(big, cursor, stopping_criteria):
            break

    return _mask_through_cursor(big, cursor)
