"""JAX-native probability distributions for generative event-stream heads.

Replaces the reference's use of ``torch.distributions`` (Categorical,
Bernoulli, Normal, Exponential) and the external ``pytorch_lognormal_mixture``
package (``/root/reference/EventStream/transformer/generative_layers.py:3``)
with pytree-registered dataclasses. Every distribution is a
``flax.struct`` pytree, so distributions can be produced inside ``jit``,
returned through ``lax.scan`` carries, sliced with ordinary indexing (the
reference needs a bespoke ``idx_distribution`` helper for this —
``transformer/utils.py:247``; here slicing is a ``tree_map``), and sampled
with explicit PRNG keys.

Parameterization conventions (parity-critical for NLL):

* ``Categorical``/``Bernoulli`` accept logits; log-probs are computed with
  ``log_softmax`` / ``log_sigmoid`` exactly as torch does.
* ``Exponential.log_prob(x) = log(rate) - rate * x``.
* ``LogNormalMixture`` follows Shchur et al. (intensity-free TPP), matching
  ``pytorch_lognormal_mixture``: a GMM over ``z = (log(t) - mean_log)/std_log``
  with ``log_prob(t) = gmm.log_prob(z) - log(std_log) - log(t)``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

Array = Any


def _tree_index(dist, index):
    """Slices every array leaf of a distribution pytree with ``index``."""
    return jax.tree_util.tree_map(lambda x: x[index], dist)


class _Indexable:
    """Mixin giving distributions ``dist[index]`` slicing over batch dims."""

    def __getitem__(self, index):
        return _tree_index(self, index)


@struct.dataclass
class Categorical(_Indexable):
    """A categorical distribution over the last axis of ``logits``."""

    logits: Array

    @property
    def log_probs(self) -> Array:
        return jax.nn.log_softmax(self.logits, axis=-1)

    @property
    def probs(self) -> Array:
        return jax.nn.softmax(self.logits, axis=-1)

    def log_prob(self, value: Array) -> Array:
        value = value.astype(jnp.int32)
        # mode="clip" keeps CPU and TPU behavior identical on out-of-range
        # labels (TPU hardware gathers clamp; CPU would return NaN).
        return jnp.take_along_axis(self.log_probs, value[..., None], axis=-1, mode="clip")[..., 0]

    def sample(self, key: jax.Array, sample_shape: tuple[int, ...] = ()) -> Array:
        shape = sample_shape + self.logits.shape[:-1]
        return jax.random.categorical(key, self.logits, axis=-1, shape=shape)

    @property
    def mode(self) -> Array:
        return jnp.argmax(self.logits, axis=-1)


@struct.dataclass
class Bernoulli(_Indexable):
    """An elementwise Bernoulli distribution parameterized by logits."""

    logits: Array

    @property
    def probs(self) -> Array:
        return jax.nn.sigmoid(self.logits)

    def log_prob(self, value: Array) -> Array:
        value = value.astype(self.logits.dtype)
        # -BCEWithLogits: value*log(sigmoid(l)) + (1-value)*log(1-sigmoid(l)).
        return value * jax.nn.log_sigmoid(self.logits) + (1 - value) * jax.nn.log_sigmoid(-self.logits)

    def sample(self, key: jax.Array, sample_shape: tuple[int, ...] = ()) -> Array:
        shape = sample_shape + self.logits.shape
        return jax.random.bernoulli(key, self.probs, shape=shape).astype(jnp.float32)


@struct.dataclass
class Normal(_Indexable):
    """An elementwise Gaussian."""

    loc: Array
    scale: Array

    def log_prob(self, value: Array) -> Array:
        var = self.scale**2
        return -((value - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - 0.5 * jnp.log(2 * jnp.pi)

    def sample(self, key: jax.Array, sample_shape: tuple[int, ...] = ()) -> Array:
        shape = sample_shape + self.loc.shape
        return self.loc + self.scale * jax.random.normal(key, shape, dtype=jnp.result_type(self.loc))

    @property
    def mean(self) -> Array:
        return self.loc

    @property
    def stddev(self) -> Array:
        return self.scale


@struct.dataclass
class Exponential(_Indexable):
    """An elementwise exponential distribution with rate parameterization."""

    rate: Array

    def log_prob(self, value: Array) -> Array:
        return jnp.log(self.rate) - self.rate * value

    def sample(self, key: jax.Array, sample_shape: tuple[int, ...] = ()) -> Array:
        shape = sample_shape + self.rate.shape
        return jax.random.exponential(key, shape, dtype=jnp.result_type(self.rate)) / self.rate

    @property
    def mean(self) -> Array:
        return 1.0 / self.rate


@struct.dataclass
class MixtureSameFamily(_Indexable):
    """A mixture of a component family over the last parameter axis."""

    mixture_logits: Array  # (..., K)
    component: Any  # distribution with params of shape (..., K)

    def log_prob(self, value: Array) -> Array:
        log_weights = jax.nn.log_softmax(self.mixture_logits, axis=-1)
        comp_lp = self.component.log_prob(value[..., None])
        return jax.nn.logsumexp(log_weights + comp_lp, axis=-1)

    def sample(self, key: jax.Array, sample_shape: tuple[int, ...] = ()) -> Array:
        k_mix, k_comp = jax.random.split(key)
        comps = self.component.sample(k_comp, sample_shape)  # (..., K)
        shape = sample_shape + self.mixture_logits.shape[:-1]
        choice = jax.random.categorical(k_mix, self.mixture_logits, axis=-1, shape=shape)
        return jnp.take_along_axis(comps, choice[..., None], axis=-1)[..., 0]


@struct.dataclass
class LogNormalMixture(_Indexable):
    """Mixture-of-lognormals TTE distribution (Shchur et al. parameterization).

    Matches the external ``pytorch_lognormal_mixture`` package the reference
    uses (``generative_layers.py:6-59``): components are Gaussians over
    ``z = (log(t) - mean_log_inter_time) / std_log_inter_time``; the density
    picks up the Jacobian ``1/(t * std_log_inter_time)``.

    Parameters ``locs``, ``log_scales``, ``log_weights`` all have shape
    ``(..., K)``; ``mean_log_inter_time``/``std_log_inter_time`` are static
    python floats (treedef aux data, NOT pytree leaves — so tree_map slicing
    leaves them untouched; do not pass jax arrays for them).
    """

    locs: Array
    log_scales: Array
    log_weights: Array
    mean_log_inter_time: Array = struct.field(pytree_node=False, default=0.0)
    std_log_inter_time: Array = struct.field(pytree_node=False, default=1.0)

    def _gmm(self) -> MixtureSameFamily:
        return MixtureSameFamily(
            mixture_logits=self.log_weights,
            component=Normal(loc=self.locs, scale=jnp.exp(self.log_scales)),
        )

    def log_prob(self, value: Array) -> Array:
        eps = jnp.finfo(jnp.result_type(self.locs)).tiny
        value = jnp.maximum(value, eps)
        z = (jnp.log(value) - self.mean_log_inter_time) / self.std_log_inter_time
        return self._gmm().log_prob(z) - jnp.log(value) - jnp.log(jnp.asarray(self.std_log_inter_time))

    def sample(self, key: jax.Array, sample_shape: tuple[int, ...] = ()) -> Array:
        z = self._gmm().sample(key, sample_shape)
        return jnp.exp(z * self.std_log_inter_time + self.mean_log_inter_time)

    @property
    def mean(self) -> Array:
        """E[t] = sum_k w_k * exp(mu'_k + sigma'_k**2 / 2) in original time units."""
        w = jax.nn.softmax(self.log_weights, axis=-1)
        mu = self.locs * self.std_log_inter_time + self.mean_log_inter_time
        sigma = jnp.exp(self.log_scales) * self.std_log_inter_time
        return (w * jnp.exp(mu + sigma**2 / 2)).sum(axis=-1)
