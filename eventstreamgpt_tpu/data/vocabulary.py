"""Frequency-ordered vocabularies for categorical measurements.

TPU-native rebuild of ``/root/reference/EventStream/data/vocabulary.py:23``.
Behavioral contract preserved: index 0 is always the ``'UNK'`` sentinel, the
remaining elements are sorted by decreasing observed frequency (ties broken by
element, descending), ``filter`` folds dropped probability mass into UNK, and
``__getitem__`` is bidirectional (element→index, index→element).
"""

from __future__ import annotations

import copy
import dataclasses
import math
from functools import cached_property
from io import TextIOBase
from textwrap import shorten, wrap
from typing import Generic, TypeVar, Union

import numpy as np

from ..utils import COUNT_OR_PROPORTION, num_initial_spaces

VOCAB_ELEMENT = TypeVar("VOCAB_ELEMENT")
NESTED_VOCAB_SEQUENCE = Union[VOCAB_ELEMENT, list]

_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float]) -> str:
    """Renders values as a unicode block sparkline (0..max scaled).

    Examples:
        >>> sparkline([0.4, 0.3, 0.1])
        '█▆▁'
    """
    vals = np.asarray(values, dtype=float)
    if len(vals) == 0:
        return ""
    lo, hi = float(np.nanmin(vals)), float(np.nanmax(vals))
    if hi == lo:
        return _SPARK_BLOCKS[-1] * len(vals)
    scaled = (vals - lo) / (hi - lo)
    idx = np.clip((scaled * (len(_SPARK_BLOCKS) - 1)).round().astype(int), 0, len(_SPARK_BLOCKS) - 1)
    return "".join(_SPARK_BLOCKS[i] for i in idx)


@dataclasses.dataclass
class Vocabulary(Generic[VOCAB_ELEMENT]):
    """A frequency-sorted vocabulary with a mandatory UNK element at index 0.

    Examples:
        >>> vocab = Vocabulary(vocabulary=['apple', 'banana', 'UNK'], obs_frequencies=[3, 5, 2])
        >>> vocab.vocabulary
        ['UNK', 'banana', 'apple']
        >>> vocab.obs_frequencies
        [0.2, 0.5, 0.3]
        >>> vocab.idxmap
        {'UNK': 0, 'banana': 1, 'apple': 2}
        >>> vocab[1]
        'banana'
        >>> vocab['apple']
        2
        >>> vocab['zebra']
        0
        >>> len(vocab)
        3
    """

    vocabulary: list[str] | None = None
    obs_frequencies: "np.ndarray | list[float] | None" = None

    def __post_init__(self):
        if len(self.vocabulary) == 0:
            raise ValueError("Empty vocabularies are not supported.")
        if len(self.vocabulary) != len(self.obs_frequencies):
            raise ValueError(
                "self.vocabulary and self.obs_frequencies must have the same length. Got "
                f"{len(self.vocabulary)} and {len(self.obs_frequencies)}."
            )
        vocab_set = set(self.vocabulary)
        if len(self.vocabulary) != len(vocab_set):
            raise ValueError(
                f"Vocabulary has duplicates. len(self.vocabulary) = {len(self.vocabulary)}, but "
                f"len(set(self.vocabulary)) = {len(vocab_set)}."
            )
        self.element_types = {type(v) for v in self.vocabulary if v != "UNK"}
        if int in self.element_types:
            raise ValueError("Integer elements in the vocabulary are not supported.")

        freqs = np.asarray(self.obs_frequencies, dtype=float)
        freqs = freqs / freqs.sum()

        vocab = copy.deepcopy(self.vocabulary)
        if "UNK" in vocab_set:
            unk_index = vocab.index("UNK")
            unk_freq = freqs[unk_index]
            freqs = np.delete(freqs, unk_index)
            del vocab[unk_index]
        else:
            unk_freq = 0.0

        # Decreasing frequency; ties broken by element, descending (lexsort parity
        # with reference ``vocabulary.py:183``).
        idx = np.lexsort((vocab, freqs))[::-1]
        self.vocabulary = ["UNK"] + [vocab[i] for i in idx]
        self.obs_frequencies = np.concatenate(([unk_freq], freqs[idx])).tolist()

    @cached_property
    def idxmap(self) -> dict[VOCAB_ELEMENT, int]:
        """Mapping from vocabulary element to its integer index."""
        return {v: i for i, v in enumerate(self.vocabulary)}

    def __getitem__(self, q):
        if type(q) is int:
            return self.vocabulary[q]
        if (type(q) not in self.element_types) and (q != "UNK"):
            raise TypeError(f"Type {type(q)} is not a valid type for this vocabulary.")
        return self.idxmap.get(q, 0)

    def __len__(self) -> int:
        return len(self.vocabulary)

    def __eq__(self, other) -> bool:
        return (
            (type(self) is type(other))
            and (self.vocabulary == other.vocabulary)
            and (np.array(self.obs_frequencies).round(3) == np.array(other.obs_frequencies).round(3)).all()
        )

    def extend_with_counts(
        self, counts: dict[VOCAB_ELEMENT, int], prior_total: int
    ) -> list[str]:
        """Append-only vocabulary growth for the incremental-fit path.

        EXISTING INDICES ARE FROZEN: no element moves, whatever the merged
        frequencies say (the DL cache stores indices; re-sorting would
        silently corrupt every cached row). Unseen elements are appended
        AFTER the current vocabulary, ordered by (count desc, element desc)
        — the same tie-break rule the from-scratch fit uses within its
        frequency sort. ``prior_total`` is the observation count behind the
        current ``obs_frequencies`` (persisted in the cache's
        sufficient-statistics sidecar) so the merged frequencies stay
        honest. Returns the appended elements in index order.

        Examples:
            >>> v = Vocabulary(vocabulary=["apple", "banana", "UNK"], obs_frequencies=[3, 5, 2])
            >>> v.vocabulary
            ['UNK', 'banana', 'apple']
            >>> v.extend_with_counts({"pear": 40, "banana": 10}, prior_total=10)
            ['pear']
            >>> v.vocabulary  # banana gained mass but kept its index
            ['UNK', 'banana', 'apple', 'pear']
            >>> [round(f, 3) for f in v.obs_frequencies]
            [0.033, 0.25, 0.05, 0.667]
        """
        counts = {k: int(c) for k, c in counts.items() if c}
        merged = np.asarray(self.obs_frequencies, dtype=float) * float(prior_total)
        idxmap = self.idxmap
        new_elements: list = []
        for el, c in counts.items():
            if el in idxmap:
                merged[idxmap[el]] += c
            else:
                new_elements.append(el)
        new_elements.sort(key=lambda el: (counts[el], str(el)), reverse=True)

        self.vocabulary = list(self.vocabulary) + new_elements
        merged = np.concatenate(
            [merged, np.asarray([counts[el] for el in new_elements], dtype=float)]
        )
        total = merged.sum()
        self.obs_frequencies = (merged / total if total > 0 else merged).tolist()
        self.element_types |= {type(el) for el in new_elements if el != "UNK"}
        self.__dict__.pop("idxmap", None)
        return new_elements

    def filter(self, total_observations: int | None, min_valid_element_freq: COUNT_OR_PROPORTION) -> None:
        """Drops elements rarer than the cutoff, folding their mass into UNK.

        Reference contract: ``vocabulary.py:186-231``; UNK survives regardless
        of its own frequency.

        Examples:
            >>> vocab = Vocabulary(vocabulary=['apple', 'banana', 'UNK'], obs_frequencies=[5, 3, 2])
            >>> vocab.filter(total_observations=10, min_valid_element_freq=0.4)
            >>> vocab.vocabulary
            ['UNK', 'apple']
            >>> vocab.obs_frequencies
            [0.5, 0.5]
        """
        if type(min_valid_element_freq) is not float:
            min_valid_element_freq /= total_observations

        freqs = np.array(self.obs_frequencies)
        # Number of non-UNK elements with frequency >= cutoff. Frequencies after
        # index 0 are sorted descending, so searchsorted on the negated array
        # finds the boundary.
        keep_n = int(np.searchsorted(-freqs[1:], -min_valid_element_freq, side="right"))

        freqs[0] += freqs[keep_n + 1 :].sum()
        self.vocabulary = self.vocabulary[: keep_n + 1]
        self.obs_frequencies = freqs[: keep_n + 1].tolist()
        self.__dict__.pop("idxmap", None)

    def describe(
        self,
        line_width: int = 60,
        wrap_lines: bool = True,
        n_head: int = 3,
        n_tail: int = 2,
        stream: TextIOBase | None = None,
    ) -> int | None:
        """Prints a text summary: size, UNK rate, sparkline, head/tail elements.

        Examples:
            >>> vocab = Vocabulary(
            ...     vocabulary=['apple', 'banana', 'pear', 'UNK'],
            ...     obs_frequencies=[3, 4, 1, 2],
            ... )
            >>> vocab.describe(n_head=2, n_tail=1, wrap_lines=False)
            4 elements, 20.0% UNKs
            Frequencies: █▆▁
            Elements:
              (40.0%) banana
              (30.0%) apple
              (10.0%) pear
        """
        lines = []
        lines.append(f"{len(self)} elements, {self.obs_frequencies[0] * 100:.1f}% UNKs")

        sparkline_prefix = "Frequencies:"
        W = line_width - len(sparkline_prefix) - 2
        if W > len(self):
            freqs = self.obs_frequencies[1:]
        else:
            freqs = self.obs_frequencies[1 : len(self) : int(math.ceil(len(self) / W))]
        lines.append(f"{sparkline_prefix} {sparkline(freqs)}")

        if len(self) - 1 <= (n_head + n_tail):
            lines.append("Elements:")
            for v, f in zip(self.vocabulary[1:], self.obs_frequencies[1:]):
                lines.append(f"  ({f * 100:.1f}%) {v}")
        else:
            lines.append("Examples:")
            for i in range(n_head):
                lines.append(f"  ({self.obs_frequencies[i + 1] * 100:.1f}%) {self.vocabulary[i + 1]}")
            lines.append("  ...")
            for i in range(n_tail):
                lines.append(
                    f"  ({self.obs_frequencies[-n_tail + i] * 100:.1f}%) {self.vocabulary[-n_tail + i]}"
                )

        line_indents = [num_initial_spaces(line) for line in lines]
        if wrap_lines:
            new_lines = []
            for line, ind in zip(lines, line_indents):
                new_lines.extend(wrap(line, width=line_width, initial_indent="", subsequent_indent=" " * ind))
            lines = new_lines
        else:
            lines = [
                shorten(line, width=line_width, initial_indent=" " * ind)
                for line, ind in zip(lines, line_indents)
            ]

        desc = "\n".join(lines)
        if stream is None:
            print(desc)
            return None
        return stream.write(desc)
