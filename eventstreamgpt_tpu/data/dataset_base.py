"""The abstract ETL/preprocessing engine for event-stream datasets.

Rebuild of ``/root/reference/EventStream/data/dataset_base.py:41``
(``DatasetBase``): the backend-agnostic pipeline that

1. builds subjects/events/measurements dataframes from ``InputDFSchema``s,
2. splits subjects into train/tuning/held-out,
3. preprocesses (filter subjects → add time-dependent measures → fit
   per-measurement metadata + vocabularies on train → transform all splits),
4. saves/loads the processed dataset directory, and
5. writes the deep-learning cache (``DL_reps/{split}_{chunk}.parquet``) plus
   the unified ``VocabularyConfig`` that the model layer consumes.

Orchestration, ordering, and on-disk artifacts match the reference; the
dataframe ops are deferred to a backend subclass (the pandas backend in
``dataset_pandas.py`` — the reference's Polars is not available in this
image, see that module's docstring).
"""

from __future__ import annotations

import abc
import copy
import itertools
import json
import pickle
from collections import defaultdict
from pathlib import Path
from typing import Any, Generic, Hashable, Sequence, TypeVar

import numpy as np
import pandas as pd

from ..utils import SeedableMixin, TimeableMixin, count_or_proportion, lt_count_or_proportion
from .config import (
    DatasetConfig,
    DatasetSchema,
    InputDFSchema,
    MeasurementConfig,
    VocabularyConfig,
)
from .types import DataModality, InputDFType, TemporalityType
from .vocabulary import Vocabulary

DF_T = TypeVar("DF_T")

# ------------------------------------------------------------ worker plumbing
# Fork-based process-pool helpers for the subject/measurement-sharded ETL
# phases. The payload (dataset object, or a build-phase spec) is handed to
# workers through fork-inherited memory (a global set just before the pool
# spawns) rather than pickling — events/measurements frames can be GBs.
# Deterministic by construction: results come back in task order and are
# merged in that order.
_FORK_SELF = None


def _dl_rep_shard_to_disk_worker(task):
    """Builds one DL-rep subject shard and streams it to parquet; only the
    path travels back through the pipe, so parent+worker peak RSS is
    O(shard), not O(chunk)."""
    shard, fp = task
    df = _FORK_SELF.build_DL_cached_representation(subject_ids=list(shard))
    type(_FORK_SELF)._write_df(df, fp, do_overwrite=True)
    return fp


def _transform_measure_worker(measure):
    return _FORK_SELF._transform_one_measurement(measure)


def _etl_build_shard_worker(task):
    """Builds one subject shard's raw event/measurement blocks and streams
    them to parquet (see `DatasetBase.build_event_and_measurement_dfs_sharded`).

    `_FORK_SELF` holds ``(cls, shards, subject_id_col, subject_id_dtype,
    schemas_by_df, stream_dir, source_slices)``; the task is the shard
    index. ``source_slices`` is the parse-once handoff: per-shard parquet
    slice paths for every path-valued source, parsed ONCE in the parent
    (with original row positions stamped) and streamed to ``stream_dir``
    so workers never re-parse the raw CSV/parquet and never inherit a raw
    frame through fork memory. Returns a manifest:
    ``(shard_idx, [(event_type, events_fp, meas_fp | None), ...])``
    in serial block order.
    """
    (
        cls,
        shards,
        subject_id_col,
        subject_id_dtype,
        schemas_by_df,
        stream_dir,
        source_slices,
    ) = _FORK_SELF
    w = task
    shard_map = shards[w]
    manifest = []
    for b, (event_type, events, meas) in enumerate(
        cls._iter_source_blocks(
            shard_map,
            subject_id_col,
            subject_id_dtype,
            schemas_by_df,
            keep_row_pos=True,
            source_overrides=None if source_slices is None else source_slices[w],
        )
    ):
        ev_fp = Path(stream_dir) / f"shard{w}_block{b}_events.parquet"
        cls._write_df(events, ev_fp, do_overwrite=True)
        me_fp = None
        if meas is not None:
            me_fp = Path(stream_dir) / f"shard{w}_block{b}_measurements.parquet"
            cls._write_df(meas, me_fp, do_overwrite=True)
        manifest.append((event_type, str(ev_fp), None if me_fp is None else str(me_fp)))
    return (w, manifest)


def _fork_map(payload, worker, tasks, n_workers: int) -> list:
    """Maps ``worker`` over ``tasks`` in a fork pool with ``payload``
    visible as `_FORK_SELF`; preserves task order."""
    import multiprocessing as mp
    from concurrent.futures import ProcessPoolExecutor

    global _FORK_SELF
    _FORK_SELF = payload
    try:
        ctx = mp.get_context("fork")
        with ProcessPoolExecutor(
            max_workers=min(n_workers, len(tasks)), mp_context=ctx
        ) as ex:
            return list(ex.map(worker, tasks))
    finally:
        _FORK_SELF = None


def shard_subject_ids(subject_ids_map: dict, n_shards: int) -> list[dict]:
    """Partitions a raw-key → numeric-id map into ``n_shards`` contiguous
    sub-maps by mapped id (assignment order), dropping empty shards.

    Contiguity by numeric id makes the plan deterministic for a given map
    and keeps each subject's rows in exactly one worker — the property the
    bit-identical merge (and per-shard dedup) relies on.

    Examples:
        >>> shard_subject_ids({"a": 0, "b": 1, "c": 2}, 2)
        [{'a': 0, 'b': 1}, {'c': 2}]
        >>> shard_subject_ids({"a": 0}, 4)
        [{'a': 0}]
    """
    items = sorted(subject_ids_map.items(), key=lambda kv: kv[1])
    n_shards = max(1, min(int(n_shards), len(items)))
    bounds = np.linspace(0, len(items), n_shards + 1).round().astype(int)
    shards = [dict(items[bounds[i] : bounds[i + 1]]) for i in range(n_shards)]
    return [s for s in shards if s]
INPUT_DF_T = TypeVar("INPUT_DF_T")


class DatasetBase(abc.ABC, Generic[DF_T, INPUT_DF_T], SeedableMixin, TimeableMixin):
    """A unified base class for dataset objects using different processing libraries.

    Reference: ``dataset_base.py:41-86``. Subclasses supply the concrete
    dataframe operations via the abstract ``_*`` methods.
    """

    SUBJECTS_FN = "subjects_df.parquet"
    EVENTS_FN = "events_df.parquet"
    DYNAMIC_MEASUREMENTS_FN = "dynamic_measurements_df.parquet"
    DF_SAVE_FORMAT = "parquet"

    PREPROCESSORS: dict[str, type] = {}

    @classmethod
    def subjects_fp(cls, save_dir: Path) -> Path:
        return Path(save_dir) / cls.SUBJECTS_FN

    @classmethod
    def events_fp(cls, save_dir: Path) -> Path:
        return Path(save_dir) / cls.EVENTS_FN

    @classmethod
    def dynamic_measurements_fp(cls, save_dir: Path) -> Path:
        return Path(save_dir) / cls.DYNAMIC_MEASUREMENTS_FN

    # ------------------------------------------------- abstract backend ops
    @classmethod
    @abc.abstractmethod
    def _parse_source(cls, src):
        """Reads a path-valued raw source (csv/parquet) into the backend's
        frame format, row order preserved — the ONE place raw bytes become
        a frame, shared by `_load_input_df` and the sharded build's
        parse-once handoff."""

    @classmethod
    @abc.abstractmethod
    def _load_input_df(cls, df, columns, subject_id_col=None, subject_ids_map=None,
                       subject_id_dtype=None, filter_on=None, subject_id_source_col=None,
                       keep_row_pos=False):
        """Loads an input dataframe into the backend's format (``dataset_polars.py:147``).

        ``keep_row_pos=True`` adds a ``__row_pos__`` column holding each
        kept row's position in the loaded source (used by the sharded build
        to restore serial row order on merge)."""

    @classmethod
    @abc.abstractmethod
    def _process_events_and_measurements_df(cls, df, event_type, columns_schema):
        """Splits one input df into (events_df, measurements_df | None) (``:311``)."""

    @classmethod
    @abc.abstractmethod
    def _split_range_events_df(cls, df):
        """Splits a range df into EQ/start/end event dfs (``:357``)."""

    @classmethod
    @abc.abstractmethod
    def _inc_df_col(cls, df, col, inc_by):
        """Increments an integer column by a constant (``:384``)."""

    @classmethod
    @abc.abstractmethod
    def _concat_dfs(cls, dfs):
        """Diagonally concatenates dataframes (``:390``)."""

    @classmethod
    @abc.abstractmethod
    def _resolve_ts_col(cls, df, ts_col, out_name="timestamp"):
        """Unifies one-or-multiple timestamp columns into ``out_name`` (``:299``)."""

    @classmethod
    @abc.abstractmethod
    def _rename_cols(cls, df, to_rename):
        """Renames columns (``:271``)."""

    @classmethod
    @abc.abstractmethod
    def _read_df(cls, fp: Path, **kwargs):
        """Reads a dataframe from disk (``:394``)."""

    @classmethod
    @abc.abstractmethod
    def _write_df(cls, df, fp: Path, **kwargs):
        """Writes a dataframe to disk, honoring ``do_overwrite`` (``:398``)."""

    @classmethod
    @abc.abstractmethod
    def _filter_col_inclusion(cls, df, col_inclusion_targets: dict[str, bool | Sequence[Any]]):
        """Filters rows via {col: True (non-null) | False (null) | values} (``:707``)."""

    @abc.abstractmethod
    def _validate_initial_dfs(self, subjects_df, events_df, dynamic_measurements_df):
        """Validates input dfs and shrinks dtypes (``dataset_base.py:594``)."""

    @abc.abstractmethod
    def _update_subject_event_properties(self):
        """Updates ``subject_ids`` / ``event_types`` / ``n_events_per_subject`` (``:601``)."""

    @abc.abstractmethod
    def _agg_by_time(self):
        """Aggregates events into temporal buckets (``:622``, ``dataset_polars.py:643``)."""

    @abc.abstractmethod
    def _sort_events(self):
        """Sorts events by subject and timestamp (``:635``)."""

    @abc.abstractmethod
    def _add_time_dependent_measurements(self):
        """Evaluates functional-time-dependent functors onto events_df (``:775``)."""

    @abc.abstractmethod
    def _total_possible_and_observed(self, measure, config, source_df):
        """(total possible, total observed) instances for a measure (``:882``)."""

    @abc.abstractmethod
    def _fit_measurement_metadata(self, measure, config, source_df) -> pd.DataFrame:
        """Fits numeric pre-processing metadata (``:900``)."""

    @abc.abstractmethod
    def _fit_vocabulary(self, measure, config, source_df) -> Vocabulary:
        """Fits the categorical vocabulary (``:916``)."""

    @abc.abstractmethod
    def _update_attr_df(self, attr, id_col, df, cols_to_update):
        """Writes transformed columns back into an internal df (``:959``)."""

    @abc.abstractmethod
    def _vocab_observations(self, measure, config, source_df):
        """The vocabulary observation series for one measure — shared by the
        from-scratch fit and the incremental append path."""

    @abc.abstractmethod
    def _incremental_update_numeric_fit(self, measure, config, source_df, stats_store):
        """Merges a new shard's observations into persisted sufficient
        statistics and refreshes moment-derived fit params."""

    @abc.abstractmethod
    def _transform_numerical_measurement(self, measure, config, source_df):
        """Applies bounds/outlier/normalizer transforms (``:970``)."""

    @abc.abstractmethod
    def _transform_categorical_measurement(self, measure, config, source_df):
        """Applies vocabulary filtering / categorization (``:993``)."""

    @abc.abstractmethod
    def build_DL_cached_representation(self, subject_ids=None, do_sort_outputs=False):
        """Produces the one-row-per-subject DL dataframe (``:1182``)."""

    @abc.abstractmethod
    def _denormalize(self, events_df, col: str):
        """Un-normalizes column ``col`` (``:1191``)."""

    # --------------------------------------------------------- construction
    @classmethod
    def build_subjects_dfs(cls, schema: InputDFSchema) -> tuple[DF_T, dict[Hashable, int]]:
        """Builds the subjects df + raw→numeric subject ID map (``dataset_base.py:179``)."""
        from .types import InputDataType

        subjects_df, ID_map = cls._load_input_df(
            schema.input_df,
            [(schema.subject_id_col, InputDataType.CATEGORICAL)] + schema.columns_to_load,
            filter_on=schema.filter_on,
            subject_id_source_col=schema.subject_id_col,
        )
        subjects_df = cls._rename_cols(
            subjects_df, {i: o for i, (o, _) in schema.unified_schema.items()}
        )
        return subjects_df, ID_map

    @classmethod
    def _iter_source_blocks(
        cls,
        subject_ids_map: dict[Any, int],
        subject_id_col: str,
        subject_id_dtype: Any,
        schemas_by_df: dict[Any, list[InputDFSchema]],
        keep_row_pos: bool = False,
        source_overrides: dict[Any, Any] | None = None,
    ):
        """Yields ``(event_type, events_df, measurements_df | None)`` per
        (source df, schema[, range-leg]) block, in the serial enumeration
        order. The block structure depends only on the schema map — never on
        which subjects are present — which is what lets the subject-sharded
        build line its workers' outputs back up block by block.

        ``keep_row_pos=True`` threads a ``__row_pos__`` column (the row's
        position in its loaded source df) through to the outputs so a
        sharded run can restore the exact serial row order on merge.

        ``source_overrides`` maps a ``schemas_by_df`` key to a pre-sliced
        replacement — the sharded build's parse-once handoff: either a
        frame or a path to one of `_preparse_shard_sources`'s streamed
        parquet slices (read back with `_read_df`, never `_parse_source` —
        raw sources parse exactly once, in the parent). Slices carry a
        ``__row_pos__`` column stamped from the ORIGINAL source, which
        `_load_input_df` honors over slice-local row order, so the outputs
        are bit-identical to loading the full source and filtering.
        """
        for src, schemas in schemas_by_df.items():
            all_columns = list(itertools.chain.from_iterable(s.columns_to_load for s in schemas))

            df = src if source_overrides is None else source_overrides.get(src, src)
            if df is not src and isinstance(df, (str, Path)):
                # A streamed parse-once slice: our own parquet, read with
                # the backend reader so the one-parse-per-raw-source
                # contract stays countable at `_parse_source`.
                df = cls._read_df(Path(df))
            try:
                df = cls._load_input_df(
                    df, all_columns, subject_id_col, subject_ids_map, subject_id_dtype,
                    keep_row_pos=keep_row_pos,
                )
            except Exception as e:
                raise ValueError(f"Errored while loading {src}") from e

            for schema in schemas:
                sub_df = df
                if schema.filter_on:
                    sub_df = cls._filter_col_inclusion(sub_df, schema.filter_on)
                if schema.type == InputDFType.EVENT:
                    sub_df = cls._resolve_ts_col(sub_df, schema.ts_col, "timestamp")
                    events, measurements = cls._process_events_and_measurements_df(
                        df=sub_df, event_type=schema.event_type,
                        columns_schema=schema.unified_schema,
                    )
                    yield schema.event_type, events, measurements
                elif schema.type == InputDFType.RANGE:
                    sub_df = cls._resolve_ts_col(sub_df, schema.start_ts_col, "start_time")
                    sub_df = cls._resolve_ts_col(sub_df, schema.end_ts_col, "end_time")
                    for et, unified_schema, sp_df in zip(
                        schema.event_type, schema.unified_schema, cls._split_range_events_df(sub_df)
                    ):
                        events, measurements = cls._process_events_and_measurements_df(
                            sp_df, columns_schema=unified_schema, event_type=et
                        )
                        yield et, events, measurements
                else:
                    raise ValueError(f"Invalid schema type {schema.type}.")

    @classmethod
    def _merge_event_blocks(cls, blocks) -> tuple[DF_T, DF_T]:
        """Assigns globally unique event ids across blocks and concatenates
        (the tail of the historical ``build_event_and_measurement_dfs``)."""
        all_events, all_measurements = [], []
        running_event_id_max = 0
        for event_type, events, measurements in blocks:
            try:
                new_events = cls._inc_df_col(events, "event_id", running_event_id_max)
            except Exception as e:
                raise ValueError(f"Failed to increment event_id on {event_type}") from e

            if len(new_events) == 0:
                print(f"Empty new events dataframe of type {event_type}!")
                continue

            all_events.append(new_events)
            if measurements is not None:
                all_measurements.append(cls._inc_df_col(measurements, "event_id", running_event_id_max))

            running_event_id_max = int(all_events[-1]["event_id"].max()) + 1

        return cls._concat_dfs(all_events), cls._concat_dfs(all_measurements)

    @classmethod
    def build_event_and_measurement_dfs(
        cls,
        subject_ids_map: dict[Any, int],
        subject_id_col: str,
        subject_id_dtype: Any,
        schemas_by_df: dict[Any, list[InputDFSchema]],
    ) -> tuple[DF_T, DF_T]:
        """Builds events + measurements dfs from the schema map (``dataset_base.py:202``)."""
        return cls._merge_event_blocks(
            cls._iter_source_blocks(subject_ids_map, subject_id_col, subject_id_dtype, schemas_by_df)
        )

    @classmethod
    def _preparse_shard_sources(
        cls,
        schemas_by_df: dict[Any, list[InputDFSchema]],
        shards: list[dict],
        subject_id_col: str,
        stream_dir: Path | str,
    ) -> list[dict] | None:
        """Parses each path-valued raw source ONCE and streams its per-shard
        slices to parquet under ``stream_dir`` — the sharded build's
        parse-once handoff.

        Returns one ``{schemas_by_df key: slice path}`` map per shard
        (``None`` when no source is a path). Every slice carries a
        ``__row_pos__`` column stamped with the row's position in the
        ORIGINAL parsed source, which `_load_input_df` honors over
        slice-local order — that is what keeps the sharded merge's
        ``__row_pos__`` sort (and therefore the whole cache) bit-identical
        to the serial path. Sources parse one at a time and each frame is
        dropped before the next parse; workers read back only their own
        slices — parent peak RSS is O(one parsed source) no matter how
        many sources the schema maps (the r11 bounded-RSS property,
        preserved), and the slices land in the same ``stream_dir`` the
        block outputs already use, so the merge's cleanup owns them too.
        """
        path_sources = [
            src for src in schemas_by_df if isinstance(src, (str, Path))
        ]
        if not path_sources:
            return None
        stream_dir = Path(stream_dir)
        stream_dir.mkdir(parents=True, exist_ok=True)
        shard_keysets = [set(map(str, shard.keys())) for shard in shards]
        out: list[dict] = [{} for _ in shards]
        for si, src in enumerate(path_sources):
            raw = cls._parse_source(src)
            raw = raw.reset_index(drop=True)
            raw = raw.assign(
                __row_pos__=np.arange(len(raw), dtype=np.int64)
            )
            key = raw[subject_id_col].astype(str)
            for w, keyset in enumerate(shard_keysets):
                fp = stream_dir / f"preparse_src{si}_shard{w}.parquet"
                cls._write_df(raw[key.isin(keyset)], fp, do_overwrite=True)
                out[w][src] = fp
            del raw, key
        return out

    @classmethod
    def build_event_and_measurement_dfs_sharded(
        cls,
        subject_ids_map: dict[Any, int],
        subject_id_col: str,
        subject_id_dtype: Any,
        schemas_by_df: dict[Any, list[InputDFSchema]],
        n_workers: int,
        stream_dir: Path | str,
    ) -> tuple[DF_T, DF_T]:
        """Subject-sharded, multi-process `build_event_and_measurement_dfs`.

        The raw subject-id map is partitioned into contiguous shards
        (`shard_subject_ids`); each worker runs the identical per-source
        block pipeline on its shard only and STREAMS its per-block outputs
        to parquet under ``stream_dir`` (worker→parent traffic is a path
        list, worker RSS is O(shard)). The parent then merges block by
        block: within a block, every row carries its position in the loaded
        source df (``__row_pos__``), duplicates can only collide within one
        subject (rows carry ``subject_id``), and dedup keeps first — so a
        stable sort on ``__row_pos__`` reproduces the serial block row
        order exactly, and the serial event-id assignment follows. The
        merged frames are bit-identical to the single-process path (pinned
        by test).
        """
        shards = shard_subject_ids(subject_ids_map, n_workers)
        if len(shards) <= 1:
            return cls.build_event_and_measurement_dfs(
                subject_ids_map, subject_id_col, subject_id_dtype, schemas_by_df
            )

        import shutil

        stream_dir = Path(stream_dir)
        stream_dir.mkdir(parents=True, exist_ok=True)
        try:
            # Parse-once handoff: each path-valued source is parsed ONCE
            # here and its per-shard slices streamed to parquet (original
            # row positions stamped), so workers read pre-sliced parquet
            # instead of re-parsing the raw CSV K times — the load/parse
            # phase cost drops from K× to 1× serial (the r11 known cost,
            # docs/ingestion.md) while parent peak RSS stays O(one parsed
            # source): each frame is dropped before the next source
            # parses, and nothing raw is held across the fork.
            source_slices = cls._preparse_shard_sources(
                schemas_by_df, shards, subject_id_col, stream_dir
            )
            payload = (
                cls,
                shards,
                subject_id_col,
                subject_id_dtype,
                schemas_by_df,
                stream_dir,
                source_slices,
            )
            manifests = _fork_map(
                payload, _etl_build_shard_worker, list(range(len(shards))), n_workers
            )
            manifests = [m for _, m in sorted(manifests, key=lambda wm: wm[0])]

            n_blocks = len(manifests[0])

            def merged_blocks():
                for b in range(n_blocks):
                    event_type = manifests[0][b][0]
                    ev_parts = [cls._read_df(Path(m[b][1])) for m in manifests]
                    # pandas used directly for the order-restoring merge: the
                    # shard files are the backend's own parquet, and the base
                    # class already leans on pandas for the DL shard concat.
                    events = pd.concat(ev_parts, ignore_index=True)
                    events = events.sort_values("__row_pos__", kind="stable").reset_index(drop=True)
                    events["event_id"] = np.arange(len(events), dtype=np.int64)
                    meas = None
                    if manifests[0][b][2] is not None:
                        me_parts = [cls._read_df(Path(m[b][2])) for m in manifests]
                        meas = pd.concat(me_parts, ignore_index=True)
                        meas = meas.sort_values("__row_pos__", kind="stable").reset_index(drop=True)
                        meas["event_id"] = events["event_id"].to_numpy()
                        meas = meas.drop(columns=["__row_pos__"])
                    yield event_type, events.drop(columns=["__row_pos__"]), meas

            return cls._merge_event_blocks(merged_blocks())
        finally:
            # The whole directory is ours (a dedicated .etl_shards/ or
            # tempdir): multi-GB shard files must not outlive the merge,
            # successful or not.
            shutil.rmtree(stream_dir, ignore_errors=True)

    @classmethod
    def _get_preprocessing_model(cls, model_config: dict[str, Any], for_fit: bool = False):
        """Resolves a preprocessor class/instance from config (``dataset_base.py:286``).

        Examples:
            >>> class MockPreprocessor:
            ...     def __init__(self, name: str = ""):
            ...         self.name = name
            >>> class D(DatasetBase):
            ...     PREPROCESSORS = {"mock": MockPreprocessor}
            >>> D.__abstractmethods__ = frozenset()
            >>> D._get_preprocessing_model({"cls": "mock", "name": "a"}, for_fit=True).name
            'a'
            >>> D._get_preprocessing_model({"cls": "mock"}, for_fit=False)
            <class '...MockPreprocessor'>
            >>> D._get_preprocessing_model({}, for_fit=True)
            Traceback (most recent call last):
                ...
            KeyError: "Missing mandatory preprocessor class configuration parameter `'cls'`."
        """
        if "cls" not in model_config:
            raise KeyError("Missing mandatory preprocessor class configuration parameter `'cls'`.")
        if model_config["cls"] not in cls.PREPROCESSORS:
            raise KeyError(
                f"Invalid preprocessor model class {model_config['cls']}! {cls.__name__} options are "
                f"{', '.join(cls.PREPROCESSORS.keys())}"
            )

        model_cls = cls.PREPROCESSORS[model_config["cls"]]
        if not for_fit:
            return model_cls
        return model_cls(**{k: v for k, v in model_config.items() if k != "cls"})

    # ------------------------------------------------------------- save/load
    @classmethod
    def load(cls, load_dir: Path) -> "DatasetBase":
        """Re-loads a saved dataset directory (``dataset_base.py:412``)."""
        load_dir = Path(load_dir)
        attrs_fp = load_dir / "E.pkl"
        with open(attrs_fp, "rb") as f:
            attrs = pickle.load(f)

        attrs["config"] = DatasetConfig.from_json_file(load_dir / "config.json")
        inferred_fp = load_dir / "inferred_measurement_configs.json"
        if inferred_fp.is_file():
            with open(inferred_fp) as f:
                # base_dir re-roots stale absolute metadata-CSV paths when the
                # dataset directory was produced on another machine.
                attrs["inferred_measurement_configs"] = {
                    k: MeasurementConfig.from_dict(v, base_dir=load_dir)
                    for k, v in json.load(f).items()
                }

        obj = cls.__new__(cls)
        for k, v in attrs.items():
            setattr(obj, k, v)
        # Incremental-fit sidecars (absent on legacy caches).
        if not hasattr(obj, "_frozen_vocab"):
            obj._frozen_vocab = None
        if not hasattr(obj, "_raw_subject_key_map"):
            obj._raw_subject_key_map = None
        stats_fp = load_dir / "preprocessor_sufficient_stats.json"
        if stats_fp.is_file():
            with open(stats_fp) as f:
                obj._preproc_stats = json.load(f)
        else:
            obj._preproc_stats = None

        for attr, fp_fn in (
            ("subjects_df", cls.subjects_fp),
            ("events_df", cls.events_fp),
            ("dynamic_measurements_df", cls.dynamic_measurements_fp),
        ):
            fp = fp_fn(load_dir)
            setattr(obj, attr, cls._read_df(fp) if fp.is_file() else None)
        return obj

    def save(self, **kwargs):
        """Saves the dataset directory (``dataset_base.py:450``): config.json,
        inferred_measurement_configs.json (+ per-measure metadata CSVs),
        vocabulary_config.json, the three parquet dfs, and E.pkl attrs."""
        save_dir = Path(self.config.save_dir)
        save_dir.mkdir(parents=True, exist_ok=True)
        do_overwrite = kwargs.get("do_overwrite", False)

        self.config.to_json_file(save_dir / "config.json", do_overwrite=do_overwrite)

        if self._is_fit:
            self._freeze_unified_layout()
            metadata_dir = save_dir / "inferred_measurement_metadata"
            for k, v in self.inferred_measurement_configs.items():
                v.cache_measurement_metadata(metadata_dir / f"{k}.csv")

            with open(save_dir / "inferred_measurement_configs.json", "w") as f:
                json.dump({k: v.to_dict() for k, v in self.inferred_measurement_configs.items()}, f)

            self.vocabulary_config.to_json_file(
                save_dir / "vocabulary_config.json", do_overwrite=do_overwrite
            )

            if getattr(self, "_preproc_stats", None) is not None:
                with open(save_dir / "preprocessor_sufficient_stats.json", "w") as f:
                    json.dump(self._preproc_stats, f)

        attrs = {
            "_is_fit": self._is_fit,
            "split_subjects": self.split_subjects,
            "subject_ids": self.subject_ids,
            "event_types": self.event_types,
            "n_events_per_subject": self.n_events_per_subject,
            "_frozen_vocab": getattr(self, "_frozen_vocab", None),
            "_raw_subject_key_map": getattr(self, "_raw_subject_key_map", None),
        }
        attrs_fp = save_dir / "E.pkl"
        if attrs_fp.exists() and not do_overwrite:
            raise FileExistsError(f"{attrs_fp} exists and do_overwrite is False!")
        with open(attrs_fp, "wb") as f:
            pickle.dump(attrs, f)

        self._write_df(self.subjects_df, self.subjects_fp(save_dir), do_overwrite=do_overwrite)
        self._write_df(self.events_df, self.events_fp(save_dir), do_overwrite=do_overwrite)
        self._write_df(
            self.dynamic_measurements_df,
            self.dynamic_measurements_fp(save_dir),
            do_overwrite=do_overwrite,
        )

    # ------------------------------------------------------------------ init
    def __init__(
        self,
        config: DatasetConfig,
        subjects_df: DF_T | None = None,
        events_df: DF_T | None = None,
        dynamic_measurements_df: DF_T | None = None,
        input_schema: DatasetSchema | None = None,
        n_workers: int = 1,
        **kwargs,
    ):
        super().__init__(**kwargs)

        if (
            subjects_df is None or events_df is None or dynamic_measurements_df is None
        ) and input_schema is None:
            raise ValueError(
                "Must set input_schema if subjects_df, events_df, or dynamic_measurements_df are None!"
            )

        if input_schema is None:
            if subjects_df is None:
                raise ValueError("Must set subjects_df if input_schema is None!")
            if events_df is None:
                raise ValueError("Must set events_df if input_schema is None!")
            if dynamic_measurements_df is None:
                raise ValueError("Must set dynamic_measurements_df if input_schema is None!")
        else:
            if subjects_df is not None:
                raise ValueError("Can't set subjects_df if input_schema is not None!")
            if events_df is not None:
                raise ValueError("Can't set events_df if input_schema is not None!")
            if dynamic_measurements_df is not None:
                raise ValueError("Can't set dynamic_measurements_df if input_schema is not None!")

            with self._time_as("build_subjects_dfs"):
                subjects_df, ID_map = self.build_subjects_dfs(input_schema.static)
            # Persisted so `append_subjects` can detect a re-submitted raw
            # subject key instead of silently minting a duplicate subject.
            self._raw_subject_key_map = dict(ID_map)
            subject_id_dtype = subjects_df["subject_id"].dtype

            with self._time_as("build_event_and_measurement_dfs"):
                if n_workers > 1:
                    import tempfile

                    stream_root = (
                        Path(config.save_dir) / ".etl_shards"
                        if config.save_dir is not None
                        else Path(tempfile.mkdtemp(prefix="esgpt_etl_shards_"))
                    )
                    events_df, dynamic_measurements_df = (
                        self.build_event_and_measurement_dfs_sharded(
                            ID_map,
                            input_schema.static.subject_id_col,
                            subject_id_dtype,
                            input_schema.dynamic_by_df,
                            n_workers=n_workers,
                            stream_dir=stream_root,
                        )
                    )
                else:
                    events_df, dynamic_measurements_df = self.build_event_and_measurement_dfs(
                        ID_map,
                        input_schema.static.subject_id_col,
                        subject_id_dtype,
                        input_schema.dynamic_by_df,
                    )

        self.config = config
        self._is_fit = False
        self.inferred_measurement_configs: dict[str, MeasurementConfig] = {}
        # Incremental-fit state: per-stage sufficient statistics collected at
        # fit time, and the unified-vocabulary snapshot frozen at first
        # save/cache (None until then — the live derivation applies). The
        # raw-key map exists only when this dataset ingested raw inputs
        # itself (set above); frames-constructed datasets can't collision-
        # check appends.
        self._preproc_stats: dict[str, Any] | None = None
        self._frozen_vocab: dict[str, Any] | None = None
        if not hasattr(self, "_raw_subject_key_map"):
            self._raw_subject_key_map: dict | None = None

        self._validate_and_set_initial_properties(subjects_df, events_df, dynamic_measurements_df)

        self.split_subjects: dict[str, set] = {}

    def _validate_and_set_initial_properties(self, subjects_df, events_df, dynamic_measurements_df):
        """Validates inputs, shrinks dtypes, aggs+sorts events (``dataset_base.py:566``)."""
        self.subject_ids = []
        self.event_types = []
        self.n_events_per_subject = {}

        with self._time_as("_validate_initial_dfs"):
            (
                self.subjects_df,
                self.events_df,
                self.dynamic_measurements_df,
            ) = self._validate_initial_dfs(subjects_df, events_df, dynamic_measurements_df)

        if self.events_df is not None:
            with self._time_as("_agg_by_time"):
                self._agg_by_time()
            with self._time_as("_sort_events"):
                self._sort_events()
        with self._time_as("_update_subject_event_properties"):
            self._update_subject_event_properties()

    # ------------------------------------------------------------- filtering
    @TimeableMixin.TimeAs
    def _filter_subjects(self):
        """Drops subjects with too few events (``dataset_base.py:607``)."""
        if self.config.min_events_per_subject is None:
            return

        subjects_to_keep = [
            s for s, n in self.n_events_per_subject.items() if n >= self.config.min_events_per_subject
        ]
        self.subjects_df = self._filter_col_inclusion(self.subjects_df, {"subject_id": subjects_to_keep})
        self.events_df = self._filter_col_inclusion(self.events_df, {"subject_id": subjects_to_keep})
        self.dynamic_measurements_df = self._filter_col_inclusion(
            self.dynamic_measurements_df, {"event_id": list(self.events_df["event_id"])}
        )
        self._update_subject_event_properties()

    # ------------------------------------------------------------------ split
    @SeedableMixin.WithSeed
    @TimeableMixin.TimeAs
    def split(
        self,
        split_fracs: Sequence[float],
        split_names: Sequence[str] | None = None,
    ):
        """Randomly splits subjects into named splits (``dataset_base.py:642``)."""
        split_fracs = list(split_fracs)

        if min(split_fracs) <= 0 or max(split_fracs) > 1 or sum(split_fracs) > 1:
            raise ValueError(
                "split_fracs invalid! Want a list of numbers in (0, 1] that sums to no more than 1; got "
                f"{repr(split_fracs)}"
            )

        if sum(split_fracs) < 1:
            split_fracs.append(1 - sum(split_fracs))

        if split_names is None:
            if len(split_fracs) == 2:
                split_names = ["train", "held_out"]
            elif len(split_fracs) == 3:
                split_names = ["train", "tuning", "held_out"]
            else:
                split_names = [f"split_{i}" for i in range(len(split_fracs))]
        elif len(split_names) != len(split_fracs):
            raise ValueError(
                f"split_names and split_fracs must be the same length; got {len(split_names)} and "
                f"{len(split_fracs)}"
            )

        # Shuffle names+fracs so rounding excess doesn't always hit the same split.
        split_names_idx = np.random.permutation(len(split_names))
        split_names = [split_names[i] for i in split_names_idx]
        split_fracs = [split_fracs[i] for i in split_names_idx]

        subjects = np.random.permutation(list(self.subject_ids))
        split_lens = (np.array(split_fracs[:-1]) * len(subjects)).round().astype(int)
        split_lens = np.append(split_lens, len(subjects) - split_lens.sum())

        subjects_per_split = np.split(subjects, split_lens.cumsum())

        self.split_subjects = {k: set(v.tolist()) for k, v in zip(split_names, subjects_per_split)}

    # --------------------------------------------------------- split accessors
    @property
    def train_subjects_df(self) -> DF_T:
        return self._filter_col_inclusion(self.subjects_df, {"subject_id": self.split_subjects["train"]})

    @property
    def tuning_subjects_df(self) -> DF_T:
        return self._filter_col_inclusion(self.subjects_df, {"subject_id": self.split_subjects["tuning"]})

    @property
    def held_out_subjects_df(self) -> DF_T:
        return self._filter_col_inclusion(
            self.subjects_df, {"subject_id": self.split_subjects["held_out"]}
        )

    @property
    def train_events_df(self) -> DF_T:
        return self._filter_col_inclusion(self.events_df, {"subject_id": self.split_subjects["train"]})

    @property
    def tuning_events_df(self) -> DF_T:
        return self._filter_col_inclusion(self.events_df, {"subject_id": self.split_subjects["tuning"]})

    @property
    def held_out_events_df(self) -> DF_T:
        return self._filter_col_inclusion(self.events_df, {"subject_id": self.split_subjects["held_out"]})

    @property
    def train_dynamic_measurements_df(self) -> DF_T:
        event_ids = self.train_events_df["event_id"]
        return self._filter_col_inclusion(self.dynamic_measurements_df, {"event_id": list(event_ids)})

    @property
    def tuning_dynamic_measurements_df(self) -> DF_T:
        event_ids = self.tuning_events_df["event_id"]
        return self._filter_col_inclusion(self.dynamic_measurements_df, {"event_id": list(event_ids)})

    @property
    def held_out_dynamic_measurements_df(self) -> DF_T:
        event_ids = self.held_out_events_df["event_id"]
        return self._filter_col_inclusion(self.dynamic_measurements_df, {"event_id": list(event_ids)})

    # ------------------------------------------------------------ preprocess
    @TimeableMixin.TimeAs
    def preprocess(self, n_workers: int = 1):
        """filter → add time-dependent measures → fit → transform (``dataset_base.py:757``).

        ``n_workers > 1`` process-pools the per-measurement transform phase
        (byte-identical outputs; see `transform_measurements`).
        """
        self._filter_subjects()
        self._add_time_dependent_measurements()
        self.fit_measurements()
        self.transform_measurements(n_workers=n_workers)

    @TimeableMixin.TimeAs
    def _get_source_df(self, config: MeasurementConfig, do_only_train: bool = True):
        """(source attr name, id col, df) for a measurement config (``dataset_base.py:780``)."""
        if config.temporality == TemporalityType.DYNAMIC:
            source_attr = "dynamic_measurements_df"
            source_id = "measurement_id"
            source_df = (
                self.train_dynamic_measurements_df if do_only_train else self.dynamic_measurements_df
            )
        elif config.temporality == TemporalityType.STATIC:
            source_attr = "subjects_df"
            source_id = "subject_id"
            source_df = self.train_subjects_df if do_only_train else self.subjects_df
        elif config.temporality == TemporalityType.FUNCTIONAL_TIME_DEPENDENT:
            source_attr = "events_df"
            source_id = "event_id"
            source_df = self.train_events_df if do_only_train else self.events_df
        else:
            raise ValueError(f"Called get_source_df on temporality type {config.temporality}!")
        return source_attr, source_id, source_df

    def _stash_fit_stats(self, stage: str, measure: str, stats) -> None:
        """Records per-key sufficient statistics (or vocab totals) gathered
        during fitting — the persisted state the incremental-fit path merges
        new shards into (`append_subjects`)."""
        if self._preproc_stats is None:
            self._preproc_stats = {"outlier": {}, "normalizer": {}, "vocab_totals": {}}
        self._preproc_stats[stage][measure] = stats

    @TimeableMixin.TimeAs
    def fit_measurements(self):
        """Fits all preprocessing parameters over the train split (``dataset_base.py:819``)."""
        self._is_fit = False
        self._preproc_stats = {"outlier": {}, "normalizer": {}, "vocab_totals": {}}

        for measure, config in self.config.measurement_configs.items():
            if config.is_dropped:
                continue

            self.inferred_measurement_configs[measure] = copy.deepcopy(config)
            config = self.inferred_measurement_configs[measure]

            _, _, source_df = self._get_source_df(config, do_only_train=True)

            if measure not in source_df:
                print(f"WARNING: Measure {measure} not found! Dropping...")
                config.drop()
                continue

            total_possible, total_observed = self._total_possible_and_observed(
                measure, config, source_df
            )
            source_df = self._filter_col_inclusion(source_df, {measure: True})

            if total_possible == 0:
                print(f"Found no possible events for {measure}!")
                config.drop()
                continue

            config.observation_frequency = total_observed / total_possible

            # Drop the column if observations occur too rarely.
            if lt_count_or_proportion(
                total_observed, self.config.min_valid_column_observations, total_possible
            ):
                config.drop()
                continue

            if config.is_numeric:
                config.add_missing_mandatory_metadata_cols()
                try:
                    config.measurement_metadata = self._fit_measurement_metadata(
                        measure, config, source_df
                    )
                except BaseException as e:
                    raise ValueError(f"Fitting measurement metadata failed for measure {measure}!") from e

            if config.vocabulary is None:
                config.vocabulary = self._fit_vocabulary(measure, config, source_df)

                # Eliminate observations that occur too rarely.
                if config.vocabulary is not None:
                    if self.config.min_valid_vocab_element_observations is not None:
                        config.vocabulary.filter(
                            len(source_df), self.config.min_valid_vocab_element_observations
                        )

                    # If all observations were eliminated, drop the column.
                    if config.vocabulary.vocabulary == ["UNK"]:
                        config.drop()

        self._is_fit = True

    def _transform_one_measurement(self, measure: str):
        """Transforms one measurement; returns ``(source_attr, id_col,
        transformed_df, updated_cols)`` without mutating the dataset.

        Measurements are mutually independent — each reads and writes only
        its own columns — which is what makes `transform_measurements`'s
        process-pool mode byte-identical to the serial loop.
        """
        config = self.measurement_configs[measure]
        source_attr, id_col, source_df = self._get_source_df(config, do_only_train=False)

        source_df = self._filter_col_inclusion(source_df, {measure: True})
        updated_cols = [measure]

        try:
            if config.is_numeric:
                source_df = self._transform_numerical_measurement(measure, config, source_df)

                if config.modality == DataModality.MULTIVARIATE_REGRESSION:
                    updated_cols.append(config.values_column)

                if self.config.outlier_detector_config is not None:
                    updated_cols.append(f"{measure}_is_inlier")

            if config.vocabulary is not None:
                source_df = self._transform_categorical_measurement(measure, config, source_df)

        except BaseException as e:
            raise ValueError(f"Transforming measurement failed for measure {measure}!") from e

        return source_attr, id_col, source_df, updated_cols

    @TimeableMixin.TimeAs
    def transform_measurements(self, n_workers: int = 1):
        """Transforms all splits via the fit parameters (``dataset_base.py:928``).

        ``n_workers > 1`` runs the per-measurement transforms in a fork-based
        process pool (the reference gets this parallelism for free from
        Polars' Rust threadpool, ``dataset_polars.py:643``); results apply in
        measurement order, so outputs are byte-identical to the serial loop.
        """
        measures = list(self.measurement_configs)
        if n_workers > 1 and len(measures) > 1:
            results = _fork_map(self, _transform_measure_worker, measures, n_workers)
        else:
            results = (self._transform_one_measurement(m) for m in measures)
        for source_attr, id_col, source_df, updated_cols in results:
            self._update_attr_df(source_attr, id_col, source_df, updated_cols)

    # ------------------------------------------------- incremental ingestion
    def make_shard_view(
        self,
        subjects_df,
        events_df,
        dynamic_measurements_df,
        transform_configs: dict[str, MeasurementConfig] | None = None,
    ) -> "DatasetBase":
        """A lightweight dataset over one RAW subject shard, sharing this
        dataset's config and FROZEN fit state.

        The view runs the exact batch pipeline on its shard — validate →
        agg-by-time → sort → time-dependent functors → frozen-preprocessor
        transforms → DL representation — through the same instance methods
        the full ETL uses. Both `append_subjects` and the online-admission
        path (`serving.ingest`) are built on it, which is what makes their
        outputs bit-identical to the batch ETL for the same subject.
        """
        if not self._is_fit:
            raise ValueError("Can't make a shard view of an unfit dataset!")
        view = type(self).__new__(type(self))
        view.config = self.config
        view._is_fit = True
        view._preproc_stats = None
        view._frozen_vocab = copy.deepcopy(getattr(self, "_frozen_vocab", None))
        view.split_subjects = {}
        view.inferred_measurement_configs = (
            transform_configs if transform_configs is not None else self._frozen_transform_configs()
        )
        view._validate_and_set_initial_properties(subjects_df, events_df, dynamic_measurements_df)
        return view

    def _update_fit_from_shard(self, shard: "DatasetBase") -> None:
        """Incremental fit: merges one new shard into the persisted fit state.

        Vocabularies grow APPEND-ONLY (`Vocabulary.extend_with_counts` —
        existing indices frozen); scaler/outlier params refresh from merged
        (count, sum, sumsq) sufficient statistics; brand-new vocabulary
        keys are recorded but not type-inferred (they surface as UNK under
        the frozen unified layout until the next full re-fit).
        """
        stats = getattr(self, "_preproc_stats", None)
        if stats is None:
            raise ValueError(
                "append_subjects requires a cache with persisted sufficient statistics "
                "(preprocessor_sufficient_stats.json) — re-run fit/save with this version."
            )
        for measure, config in self.measurement_configs.items():
            _, _, source_df = shard._get_source_df(config, do_only_train=False)
            if measure not in source_df:
                continue
            source_df = self._filter_col_inclusion(source_df, {measure: True})
            if len(source_df) == 0:
                continue

            if config.is_numeric:
                self._incremental_update_numeric_fit(measure, config, source_df, stats)

            if config.vocabulary is not None:
                obs = shard._vocab_observations(measure, config, source_df)
                if obs is not None and len(obs):
                    counts = obs.value_counts()
                    prior_total = stats.setdefault("vocab_totals", {}).get(measure)
                    if prior_total is None:
                        # A fit-time vocabulary always stashed its total; the
                        # only current-version way here is a PRESET vocabulary
                        # (no observed total exists). Skip growth — the frozen
                        # transform parks unseen elements as UNK regardless.
                        print(
                            f"WARNING: no persisted vocabulary totals for {measure!r} "
                            "(preset vocabulary?); skipping append-only growth."
                        )
                        continue
                    # Raw elements, NOT str(k): vocabularies may hold
                    # non-string elements (e.g. booleans) and a stringified
                    # key would miss the idxmap and duplicate the element.
                    config.vocabulary.extend_with_counts(
                        {k: int(c) for k, c in counts.items()}, prior_total
                    )
                    stats["vocab_totals"][measure] = int(prior_total + int(counts.sum()))

    def append_subjects(
        self,
        input_schema: DatasetSchema,
        split: str = "train",
        n_workers: int = 1,
        subjects_per_output_file: int | None = None,
        do_save: bool = True,
    ) -> dict[str, Any]:
        """Appends new subjects to a fit, cached dataset WITHOUT a full re-fit
        or re-cache.

        Pipeline: ingest the new subjects' raw inputs (optionally
        subject-sharded over ``n_workers``), run the frozen batch transforms
        on the new shard only, update the incremental fit state
        (append-only vocabularies, sufficient-statistic scaler updates),
        write the new subjects as NEW ``DL_reps/{split}_{chunk}`` files —
        existing shard files are never touched — and merge the shard into
        the in-memory frames. Fit state only updates when ``split`` is
        ``"train"`` (mirroring the train-only full fit).

        ``do_save`` (default True) re-persists the dataset directory at the
        end (`save(do_overwrite=True)` — sidecars + the three frame
        parquets; it never touches ``DL_reps/``): without it, a process
        that exits after append leaves on-disk fit state (grown vocab,
        merged statistics, the duplicate-subject guard's key map) behind
        the durable new chunks, and a replayed ingestion job would admit
        the same batch twice. Pass ``do_save=False`` only to batch several
        appends under one final `save`.

        Returns ``{"subject_ids", "n_events", "chunk_paths"}``.
        """
        if not self._is_fit:
            raise ValueError("append_subjects requires a fit dataset")
        if self.config.save_dir is None:
            raise ValueError("append_subjects requires a save_dir-backed dataset")
        self._freeze_unified_layout()

        with self._time_as("append_build_subjects"):
            new_subjects_df, ID_map = self.build_subjects_dfs(input_schema.static)
            known_keys = getattr(self, "_raw_subject_key_map", None)
            if known_keys:
                collisions = sorted(set(ID_map) & set(known_keys))
                if collisions:
                    raise ValueError(
                        f"append_subjects: {len(collisions)} raw subject key(s) already "
                        f"exist in this dataset (e.g. {collisions[:5]}); re-ingesting a "
                        "subject would mint a duplicate numeric id. Filter the input or "
                        "run a full rebuild."
                    )
            id_offset = int(max(self.subject_ids)) + 1 if self.subject_ids else 0
            new_subjects_df = self._inc_df_col(new_subjects_df, "subject_id", id_offset)
            ID_map = {k: v + id_offset for k, v in ID_map.items()}
            id_dtype = type(self).get_smallest_valid_int_type(id_offset + len(ID_map))
            new_subjects_df["subject_id"] = new_subjects_df["subject_id"].astype(id_dtype)

        with self._time_as("append_build_events"):
            if n_workers > 1:
                events_df, meas_df = self.build_event_and_measurement_dfs_sharded(
                    ID_map,
                    input_schema.static.subject_id_col,
                    id_dtype,
                    input_schema.dynamic_by_df,
                    n_workers=n_workers,
                    stream_dir=Path(self.config.save_dir) / ".etl_shards",
                )
            else:
                events_df, meas_df = self.build_event_and_measurement_dfs(
                    ID_map, input_schema.static.subject_id_col, id_dtype,
                    input_schema.dynamic_by_df,
                )

        with self._time_as("append_shard_pipeline"):
            shard = self.make_shard_view(new_subjects_df, events_df, meas_df)
            shard._filter_subjects()
            shard._add_time_dependent_measurements()

            if split == "train":
                self._update_fit_from_shard(shard)
                # Re-freeze nothing: the layout snapshot pins transforms, but
                # numeric params just moved — hand the shard fresh configs.
                shard.inferred_measurement_configs = self._frozen_transform_configs()

            shard.transform_measurements(n_workers=n_workers)

        with self._time_as("append_cache_shard"):
            DL_dir = Path(self.config.save_dir) / "DL_reps"
            DL_dir.mkdir(exist_ok=True, parents=True)
            suffixes = [
                fp.stem.rpartition("_")[2] for fp in DL_dir.glob(f"*.{self.DF_SAVE_FORMAT}")
            ]
            existing = [int(s) for s in suffixes if s.isdigit()]
            next_chunk = (max(existing) + 1) if existing else 0

            if subjects_per_output_file is None:
                subject_chunks = [sorted(shard.subject_ids)]
            else:
                ids = np.asarray(sorted(shard.subject_ids))
                subject_chunks = [
                    list(c)
                    for c in np.array_split(
                        ids, max(1, -(-len(ids) // subjects_per_output_file))
                    )
                ]
            chunk_paths = []
            for i, chunk_ids in enumerate(subject_chunks):
                rep = shard._build_dl_rep_sharded(list(chunk_ids), n_workers)
                fp = DL_dir / f"{split}_{next_chunk + i}.{self.DF_SAVE_FORMAT}"
                self._write_df(rep, fp, do_overwrite=False)
                chunk_paths.append(fp)

        with self._time_as("append_merge_frames"):
            self._merge_shard_frames(shard, split)
            if getattr(self, "_raw_subject_key_map", None) is not None:
                kept = set(shard.subject_ids)
                self._raw_subject_key_map.update(
                    {k: v for k, v in ID_map.items() if v in kept}
                )

        if do_save:
            with self._time_as("append_save_metadata"):
                self.save(do_overwrite=True)

        return {
            "subject_ids": sorted(shard.subject_ids),
            "n_events": len(shard.events_df),
            "chunk_paths": chunk_paths,
        }

    def _merge_shard_frames(self, shard: "DatasetBase", split: str) -> None:
        """Merges a transformed shard view's frames and bookkeeping into this
        dataset: event/measurement ids rebase past the current maxima, the
        live event-type list grows append-only (frozen snapshot untouched),
        and the new subjects join ``split``."""
        ev_offset = int(self.events_df["event_id"].max()) + 1 if len(self.events_df) else 0
        shard_events = shard.events_df.copy()
        shard_events["event_id"] = shard_events["event_id"].astype(np.int64) + ev_offset
        shard_meas = shard.dynamic_measurements_df
        if shard_meas is not None:
            shard_meas = shard_meas.copy()
            shard_meas["event_id"] = shard_meas["event_id"].astype(np.int64) + ev_offset
            if (
                self.dynamic_measurements_df is not None
                and "measurement_id" in shard_meas
                and "measurement_id" in self.dynamic_measurements_df
            ):
                m_offset = (
                    int(self.dynamic_measurements_df["measurement_id"].max()) + 1
                    if len(self.dynamic_measurements_df)
                    else 0
                )
                shard_meas["measurement_id"] = (
                    shard_meas["measurement_id"].astype(np.int64) + m_offset
                )

        id_dt = type(self).get_smallest_valid_int_type(
            ev_offset + len(shard_events) + 1
        )
        self.events_df = self._concat_dfs(
            [self.events_df.assign(event_id=self.events_df["event_id"].astype(id_dt)),
             shard_events.assign(event_id=shard_events["event_id"].astype(id_dt))]
        )
        if shard_meas is not None:
            self.dynamic_measurements_df = self._concat_dfs(
                [self.dynamic_measurements_df, shard_meas]
            )
        self.subjects_df = self._concat_dfs([self.subjects_df, shard.subjects_df])

        # Live event-type growth, append-only: existing order is load-bearing
        # (the frozen snapshot indexes into it for pre-freeze types).
        known = set(self.event_types)
        self.event_types = list(self.event_types) + [
            et for et in shard.event_types if et not in known
        ]
        self.n_events_per_subject.update(shard.n_events_per_subject)
        self.subject_ids = set(self.subject_ids) | set(shard.subject_ids)
        self.split_subjects.setdefault(split, set())
        self.split_subjects[split] |= set(shard.subject_ids)

    # ------------------------------------------------------------ properties
    @property
    def has_static_measurements(self):
        return (self.subjects_df is not None) and any(
            cfg.temporality == TemporalityType.STATIC for cfg in self.measurement_configs.values()
        )

    @property
    def measurement_configs(self):
        """All fit, non-dropped measurement configs (``dataset_base.py:1013``)."""
        if not self._is_fit:
            raise ValueError("Can't call measurement_configs if not yet fit!")
        return {m: c for m, c in self.inferred_measurement_configs.items() if not c.is_dropped}

    @property
    def dynamic_numerical_columns(self):
        return [
            (k, cfg.values_column)
            for k, cfg in self.measurement_configs.items()
            if (cfg.is_numeric and cfg.temporality == TemporalityType.DYNAMIC)
        ]

    @property
    def time_dependent_numerical_columns(self):
        return [
            k
            for k, cfg in self.measurement_configs.items()
            if (cfg.is_numeric and cfg.temporality == TemporalityType.FUNCTIONAL_TIME_DEPENDENT)
        ]

    @property
    def measurement_idxmaps(self):
        """Per-measurement vocab idxmaps; event_type first (``dataset_base.py:1043``)."""
        frozen = getattr(self, "_frozen_vocab", None)
        if frozen is not None:
            return {
                m: {v: i for i, v in enumerate(vocab)}
                for m, vocab in self.measurement_vocabs.items()
            }
        # Unfrozen: reuse each Vocabulary's cached idxmap — these properties
        # sit on the ETL hot path (melt/vocab-config), so rebuilding every
        # dict per access would be quadratic in measures x vocab.
        idxmaps = {"event_type": {et: i for i, et in enumerate(self.event_types)}}
        for m, config in self.measurement_configs.items():
            if config.vocabulary is not None:
                idxmaps[m] = config.vocabulary.idxmap
        return idxmaps

    @property
    def measurement_vocabs(self):
        """Per-measurement vocab element lists, event_type first.

        Once the unified layout is frozen (`_freeze_unified_layout` — first
        save or DL-cache write), this returns the SNAPSHOT: the DL cache
        stores unified indices, so the layout every downstream consumer
        derives from here must never move even as the live vocabularies
        grow append-only under `append_subjects`.
        """
        frozen = getattr(self, "_frozen_vocab", None)
        if frozen is not None:
            vocabs = {"event_type": list(frozen["event_types"])}
            for m, v in frozen["measurement_vocabs"].items():
                vocabs[m] = list(v)
            return vocabs
        vocabs = {"event_type": self.event_types}
        for m, config in self.measurement_configs.items():
            if config.vocabulary is not None:
                vocabs[m] = config.vocabulary.vocabulary
        return vocabs

    def _freeze_unified_layout(self) -> None:
        """Snapshots the unified vocabulary layout (idempotent).

        Called on first save/DL-cache write: from this point the cache on
        disk references these indices and offsets, so the derived unified
        properties pin to the snapshot. Live vocabularies keep growing
        (append-only) for future full re-fits; the frozen view is what
        transforms, melts, and `vocabulary_config` see.
        """
        if getattr(self, "_frozen_vocab", None) is not None or not self._is_fit:
            return
        self._frozen_vocab = {
            "event_types": list(self.event_types),
            "measurement_vocabs": {
                m: list(config.vocabulary.vocabulary)
                for m, config in self.measurement_configs.items()
                if config.vocabulary is not None
            },
        }

    def _frozen_transform_configs(self) -> dict[str, MeasurementConfig]:
        """Deep-copied measurement configs with vocabularies REBUILT from the
        frozen snapshot — the transform state for post-freeze shards (append
        + online admission), so elements appended after the freeze become
        UNK in the cache exactly as a rare element would.

        Rebuilt, not prefix-truncated: `Vocabulary.__post_init__` re-sorts
        by frequency on every save/load round trip, so after an append +
        reload the live element ORDER no longer extends the snapshot — only
        the snapshot itself is authoritative for the frozen layout. The
        element set is what the transform consumes; frequencies are carried
        over per element (advisory only)."""
        configs = copy.deepcopy(self.measurement_configs)
        frozen = (getattr(self, "_frozen_vocab", None) or {}).get("measurement_vocabs", {})
        for m, cfg in configs.items():
            if cfg.vocabulary is not None and m in frozen:
                v = cfg.vocabulary
                live_idx = v.idxmap
                v.vocabulary = list(frozen[m])
                v.obs_frequencies = [
                    v.obs_frequencies[live_idx[el]] if el in live_idx else 0.0
                    for el in v.vocabulary
                ]
                v.__dict__.pop("idxmap", None)
        return configs

    @property
    def unified_measurements_vocab(self) -> list[str]:
        return ["event_type"] + list(sorted(self.measurement_configs.keys()))

    @property
    def unified_measurements_idxmap(self) -> dict[str, int]:
        return {m: i + 1 for i, m in enumerate(self.unified_measurements_vocab)}

    @property
    def unified_vocabulary_offsets(self) -> dict[str, int]:
        offsets, curr_offset = {}, 1
        for m in self.unified_measurements_vocab:
            offsets[m] = curr_offset
            if m in self.measurement_vocabs:
                curr_offset += len(self.measurement_vocabs[m])
            else:
                curr_offset += 1
        return offsets

    @property
    def unified_vocabulary_idxmap(self) -> dict[str, dict[str, int]]:
        idxmaps = {}
        meas_idxmaps = self.measurement_idxmaps  # bound once: property rebuilds
        for m, offset in self.unified_vocabulary_offsets.items():
            if m in meas_idxmaps:
                idxmaps[m] = {v: i + offset for v, i in meas_idxmaps[m].items()}
            else:
                idxmaps[m] = {m: offset}
        return idxmaps

    @property
    def vocabulary_config(self) -> VocabularyConfig:
        """The unified `VocabularyConfig` for downstream DL (``dataset_base.py:1124``)."""
        measurements_per_generative_mode = defaultdict(list)
        measurements_per_generative_mode[DataModality.SINGLE_LABEL_CLASSIFICATION].append("event_type")
        for m, cfg in self.measurement_configs.items():
            if cfg.temporality != TemporalityType.DYNAMIC:
                continue

            measurements_per_generative_mode[cfg.modality].append(m)
            if cfg.modality == DataModality.MULTIVARIATE_REGRESSION:
                measurements_per_generative_mode[DataModality.MULTI_LABEL_CLASSIFICATION].append(m)

        return VocabularyConfig(
            vocab_sizes_by_measurement={
                m: len(idxmap) for m, idxmap in self.measurement_idxmaps.items()
            },
            vocab_offsets_by_measurement=self.unified_vocabulary_offsets,
            measurements_idxmap=self.unified_measurements_idxmap,
            event_types_idxmap=self.unified_vocabulary_idxmap["event_type"],
            measurements_per_generative_mode=dict(measurements_per_generative_mode),
        )

    # ------------------------------------------------------------- describe
    def describe(self, do_print_measurement_summaries: bool = True) -> None:
        """Prints a text summary of the dataset (reference ``dataset_base.py:1196``)."""
        print(f"Dataset has {len(self.subject_ids)} subjects and {len(self.events_df)} events.")
        if self.n_events_per_subject:
            counts = np.asarray(list(self.n_events_per_subject.values()))
            print(
                f"Events per subject: min {counts.min()}, median {int(np.median(counts))}, "
                f"max {counts.max()}"
            )
        print(f"Event types ({len(self.event_types)}): {', '.join(self.event_types[:10])}")
        if do_print_measurement_summaries and self._is_fit:
            print(f"\nDataset has {len(self.measurement_configs)} measurements:")
            for _, cfg in self.measurement_configs.items():
                cfg.describe()
                print()

    def visualize(self, visualizer, save_dir: Path | str) -> list[Path]:
        """Plots dataset dashboards via a `Visualizer` (reference ``:1218``)."""
        return visualizer.plot(self, save_dir)

    # --------------------------------------------------------------- DL cache
    @TimeableMixin.TimeAs
    def cache_deep_learning_representation(
        self,
        subjects_per_output_file: int | None = None,
        do_overwrite: bool = False,
        n_workers: int = 1,
    ):
        """Writes ``DL_reps/{split}_{chunk}.parquet`` (``dataset_base.py:1062``).

        ``n_workers > 1`` builds each chunk's representation over
        subject-sharded worker processes (DL rows are per-subject
        independent; the output is subject-id-sorted, so concatenating
        sorted consecutive shards reproduces the serial build byte for
        byte — tested). The reference gets the equivalent parallelism from
        Polars' Rust threadpool (``dataset_polars.py:643``).
        """
        self._freeze_unified_layout()
        DL_dir = Path(self.config.save_dir) / "DL_reps"
        DL_dir.mkdir(exist_ok=True, parents=True)

        if subjects_per_output_file is None:
            subject_chunks = [None]
        else:
            subjects = np.random.permutation(list(self.subject_ids))
            subject_chunks = np.array_split(
                subjects,
                np.arange(subjects_per_output_file, len(subjects), subjects_per_output_file),
            )
            subject_chunks = [list(c) for c in subject_chunks]

        for chunk_idx, subjects_list in enumerate(subject_chunks):
            cached_df = self._build_dl_rep_sharded(subjects_list, n_workers)

            for split, subjects in self.split_subjects.items():
                fp = DL_dir / f"{split}_{chunk_idx}.{self.DF_SAVE_FORMAT}"

                split_cached_df = self._filter_col_inclusion(cached_df, {"subject_id": subjects})
                self._write_df(split_cached_df, fp, do_overwrite=do_overwrite)

    def _build_dl_rep_sharded(self, subjects_list, n_workers: int):
        """`build_DL_cached_representation`, optionally subject-sharded over
        a process pool with a deterministic sorted-shard merge.

        Shard outputs STREAM through per-shard parquet files rather than the
        result pipe: each worker writes its frame to disk and returns only
        the path, so worker RSS is O(shard) and no multi-GB frame is ever
        pickled. The parent reads the shards back in order; the serial
        output is subject-id-sorted (np.unique grouping + sorted outer
        merge), so consecutive shards of the sorted id list concat to the
        identical frame (pinned by test)."""
        if n_workers <= 1:
            return self.build_DL_cached_representation(subject_ids=subjects_list)
        import shutil
        import tempfile

        ids = sorted(subjects_list if subjects_list is not None else list(self.subject_ids))
        if len(ids) < 2 * n_workers:
            return self.build_DL_cached_representation(subject_ids=subjects_list)
        shards = [list(s) for s in np.array_split(np.asarray(ids), n_workers)]
        stream_dir = Path(tempfile.mkdtemp(prefix="esgpt_dl_shards_"))
        try:
            tasks = [
                (shard, stream_dir / f"dl_shard_{i}.{self.DF_SAVE_FORMAT}")
                for i, shard in enumerate(shards)
            ]
            paths = _fork_map(self, _dl_rep_shard_to_disk_worker, tasks, n_workers)
            return pd.concat([self._read_df(Path(fp)) for fp in paths], ignore_index=True)
        finally:
            shutil.rmtree(stream_dir, ignore_errors=True)
