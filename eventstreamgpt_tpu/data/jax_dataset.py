"""Host-side dataset over the DL cache, feeding static-shape device batches.

TPU-native rebuild of ``/root/reference/EventStream/data/pytorch_dataset.py``.
Behavioral parity: reads ``DL_reps/{split}*.parquet`` plus
``vocabulary_config.json`` / ``inferred_measurement_configs.json`` artifacts
(including those produced by the reference itself — pandas/pyarrow replaces
Polars), converts absolute times to deltas (next-event minus current, last
filled with 1; ``pytorch_dataset.py:245-256``), computes inter-event-time
statistics and quarantines malformed subjects (``:258-287``), restricts to
task windows (``:311-459``), samples subsequences per the configured strategy
(``:471-520``), and collates with right/left padding into an
`EventStreamBatch` (``:527-683``).

The *representation* diverges deliberately (SURVEY.md §7.3): instead of
per-subject Python lists padded in a per-item loop (the reference's known CPU
bottleneck), events are flattened at load time into contiguous CSR-style
numpy arrays (values + offsets). Collation is then a handful of vectorized
gathers into **static-shape** ``(B, max_seq_len, max_n_dynamic)`` buffers, so
XLA compiles the training step exactly once and the host never bottlenecks
the chip.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

import numpy as np
import pandas as pd

from ..utils import SeedableMixin, TimeableMixin
from .config import (
    MeasurementConfig,
    PytorchDatasetConfig,
    SeqPaddingSide,
    SubsequenceSamplingStrategy,
    VocabularyConfig,
)
from .types import EventStreamBatch


def to_int_index(col: pd.Series) -> tuple[pd.Series, list]:
    """Maps string/categorical labels to integer indices (sorted unique order).

    Reference: ``pytorch_dataset.py:22-55`` (polars ``to_int_index``).
    """
    vocab = sorted(col.dropna().unique().tolist())
    mapping = {v: i for i, v in enumerate(vocab)}
    return col.map(mapping), vocab


@dataclasses.dataclass
class BatchPlan:
    """The host-decided, rng-dependent part of one batch (~100 bytes).

    Produced by `JaxDataset.plan_batches`; consumed by host collation
    (`JaxDataset.batches`) and on-device collation
    (`DeviceDataset <device_dataset.DeviceDataset>`) identically.
    """

    subject_indices: np.ndarray  # (B,) int32
    starts: np.ndarray  # (B,) int32 — subsequence crop start per subject
    kept: np.ndarray  # (B,) int32 — events kept (min(seq_len, L))
    valid_mask: np.ndarray  # (B,) bool — False for cyclic fill rows
    n_events: int  # real (non-fill, non-pad) events in the batch
    start_time: np.ndarray | None = None  # (B,) float32, when configured


@dataclasses.dataclass
class _CSRData:
    """Flattened ragged event data for one split.

    ``event_*`` arrays are indexed by global event id; ``data_*`` by global
    data-element id. ``subject_event_offsets[i] : subject_event_offsets[i+1]``
    is subject ``i``'s event range.

    Collation-speed layout choices (the host is the system bottleneck at
    ~0.3 ms device steps): values are stored **NaN-cleaned** with a separate
    observed mask, so the per-batch hot path is pure gathers — no
    ``isnan``/``nan_to_num`` passes; offset/index arrays are int32 whenever
    sizes permit, halving index-arithmetic memory traffic.
    """

    subject_event_offsets: np.ndarray  # (n_subjects + 1,) int
    time_delta: np.ndarray  # (n_events,) float32
    event_data_offsets: np.ndarray  # (n_events + 1,) int
    dynamic_indices: np.ndarray  # (n_data,) int
    dynamic_measurement_indices: np.ndarray  # (n_data,) int
    dynamic_values: np.ndarray  # (n_data,) float32, 0 where unobserved
    dynamic_values_observed: np.ndarray  # (n_data,) bool
    static_offsets: np.ndarray  # (n_subjects + 1,) int
    static_indices: np.ndarray  # (n_static,) int
    static_measurement_indices: np.ndarray  # (n_static,) int
    start_time_min: np.ndarray  # (n_subjects,) float64 (minutes since epoch)

    @property
    def n_subjects(self) -> int:
        return len(self.subject_event_offsets) - 1

    def n_events(self, i: int) -> int:
        return int(self.subject_event_offsets[i + 1] - self.subject_event_offsets[i])


class JaxDataset(SeedableMixin, TimeableMixin):
    """A dataset over the cached DL representation, yielding numpy batches.

    API mirrors the reference ``PytorchDataset`` (``pytorch_dataset.py:58``):
    ``len``, ``__getitem__`` → per-subject dict, ``collate`` → batch; plus a
    vectorized `collate_indices` fast path used by `batches`.
    """

    TASK_TYPES = {"multi_class_classification", "binary_classification", "regression"}

    @classmethod
    def normalize_task(cls, col: pd.Series) -> tuple[str, pd.Series, list | None]:
        """Infers task type and normalizes labels (``pytorch_dataset.py:108``)."""
        dtype = col.dtype
        if pd.api.types.is_bool_dtype(dtype):
            return "binary_classification", col.astype(np.float32), [False, True]
        if pd.api.types.is_integer_dtype(dtype):
            return "multi_class_classification", col, list(range(int(col.max()) + 1))
        if pd.api.types.is_float_dtype(dtype):
            return "regression", col, None
        if isinstance(dtype, pd.CategoricalDtype) or pd.api.types.is_object_dtype(dtype):
            normalized, vocab = to_int_index(col)
            return "multi_class_classification", normalized, vocab
        raise TypeError(f"Can't process label of {dtype} type!")

    def __init__(self, config: PytorchDatasetConfig, split: str):
        super().__init__()
        self.config = config
        self.split = split
        self.task_types: dict[str, str] = {}
        self.task_vocabs: dict[str, list] = {}

        save_dir = Path(config.save_dir)
        self.vocabulary_config = VocabularyConfig.from_json_file(save_dir / "vocabulary_config.json")

        with open(save_dir / "inferred_measurement_configs.json") as f:
            inferred = {
                k: MeasurementConfig.from_dict(v, base_dir=save_dir)
                for k, v in json.load(f).items()
            }
        self.measurement_configs = {k: v for k, v in inferred.items() if not v.is_dropped}

        if config.task_df_name is not None:
            self.has_task = True
            df, self.tasks = self._load_task_data(save_dir, config.task_df_name, split)
        else:
            self.has_task = False
            self.tasks = None
            self.task_vocabs = None
            df = self._read_dl_reps(save_dir / "DL_reps", split)

        self.do_produce_static_data = "static_indices" in df.columns
        self.seq_padding_side = config.seq_padding_side
        self.max_seq_len = config.max_seq_len

        df = self._to_time_deltas(df)

        # Filter short sequences.
        lens = df["time_delta"].map(len)
        df = df[lens >= config.min_seq_len].reset_index(drop=True)

        # Inter-event-time stats + malformed-subject quarantine
        # (reference ``pytorch_dataset.py:258-287``). The last delta of each
        # subject is a filler (1.0) and excluded from stats.
        def _real_deltas(row):
            return row[:-1] if len(row) > 1 else row[:0]

        all_deltas = (
            np.concatenate([_real_deltas(np.asarray(r)) for r in df["time_delta"]])
            if len(df)
            else np.asarray([1.0])
        )
        if len(all_deltas) == 0:
            all_deltas = np.asarray([1.0])
        min_delta = float(all_deltas.min()) if len(all_deltas) else 1.0
        if min_delta <= 0:
            bad_mask = df["time_delta"].map(lambda r: float(np.min(_real_deltas(np.asarray(r)))) <= 0 if len(r) > 1 else False)
            bad = df[bad_mask]
            print(
                f"WARNING: Observed inter-event times <= 0 for {len(bad)} subjects!\n"
                f"ESD Subject IDs: {', '.join(str(x) for x in bad['subject_id'].tolist())}\n"
                f"Global min: {min_delta}"
            )
            if config.save_dir is not None:
                fp = Path(config.save_dir) / f"malformed_data_{split}.parquet"
                bad.to_parquet(fp)
                print(f"Wrote malformed data records to {fp}")
            print("Removing malformed subjects")
            df = df[~bad_mask].reset_index(drop=True)
            all_deltas = np.concatenate([_real_deltas(np.asarray(r)) for r in df["time_delta"]])

        with np.errstate(divide="ignore", invalid="ignore"):
            logs = np.log(all_deltas[all_deltas > 0])
        self.mean_log_inter_event_time_min = float(logs.mean()) if len(logs) else 0.0
        self.std_log_inter_event_time_min = float(logs.std(ddof=1)) if len(logs) > 1 else 1.0

        # Train-subset subsampling (``pytorch_dataset.py:291-303``).
        if config.train_subset_size not in (None, "FULL") and split == "train":
            if isinstance(config.train_subset_size, int) and config.train_subset_size > 0:
                n = min(config.train_subset_size, len(df))
            elif isinstance(config.train_subset_size, float) and 0 < config.train_subset_size < 1:
                n = int(round(config.train_subset_size * len(df)))
            else:
                raise TypeError(
                    f"Can't process subset size of {type(config.train_subset_size)}, "
                    f"{config.train_subset_size}"
                )
            df = df.sample(n=n, random_state=config.train_subset_seed).reset_index(drop=True)

        self.subject_ids = df["subject_id"].tolist()
        self.stream_labels = (
            {t: np.asarray(df[t].to_numpy()) for t in self.tasks} if self.has_task else None
        )
        self.data = self._flatten(df)

        # Static data-element axis sizes for shape-stable collation.
        data_lens = np.diff(self.data.event_data_offsets)
        inferred_max_n = int(data_lens.max()) if len(data_lens) else 1
        self.max_n_dynamic = config.max_n_dynamic or max(inferred_max_n, 1)
        static_lens = np.diff(self.data.static_offsets)
        self.max_n_static = config.max_n_static or max(int(static_lens.max()) if len(static_lens) else 1, 1)

    # ------------------------------------------------------------------ I/O
    @staticmethod
    def _read_dl_reps(dl_dir: Path, split: str) -> pd.DataFrame:
        # Chunk order is load-bearing (subject order feeds the deterministic
        # batch stream); `append_subjects` grows chunk counts past 9, where
        # lexicographic sorting would interleave ("x_10" < "x_2") and shuffle
        # subjects between runs — so order numerically by the chunk suffix.
        def chunk_key(fp: Path):
            stem, _, suffix = fp.stem.rpartition("_")
            return (stem, int(suffix)) if suffix.isdigit() else (fp.stem, -1)

        files = sorted(Path(dl_dir).glob(f"{split}*.parquet"), key=chunk_key)
        if not files:
            raise FileNotFoundError(f"No DL_reps parquet files for split {split} in {dl_dir}")
        return pd.concat([pd.read_parquet(fp) for fp in files], ignore_index=True)

    def _load_task_data(self, save_dir: Path, task_df_name: str, split: str):
        """Task-restricted data loading (``pytorch_dataset.py:149-236``)."""
        task_dir = save_dir / "DL_reps" / "for_task" / task_df_name
        raw_task_df_fp = save_dir / "task_dfs" / f"{task_df_name}.parquet"
        task_info_fp = task_dir / "task_info.json"

        cached_files = sorted(task_dir.glob(f"{split}*.parquet"))
        if cached_files:
            df = pd.concat([pd.read_parquet(fp) for fp in cached_files], ignore_index=True)
            with open(task_info_fp) as f:
                task_info = json.load(f)
            tasks = sorted(task_info["tasks"])
            self.task_vocabs = task_info["vocabs"]
            self.task_types = task_info["types"]
            return df, tasks

        if not raw_task_df_fp.is_file():
            raise FileNotFoundError(
                f"Neither {task_dir} nor {raw_task_df_fp} exist, but config.task_df_name = "
                f"{task_df_name}!"
            )

        task_df = pd.read_parquet(raw_task_df_fp)
        tasks = sorted(c for c in task_df.columns if c not in ("subject_id", "start_time", "end_time"))
        for t in tasks:
            task_type, normalized, vocab = self.normalize_task(task_df[t])
            self.task_types[t] = task_type
            task_df[t] = normalized
            if vocab is not None:
                self.task_vocabs[t] = vocab

        task_info = {"tasks": sorted(tasks), "vocabs": self.task_vocabs, "types": self.task_types}
        if task_info_fp.is_file():
            with open(task_info_fp) as f:
                loaded = json.load(f)
            if loaded != task_info and split != "train":
                raise ValueError(
                    f"Task info differs from on disk!\nDisk:\n{loaded}\nLocal:\n{task_info}\n"
                    f"Split: {split}"
                )
        else:
            task_info_fp.parent.mkdir(exist_ok=True, parents=True)
            with open(task_info_fp, mode="w") as f:
                json.dump(task_info, f)

        for cached_fp in sorted((save_dir / "DL_reps").glob(f"{split}*.parquet")):
            out_fp = task_dir / cached_fp.name
            if out_fp.is_file():
                continue
            restricted = self._build_task_cached_df(task_df, pd.read_parquet(cached_fp))
            out_fp.parent.mkdir(exist_ok=True, parents=True)
            restricted.to_parquet(out_fp)

        df = pd.concat(
            [pd.read_parquet(fp) for fp in sorted(task_dir.glob(f"{split}*.parquet"))],
            ignore_index=True,
        )
        return df, tasks

    @staticmethod
    def _build_task_cached_df(task_df: pd.DataFrame, cached_data: pd.DataFrame) -> pd.DataFrame:
        """Slices each subject's event lists to task ``[start, end]`` windows.

        Reference: ``pytorch_dataset.py:311-459`` (searchsorted over absolute
        event times per task row).
        """
        # Window bounds computed vectorized up front; the remaining per-row
        # work is ragged-list slicing, done over plain numpy/python objects
        # (no pandas row objects) so host cost stays linear in task rows with
        # small constants (VERDICT weak #6: the previous iterrows version was
        # pandas-overhead-bound at MIMIC scale).
        cached = cached_data.set_index("subject_id")
        in_cache = task_df["subject_id"].isin(cached.index)
        tdf = task_df[in_cache].reset_index(drop=True)
        empty = pd.DataFrame(
            columns=list(cached_data.columns)
            + [c for c in task_df.columns if c not in ("subject_id", "start_time", "end_time")]
        )
        if not len(tdf):
            return empty

        sids = tdf["subject_id"].to_numpy()
        # Lookups only over subjects the task actually references: a small
        # task cohort must not pay per-subject conversion for a whole chunk.
        cached = cached.loc[np.unique(sids)]
        base_start = cached["start_time"].reindex(sids).to_numpy(dtype="datetime64[ns]")
        start_min = (
            tdf["start_time"].to_numpy(dtype="datetime64[ns]") - base_start
        ) / np.timedelta64(1, "m")
        end_min = (
            tdf["end_time"].to_numpy(dtype="datetime64[ns]") - base_start
        ) / np.timedelta64(1, "m")

        times_by_sid = {sid: np.asarray(t, dtype=np.float64) for sid, t in cached["time"].items()}
        col_by_sid = {
            c: cached[c].to_dict()
            for c in ("dynamic_indices", "dynamic_measurement_indices", "dynamic_values")
        }
        static_cols = [
            c for c in ("static_indices", "static_measurement_indices") if c in cached_data.columns
        ]
        static_by_sid = {c: cached[c].to_dict() for c in static_cols}
        label_cols = [c for c in task_df.columns if c not in ("subject_id", "start_time", "end_time")]
        labels = {t: tdf[t].to_numpy() for t in label_cols}

        rows = []
        for i in range(len(tdf)):
            sid = sids[i]
            times = times_by_sid[sid]
            lo = int(np.searchsorted(times, start_min[i], side="left"))
            hi = int(np.searchsorted(times, end_min[i], side="right"))
            if hi <= lo:
                continue
            new_row = {
                "subject_id": sid,
                "start_time": pd.Timestamp(base_start[i]) + pd.Timedelta(minutes=float(times[lo])),
                "time": times[lo:hi] - times[lo],
            }
            for c in ("dynamic_indices", "dynamic_measurement_indices", "dynamic_values"):
                new_row[c] = np.asarray(col_by_sid[c][sid][lo:hi], dtype=object)
            for c in static_cols:
                new_row[c] = static_by_sid[c][sid]
            for t in label_cols:
                new_row[t] = labels[t][i]
            rows.append(new_row)
        # All-windows-empty must still return the full column schema.
        return pd.DataFrame(rows) if rows else empty

    # ------------------------------------------------------ representation
    @staticmethod
    def _to_time_deltas(df: pd.DataFrame) -> pd.DataFrame:
        """``time`` (absolute minutes) → ``time_delta`` (minutes to next event).

        The final event's delta is filled with 1; it is ignored downstream via
        the event mask (``pytorch_dataset.py:245-256``).
        """
        if "time_delta" in df.columns:
            return df

        def convert(times):
            times = np.asarray(times, dtype=np.float64)
            if len(times) == 0:
                return times.astype(np.float32)
            deltas = np.empty_like(times, dtype=np.float32)
            deltas[:-1] = (times[1:] - times[:-1]).astype(np.float32)
            deltas[-1] = 1.0
            return deltas

        df = df.copy()
        df["time_delta"] = df["time"].map(convert)
        # start_time advances to the first event's absolute time.
        if "start_time" in df.columns:
            first_offset = df["time"].map(lambda t: float(t[0]) if len(t) else 0.0)
            df["start_time"] = pd.to_datetime(df["start_time"]) + pd.to_timedelta(
                first_offset, unit="m"
            )
        return df.drop(columns=["time"])

    def _flatten(self, df: pd.DataFrame) -> _CSRData:
        n_subjects = len(df)
        event_counts = np.asarray([len(r) for r in df["time_delta"]], dtype=np.int64)
        subject_event_offsets = np.zeros(n_subjects + 1, dtype=np.int64)
        np.cumsum(event_counts, out=subject_event_offsets[1:])

        time_delta = (
            np.concatenate([np.asarray(r, dtype=np.float32) for r in df["time_delta"]])
            if n_subjects
            else np.zeros(0, np.float32)
        )

        data_counts, dyn_idx, dyn_meas, dyn_vals = [], [], [], []
        for _, row in df.iterrows():
            for ev_i, ev_m, ev_v in zip(
                row["dynamic_indices"], row["dynamic_measurement_indices"], row["dynamic_values"]
            ):
                ev_i = np.asarray(ev_i if ev_i is not None else [], dtype=np.int64)
                ev_m = np.asarray(ev_m if ev_m is not None else [], dtype=np.int64)
                if ev_v is None:
                    ev_v = np.full(len(ev_i), np.nan, dtype=np.float32)
                else:
                    ev_v = np.asarray(
                        [np.nan if v is None else v for v in ev_v], dtype=np.float32
                    )
                data_counts.append(len(ev_i))
                dyn_idx.append(ev_i)
                dyn_meas.append(ev_m)
                dyn_vals.append(ev_v)

        n_events = len(data_counts)
        event_data_offsets = np.zeros(n_events + 1, dtype=np.int64)
        np.cumsum(np.asarray(data_counts, dtype=np.int64), out=event_data_offsets[1:])

        static_counts, st_idx, st_meas = [], [], []
        if self.do_produce_static_data:
            for _, row in df.iterrows():
                si = np.asarray(row["static_indices"], dtype=np.int64)
                sm = np.asarray(row["static_measurement_indices"], dtype=np.int64)
                static_counts.append(len(si))
                st_idx.append(si)
                st_meas.append(sm)
        else:
            static_counts = [0] * n_subjects
        static_offsets = np.zeros(n_subjects + 1, dtype=np.int64)
        np.cumsum(np.asarray(static_counts, dtype=np.int64), out=static_offsets[1:])

        if "start_time" in df.columns:
            start_time_min = (
                pd.to_datetime(df["start_time"]).map(lambda t: t.timestamp() / 60.0).to_numpy()
            )
        else:
            start_time_min = np.zeros(n_subjects, dtype=np.float64)

        def cat(parts, dtype):
            return np.concatenate(parts).astype(dtype) if parts else np.zeros(0, dtype)

        def shrink(x):
            """int64 → int32 when values fit (collation index arithmetic is
            memory-bound; half-width indices halve the traffic)."""
            if x.size == 0 or (x.min() >= np.iinfo(np.int32).min and x.max() <= np.iinfo(np.int32).max):
                return x.astype(np.int32)
            return x

        raw_vals = cat(dyn_vals, np.float32)
        observed = ~np.isnan(raw_vals)

        return _CSRData(
            subject_event_offsets=shrink(subject_event_offsets),
            time_delta=time_delta,
            event_data_offsets=shrink(event_data_offsets),
            dynamic_indices=shrink(cat(dyn_idx, np.int64)),
            dynamic_measurement_indices=shrink(cat(dyn_meas, np.int64)),
            dynamic_values=np.where(observed, raw_vals, 0.0).astype(np.float32),
            dynamic_values_observed=observed,
            static_offsets=shrink(static_offsets),
            static_indices=shrink(cat(st_idx, np.int64)),
            static_measurement_indices=shrink(cat(st_meas, np.int64)),
            start_time_min=start_time_min,
        )

    # ----------------------------------------------------------- item access
    def __len__(self) -> int:
        return self.data.n_subjects

    def _sample_start_idx(self, seq_len: int, rng: np.random.Generator) -> int:
        if seq_len <= self.max_seq_len:
            return 0
        strategy = self.config.subsequence_sampling_strategy
        if strategy == SubsequenceSamplingStrategy.RANDOM:
            return int(rng.integers(0, seq_len - self.max_seq_len))
        if strategy == SubsequenceSamplingStrategy.TO_END:
            return seq_len - self.max_seq_len
        if strategy == SubsequenceSamplingStrategy.FROM_START:
            return 0
        raise ValueError(f"Invalid sampling strategy: {strategy}!")

    def __getitem__(self, idx: int) -> dict:
        return self._seeded_getitem(idx)

    @SeedableMixin.WithSeed
    def _seeded_getitem(self, idx: int) -> dict:
        """Per-subject ragged dict, as in the reference ``__getitem__``."""
        d = self.data
        rng = np.random.default_rng(np.random.randint(0, 2**31))
        ev_lo, ev_hi = d.subject_event_offsets[idx], d.subject_event_offsets[idx + 1]
        seq_len = int(ev_hi - ev_lo)
        start_idx = self._sample_start_idx(seq_len, rng)
        end_idx = min(start_idx + self.max_seq_len, seq_len)

        events = np.arange(ev_lo + start_idx, ev_lo + end_idx)
        def nan_vals(e):
            sl = slice(d.event_data_offsets[e], d.event_data_offsets[e + 1])
            return np.where(d.dynamic_values_observed[sl], d.dynamic_values[sl], np.nan).tolist()

        out = {
            "time_delta": d.time_delta[events].tolist(),
            "dynamic_indices": [
                d.dynamic_indices[d.event_data_offsets[e] : d.event_data_offsets[e + 1]].tolist()
                for e in events
            ],
            "dynamic_measurement_indices": [
                d.dynamic_measurement_indices[
                    d.event_data_offsets[e] : d.event_data_offsets[e + 1]
                ].tolist()
                for e in events
            ],
            "dynamic_values": [nan_vals(e) for e in events],
        }
        if self.do_produce_static_data:
            st_lo, st_hi = d.static_offsets[idx], d.static_offsets[idx + 1]
            out["static_indices"] = d.static_indices[st_lo:st_hi].tolist()
            out["static_measurement_indices"] = d.static_measurement_indices[st_lo:st_hi].tolist()
        if self.config.do_include_subject_id:
            out["subject_id"] = self.subject_ids[idx]
        if self.config.do_include_start_time_min:
            out["start_time"] = float(
                d.start_time_min[idx] + d.time_delta[ev_lo : ev_lo + start_idx].sum()
            )
        if self.config.do_include_subsequence_indices:
            out["start_idx"] = start_idx
            out["end_idx"] = end_idx
        if self.has_task:
            for t in self.tasks:
                out[t] = self.stream_labels[t][idx]
        return out

    # ------------------------------------------------------------- collation
    def _draw_starts(
        self, subject_indices: np.ndarray, rng: np.random.Generator
    ) -> tuple[np.ndarray, np.ndarray]:
        """Draws subsequence crop starts for the given subjects.

        The single point where collation consumes randomness — shared by
        `collate_indices`, the resume fast-forward, and the device-resident
        plan stream (`plan_batches`) so all three advance the rng stream
        identically and produce bit-identical crops.

        Returns ``(starts, kept)``: the start offset into each subject's
        event range and the number of events kept (``min(seq_len, L)``).

        RANDOM draws from ``[0, seq_len - L)`` — an *exclusive* high bound,
        deliberately matching the reference's ``np.random.choice(seq_len -
        max_seq_len)`` (``pytorch_dataset.py:498``), which never samples the
        final full window. The packed path (`_pack_rows`), a net-new feature
        with no reference analog, uses the inclusive bound.
        """
        d = self.data
        idx = np.asarray(subject_indices)
        L = self.max_seq_len
        seq_lens = d.subject_event_offsets[idx + 1] - d.subject_event_offsets[idx]
        starts = np.zeros(len(idx), dtype=np.int32)
        over = seq_lens > L
        strategy = self.config.subsequence_sampling_strategy
        if strategy == SubsequenceSamplingStrategy.RANDOM:
            starts[over] = rng.integers(0, seq_lens[over] - L)
        elif strategy == SubsequenceSamplingStrategy.TO_END:
            starts[over] = seq_lens[over] - L
        elif strategy != SubsequenceSamplingStrategy.FROM_START:
            raise ValueError(f"Invalid sampling strategy: {strategy}!")
        return starts, np.minimum(seq_lens, L)

    def collate_indices(
        self, subject_indices: np.ndarray, rng: np.random.Generator | None = None
    ) -> EventStreamBatch:
        """Vectorized collation of the given subjects into a static-shape batch.

        All shapes are fixed by config — ``(B, max_seq_len)`` and
        ``(B, max_seq_len, max_n_dynamic)`` — regardless of batch content, so
        the jitted train step never recompiles.
        """
        rng = rng or np.random.default_rng()
        starts, kept = self._draw_starts(subject_indices, rng)
        return self._collate_with_starts(subject_indices, starts, kept)

    def _collate_with_starts(
        self,
        subject_indices: np.ndarray,
        starts: np.ndarray,
        kept: np.ndarray,
        start_time: np.ndarray | None = None,
    ) -> EventStreamBatch:
        """Collation body with the crop starts already drawn (rng-free).

        ``start_time`` short-circuits the per-row prior-delta summation when
        the caller (`batches` via `plan_batches`) already computed it.
        """
        d = self.data
        B = len(subject_indices)
        L = self.max_seq_len
        M = self.max_n_dynamic
        S = self.max_n_static

        ev_lo = d.subject_event_offsets[subject_indices]

        # (B, L) global event ids + validity. int32 end to end: the (B, L, M)
        # index arithmetic below is memory-bound and half-width indices halve
        # its traffic.
        pos = np.arange(L, dtype=np.int32)[None, :]
        if self.seq_padding_side == SeqPaddingSide.RIGHT:
            event_ids = ev_lo[:, None] + starts[:, None] + pos
            event_mask = pos < kept[:, None]
        else:
            pad = (L - kept)[:, None]
            event_ids = ev_lo[:, None] + starts[:, None] + (pos - pad)
            event_mask = pos >= pad
        event_ids = np.where(event_mask, event_ids, 0)

        time_delta = np.where(event_mask, d.time_delta[event_ids], 0.0).astype(np.float32)

        # (B, L, M) data-element gather. Values are pre-cleaned (0 where
        # unobserved) with a stored observed mask, so this is pure gathers —
        # no isnan / nan_to_num passes in the hot path.
        data_lo = d.event_data_offsets[event_ids]
        data_n = d.event_data_offsets[event_ids + 1] - data_lo
        mpos = np.arange(M, dtype=np.int32)[None, None, :]
        data_ids = data_lo[..., None] + mpos
        data_valid = (mpos < data_n[..., None]) & event_mask[..., None]
        data_ids = np.where(data_valid, data_ids, 0)

        dynamic_indices = np.where(data_valid, d.dynamic_indices[data_ids], 0)
        dynamic_meas = np.where(data_valid, d.dynamic_measurement_indices[data_ids], 0)
        values_mask = data_valid & d.dynamic_values_observed[data_ids]
        dynamic_values = np.where(values_mask, d.dynamic_values[data_ids], 0.0)

        batch = dict(
            event_mask=event_mask,
            time_delta=time_delta,
            dynamic_indices=dynamic_indices,
            dynamic_measurement_indices=dynamic_meas,
            dynamic_values=dynamic_values,
            dynamic_values_mask=values_mask,
        )

        if self.do_produce_static_data:
            st_lo = d.static_offsets[subject_indices]
            st_n = d.static_offsets[np.asarray(subject_indices) + 1] - st_lo
            spos = np.arange(S)[None, :]
            st_ids = st_lo[:, None] + spos
            st_valid = spos < st_n[:, None]
            st_ids = np.where(st_valid, st_ids, 0)
            batch["static_indices"] = np.where(st_valid, d.static_indices[st_ids], 0)
            batch["static_measurement_indices"] = np.where(
                st_valid, d.static_measurement_indices[st_ids], 0
            )

        if self.config.do_include_start_time_min:
            if start_time is None:
                prior = np.zeros(B, dtype=np.float64)
                for b, (lo, s) in enumerate(zip(ev_lo, starts)):
                    prior[b] = d.time_delta[lo : lo + s].sum()
                start_time = (d.start_time_min[subject_indices] + prior).astype(np.float32)
            batch["start_time"] = start_time
        if self.config.do_include_subsequence_indices:
            batch["start_idx"] = starts
            batch["end_idx"] = starts + kept
        if self.config.do_include_subject_id:
            batch["subject_id"] = np.asarray(
                [self.subject_ids[i] for i in subject_indices], dtype=np.int64
            )
        if self.has_task:
            batch["stream_labels"] = {
                t: np.asarray(
                    self.stream_labels[t][subject_indices],
                    dtype=np.int64 if self.task_types[t] == "multi_class_classification" else np.float32,
                )
                for t in self.tasks
            }

        return EventStreamBatch(**batch)

    def collate(self, batch: list[dict]) -> EventStreamBatch:
        """Collates ``__getitem__`` dicts (reference-compatible slow path).

        Pads to the same static shapes as `collate_indices`.
        """
        B = len(batch)
        L, M, S = self.max_seq_len, self.max_n_dynamic, self.max_n_static
        event_mask = np.zeros((B, L), dtype=bool)
        time_delta = np.zeros((B, L), dtype=np.float32)
        dynamic_indices = np.zeros((B, L, M), dtype=np.int64)
        dynamic_meas = np.zeros((B, L, M), dtype=np.int64)
        dynamic_values = np.zeros((B, L, M), dtype=np.float32)
        values_mask = np.zeros((B, L, M), dtype=bool)

        for b, e in enumerate(batch):
            n = len(e["time_delta"])
            offset = 0 if self.seq_padding_side == SeqPaddingSide.RIGHT else L - n
            event_mask[b, offset : offset + n] = True
            time_delta[b, offset : offset + n] = e["time_delta"]
            for j in range(n):
                row_i = e["dynamic_indices"][j] or []
                row_m = e["dynamic_measurement_indices"][j] or []
                row_v = e["dynamic_values"][j] or []
                k = len(row_i)
                dynamic_indices[b, offset + j, :k] = row_i
                dynamic_meas[b, offset + j, :k] = row_m
                vals = np.asarray(
                    [np.nan if v is None else v for v in row_v], dtype=np.float32
                )
                obs = ~np.isnan(vals)
                dynamic_values[b, offset + j, :k] = np.nan_to_num(vals, nan=0.0)
                values_mask[b, offset + j, :k] = obs

        out = dict(
            event_mask=event_mask,
            time_delta=time_delta,
            dynamic_indices=dynamic_indices,
            dynamic_measurement_indices=dynamic_meas,
            dynamic_values=dynamic_values,
            dynamic_values_mask=values_mask,
        )

        if self.do_produce_static_data:
            static_indices = np.zeros((B, S), dtype=np.int64)
            static_meas = np.zeros((B, S), dtype=np.int64)
            for b, e in enumerate(batch):
                k = len(e["static_indices"])
                static_indices[b, :k] = e["static_indices"]
                static_meas[b, :k] = e["static_measurement_indices"]
            out["static_indices"] = static_indices
            out["static_measurement_indices"] = static_meas

        if self.config.do_include_start_time_min:
            out["start_time"] = np.asarray([e["start_time"] for e in batch], dtype=np.float32)
        if self.config.do_include_subsequence_indices:
            out["start_idx"] = np.asarray([e["start_idx"] for e in batch], dtype=np.int64)
            out["end_idx"] = np.asarray([e["end_idx"] for e in batch], dtype=np.int64)
        if self.config.do_include_subject_id:
            out["subject_id"] = np.asarray([e["subject_id"] for e in batch], dtype=np.int64)
        if self.has_task:
            out["stream_labels"] = {
                t: np.asarray(
                    [e[t] for e in batch],
                    dtype=np.int64 if self.task_types[t] == "multi_class_classification" else np.float32,
                )
                for t in self.tasks
            }
        return EventStreamBatch(**out)

    # -------------------------------------------------------------- batching
    # ---------------------------------------------------------- shard pools
    def subject_shards(self, n_shards: int) -> np.ndarray:
        """Contiguous subject-pool boundaries for an ``n_shards``-way layout.

        Returns ``(n_shards + 1,)`` indices into the subject axis; shard ``k``
        owns subjects ``[bounds[k], bounds[k+1])``. Boundaries balance EVENT
        counts (not subject counts): the device-resident sharded layout pads
        every shard's dense event table to the largest shard, so balancing
        events minimizes padding waste and balances per-process HBM.

        The partition is a pure function of the dataset (no rng), so every
        process computes the identical layout.
        """
        n = self.data.n_subjects
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        if n < n_shards:
            raise ValueError(
                f"cannot shard {n} subjects over {n_shards} shards; every shard "
                "needs at least one subject (lower the shard count or use the "
                "replicated layout)."
            )
        cum = np.asarray(self.data.subject_event_offsets, np.int64)
        total = cum[-1]
        targets = (np.arange(1, n_shards) * total) // n_shards
        bounds = np.searchsorted(cum, targets, side="left").astype(np.int64)
        bounds = np.concatenate([[0], bounds, [n]])
        # Event-balanced split points can collide on skewed cohorts; force
        # strictly increasing boundaries so every shard is non-empty.
        for k in range(1, n_shards + 1):
            bounds[k] = min(max(bounds[k], bounds[k - 1] + 1), n - (n_shards - k))
        return bounds

    def _shard_orders(
        self, n_shards: int, rng: np.random.Generator, shuffle: bool
    ) -> list[np.ndarray]:
        """Per-shard subject orders, drawn shard-by-shard from ONE rng stream.

        With ``n_shards == 1`` this consumes the rng exactly like the
        historical single-stream path (one ``rng.permutation(n)``), so the
        degenerate case reproduces the existing epoch streams bit-for-bit.
        """
        if n_shards == 1:
            n = self.data.n_subjects
            return [rng.permutation(n) if shuffle else np.arange(n)]
        bounds = self.subject_shards(n_shards)
        return [
            bounds[k]
            + (
                rng.permutation(bounds[k + 1] - bounds[k])
                if shuffle
                else np.arange(bounds[k + 1] - bounds[k])
            )
            for k in range(n_shards)
        ]

    # ------------------------------------------------------------- packing
    def _pack_rows(self, L: int, rng: np.random.Generator, order: np.ndarray):
        """First-fit packs subject (sub)sequences into rows of ``L`` events.

        Returns ``[(subject, start, n_events), ...]`` per row. Deterministic
        given the rng state and order (`packed_batch_count` relies on this to
        predict `packed_batches`' stream exactly).
        """
        d = self.data
        strategy = self.config.subsequence_sampling_strategy

        # Greedy first-fit packing over a bounded set of open rows: unbounded
        # first-fit is O(n·rows) in Python — quadratic host time at cohort
        # scale. A row closes once it cannot fit the smallest subject (or
        # when the open set exceeds a fixed cap), keeping packing linear with
        # essentially the same fill quality.
        min_len = int(
            min(
                (min(int(d.subject_event_offsets[s + 1] - d.subject_event_offsets[s]), L) for s in order),
                default=1,
            )
        )
        MAX_OPEN_ROWS = 64
        rows: list[list[tuple[int, int, int]]] = []  # [(subject, start, n_events)]
        row_fill: list[int] = []
        open_rows: list[int] = []
        for subj in order:
            lo, hi = d.subject_event_offsets[subj], d.subject_event_offsets[subj + 1]
            n_ev = int(hi - lo)
            start = 0
            if n_ev > L:
                if strategy == SubsequenceSamplingStrategy.RANDOM:
                    start = int(rng.integers(0, n_ev - L + 1))
                elif strategy == SubsequenceSamplingStrategy.TO_END:
                    start = n_ev - L
                n_ev = L
            placed = False
            for r in open_rows:
                if row_fill[r] + n_ev <= L:
                    rows[r].append((int(subj), start, n_ev))
                    row_fill[r] += n_ev
                    placed = True
                    break
            if not placed:
                rows.append([(int(subj), start, n_ev)])
                row_fill.append(n_ev)
                open_rows.append(len(rows) - 1)
            open_rows = [r for r in open_rows if row_fill[r] + min_len <= L]
            if len(open_rows) > MAX_OPEN_ROWS:
                open_rows = open_rows[-MAX_OPEN_ROWS:]
        return rows

    def packed_rows_dealt(
        self,
        batch_size: int,
        seq_len: int | None = None,
        shuffle: bool = True,
        seed: int | None = None,
        n_shards: int = 1,
    ) -> list:
        """The epoch's packed rows in batch order, optionally dealt per shard.

        ``n_shards == 1``: exactly the historical stream — one permutation,
        one `_pack_rows` pass (the trailing short batch, if any, is left for
        callers to keep or drop). ``n_shards > 1``: each shard's subject pool
        is packed separately (rows reference one pool only, so the sharded
        device tables can gather locally), rows are dealt shard-major with
        ``batch_size / n_shards`` rows per shard per batch, and only full
        batches survive (the per-shard row counts differ, so the stream stops
        at the shortest shard). All randomness comes from one shared rng
        stream, consumed shard-by-shard — every process derives the same
        rows.
        """
        L = seq_len or self.max_seq_len
        rng = np.random.default_rng(seed)
        if n_shards == 1:
            n = len(self)
            order = rng.permutation(n) if shuffle else np.arange(n)
            return self._pack_rows(L, rng, order)
        if batch_size % n_shards != 0:
            raise ValueError(
                f"batch_size ({batch_size}) must be divisible by n_shards ({n_shards})."
            )
        b_local = batch_size // n_shards
        orders = self._shard_orders(n_shards, rng, shuffle)
        rows_by_shard = [self._pack_rows(L, rng, order) for order in orders]
        n_batches = min(len(r) // b_local for r in rows_by_shard)
        rows: list = []
        for i in range(n_batches):
            for shard_rows in rows_by_shard:
                rows.extend(shard_rows[i * b_local : (i + 1) * b_local])
        return rows

    def packed_row_plan(
        self, rows_chunk: list, L: int
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
        """Materializes packed rows into a ``(B, L)`` event-id/segment plan.

        The single definition of the packed-row layout (incl. the convention
        that trailing padding shares the last segment id so it never creates
        a phantom segment boundary) — consumed by host collation
        (`packed_batches`) and by on-device collation
        (``DeviceDataset.packed_batches`` / ``packed_plan_chunks``) so the
        two can never drift.

        Returns ``(event_ids, segment_ids, event_mask, n_events)``.
        """
        d = self.data
        B = len(rows_chunk)
        event_ids = np.zeros((B, L), dtype=np.int64)
        seg = np.zeros((B, L), dtype=np.int64)
        mask = np.zeros((B, L), dtype=bool)
        n_events = 0
        for b, placements in enumerate(rows_chunk):
            pos = 0
            for s_idx, (subj, start, n_ev) in enumerate(placements):
                lo = d.subject_event_offsets[subj] + start
                event_ids[b, pos : pos + n_ev] = np.arange(lo, lo + n_ev)
                seg[b, pos : pos + n_ev] = s_idx
                mask[b, pos : pos + n_ev] = True
                pos += n_ev
            if placements and pos < L:
                seg[b, pos:] = seg[b, pos - 1]
            n_events += pos
        return event_ids, seg, mask, n_events

    def packed_batch_count(
        self,
        batch_size: int,
        seq_len: int | None = None,
        shuffle: bool = True,
        seed: int | None = None,
        n_shards: int = 1,
    ) -> int:
        """Number of **full** batches `packed_batches` will yield.

        Runs only the packing (no collation), so step budgets and LR
        schedules can be derived from the packed stream before training
        (packing several subjects per row makes the per-epoch batch count a
        packing-factor smaller than the padded count).
        """
        rows = self.packed_rows_dealt(
            batch_size, seq_len=seq_len, shuffle=shuffle, seed=seed, n_shards=n_shards
        )
        return len(rows) // batch_size

    def packed_batches(
        self,
        batch_size: int,
        seq_len: int | None = None,
        shuffle: bool = True,
        seed: int | None = None,
        n_shards: int = 1,
    ):
        """Yields packed long-context batches with per-event ``segment_ids``.

        The long-context path (SURVEY §5.7; BASELINE config 5): instead of one
        right/left-padded subject per row, whole subject sequences are
        greedily first-fit packed into rows of ``seq_len`` (default
        ``config.max_seq_len``), with ``segment_ids`` marking subject
        boundaries. Attention, temporal encoding, history embeddings, and
        next-event alignment are segment-aware in both the CI and NA models,
        so padding waste drops from ``1 - mean_len/max_len`` to near zero at
        long sequence lengths.

        Subjects longer than ``seq_len`` are cropped by the configured
        subsequence-sampling strategy. Static data and stream labels are
        per-subject, not per-row, and are omitted from packed batches (the
        packed path targets generative pretraining throughput).
        """
        L = seq_len or self.max_seq_len
        M = self.max_n_dynamic
        d = self.data
        rows = self.packed_rows_dealt(
            batch_size, seq_len=L, shuffle=shuffle, seed=seed, n_shards=n_shards
        )

        for lo_idx in range(0, len(rows), batch_size):
            chunk = rows[lo_idx : lo_idx + batch_size]
            B = len(chunk)
            event_ids, segment_ids, event_mask, _ = self.packed_row_plan(chunk, L)

            time_delta = np.where(event_mask, d.time_delta[event_ids], 0.0).astype(np.float32)

            data_lo = d.event_data_offsets[event_ids]
            data_n = d.event_data_offsets[event_ids + 1] - data_lo
            mpos = np.arange(M, dtype=np.int32)[None, None, :]
            data_ids = data_lo[..., None] + mpos
            data_valid = (mpos < data_n[..., None]) & event_mask[..., None]
            data_ids = np.where(data_valid, data_ids, 0)

            dynamic_indices = np.where(data_valid, d.dynamic_indices[data_ids], 0)
            dynamic_meas = np.where(data_valid, d.dynamic_measurement_indices[data_ids], 0)
            values_mask = data_valid & d.dynamic_values_observed[data_ids]
            dynamic_values = np.where(values_mask, d.dynamic_values[data_ids], 0.0)

            yield EventStreamBatch(
                event_mask=event_mask,
                time_delta=time_delta,
                dynamic_indices=dynamic_indices,
                dynamic_measurement_indices=dynamic_meas,
                dynamic_values=dynamic_values,
                dynamic_values_mask=values_mask,
                segment_ids=segment_ids,
                valid_mask=np.ones(B, dtype=bool),
            )

    def batches(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed: int | None = None,
        drop_last: bool | None = None,
        skip_batches: int = 0,
        n_shards: int = 1,
    ):
        """Yields `EventStreamBatch`es of exactly ``batch_size`` subjects.

        The batch shape is always static. With ``drop_last=False`` (the
        default when ``shuffle=False``, i.e. eval), a final short batch is
        filled by cyclically repeating the epoch's first subjects — but every
        fill row is **blanked** (``event_mask`` and ``dynamic_values_mask``
        all False) and marked invalid in ``batch.valid_mask`` so eval loops
        never double-count subjects: weight per-subject metrics (incl.
        ``stream_labels``) by ``valid_mask``. With ``drop_last=True``
        (default when shuffling, i.e. training) the remainder is dropped.

        ``skip_batches`` fast-forwards past the first N batches without
        collating them (mid-epoch resume after preemption): the rng stream is
        advanced identically, so batch N+1 onward is bitwise-identical to an
        uninterrupted epoch.

        ``n_shards`` selects the dealt (sharded) plan stream — see
        `plan_batches`. Host collation handles dealt plans transparently
        (indices are global either way), which is what the multi-process
        parity tests lean on.
        """
        for plan in self.plan_batches(
            batch_size,
            shuffle=shuffle,
            seed=seed,
            drop_last=drop_last,
            skip_batches=skip_batches,
            n_shards=n_shards,
        ):
            b = self._collate_with_starts(
                plan.subject_indices, plan.starts, plan.kept, start_time=plan.start_time
            )
            if not plan.valid_mask.all():
                # Blank fill rows wherever they sit (a dealt stream can have
                # them mid-batch, one run per exhausted shard).
                event_mask = np.asarray(b.event_mask).copy()
                event_mask[~plan.valid_mask] = False
                values_mask = np.asarray(b.dynamic_values_mask).copy()
                values_mask[~plan.valid_mask] = False
                b = b.replace(
                    event_mask=event_mask, dynamic_values_mask=values_mask,
                    valid_mask=plan.valid_mask,
                )
            else:
                b = b.replace(valid_mask=plan.valid_mask)
            yield b

    def plan_batches(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed: int | None = None,
        drop_last: bool | None = None,
        skip_batches: int = 0,
        n_shards: int = 1,
    ):
        """Yields `BatchPlan`s — the ~100-byte rng-dependent part of a batch.

        A plan is everything `batches` decides on the host (subject order,
        subsequence crop starts, fill-row validity) with none of the array
        materialization. `batches` collates plans on the host;
        `DeviceDataset` (``device_dataset.py``) collates them **on device**
        from HBM-resident arrays, so a training step's host→device traffic is
        the plan instead of the ~MB batch. Both consume the identical rng
        stream via `_draw_starts`, so device- and host-collated epochs are
        bit-identical and ``skip_batches`` resume semantics are shared.

        ``n_shards > 1`` selects the DEALT stream for the sharded
        device-resident layout (multi-host pods): subjects are partitioned
        into ``n_shards`` contiguous pools (`subject_shards`), each batch
        takes ``batch_size / n_shards`` rows from every pool in shard-major
        row order, and all randomness (per-pool permutations, then crop
        starts per batch) is drawn from the SAME single rng stream on every
        process — so all processes derive identical plans and each data-axis
        shard's rows reference only subjects resident in its own table
        shard. ``n_shards=1`` reproduces the historical global stream
        bit-for-bit. Plans always carry GLOBAL subject indices; the sharded
        collate kernel rebases them on device.
        """
        if batch_size % n_shards != 0:
            raise ValueError(
                f"batch_size ({batch_size}) must be divisible by n_shards "
                f"({n_shards}) to deal equal per-shard rows."
            )
        b_local = batch_size // n_shards
        if drop_last is None:
            drop_last = shuffle
        rng = np.random.default_rng(seed)
        orders = self._shard_orders(n_shards, rng, shuffle)
        if drop_last:
            n_batches = min(len(o) // b_local for o in orders)
        else:
            n_batches = max(-(-len(o) // b_local) for o in orders)
        for i in range(n_batches):
            lo = i * b_local
            parts, valid_parts = [], []
            for order in orders:
                idx_k = order[lo : lo + b_local]
                n_real_k = len(idx_k)
                if n_real_k < b_local:
                    # np.resize repeats cyclically, so this stays full even
                    # when the pool is smaller than its per-batch share.
                    idx_k = np.concatenate([idx_k, np.resize(order, b_local - n_real_k)])
                parts.append(idx_k)
                valid_parts.append(np.arange(b_local) < n_real_k)
            idx = np.concatenate(parts)
            valid_mask = np.concatenate(valid_parts)
            starts, kept = self._draw_starts(idx, rng)
            if i < skip_batches:
                continue
            start_time = None
            if self.config.do_include_start_time_min:
                d = self.data
                ev_lo = d.subject_event_offsets[idx]
                prior = np.zeros(batch_size, dtype=np.float64)
                for b, (elo, s) in enumerate(zip(ev_lo, starts)):
                    prior[b] = d.time_delta[elo : elo + s].sum()
                start_time = (d.start_time_min[idx] + prior).astype(np.float32)
            yield BatchPlan(
                subject_indices=np.asarray(idx, dtype=np.int32),
                starts=starts.astype(np.int32),
                kept=kept.astype(np.int32),
                valid_mask=valid_mask,
                n_events=int(kept[valid_mask].sum()),
                start_time=start_time,
            )
