"""Dataset visualization: config + plotting over a built `Dataset`.

Rebuild of ``/root/reference/EventStream/data/visualize.py:14`` on matplotlib
(the reference uses Plotly, which is not installed in this image; the figures
are static PNGs instead of interactive HTML, same plot families):

* by-time curves (``plot_by_time``): active subjects, cumulative subjects,
  cumulative events, events/subject, events/(subject·time), each optionally
  split by static covariates (reference ``plot_counts_over_time``);
* by-age curves (``plot_by_age``): cumulative subjects, cumulative events,
  events/subject over age buckets (reference ``plot_counts_over_age``);
* events-per-subject histogram (reference ``plot_events_per_patient:417``);
* age distribution of active subjects over time as a median + interquartile
  band (reference ``plot_age_distribution_over_time:254``);
* static-covariate breakdown bars (reference
  ``plot_static_variables_breakdown:327``).

The class is both configuration (JSONable, reference-matching validation) and
executor: ``plot(dataset, save_dir)`` writes one PNG per plot family.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np
import pandas as pd

from ..utils import JSONableMixin, config_dataclass


@config_dataclass
class Visualizer(JSONableMixin):
    """Visualization config + plotter (reference ``visualize.py:14``).

    Examples:
        >>> V = Visualizer()
        >>> V = Visualizer(
        ...     subset_size=100, subset_random_seed=1,
        ...     plot_by_age=True, age_col='age', dob_col='dob', n_age_buckets=100,
        ...     plot_by_time=True, time_unit='1y',
        ... )
        >>> Visualizer(subset_size=100)
        Traceback (most recent call last):
            ...
        ValueError: subset_size is specified, but subset_random_seed is not!
        >>> Visualizer(plot_by_age=True, age_col='age', n_age_buckets=None)
        Traceback (most recent call last):
            ...
        ValueError: plot_by_age is True, but n_age_buckets is unspecified!
        >>> Visualizer(age_col='age')
        Traceback (most recent call last):
            ...
        ValueError: age_col is specified, but dob_col is not!
        >>> Visualizer(plot_by_time=True, time_unit=None)
        Traceback (most recent call last):
            ...
        ValueError: plot_by_time is True, but time_unit is unspecified!
    """

    subset_size: int | None = None
    subset_random_seed: int | None = None

    static_covariates: list[str] = dataclasses.field(default_factory=list)

    plot_by_time: bool = True
    time_unit: str | None = "1y"

    plot_by_age: bool = False
    age_col: str | None = None
    dob_col: str | None = None
    n_age_buckets: int | None = 200

    min_sub_to_plot_age_dist: int | None = 50

    def __post_init__(self):
        if self.subset_size is not None and self.subset_random_seed is None:
            raise ValueError("subset_size is specified, but subset_random_seed is not!")
        if self.plot_by_age:
            if self.age_col is None:
                raise ValueError("plot_by_age is True, but age_col is unspecified!")
            if self.n_age_buckets is None:
                raise ValueError("plot_by_age is True, but n_age_buckets is unspecified!")
        if self.age_col is not None and self.dob_col is None:
            raise ValueError("age_col is specified, but dob_col is not!")
        if self.plot_by_time and self.time_unit is None:
            raise ValueError("plot_by_time is True, but time_unit is unspecified!")

    # ----------------------------------------------------------------- data
    def _subject_spans(self, dataset) -> pd.DataFrame:
        """Per-subject first/last event times + event counts (+ covariates)."""
        ev = dataset.events_df
        spans = (
            ev.groupby("subject_id")["timestamp"]
            .agg(first="min", last="max", n_events="count")
            .reset_index()
        )
        if self.subset_size is not None and len(spans) > self.subset_size:
            spans = spans.sample(self.subset_size, random_state=self.subset_random_seed)
        if self.static_covariates:
            cov = dataset.subjects_df[["subject_id", *self.static_covariates]]
            spans = spans.merge(cov, on="subject_id", how="left")
        return spans

    @staticmethod
    def _groups(spans: pd.DataFrame, covariates: list[str]):
        if not covariates:
            yield "all subjects", spans
        else:
            for key, grp in spans.groupby(covariates):
                key = key if isinstance(key, tuple) else (key,)
                label = ", ".join(f"{c}={k}" for c, k in zip(covariates, key))
                yield label, grp

    # ----------------------------------------------------------------- plots
    def plot(self, dataset, save_dir: Path | str) -> list[Path]:
        """Writes the configured plot families as PNGs; returns their paths."""
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        save_dir = Path(save_dir)
        save_dir.mkdir(parents=True, exist_ok=True)
        written: list[Path] = []

        spans = self._subject_spans(dataset)

        if self.plot_by_time:
            fig, axes = plt.subplots(1, 5, figsize=(25, 4))
            ev = dataset.events_df
            ts = ev[ev["subject_id"].isin(set(spans["subject_id"]))]["timestamp"]
            # Grid at time_unit granularity so the rate panel measures events
            # per (subject · time_unit); very long spans cap at 400 points
            # (the rate is then per grid interval, noted in the title).
            if len(ts):
                grid = pd.date_range(ts.min(), ts.max(), freq=_pd_freq(self.time_unit))
                rate_unit = self.time_unit
                if len(grid) < 2 or len(grid) > 400:
                    grid = pd.date_range(ts.min(), ts.max(), periods=100)
                    rate_unit = "grid interval"
            else:
                grid, rate_unit = [], self.time_unit

            for label, grp in self._groups(spans, self.static_covariates):
                firsts = grp["first"].to_numpy()
                lasts = grp["last"].to_numpy()
                sub_ev = ev[ev["subject_id"].isin(set(grp["subject_id"]))]
                ev_times = np.sort(sub_ev["timestamp"].to_numpy())

                active = [((firsts <= t.to_datetime64()) & (lasts >= t.to_datetime64())).sum() for t in grid]
                cum_subj = [(firsts <= t.to_datetime64()).sum() for t in grid]
                cum_ev = [np.searchsorted(ev_times, t.to_datetime64(), side="right") for t in grid]
                ev_per_subj = [e / max(s, 1) for e, s in zip(cum_ev, cum_subj)]
                # events per subject per time_unit, within each grid interval
                rate = np.diff([0] + cum_ev) / np.maximum(active, 1)

                axes[0].plot(grid, active, label=label)
                axes[1].plot(grid, cum_subj, label=label)
                axes[2].plot(grid, cum_ev, label=label)
                axes[3].plot(grid, ev_per_subj, label=label)
                axes[4].plot(grid, rate, label=label)

            for ax, title in zip(
                axes,
                (
                    "Active Subjects",
                    "Cumulative Subjects",
                    "Cumulative Events",
                    "Events / Subject",
                    f"Events / (Subject, {rate_unit})",
                ),
            ):
                ax.set_title(title)
                ax.set_xlabel("time")
                ax.tick_params(axis="x", rotation=45)
                ax.legend(fontsize=6)
            fig.tight_layout()
            fp = save_dir / "dataset_by_time.png"
            fig.savefig(fp, dpi=100)
            plt.close(fig)
            written.append(fp)

        if self.plot_by_age:
            ev = dataset.events_df
            if self.age_col in ev.columns:
                ages = ev[["subject_id", self.age_col]].dropna()
            else:
                dob = dataset.subjects_df.set_index("subject_id")[self.dob_col]
                ages = ev[["subject_id", "timestamp"]].copy()
                dob_per_event = ages["subject_id"].map(dob)
                ages[self.age_col] = (
                    (ages["timestamp"] - pd.to_datetime(dob_per_event)).dt.total_seconds()
                    / (60 * 60 * 24 * 365.25)
                )
                ages = ages[["subject_id", self.age_col]].dropna()

            ages = ages[ages["subject_id"].isin(set(spans["subject_id"]))]
            buckets = np.linspace(
                ages[self.age_col].min(), ages[self.age_col].max(), self.n_age_buckets
            )
            fig, axes = plt.subplots(1, 3, figsize=(15, 4))
            for label, grp in self._groups(spans, self.static_covariates):
                if (
                    self.min_sub_to_plot_age_dist is not None
                    and len(grp) < self.min_sub_to_plot_age_dist
                ):
                    continue  # sub-population too small for stable age curves
                sub = ages[ages["subject_id"].isin(set(grp["subject_id"]))]
                a = np.sort(sub[self.age_col].to_numpy())
                cum_ev = [np.searchsorted(a, b, side="right") for b in buckets]
                per_subj_first = sub.groupby("subject_id")[self.age_col].min().to_numpy()
                cum_subj = [(per_subj_first <= b).sum() for b in buckets]
                axes[0].plot(buckets, cum_subj, label=label)
                axes[1].plot(buckets, cum_ev, label=label)
                axes[2].plot(
                    buckets, [e / max(s, 1) for e, s in zip(cum_ev, cum_subj)], label=label
                )
            for ax, title in zip(
                axes, ("Cumulative Subjects", "Cumulative Events", "Events / Subject")
            ):
                ax.set_title(title)
                ax.set_xlabel("age")
                ax.legend(fontsize=6)
            fig.tight_layout()
            fp = save_dir / "dataset_by_age.png"
            fig.savefig(fp, dpi=100)
            plt.close(fig)
            written.append(fp)

        # Events-per-subject histogram (reference plot_events_per_patient).
        fig, ax = plt.subplots(figsize=(6, 4))
        for label, grp in self._groups(spans, self.static_covariates):
            ax.hist(grp["n_events"].to_numpy(), bins=30, alpha=0.6, label=label)
        ax.set_title("Events per Subject")
        ax.set_xlabel("# of events")
        ax.set_ylabel("# of subjects")
        ax.legend(fontsize=6)
        fig.tight_layout()
        fp = save_dir / "dataset_events_per_subject.png"
        fig.savefig(fp, dpi=100)
        plt.close(fig)
        written.append(fp)

        # Static-covariate breakdown (reference plot_static_variables_breakdown).
        if self.static_covariates:
            fig, axes = plt.subplots(
                1, len(self.static_covariates), figsize=(5 * len(self.static_covariates), 4),
                squeeze=False,
            )
            for ax, cov in zip(axes[0], self.static_covariates):
                counts = dataset.subjects_df[cov].value_counts()
                ax.bar([str(v) for v in counts.index], counts.to_numpy())
                ax.set_title(f"Subjects by {cov}")
                ax.tick_params(axis="x", rotation=45)
            fig.tight_layout()
            fp = save_dir / "dataset_static_breakdown.png"
            fig.savefig(fp, dpi=100)
            plt.close(fig)
            written.append(fp)

        # Age distribution of active subjects over time: median + IQR band
        # (reference plot_age_distribution_over_time).
        if self.plot_by_age and self.dob_col is not None:
            dob = pd.to_datetime(dataset.subjects_df.set_index("subject_id")[self.dob_col])
            sp = spans.merge(
                dob.rename("dob"), left_on="subject_id", right_index=True, how="inner"
            ).dropna(subset=["dob"])
            if len(sp) >= (self.min_sub_to_plot_age_dist or 0):
                grid = pd.date_range(sp["first"].min(), sp["last"].max(), periods=60)
                fig, ax = plt.subplots(figsize=(7, 4))
                for label, grp in self._groups(sp, self.static_covariates):
                    q25, q50, q75, xs = [], [], [], []
                    firsts = grp["first"].to_numpy()
                    lasts = grp["last"].to_numpy()
                    dobs = grp["dob"].to_numpy()
                    for t in grid:
                        t64 = t.to_datetime64()
                        active = (firsts <= t64) & (lasts >= t64)
                        if active.sum() < 2:
                            continue
                        ages = (t64 - dobs[active]) / np.timedelta64(1, "D") / 365.25
                        lo, mid, hi = np.quantile(ages, (0.25, 0.5, 0.75))
                        xs.append(t)
                        q25.append(lo)
                        q50.append(mid)
                        q75.append(hi)
                    if xs:
                        (line,) = ax.plot(xs, q50, label=label)
                        ax.fill_between(xs, q25, q75, alpha=0.2, color=line.get_color())
                ax.set_title("Age of Active Subjects over Time (median, IQR)")
                ax.set_xlabel("time")
                ax.set_ylabel("age (years)")
                ax.tick_params(axis="x", rotation=45)
                ax.legend(fontsize=6)
                fig.tight_layout()
                fp = save_dir / "dataset_age_distribution.png"
                fig.savefig(fp, dpi=100)
                plt.close(fig)
                written.append(fp)

        return written


def _pd_freq(time_unit: str) -> str:
    """Maps the reference's '1y'-style units to pandas frequency aliases."""
    return {"1y": "YS", "1mo": "MS", "1w": "W", "1d": "D", "1h": "h"}.get(time_unit, time_unit)
