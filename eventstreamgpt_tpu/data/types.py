"""Core data-model types: modality/temporality enums and the device batch.

TPU-native re-design of ``/root/reference/EventStream/data/types.py``. The
reference's ``PytorchBatch`` (``types.py:87``) is a mutable dataclass of torch
tensors with dynamic per-batch shapes; here the batch is a frozen
``flax.struct`` pytree of arrays with **static shapes** so it can flow through
``jax.jit`` / ``pjit`` / ``lax.scan`` unchanged. Dynamic-shape helpers the
reference implements as tensor surgery (``repeat_batch_elements`` ``:318``,
``split_repeated_batch`` ``:469``) become pure jnp reshapes.
"""

from __future__ import annotations

import enum
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..utils import StrEnum


def de_pad(L: list[int], *other_L) -> list[int] | tuple[list[int], ...]:
    """Filters all passed lists to indices where the first list is non-zero.

    Reference contract: ``data/types.py:14``.

    Examples:
        >>> de_pad([1, 3, 0, 4, 0, 0], [10, 0, 5, 8, 1, 0])
        ([1, 3, 4], [10, 0, 8])
        >>> de_pad([1, 3, 0, 4, 0, 0])
        [1, 3, 4]
    """
    out_L = []
    out_other: list[list | None] = [None if x is None else [] for x in other_L]
    for i, v in enumerate(L):
        if v != 0:
            out_L.append(v)
            for j, LL in enumerate(other_L):
                if LL is not None:
                    out_other[j].append(LL[i])
    if other_L:
        return tuple([out_L] + out_other)
    return out_L


class InputDFType(StrEnum):
    """The kinds of input dataframes usable to construct a dataset."""

    STATIC = enum.auto()
    EVENT = enum.auto()
    RANGE = enum.auto()


class InputDataType(StrEnum):
    """The kinds of data an input dataframe column can contain."""

    CATEGORICAL = enum.auto()
    FLOAT = enum.auto()
    TIMESTAMP = enum.auto()
    BOOLEAN = enum.auto()


class TemporalityType(StrEnum):
    """The ways a measurement can vary in time (reference: ``types.py:802``)."""

    STATIC = enum.auto()
    DYNAMIC = enum.auto()
    FUNCTIONAL_TIME_DEPENDENT = enum.auto()


class DataModality(StrEnum):
    """The modality of a data element (reference: ``types.py:826``)."""

    DROPPED = enum.auto()
    SINGLE_LABEL_CLASSIFICATION = enum.auto()
    MULTI_LABEL_CLASSIFICATION = enum.auto()
    MULTIVARIATE_REGRESSION = enum.auto()
    UNIVARIATE_REGRESSION = enum.auto()


class NumericDataModalitySubtype(StrEnum):
    """Numeric value subtypes (reference: ``types.py:865``)."""

    DROPPED = enum.auto()
    INTEGER = enum.auto()
    FLOAT = enum.auto()
    CATEGORICAL_INTEGER = enum.auto()
    CATEGORICAL_FLOAT = enum.auto()


Array = Any  # jnp.ndarray or np.ndarray — batches are host-built then device-put.


@struct.dataclass
class EventStreamBatch:
    """A static-shape batch of event-stream data, registered as a JAX pytree.

    Field names and shapes mirror the reference ``PytorchBatch``
    (``/root/reference/EventStream/data/types.py:87-163``) so the data contract
    is identical; the representation differs in being immutable and pytree-
    flattenable so whole batches move through ``jit`` boundaries, shardings,
    and scans without host sync.

    Shapes (``B`` batch, ``L`` sequence length, ``M`` dynamic data elements,
    ``S`` static data elements):

    * ``event_mask``: bool ``(B, L)`` — True for real (non-padding) events.
    * ``time_delta``: float ``(B, L)`` — minutes to the *next* event.
    * ``time``: float ``(B, L)`` — minutes since sequence start (optional).
    * ``static_indices`` / ``static_measurement_indices``: int ``(B, S)``.
    * ``dynamic_indices`` / ``dynamic_measurement_indices``: int ``(B, L, M)``.
    * ``dynamic_values``: float ``(B, L, M)``; ``dynamic_values_mask``: bool.
    * ``start_time``: float ``(B,)`` minutes since epoch (generation only).
    * ``start_idx`` / ``end_idx`` / ``subject_id``: int ``(B,)`` (optional).
    * ``stream_labels``: dict of per-task label arrays ``(B,)`` (optional).
    * ``valid_mask``: bool ``(B,)`` — False for wrap-around fill rows in the
      final short eval batch (optional; absent means all rows valid). Eval
      loops must weight per-subject metrics (incl. ``stream_labels``) by it.
    * ``segment_ids``: int ``(B, L)`` — packed-sequence segment index per
      event (optional). When present, each row holds several subjects'
      sequences concatenated; attention, temporal encoding, and next-event
      alignment all respect segment boundaries (long-context packed path;
      SURVEY §5.7). Padding positions share the id of the last segment and
      are excluded by ``event_mask``.
    """

    event_mask: Optional[Array] = None
    time_delta: Optional[Array] = None
    time: Optional[Array] = None

    static_indices: Optional[Array] = None
    static_measurement_indices: Optional[Array] = None

    dynamic_indices: Optional[Array] = None
    dynamic_measurement_indices: Optional[Array] = None
    dynamic_values: Optional[Array] = None
    dynamic_values_mask: Optional[Array] = None

    start_time: Optional[Array] = None
    start_idx: Optional[Array] = None
    end_idx: Optional[Array] = None
    subject_id: Optional[Array] = None

    stream_labels: Optional[dict[str, Array]] = None

    valid_mask: Optional[Array] = None

    segment_ids: Optional[Array] = None

    # -- dict-like conveniences matching the reference API ------------------
    def keys(self):
        return (f.name for f in self.__dataclass_fields__.values())

    def get(self, item: str, default: Any = None) -> Any:
        v = getattr(self, item, None)
        return default if v is None else v

    def __getitem__(self, item):
        if isinstance(item, str):
            return getattr(self, item)
        return self.slice(item)

    @property
    def batch_size(self) -> int:
        return self.event_mask.shape[0]

    @property
    def sequence_length(self) -> int:
        return self.event_mask.shape[1]

    @property
    def n_data_elements(self) -> int:
        return self.dynamic_indices.shape[2]

    @property
    def n_static_data_elements(self) -> int:
        return self.static_indices.shape[1]

    def slice(self, index) -> "EventStreamBatch":
        """Slices batch (dim 0), sequence (dim 1), and data-element (dim 2) axes.

        Mirrors ``PytorchBatch._slice`` (``types.py:209``).
        """
        if not isinstance(index, tuple):
            index = (index,)
        if len(index) == 0 or len(index) > 3:
            raise ValueError(f"Invalid index {index}: must have 1-3 elements.")
        b = index[0]
        s = index[1] if len(index) > 1 else slice(None)
        m = index[2] if len(index) > 2 else slice(None)

        def _b(x):
            return None if x is None else x[b]

        return EventStreamBatch(
            event_mask=self.event_mask[b, s],
            time_delta=None if self.time_delta is None else self.time_delta[b, s],
            time=None if self.time is None else self.time[b, s],
            static_indices=_b(self.static_indices),
            static_measurement_indices=_b(self.static_measurement_indices),
            dynamic_indices=self.dynamic_indices[b, s, m],
            dynamic_measurement_indices=self.dynamic_measurement_indices[b, s, m],
            dynamic_values=self.dynamic_values[b, s, m],
            dynamic_values_mask=self.dynamic_values_mask[b, s, m],
            start_time=_b(self.start_time),
            start_idx=_b(self.start_idx),
            end_idx=_b(self.end_idx),
            subject_id=_b(self.subject_id),
            stream_labels=(
                None if self.stream_labels is None else {k: v[b] for k, v in self.stream_labels.items()}
            ),
            valid_mask=_b(self.valid_mask),
            segment_ids=None if self.segment_ids is None else self.segment_ids[b, s],
        )

    def last_sequence_element_unsqueezed(self) -> "EventStreamBatch":
        """The last event of each sequence, retaining the sequence dim."""
        return self.slice((slice(None), slice(-1, None)))

    def repeat_batch_elements(self, expand_size: int) -> "EventStreamBatch":
        """Repeats each batch element ``expand_size`` times, in order.

        Reference: ``PytorchBatch.repeat_batch_elements`` (``types.py:318``).
        Implemented as a pure ``jnp.repeat`` over every pytree leaf, so it is
        jit-safe (``expand_size`` is static).
        """

        def rep(x):
            return None if x is None else jnp.repeat(x, expand_size, axis=0)

        return jax.tree_util.tree_map(rep, self)

    def split_repeated_batch(self, n_splits: int) -> list["EventStreamBatch"]:
        """Inverse of `repeat_batch_elements`: regroups samples per source element.

        Returns ``n_splits`` batches; the i-th batch holds the i-th repeated
        sample of each original element (reference: ``types.py:469``).
        """

        def sel(x, i):
            if x is None:
                return None
            reshaped = x.reshape((x.shape[0] // n_splits, n_splits) + x.shape[1:])
            return reshaped[:, i]

        return [jax.tree_util.tree_map(lambda x, i=i: sel(x, i), self) for i in range(n_splits)]

    def to_numpy(self) -> "EventStreamBatch":
        """Converts all leaves to host numpy arrays (for labelers/writers)."""
        return jax.tree_util.tree_map(lambda x: np.asarray(x), self)

    def convert_to_DL_DF(self):
        """Converts the batch into the sparse deep-learning DataFrame format.

        Reference: ``PytorchBatch.convert_to_DL_DF`` (``types.py:684``), with
        pandas as the frame library. One row per subject; ragged columns are
        de-padded lists (``time_delta``/``time`` per event; doubly-nested
        ``dynamic_*`` per event per observation, with unobserved values as
        None); scalar columns (``start_time``/``subject_id``/``start_idx``/
        ``end_idx``) pass through.
        """
        import pandas as pd

        b = self.to_numpy()
        df: dict[str, list] = {
            k: []
            for k in (
                "time_delta",
                "time",
                "static_indices",
                "static_measurement_indices",
                "dynamic_indices",
                "dynamic_measurement_indices",
                "dynamic_values",
            )
            if getattr(b, k) is not None
        }

        for k in ("start_time", "subject_id", "start_idx", "end_idx"):
            if getattr(b, k) is not None:
                df[k] = list(np.asarray(getattr(b, k)).tolist())

        for i in range(b.batch_size):
            if b.static_indices is not None:
                idx, measurement_idx = de_pad(
                    b.static_indices[i].tolist(), b.static_measurement_indices[i].tolist()
                )
                df["static_indices"].append(idx)
                df["static_measurement_indices"].append(measurement_idx)

            _, time_delta, time, idx, measurement_idx, vals, vals_mask = de_pad(
                b.event_mask[i].tolist(),
                None if b.time_delta is None else b.time_delta[i].tolist(),
                None if b.time is None else b.time[i].tolist(),
                b.dynamic_indices[i].tolist(),
                b.dynamic_measurement_indices[i].tolist(),
                b.dynamic_values[i].tolist(),
                b.dynamic_values_mask[i].tolist(),
            )

            if time_delta is not None:
                df["time_delta"].append(time_delta)
            if time is not None:
                df["time"].append(time)

            names = ("dynamic_indices", "dynamic_measurement_indices", "dynamic_values")
            for n in names:
                df[n].append([])

            for j in range(len(idx)):
                de_padded = de_pad(idx[j], measurement_idx[j], vals[j], vals_mask[j])
                for n, v in zip(names[:-1], de_padded[:-2]):
                    df[n][i].append(v)
                df["dynamic_values"][i].append(
                    [v if m else None for v, m in zip(*de_padded[-2:])]
                )

        return pd.DataFrame(df)

    def with_fields(self, **updates: Any) -> "EventStreamBatch":
        """Returns a copy with the given fields replaced."""
        return self.replace(**updates)
