"""Synthetic DL-cache generation: MIMIC-shaped datasets written to disk.

Fabricates the on-disk artifacts the data layer consumes — ``DL_reps/
{split}_0.parquet`` + ``vocabulary_config.json`` +
``inferred_measurement_configs.json`` in the reference's exact schema
(``/root/reference/sample_data/processed/sample/``) — at configurable scale.
Used by ``bench.py`` so the benchmark exercises the real pipeline (parquet →
``JaxDataset`` → host collation → device) rather than a resident synthetic
batch, and by tests needing bigger-than-sample fixtures.

Shape targets mirror the MIMIC-IV tutorial config (BASELINE.json config 2):
ragged sequence lengths, ~1 event type + a bag of lab observations per event,
a few-thousand-entry unified vocabulary.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pandas as pd

__all__ = ["write_synthetic_dataset", "write_synthetic_raw_csvs"]


def _vocab_entry(name: str, size: int) -> dict:
    """A MeasurementConfig 'vocabulary' dict with UNK at 0 (reference schema)."""
    freqs = np.linspace(2.0, 1.0, size - 1)
    freqs = freqs / freqs.sum()
    return {
        "vocabulary": ["UNK"] + [f"{name}_{i}" for i in range(1, size)],
        "obs_frequencies": [0.0] + freqs.tolist(),
    }


def write_synthetic_dataset(
    save_dir: Path | str,
    n_subjects_per_split: dict[str, int] | None = None,
    n_event_types: int = 40,
    n_labs: int = 2000,
    n_meds: int = 500,
    n_static: int = 16,
    mean_seq_len: int = 128,
    max_seq_len: int = 512,
    mean_obs_per_event: int = 14,
    max_obs_per_event: int = 24,
    seed: int = 0,
) -> Path:
    """Writes a synthetic processed dataset; returns ``save_dir``.

    Measurements: ``event_type`` (single-label), ``lab`` (multivariate
    regression + multi-label), ``med`` (multi-label), ``demo`` (static
    single-label). Sequence lengths are lognormal-ragged, clipped to
    ``[4, max_seq_len]``.
    """
    save_dir = Path(save_dir)
    (save_dir / "DL_reps").mkdir(parents=True, exist_ok=True)
    if n_subjects_per_split is None:
        n_subjects_per_split = {"train": 256, "tuning": 64, "held_out": 64}

    rng = np.random.default_rng(seed)

    # Unified vocabulary layout: UNK/pad at 0, then per-measurement slices.
    vocab_offsets = {"event_type": 1}
    vocab_sizes = {"event_type": n_event_types}
    vocab_offsets["lab"] = 1 + n_event_types
    vocab_sizes["lab"] = n_labs
    vocab_offsets["med"] = vocab_offsets["lab"] + n_labs
    vocab_sizes["med"] = n_meds
    vocab_offsets["demo"] = vocab_offsets["med"] + n_meds
    vocab_sizes["demo"] = n_static
    total_vocab = vocab_offsets["demo"] + n_static

    vocabulary_config = {
        "vocab_sizes_by_measurement": vocab_sizes,
        "vocab_offsets_by_measurement": vocab_offsets,
        "measurements_idxmap": {"event_type": 1, "lab": 2, "med": 3, "demo": 4},
        "measurements_per_generative_mode": {
            "single_label_classification": ["event_type"],
            "multi_label_classification": ["lab", "med"],
            "multivariate_regression": ["lab"],
        },
        "event_types_idxmap": {f"event_type_{i}": i for i in range(1, n_event_types)},
    }
    with open(save_dir / "vocabulary_config.json", "w") as f:
        json.dump(vocabulary_config, f)

    # event_type is deliberately absent: the reference keeps it out of
    # inferred_measurement_configs (it is the special event-type measurement).
    measurement_configs = {
        "lab": {
            "name": "lab",
            "temporality": "dynamic",
            "modality": "multivariate_regression",
            "observation_frequency": 0.95,
            "functor": None,
            "vocabulary": _vocab_entry("lab", n_labs),
            "values_column": "lab_value",
            "_measurement_metadata": None,
        },
        "med": {
            "name": "med",
            "temporality": "dynamic",
            "modality": "multi_label_classification",
            "observation_frequency": 0.4,
            "functor": None,
            "vocabulary": _vocab_entry("med", n_meds),
            "values_column": None,
            "_measurement_metadata": None,
        },
        "demo": {
            "name": "demo",
            "temporality": "static",
            "modality": "single_label_classification",
            "observation_frequency": 1.0,
            "functor": None,
            "vocabulary": _vocab_entry("demo", n_static),
            "values_column": None,
            "_measurement_metadata": None,
        },
    }
    with open(save_dir / "inferred_measurement_configs.json", "w") as f:
        json.dump(measurement_configs, f)

    subject_id = 0
    for split, n_subjects in n_subjects_per_split.items():
        rows = []
        for _ in range(n_subjects):
            L = int(np.clip(rng.lognormal(np.log(mean_seq_len), 0.6), 4, max_seq_len))
            # Strictly-positive inter-event times in minutes.
            deltas = rng.uniform(1.0, 240.0, size=L - 1).astype(np.float64)
            times = np.concatenate([[0.0], np.cumsum(deltas)])

            ev_meas, ev_idx, ev_val = [], [], []
            for _e in range(L):
                n_obs = int(np.clip(rng.poisson(mean_obs_per_event), 1, max_obs_per_event))
                meas = np.full(n_obs, 2, dtype=np.int64)  # labs by default
                meas[0] = 1  # exactly one event_type element
                if n_obs > 2 and rng.random() < 0.4:
                    meas[-(1 + int(rng.integers(0, min(3, n_obs - 2)))) :] = 3  # meds
                idx = np.empty(n_obs, dtype=np.int64)
                for m, (name, lo) in enumerate(
                    [("event_type", 1), ("lab", 2), ("med", 3)]
                ):
                    sel = meas == lo
                    if sel.any():
                        off, size = vocab_offsets[name], vocab_sizes[name]
                        idx[sel] = rng.integers(off + 1, off + size, size=int(sel.sum()))
                val = np.where(meas == 2, rng.normal(size=n_obs), np.nan).astype(np.float32)
                ev_meas.append(meas)
                ev_idx.append(idx)
                ev_val.append(val)

            rows.append(
                {
                    "subject_id": subject_id,
                    "static_measurement_indices": np.asarray([4], dtype=np.int64),
                    "static_indices": np.asarray(
                        [rng.integers(vocab_offsets["demo"] + 1, total_vocab)], dtype=np.int64
                    ),
                    "start_time": pd.Timestamp("2020-01-01") + pd.Timedelta(minutes=float(rng.uniform(0, 1e5))),
                    "time": times,
                    "dynamic_measurement_indices": ev_meas,
                    "dynamic_indices": ev_idx,
                    "dynamic_values": ev_val,
                }
            )
            subject_id += 1
        pd.DataFrame(rows).to_parquet(save_dir / "DL_reps" / f"{split}_0.parquet")

    return save_dir


def write_synthetic_raw_csvs(
    raw_dir: Path | str,
    n_subjects: int = 500,
    mean_admissions_per_subject: float = 3.0,
    mean_vitals_per_admission: float = 30.0,
    n_departments: int = 12,
    seed: int = 0,
) -> Path:
    """Writes raw CSVs in the reference ``sample_data/raw`` schema, at scale.

    Produces ``subjects.csv`` (MRN, dob, eye_color, height) and
    ``admit_vitals.csv`` (MRN, admit/disch range events, department,
    per-vitals-timestamp HR/temp readings) shaped like
    ``/root/reference/sample_data/raw/*.csv`` but with configurable row
    counts — the input side of the ETL benchmark (VERDICT r02 next #6).
    Returns ``raw_dir``.
    """
    raw_dir = Path(raw_dir)
    raw_dir.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(seed)

    # Int population (no materialized 90M-element array; Generator draws
    # without replacement via Floyd's algorithm).
    mrns = rng.choice(90_000_000, size=n_subjects, replace=False) + 10_000_000
    eye_colors = rng.choice(["BROWN", "BLUE", "GREEN", "HAZEL"], size=n_subjects)
    dob_year = rng.integers(1930, 2000, size=n_subjects)
    dob_month = rng.integers(1, 13, size=n_subjects)
    dob_day = rng.integers(1, 29, size=n_subjects)
    subjects = pd.DataFrame(
        {
            "MRN": mrns,
            "dob": [f"{m:02d}/{d:02d}/{y}" for y, m, d in zip(dob_year, dob_month, dob_day)],
            "eye_color": eye_colors,
            "height": rng.normal(170.0, 10.0, size=n_subjects),
        }
    )
    subjects.to_csv(raw_dir / "subjects.csv", index=False)

    departments = [f"DEPT_{i}" for i in range(n_departments)]
    n_adm = rng.poisson(mean_admissions_per_subject, size=n_subjects).clip(1)

    base = pd.Timestamp("2010-01-01")
    sub_rows, admit_list, disch_list, dept_list, vit_ts = [], [], [], [], []
    hr_list, temp_list = [], []
    for i in range(n_subjects):
        t = base + pd.Timedelta(minutes=int(rng.integers(0, 525_600)))
        for _ in range(int(n_adm[i])):
            stay_h = float(rng.uniform(24.0, 24.0 * 14))
            admit, disch = t, t + pd.Timedelta(hours=stay_h)
            dept = departments[int(rng.integers(n_departments))]
            n_vit = max(int(rng.poisson(mean_vitals_per_admission)), 1)
            offs = np.sort(rng.uniform(0.0, stay_h * 60.0, size=n_vit))
            for o in offs:
                sub_rows.append(mrns[i])
                admit_list.append(admit)
                disch_list.append(disch)
                dept_list.append(dept)
                vit_ts.append(admit + pd.Timedelta(minutes=float(o)))
            hr_list.append(rng.normal(85.0, 15.0, size=n_vit).round(1))
            temp_list.append(rng.normal(97.5, 1.2, size=n_vit).round(1))
            t = disch + pd.Timedelta(hours=float(rng.uniform(24.0, 24.0 * 60)))

    fmt = "%m/%d/%Y, %H:%M:%S"
    admit_vitals = pd.DataFrame(
        {
            "MRN": sub_rows,
            "admit_date": pd.Series(admit_list).dt.strftime(fmt),
            "disch_date": pd.Series(disch_list).dt.strftime(fmt),
            "department": dept_list,
            "vitals_date": pd.Series(vit_ts).dt.strftime(fmt),
            "HR": np.concatenate(hr_list),
            "temp": np.concatenate(temp_list),
        }
    )
    admit_vitals.to_csv(raw_dir / "admit_vitals.csv", index=False)
    return raw_dir
