"""The concrete pandas dataset backend.

Rebuild of ``/root/reference/EventStream/data/dataset_polars.py:69`` — the one
concrete ETL backend. The reference builds on Polars (Rust); Polars is not
installed in this image and installation is prohibited, so the same behavior
is implemented over pandas + numpy with vectorized groupby/aggregation ops
(no per-row Python loops in the fit/transform/cache paths). Behavioral
contracts reproduced from the reference, per method citation below:

* input ingestion with dtype coercion + subject-ID remapping (``:147``),
* range-event splitting into EQ/start/end (``:357``),
* temporal aggregation with datapoint-anchored buckets and ``&``-joined
  event-type unions (``:643``),
* numeric fitting: bounds drop/censor (``:437``), value-type inference
  int/float/categorical (``:794``), outlier + normalizer fitting per
  vocabulary key (``:899``), vocabulary fitting (``:1037``),
* transforms (``:1099``, ``:1198``) and the DL cache builder (``:1246``,
  ``:1305``).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Sequence

import numpy as np
import pandas as pd

from ..utils import count_or_proportion
from .config import MeasurementConfig
from .dataset_base import DatasetBase
from .preprocessing import StandardScaler, StddevCutoffOutlierDetector
from .types import DataModality, InputDataType, NumericDataModalitySubtype, TemporalityType
from .vocabulary import Vocabulary

DF_T = pd.DataFrame

BOUND_COLS = (
    "drop_upper_bound",
    "drop_upper_bound_inclusive",
    "drop_lower_bound",
    "drop_lower_bound_inclusive",
    "censor_lower_bound",
    "censor_upper_bound",
)


@dataclasses.dataclass
class Query:
    """A database query input spec (reference ``dataset_polars.py:37``).

    Database reads require a SQL connector (``connectorx``) that is not
    available in this image; constructing one is allowed (schemas may
    round-trip) but loading raises at use time.
    """

    connection_uri: str
    query: str | Path | list[str | Path] | tuple[str | Path, ...]
    partition_on: str | None = None
    partition_num: int | None = None
    protocol: str = "binary"


class Dataset(DatasetBase[pd.DataFrame, Any]):
    """Pandas-backed event-stream ETL dataset (reference ``dataset_polars.py:69``)."""

    PREPROCESSORS = {
        "standard_scaler": StandardScaler,
        "stddev_cutoff": StddevCutoffOutlierDetector,
    }

    # --------------------------------------------------------------- helpers
    @staticmethod
    def get_smallest_valid_int_type(num: int | float) -> np.dtype:
        """Smallest unsigned int dtype holding ``num`` (reference ``:110``).

        Examples:
            >>> Dataset.get_smallest_valid_int_type(num=1)
            dtype('uint8')
            >>> Dataset.get_smallest_valid_int_type(num=2**8-1)
            dtype('uint16')
            >>> Dataset.get_smallest_valid_int_type(num=2**16-1)
            dtype('uint32')
            >>> Dataset.get_smallest_valid_int_type(num=2**32-1)
            dtype('uint64')
            >>> Dataset.get_smallest_valid_int_type(num=2**64-1)
            Traceback (most recent call last):
                ...
            ValueError: Value is too large to be expressed as an int!
        """
        if num >= (2**64) - 1:
            raise ValueError("Value is too large to be expressed as an int!")
        if num >= (2**32) - 1:
            return np.dtype(np.uint64)
        elif num >= (2**16) - 1:
            return np.dtype(np.uint32)
        elif num >= (2**8) - 1:
            return np.dtype(np.uint16)
        return np.dtype(np.uint8)

    # ------------------------------------------------------------ IO backend
    @classmethod
    def _parse_source(cls, src) -> DF_T:
        """Raw source file → frame, row order preserved (the one parse site:
        `_load_input_df` and the sharded build's parse-once handoff share it)."""
        fp = Path(src)
        if fp.suffix == ".csv":
            return pd.read_csv(fp)
        if fp.suffix == ".parquet":
            return pd.read_parquet(fp)
        raise ValueError(f"Can't read dataframe from file of suffix {fp.suffix}")

    @classmethod
    def _read_df(cls, fp: Path, **kwargs) -> DF_T:
        return pd.read_parquet(fp)

    @classmethod
    def _write_df(cls, df: DF_T, fp: Path, **kwargs):
        do_overwrite = kwargs.get("do_overwrite", False)
        fp = Path(fp)
        if not do_overwrite and fp.is_file():
            raise FileExistsError(f"{fp} exists and do_overwrite is {do_overwrite}!")
        df.to_parquet(fp)

    @classmethod
    def _load_input_df(
        cls,
        df,
        columns: list[tuple[str, Any]],
        subject_id_col: str | None = None,
        subject_ids_map: dict[Any, int] | None = None,
        subject_id_dtype: Any | None = None,
        filter_on: dict[str, bool | list[Any]] | None = None,
        subject_id_source_col: str | None = None,
        keep_row_pos: bool = False,
    ):
        """Loads + type-coerces an input df (reference ``dataset_polars.py:147``)."""
        if subject_id_col is None:
            if subject_ids_map is not None:
                raise ValueError("Must not set subject_ids_map if subject_id_col is not set")
            if subject_id_dtype is not None:
                raise ValueError("Must not set subject_id_dtype if subject_id_col is not set")
        else:
            if subject_ids_map is None:
                raise ValueError("Must set subject_ids_map if subject_id_col is set")
            if subject_id_dtype is None:
                raise ValueError("Must set subject_id_dtype if subject_id_col is set")

        if isinstance(df, (str, Path)):
            df = cls._parse_source(df)
        elif isinstance(df, pd.DataFrame):
            df = df.copy()
        elif isinstance(df, Query):
            raise NotImplementedError(
                "Database query inputs require a SQL connector (connectorx), which is not "
                "available in this environment."
            )
        else:
            raise TypeError(f"Input dataframe `df` is of invalid type {type(df)}!")

        if "__row_pos__" in df.columns:
            # A pre-sliced parse-once handoff frame: the parent already
            # stamped each row's position in the ORIGINAL source. Honor it
            # (as the index, so the labels that survive filtering are those
            # positions) instead of slice-local row order — otherwise the
            # sharded merge's position sort would interleave shards wrongly.
            if keep_row_pos:
                df = df.set_index(
                    df["__row_pos__"].to_numpy()
                ).drop(columns="__row_pos__")
            else:
                df = df.drop(columns="__row_pos__")
        elif keep_row_pos:
            # Positions are row order in the loaded source; normalizing the
            # index makes the labels that survive filtering be exactly those
            # positions, identically for every subject shard of the same
            # source.
            df = df.reset_index(drop=True)

        if filter_on:
            df = cls._filter_col_inclusion(df, filter_on)

        out = pd.DataFrame(index=df.index)

        if subject_id_source_col is not None:
            df = df.reset_index(drop=True)
            out = pd.DataFrame(index=df.index)
            out["subject_id"] = np.arange(len(df), dtype=np.int64)
            ID_map = {o: n for n, o in enumerate(df[subject_id_source_col].astype(str))}
        else:
            assert subject_id_col is not None
            key = df[subject_id_col].astype(str)
            keep = key.isin(set(subject_ids_map.keys()))
            df = df[keep]
            key = key[keep]
            out = pd.DataFrame(index=df.index)
            out["subject_id"] = key.map(subject_ids_map).astype(subject_id_dtype)

        for in_col, out_dt in columns:
            col = df[in_col]
            if isinstance(out_dt, (tuple, list)):
                kind, ts_format = out_dt
                if kind != InputDataType.TIMESTAMP:
                    raise ValueError(f"Invalid out data type {out_dt}!")
                out[in_col] = pd.to_datetime(col, format=ts_format, errors="coerce")
            elif out_dt == InputDataType.FLOAT:
                out[in_col] = pd.to_numeric(col, errors="coerce").astype(np.float32)
            elif out_dt == InputDataType.CATEGORICAL:
                out[in_col] = col.astype(str).where(col.notna(), None)
            elif out_dt == InputDataType.BOOLEAN:
                out[in_col] = col.astype("boolean")
            elif out_dt == InputDataType.TIMESTAMP:
                out[in_col] = pd.to_datetime(col)
            else:
                raise ValueError(f"Invalid out data type {out_dt}!")

        if keep_row_pos:
            out["__row_pos__"] = out.index.to_numpy(dtype=np.int64)

        if subject_id_source_col is not None:
            return out.reset_index(drop=True), ID_map
        return out.reset_index(drop=True)

    @classmethod
    def _rename_cols(cls, df: DF_T, to_rename: dict[str, str]) -> DF_T:
        return df.rename(columns=to_rename)

    @classmethod
    def _resolve_ts_col(cls, df: DF_T, ts_col: str | list[str], out_name: str = "timestamp") -> DF_T:
        if isinstance(ts_col, list):
            ts = df[ts_col].min(axis=1)
            df = df.drop(columns=[c for c in ts_col if c != out_name])
            df[out_name] = ts
        else:
            ts = df[ts_col]
            if ts_col != out_name:
                df = df.drop(columns=[ts_col])
            df[out_name] = ts
        return df

    @classmethod
    def _process_events_and_measurements_df(
        cls, df: DF_T, event_type: str, columns_schema: dict[str, tuple[str, Any]]
    ):
        """Splits one input df into events + measurements (reference ``:311``)."""
        df = df[df["timestamp"].notna() & df["subject_id"].notna()].copy()

        if event_type.startswith("COL:"):
            event_type_col = event_type[len("COL:"):]
            df["event_type"] = df[event_type_col].astype(str)
        else:
            df["event_type"] = event_type

        keep_cols = ["timestamp", "subject_id", "event_type"]
        rename = {}
        for in_col, (out_col, _) in columns_schema.items():
            rename[in_col] = out_col
        df = df.rename(columns=rename)
        data_cols = [c for c in dict.fromkeys(rename.values()) if c in df.columns]

        # The sharded build threads a per-row position marker through; it
        # must ride along but never participate in dedup (its uniqueness
        # would defeat it), so dedup always runs on the serial column set.
        marker = ["__row_pos__"] if "__row_pos__" in df.columns else []
        df = (
            df[keep_cols + data_cols + marker]
            .drop_duplicates(subset=keep_cols + data_cols)
            .reset_index(drop=True)
        )
        df["event_id"] = np.arange(len(df), dtype=np.int64)

        events_df = df[["event_id", "subject_id", "timestamp", "event_type"] + marker]

        if data_cols:
            dynamic_measurements_df = df[["event_id"] + data_cols + marker]
        else:
            dynamic_measurements_df = None

        return events_df, dynamic_measurements_df

    @classmethod
    def _split_range_events_df(cls, df: DF_T):
        """Range df → (EQ, start, end) event dfs (reference ``:357``)."""
        df = df[df["start_time"] <= df["end_time"]]

        eq_df = df[df["start_time"] == df["end_time"]]
        ne_df = df[df["start_time"] != df["end_time"]]

        drop_cols = ["start_time", "end_time"]

        eq_out = eq_df.assign(timestamp=eq_df["start_time"]).drop(columns=drop_cols)
        st_out = ne_df.assign(timestamp=ne_df["start_time"]).drop(columns=drop_cols)
        end_out = ne_df.assign(timestamp=ne_df["end_time"]).drop(columns=drop_cols)
        return eq_out, st_out, end_out

    @classmethod
    def _inc_df_col(cls, df: DF_T, col: str, inc_by: int) -> DF_T:
        df = df.copy()
        df[col] = df[col] + inc_by
        return df

    @classmethod
    def _concat_dfs(cls, dfs: list[DF_T]) -> DF_T:
        return pd.concat(dfs, ignore_index=True, sort=False)

    @classmethod
    def _filter_col_inclusion(cls, df: DF_T, col_inclusion_targets: dict[str, bool | Sequence[Any]]) -> DF_T:
        mask = pd.Series(True, index=df.index)
        for col, incl_targets in col_inclusion_targets.items():
            if incl_targets is True:
                mask &= df[col].notna()
            elif incl_targets is False:
                mask &= df[col].isna()
            else:
                mask &= df[col].isin(list(incl_targets))
        return df[mask]

    # ----------------------------------------------------------- validation
    @staticmethod
    def _validate_id_col(id_col: pd.Series) -> tuple[pd.Series, np.dtype]:
        """Unique, non-negative integral ID column → smallest uint dtype (``:502``)."""
        if not id_col.is_unique:
            raise ValueError(f"ID column {id_col.name} is not unique!")
        vals = id_col.to_numpy()
        if np.issubdtype(vals.dtype, np.floating):
            if not (np.all(vals == np.round(vals)) and np.all(vals >= 0)):
                raise ValueError(f"ID column {id_col.name} is not a non-negative integer type!")
        elif np.issubdtype(vals.dtype, np.signedinteger):
            if not np.all(vals >= 0):
                raise ValueError(f"ID column {id_col.name} is not a non-negative integer type!")
        elif np.issubdtype(vals.dtype, np.unsignedinteger):
            pass
        else:
            raise ValueError(f"ID column {id_col.name} is not a non-negative integer type!")

        dt = Dataset.get_smallest_valid_int_type(int(vals.max()) if len(vals) else 0)
        return id_col.astype(dt), dt

    def _validate_initial_df(
        self,
        source_df: DF_T | None,
        id_col_name: str,
        valid_temporality_type: str,
        linked_id_cols: dict[str, np.dtype] | None = None,
    ):
        if source_df is None:
            return None, None
        source_df = source_df.copy()

        if linked_id_cols:
            for id_col, id_col_dt in linked_id_cols.items():
                if id_col not in source_df:
                    raise ValueError(f"Missing mandatory linkage col {id_col}")
                source_df[id_col] = source_df[id_col].astype(id_col_dt)

        if id_col_name not in source_df:
            source_df[id_col_name] = np.arange(len(source_df), dtype=np.int64)

        id_col, id_col_dt = self._validate_id_col(source_df[id_col_name])
        source_df[id_col_name] = id_col

        for col, cfg in self.config.measurement_configs.items():
            if cfg.modality == DataModality.DROPPED:
                continue
            elif cfg.modality == DataModality.UNIVARIATE_REGRESSION:
                cat_col, val_col = None, col
            elif cfg.modality == DataModality.MULTIVARIATE_REGRESSION:
                cat_col, val_col = col, cfg.values_column
            else:
                cat_col, val_col = col, None

            if cat_col is not None and cat_col in source_df:
                if cfg.temporality != valid_temporality_type:
                    raise ValueError(f"Column {cat_col} found in dataframe of wrong temporality")
                c = source_df[cat_col]
                source_df[cat_col] = c.astype(str).where(c.notna(), None)

            if val_col is not None and val_col in source_df:
                if cfg.temporality != valid_temporality_type:
                    raise ValueError(f"Column {val_col} found in dataframe of wrong temporality")
                source_df[val_col] = pd.to_numeric(source_df[val_col], errors="coerce").astype(
                    np.float64
                )

        return source_df, id_col_dt

    def _validate_initial_dfs(self, subjects_df, events_df, dynamic_measurements_df):
        """Reference ``dataset_polars.py:587``."""
        subjects_df, subjects_id_type = self._validate_initial_df(
            subjects_df, "subject_id", TemporalityType.STATIC
        )
        events_df, event_id_type = self._validate_initial_df(
            events_df,
            "event_id",
            TemporalityType.FUNCTIONAL_TIME_DEPENDENT,
            {"subject_id": subjects_id_type} if subjects_df is not None else None,
        )
        if events_df is not None:
            if "event_type" not in events_df:
                raise ValueError("Missing event_type column!")
            if "timestamp" not in events_df or not pd.api.types.is_datetime64_any_dtype(
                events_df["timestamp"]
            ):
                raise ValueError("Malformed timestamp column!")

        if dynamic_measurements_df is not None:
            linked_ids = {}
            if events_df is not None:
                linked_ids["event_id"] = event_id_type
            dynamic_measurements_df, _ = self._validate_initial_df(
                dynamic_measurements_df, "measurement_id", TemporalityType.DYNAMIC, linked_ids
            )

        return subjects_df, events_df, dynamic_measurements_df

    # --------------------------------------------------------- events engine
    def _sort_events(self):
        self.events_df = self.events_df.sort_values(
            ["subject_id", "timestamp"], ascending=True
        ).reset_index(drop=True)

    def _agg_by_time(self):
        """Aggregates events into temporal buckets (reference ``:643``).

        Buckets are datapoint-anchored per subject (polars ``groupby_dynamic``
        with ``start_by="datapoint"``, ``truncate=True``, ``closed="left"``):
        bucket k spans ``[first_ts + k·every, first_ts + (k+1)·every)`` and
        aggregated events take the bucket start as their timestamp. Grouped
        event types are the sorted unique union joined with ``&``.
        """
        event_id_dt = self.events_df["event_id"].dtype
        ev = self.events_df

        if self.config.agg_by_time_scale is None:
            bucket_ts = ev["timestamp"]
        else:
            every = pd.to_timedelta(self.config.agg_by_time_scale)
            first_ts = ev.groupby("subject_id")["timestamp"].transform("min")
            k = ((ev["timestamp"] - first_ts) // every).astype(np.int64)
            bucket_ts = first_ts + k * every

        ev = ev.assign(_bucket=bucket_ts).sort_values(["subject_id", "_bucket"], kind="stable")
        gb = ev.groupby(["subject_id", "_bucket"], sort=False)
        # Rows are bucket-sorted, so group ids in order of appearance are the
        # final (subject, timestamp)-sorted event ids.
        new_ids = gb.ngroup()

        # ETL hot loop #1 (SURVEY §3.1): the reference's polars groupby_dynamic
        # is Rust; a pandas groupby with a Python "&".join lambda per bucket
        # costs ~40µs/event. Vectorized instead: group ids are nondecreasing
        # over the sorted rows, so per-group metadata is a take at group
        # starts, and the sorted-unique event-type union only needs Python
        # for the rare multi-type buckets.
        gid = new_ids.to_numpy()
        g_starts = np.unique(gid, return_index=True)[1]
        pairs = (
            pd.DataFrame({"gid": gid, "et": ev["event_type"].to_numpy()})
            .drop_duplicates()
            .sort_values(["gid", "et"], kind="stable")
        )
        p_gid = pairs["gid"].to_numpy()
        p_et = pairs["et"].to_numpy()
        p_starts = np.unique(p_gid, return_index=True)[1]
        p_counts = np.diff(np.append(p_starts, len(p_gid)))
        event_type = p_et[p_starts].astype(object)
        for i in np.flatnonzero(p_counts > 1):
            event_type[i] = "&".join(p_et[p_starts[i] : p_starts[i] + p_counts[i]])

        grouped = pd.DataFrame(
            {
                "subject_id": ev["subject_id"].to_numpy()[g_starts],
                "timestamp": ev["_bucket"].to_numpy()[g_starts],
                "event_type": event_type,
            }
        )
        max_id = len(grouped)
        id_dt = (
            event_id_dt
            if np.iinfo(event_id_dt).max >= max_id
            else self.get_smallest_valid_int_type(max_id)
        )
        grouped["event_id"] = np.arange(len(grouped), dtype=id_dt)

        # Old event id → new event id mapping for the measurements df.
        old_to_new = pd.Series(new_ids.to_numpy(dtype=id_dt), index=ev["event_id"].to_numpy())

        self.events_df = grouped[["event_id", "subject_id", "timestamp", "event_type"]]

        if self.dynamic_measurements_df is not None:
            dmd = self.dynamic_measurements_df
            self.dynamic_measurements_df = dmd.assign(
                event_id=dmd["event_id"].map(old_to_new)
            )

    def _update_subject_event_properties(self):
        """Reference ``dataset_polars.py:686``."""
        if self.events_df is not None:
            self.event_types = self.events_df["event_type"].value_counts(sort=True).index.tolist()

            n_events = self.events_df["subject_id"].value_counts(sort=False)
            self.n_events_per_subject = {k: int(v) for k, v in n_events.items()}
            self.subject_ids = set(self.n_events_per_subject.keys())

        if self.subjects_df is not None:
            subjects_with_no_events = (
                set(self.subjects_df["subject_id"].tolist()) - set(self.subject_ids)
            )
            for sid in subjects_with_no_events:
                self.n_events_per_subject[sid] = 0
            self.subject_ids = set(self.subject_ids) | subjects_with_no_events

    def _add_time_dependent_measurements(self):
        """Evaluates functors over events (reference ``dataset_polars.py:721``)."""
        join_cols: set[str] = set()
        functors = {}
        for col, cfg in self.config.measurement_configs.items():
            if cfg.temporality != TemporalityType.FUNCTIONAL_TIME_DEPENDENT:
                continue
            functors[col] = cfg.functor
            join_cols.update(cfg.functor.link_static_cols)

        if not functors:
            return

        if join_cols:
            static = self.subjects_df[["subject_id", *join_cols]]
            joined = self.events_df.merge(static, on="subject_id", how="left")
        else:
            joined = self.events_df

        new_cols = {}
        for col, fn in functors.items():
            new_cols[col] = fn.compute(joined["timestamp"], joined)
        self.events_df = self.events_df.assign(**new_cols)

    # -------------------------------------------------------------- numerics
    @staticmethod
    def drop_or_censor_np(
        vals: np.ndarray, bounds: dict[str, np.ndarray | float | None]
    ) -> np.ndarray:
        """Applies drop (→ NaN) and censor (→ clamp) bounds (reference ``:437``)."""
        vals = np.asarray(vals, dtype=np.float64).copy()

        def b(name):
            v = bounds.get(name)
            if v is None:
                return None
            arr = np.asarray(v, dtype=np.float64 if "inclusive" not in name else object)
            return arr

        dlb, dub = b("drop_lower_bound"), b("drop_upper_bound")
        clb, cub = b("censor_lower_bound"), b("censor_upper_bound")
        dlb_inc = bounds.get("drop_lower_bound_inclusive")
        dub_inc = bounds.get("drop_upper_bound_inclusive")

        with np.errstate(invalid="ignore"):
            if dlb is not None:
                inc = np.asarray(dlb_inc, dtype=bool) if dlb_inc is not None else False
                cond = (vals < dlb) | ((vals == dlb) & inc)
                cond &= ~np.isnan(dlb)
                vals[cond] = np.nan
            if dub is not None:
                inc = np.asarray(dub_inc, dtype=bool) if dub_inc is not None else False
                cond = (vals > dub) | ((vals == dub) & inc)
                cond &= ~np.isnan(dub)
                vals[cond] = np.nan
            if clb is not None:
                cond = (vals < clb) & ~np.isnan(clb)
                vals[cond] = np.broadcast_to(clb, vals.shape)[cond]
            if cub is not None:
                cond = (vals > cub) & ~np.isnan(cub)
                vals[cond] = np.broadcast_to(cub, vals.shape)[cond]
        return vals

    def _metadata_as_df(self, measure: str, config: MeasurementConfig) -> tuple[pd.DataFrame, str, str]:
        """Metadata (possibly pre-set) as a key-indexed DataFrame + key/val col names
        (the pandas analog of ``_prep_numerical_source`` ``:744``)."""
        metadata = config.measurement_metadata
        if config.modality == DataModality.UNIVARIATE_REGRESSION:
            key_col, val_col = "const_key", measure
            if metadata is None:
                md = pd.DataFrame(index=pd.Index([measure], name=key_col))
            else:
                md = metadata.to_frame().T
                md.index = pd.Index([measure], name=key_col)
        elif config.modality == DataModality.MULTIVARIATE_REGRESSION:
            key_col, val_col = measure, config.values_column
            md = pd.DataFrame() if metadata is None else metadata.copy()
            md.index.name = key_col
        else:
            raise ValueError(f"Called _metadata_as_df on {config.modality} measure {measure}!")
        # Object dtype throughout: cells hold strings (value types), dicts
        # (fit params), floats (bounds) interchangeably.
        md = md.astype(object)
        return md, key_col, val_col

    def _total_possible_and_observed(self, measure, config, source_df) -> tuple[int, int]:
        """Reference ``dataset_polars.py:779``."""
        if config.temporality == TemporalityType.DYNAMIC:
            num_possible = int(source_df["event_id"].nunique())
            num_non_null = int(source_df.loc[source_df[measure].notna(), "event_id"].nunique())
        else:
            num_possible = len(source_df)
            num_non_null = int(source_df[measure].notna().sum())
        return num_possible, num_non_null

    @staticmethod
    def _ensure_metadata_rows(metadata: pd.DataFrame, keys) -> pd.DataFrame:
        """Adds missing key rows while keeping every column object-dtyped
        (``.loc`` enlargement on an empty frame re-infers float64, which would
        then reject string/dict cells)."""
        new = [k for k in keys if k not in metadata.index]
        if new:
            add = pd.DataFrame(
                index=pd.Index(new, name=metadata.index.name), columns=metadata.columns
            ).astype(object)
            metadata = pd.concat([metadata, add]).astype(object)
        return metadata

    def _fit_measurement_metadata(self, measure, config, source_df) -> pd.DataFrame | pd.Series:
        """Fits numeric metadata: bounds → value types → outliers → normalizer.

        Reference ``dataset_polars.py:899-1035``; see module docstring.
        """
        metadata, key_col, val_col = self._metadata_as_df(measure, config)

        if config.modality == DataModality.UNIVARIATE_REGRESSION:
            work = source_df[[c for c in ("event_id",) if c in source_df] + [measure]].copy()
            work[key_col] = measure
        else:
            cols = [c for c in ("event_id",) if c in source_df] + [measure, val_col]
            work = source_df[cols].copy()

        # 1. Drop keys with too few observations.
        if self.config.min_valid_vocab_element_observations is not None:
            if config.temporality == TemporalityType.DYNAMIC:
                num_possible = int(work["event_id"].nunique())
                per_key = work[work[key_col].notna()].groupby(key_col)["event_id"].nunique()
            else:
                num_possible = len(work)
                per_key = work[work[key_col].notna()].groupby(key_col).size()

            # One cutoff for every key (same N_total), one vectorized compare
            # — no per-key Python (VERDICT r03 weak #6).
            cutoff = count_or_proportion(
                num_possible, self.config.min_valid_vocab_element_observations
            )
            drop_keys = set(per_key[per_key < cutoff].index)
            metadata = self._ensure_metadata_rows(metadata, drop_keys)
            if "value_type" not in metadata.columns:
                metadata["value_type"] = None
            metadata.loc[list(drop_keys), "value_type"] = NumericDataModalitySubtype.DROPPED
            work = work[~work[key_col].isin(drop_keys)]

            if len(work) == 0:
                metadata.index.name = key_col
                if config.modality == DataModality.UNIVARIATE_REGRESSION:
                    assert len(metadata) == 1
                    return metadata.loc[measure]
                return metadata

        work = work[work[key_col].notna() & work[val_col].notna()]

        # 2. Pre-set bound-based drop/censor.
        bound_cols_present = [c for c in BOUND_COLS if c in metadata.columns]
        if bound_cols_present:
            joined = work.join(metadata[bound_cols_present], on=key_col)
            bounds = {c: joined[c].to_numpy() for c in bound_cols_present}
            work = work.assign(**{val_col: self.drop_or_censor_np(joined[val_col].to_numpy(), bounds)})

        work = work[work[val_col].notna()]
        if len(work) == 0:
            return config.measurement_metadata

        # 3. Infer value types (reference ``_add_inferred_val_types`` ``:794``).
        if "value_type" in metadata.columns and len(metadata):
            keys_with_type = set(metadata[metadata["value_type"].notna()].index)
        else:
            keys_with_type = set()
        infer = work[~work[key_col].isin(keys_with_type)]

        vals = infer[val_col]
        if self.config.min_true_float_frequency is not None:
            is_int_per_key = (vals == vals.round(0)).groupby(infer[key_col]).mean() > (
                1 - self.config.min_true_float_frequency
            )
            int_keys = set(is_int_per_key[is_int_per_key].index)
            rounded = vals.round(0).where(infer[key_col].isin(int_keys), vals)
            infer = infer.assign(**{val_col: rounded})
            vals = infer[val_col]
        else:
            int_keys = set()

        # Drop keys with a single unique observed value.
        nunique_per_key = vals.groupby(infer[key_col]).nunique()
        single_keys = set(nunique_per_key[nunique_per_key == 1].index)
        metadata = self._ensure_metadata_rows(metadata, single_keys)
        if "value_type" not in metadata.columns:
            metadata["value_type"] = None
        metadata.loc[list(single_keys), "value_type"] = NumericDataModalitySubtype.DROPPED
        infer = infer[~infer[key_col].isin(single_keys)]
        vals = infer[val_col]

        if self.config.min_unique_numerical_observations is not None:
            stats = vals.groupby(infer[key_col]).agg(["nunique", "size"])
            thresh = self.config.min_unique_numerical_observations
            # Per-key N_total (the key's own size), vectorized over keys.
            # Proportional cutoffs keep count_or_proportion's int(round(...))
            # semantics (numpy round is banker's rounding, like Python's).
            if isinstance(thresh, float):
                cut = (thresh * stats["size"]).round().astype(int)
            else:
                cut = int(thresh)
            is_cat = stats["nunique"] < cut
            cat_keys = set(is_cat[is_cat].index) if len(is_cat) else set()
        else:
            cat_keys = set()

        observed_keys = set(infer[key_col].unique()) | int_keys | cat_keys
        to_set = [k for k in observed_keys if k not in keys_with_type and k not in single_keys]
        metadata = self._ensure_metadata_rows(metadata, to_set)
        if "value_type" not in metadata.columns:
            metadata["value_type"] = None
        for k in to_set:
            if k in int_keys and k in cat_keys:
                vt = NumericDataModalitySubtype.CATEGORICAL_INTEGER
            elif k in cat_keys:
                vt = NumericDataModalitySubtype.CATEGORICAL_FLOAT
            elif k in int_keys:
                vt = NumericDataModalitySubtype.INTEGER
            else:
                vt = NumericDataModalitySubtype.FLOAT
            metadata.loc[k, "value_type"] = vt

        # 4. Round INTEGER keys; keep only INTEGER/FLOAT rows for model fitting.
        value_types = metadata["value_type"]
        work = work.join(value_types.rename("_vt"), on=key_col)
        int_mask = work["_vt"] == NumericDataModalitySubtype.INTEGER
        float_mask = work["_vt"] == NumericDataModalitySubtype.FLOAT
        work = work.assign(
            **{val_col: work[val_col].round(0).where(int_mask, work[val_col])}
        )
        work = work[int_mask | float_mask]
        work = work[work[val_col].notna()]

        # 5. Outlier detector fit (one grouped aggregation over all keys —
        # Preprocessor.fit_grouped; VERDICT r03 weak #6), then filter
        # outliers with vectorized per-row param alignment.
        if self.config.outlier_detector_config is not None:
            M = self._get_preprocessing_model(self.config.outlier_detector_config, for_fit=True)
            params = M.fit_grouped(work[val_col], work[key_col])
            # Sufficient statistics over the SAME rows the fit saw — the
            # persisted state `append_subjects` merges new shards into.
            self._stash_fit_stats(
                "outlier", measure, M.sufficient_stats_grouped(work[val_col], work[key_col])
            )
            if "outlier_model" not in metadata.columns:
                metadata["outlier_model"] = None
            metadata["outlier_model"] = metadata["outlier_model"].astype(object)
            for k, p in params.items():
                metadata.at[k, "outlier_model"] = p

            if len(params):  # no fit keys -> nothing to filter
                params_df = pd.DataFrame(list(params.to_numpy()), index=params.index)
                has_params = work[key_col].isin(params.index).to_numpy()
                per_row = {
                    f: work[key_col].map(params_df[f]).to_numpy(dtype=np.float64)
                    for f in M.params_schema()
                }
                is_outlier = M.predict(work[val_col].to_numpy(), per_row) & has_params
                work = work[~is_outlier]

        # 6. Normalizer fit, same grouped aggregation.
        if self.config.normalizer_config is not None:
            M = self._get_preprocessing_model(self.config.normalizer_config, for_fit=True)
            params = M.fit_grouped(work[val_col], work[key_col])
            self._stash_fit_stats(
                "normalizer", measure, M.sufficient_stats_grouped(work[val_col], work[key_col])
            )
            if "normalizer" not in metadata.columns:
                metadata["normalizer"] = None
            metadata["normalizer"] = metadata["normalizer"].astype(object)
            for k, p in params.items():
                metadata.at[k, "normalizer"] = p

        metadata = metadata.drop(columns=["_vt"], errors="ignore")
        metadata.index.name = key_col if config.modality == DataModality.UNIVARIATE_REGRESSION else measure

        if config.modality == DataModality.UNIVARIATE_REGRESSION:
            assert len(metadata) == 1
            return metadata.loc[measure]
        return metadata

    def _vocab_observations(self, measure, config, source_df) -> pd.Series | None:
        """The vocabulary observation series for one measure — the shared
        naming logic (``__EQ_`` re-keying for categorical numerics) used by
        the from-scratch fit AND the incremental append path, so both count
        the exact same elements."""
        if config.modality == DataModality.MULTIVARIATE_REGRESSION:
            md = config.measurement_metadata
            value_types = md["value_type"]
            keys = source_df[measure]
            vals = source_df[config.values_column]
            vt = keys.map(value_types)
            obs = keys.copy()
            ci = vt == NumericDataModalitySubtype.CATEGORICAL_INTEGER
            cf = vt == NumericDataModalitySubtype.CATEGORICAL_FLOAT
            with np.errstate(invalid="ignore"):
                obs = obs.where(
                    ~ci, keys.astype(str) + "__EQ_" + vals.round(0).astype("Int64").astype(str)
                )
                obs = obs.where(~cf, keys.astype(str) + "__EQ_" + vals.astype(str))
            observations = obs
        elif config.modality == DataModality.UNIVARIATE_REGRESSION:
            vt = config.measurement_metadata["value_type"]
            if vt == NumericDataModalitySubtype.CATEGORICAL_INTEGER:
                observations = (
                    f"{measure}__EQ_" + source_df[measure].round(0).astype("Int64").astype(str)
                )
            elif vt == NumericDataModalitySubtype.CATEGORICAL_FLOAT:
                observations = f"{measure}__EQ_" + source_df[measure].astype(str)
            else:
                return None
        else:
            observations = source_df[measure]

        return observations.dropna()

    def _fit_vocabulary(self, measure, config, source_df) -> Vocabulary | None:
        """Reference ``dataset_polars.py:1038``."""
        observations = self._vocab_observations(measure, config, source_df)
        if observations is None or len(observations) == 0:
            return None

        if config.vocabulary is None:
            value_counts = observations.value_counts()
            self._stash_fit_stats("vocab_totals", measure, int(value_counts.sum()))
            try:
                return Vocabulary(
                    vocabulary=value_counts.index.tolist(),
                    obs_frequencies=value_counts.to_numpy(),
                )
            except AssertionError as e:
                raise AssertionError(f"Failed to build vocabulary for {measure}") from e
        return None

    def _incremental_update_numeric_fit(self, measure, config, source_df, stats_store) -> None:
        """Merges one new shard's observations into the persisted sufficient
        statistics and refreshes outlier/normalizer params for keys that
        received new data (`append_subjects` leg 2).

        Frozen-fit semantics, by design:
        * value types of fitted keys NEVER change (an int key stays int);
        * brand-new keys are NOT type-inferred or fitted — they surface as
          UNK under the frozen unified layout until the next full re-fit;
        * params for updated keys come from `params_from_stats` on the
          merged (count, sum, sumsq) — mean/std may drift last-ulp from a
          from-scratch re-fit on the concatenated data (documented + pinned
          by the append drift test);
        * the new shard's outlier filtering uses the UPDATED thresholds
          (old observations were filtered with the thresholds of their own
          era — the stats sidecar records what each era actually saw).
        """
        metadata, key_col, val_col = self._metadata_as_df(measure, config)
        if "value_type" not in metadata.columns:
            return

        if config.modality == DataModality.UNIVARIATE_REGRESSION:
            work = source_df[[measure]].copy()
            work[key_col] = measure
        else:
            work = source_df[[measure, val_col]].copy()
        work = work[work[key_col].notna() & work[val_col].notna()]
        if len(work) == 0:
            return

        # Pre-set bound-based drop/censor — identical to the full fit.
        bound_cols_present = [c for c in BOUND_COLS if c in metadata.columns]
        if bound_cols_present:
            joined = work.join(metadata[bound_cols_present], on=key_col)
            bounds = {c: joined[c].to_numpy() for c in bound_cols_present}
            work = work.assign(**{val_col: self.drop_or_censor_np(joined[val_col].to_numpy(), bounds)})
        work = work[work[val_col].notna()]

        # Frozen value types: round INTEGER keys, keep INTEGER/FLOAT rows.
        work = work.join(metadata["value_type"].rename("_vt"), on=key_col)
        int_mask = work["_vt"] == NumericDataModalitySubtype.INTEGER
        float_mask = work["_vt"] == NumericDataModalitySubtype.FLOAT
        work = work.assign(**{val_col: work[val_col].round(0).where(int_mask, work[val_col])})
        work = work[int_mask | float_mask]
        work = work[work[val_col].notna()]
        if len(work) == 0:
            return

        def merge_and_refresh(stage: str, model_cfg: dict, param_col: str):
            M = self._get_preprocessing_model(model_cfg, for_fit=True)
            new_stats = M.sufficient_stats_grouped(work[val_col], work[key_col])
            stage_store = stats_store.setdefault(stage, {}).setdefault(measure, {})
            if param_col not in metadata.columns:
                metadata[param_col] = None
            metadata[param_col] = metadata[param_col].astype(object)
            for k, s in new_stats.items():
                merged = M.merge_stats(stage_store.get(str(k)), s)
                stage_store[str(k)] = merged
                metadata.at[k, param_col] = M.params_from_stats(merged)
            return M

        if self.config.outlier_detector_config is not None:
            M = merge_and_refresh("outlier", self.config.outlier_detector_config, "outlier_model")
            om = work.join(metadata["outlier_model"].rename("_om"), on=key_col)["_om"]
            per_row = {
                f: np.asarray(
                    [p[f] if isinstance(p, dict) else np.nan for p in om], dtype=np.float64
                )
                for f in M.params_schema()
            }
            with np.errstate(invalid="ignore"):
                is_outlier = M.predict(work[val_col].to_numpy(), per_row)
            work = work[~is_outlier]

        if self.config.normalizer_config is not None and len(work):
            merge_and_refresh("normalizer", self.config.normalizer_config, "normalizer")

        metadata = metadata.drop(columns=["_vt"], errors="ignore")
        metadata.index.name = (
            key_col if config.modality == DataModality.UNIVARIATE_REGRESSION else measure
        )
        if config.modality == DataModality.UNIVARIATE_REGRESSION:
            config.measurement_metadata = metadata.loc[measure]
        else:
            config.measurement_metadata = metadata

    def _transform_numerical_measurement(self, measure, config, source_df) -> DF_T:
        """Reference ``dataset_polars.py:1100-1196``."""
        metadata, key_col, val_col = self._metadata_as_df(measure, config)
        source_df = source_df.copy()
        if config.modality == DataModality.UNIVARIATE_REGRESSION:
            source_df[key_col] = measure

        joined = source_df.join(metadata, on=key_col, rsuffix="_md")

        bound_cols_present = [c for c in BOUND_COLS if c in metadata.columns]
        vals = source_df[val_col].to_numpy(dtype=np.float64, na_value=np.nan)
        if bound_cols_present:
            bounds = {c: joined[c].to_numpy() for c in bound_cols_present}
            vals = self.drop_or_censor_np(vals, bounds)

        vt = (
            joined["value_type"].to_numpy(dtype=object)
            if "value_type" in joined
            else np.full(len(joined), None, dtype=object)
        )
        keys = source_df[key_col].astype(object).to_numpy()

        ci = vt == NumericDataModalitySubtype.CATEGORICAL_INTEGER
        cf = vt == NumericDataModalitySubtype.CATEGORICAL_FLOAT
        dropped = vt == NumericDataModalitySubtype.DROPPED
        integer = vt == NumericDataModalitySubtype.INTEGER

        with np.errstate(invalid="ignore"):
            int_strs = np.where(
                np.isnan(vals), "-1", np.round(np.nan_to_num(vals, nan=-1.0)).astype(np.int64).astype(str)
            )
        new_keys = keys.copy()
        new_keys[ci] = np.char.add(
            np.char.add(keys[ci].astype(str), "__EQ_"), int_strs[ci]
        )
        new_keys[cf] = np.char.add(
            np.char.add(keys[cf].astype(str), "__EQ_"), vals[cf].astype(str)
        )
        # Parity nuance (reference :1130-1139): for categorical keys, a value
        # NaN-ed by bounds still re-keys (to __EQ_-1 → later UNK), but an
        # *originally missing* value keeps a null key (polars string-concat
        # with null is null) and so is excluded downstream. Pandas folds both
        # into NaN, so restore the distinction from the pre-bounds values.
        orig_missing = np.isnan(source_df[val_col].to_numpy(dtype=np.float64, na_value=np.nan))
        new_keys[(ci | cf) & orig_missing] = None

        new_vals = vals.copy()
        new_vals[ci | cf | dropped] = np.nan
        new_vals[integer] = np.round(new_vals[integer])

        source_df[key_col] = new_keys
        source_df[val_col] = new_vals

        present = ~pd.isna(new_keys) & ~np.isnan(new_vals)

        # Outlier tagging over present rows.
        if self.config.outlier_detector_config is not None:
            M = self._get_preprocessing_model(self.config.outlier_detector_config, for_fit=False)
            inlier_col = f"{measure}_is_inlier"
            om = (
                joined["outlier_model"]
                if "outlier_model" in joined
                else pd.Series([None] * len(joined), index=joined.index)
            )
            per_row = {
                f: np.asarray(
                    [p[f] if isinstance(p, dict) else np.nan for p in om], dtype=np.float64
                )
                for f in M.params_schema()
            }
            with np.errstate(invalid="ignore"):
                is_outlier = M.predict(new_vals, per_row)
            is_inlier = pd.array(~is_outlier, dtype="boolean")
            is_inlier[~present] = pd.NA
            source_df[inlier_col] = is_inlier
            new_vals = np.where(present & is_outlier, np.nan, new_vals)
            source_df[val_col] = new_vals
            present = present & ~is_outlier

        # Normalization over remaining present rows.
        if self.config.normalizer_config is not None:
            M = self._get_preprocessing_model(self.config.normalizer_config, for_fit=False)
            nm = (
                joined["normalizer"]
                if "normalizer" in joined
                else pd.Series([None] * len(joined), index=joined.index)
            )
            per_row = {
                f: np.asarray(
                    [p[f] if isinstance(p, dict) else np.nan for p in nm], dtype=np.float64
                )
                for f in M.params_schema()
            }
            with np.errstate(invalid="ignore"):
                normed = M.predict(new_vals, per_row)
            source_df[val_col] = np.where(present, normed, new_vals)

        return source_df

    def _transform_categorical_measurement(self, measure, config, source_df) -> DF_T:
        """Reference ``dataset_polars.py:1199-1235``."""
        if (config.modality == DataModality.UNIVARIATE_REGRESSION) and (
            config.measurement_metadata["value_type"]
            not in (
                NumericDataModalitySubtype.CATEGORICAL_INTEGER,
                NumericDataModalitySubtype.CATEGORICAL_FLOAT,
            )
        ):
            return source_df

        source_df = source_df.copy()
        vocab = set(config.vocabulary.vocabulary)

        if config.modality == DataModality.MULTIVARIATE_REGRESSION:
            keys = source_df[measure]
            in_vocab = keys.isin(vocab)
            source_df[config.values_column] = source_df[config.values_column].where(
                in_vocab, np.nan
            )
            vocab_el = keys
        elif config.modality == DataModality.UNIVARIATE_REGRESSION:
            vocab_el = source_df["const_key"]
        else:
            vocab_el = source_df[measure]

        new_col = vocab_el.where(vocab_el.isin(vocab) | vocab_el.isna(), "UNK")
        source_df[measure] = new_col
        return source_df

    def _update_attr_df(self, attr: str, id_col: str, df: DF_T, cols_to_update: list[str]):
        """Reference ``dataset_polars.py:1238``: null the target columns, then
        overwrite rows present in ``df`` by ID."""
        old_df = getattr(self, attr).copy()
        old_df = old_df.set_index(id_col)
        new_df = df.set_index(id_col)

        for c in cols_to_update:
            old_df[c] = None
            updates = new_df[c]
            old_df.loc[updates.index, c] = updates.to_numpy()
            if pd.api.types.is_numeric_dtype(new_df[c].dtype):
                old_df[c] = pd.to_numeric(old_df[c], errors="coerce")

        setattr(self, attr, old_df.reset_index())

    # --------------------------------------------------------------- DL cache
    def _melt_df(self, source_df: DF_T, id_cols: Sequence[str], measures: list[str]) -> pd.DataFrame:
        """Long-format (id cols, measurement_index, index, value) rows
        (reference ``dataset_polars.py:1246``)."""
        unified_idxmap = self.unified_vocabulary_idxmap
        meas_idxmap = self.unified_measurements_idxmap

        parts = []
        for m in measures:
            if m == "event_type":
                cfg = None
                modality = DataModality.SINGLE_LABEL_CLASSIFICATION
            else:
                cfg = self.measurement_configs[m]
                modality = cfg.modality

            col = (
                source_df[m]
                if m in source_df
                else pd.Series([None] * len(source_df), index=source_df.index)
            )

            if m in self.measurement_vocabs:
                present = col.notna() & col.isin(set(self.measurement_vocabs[m]))
                index = col.map(unified_idxmap[m])
            else:
                present = col.notna()
                index = pd.Series(unified_idxmap[m][m], index=source_df.index)

            if (modality == DataModality.UNIVARIATE_REGRESSION) and (
                cfg.measurement_metadata["value_type"]
                in (NumericDataModalitySubtype.FLOAT, NumericDataModalitySubtype.INTEGER)
            ):
                value = source_df[m]
            elif modality == DataModality.MULTIVARIATE_REGRESSION:
                value = source_df[cfg.values_column]
            else:
                value = pd.Series(np.nan, index=source_df.index)

            part = source_df.loc[present, list(id_cols)].copy()
            part["measurement_index"] = meas_idxmap[m]
            part["index"] = index[present].to_numpy()
            part["value"] = value[present].to_numpy(dtype=np.float64, na_value=np.nan)
            parts.append(part)

        if not parts:
            return pd.DataFrame(columns=[*id_cols, "measurement_index", "index", "value"])
        return pd.concat(parts, ignore_index=True)

    def build_DL_cached_representation(self, subject_ids=None, do_sort_outputs=False) -> DF_T:
        """Reference ``dataset_polars.py:1305-1389``."""
        subject_measures, event_measures, dynamic_measures = [], ["event_type"], []
        for m in self.unified_measurements_vocab[1:]:
            temporality = self.measurement_configs[m].temporality
            if temporality == TemporalityType.STATIC:
                subject_measures.append(m)
            elif temporality == TemporalityType.FUNCTIONAL_TIME_DEPENDENT:
                event_measures.append(m)
            elif temporality == TemporalityType.DYNAMIC:
                dynamic_measures.append(m)
            else:
                raise ValueError(f"Unknown temporality type {temporality} for {m}")

        # 1. Static data.
        if subject_ids:
            subjects_df = self._filter_col_inclusion(self.subjects_df, {"subject_id": subject_ids})
        else:
            subjects_df = self.subjects_df

        static_long = self._melt_df(subjects_df, ["subject_id"], subject_measures)
        static_data = (
            static_long.groupby("subject_id")
            .agg(
                static_measurement_indices=("measurement_index", list),
                static_indices=("index", list),
            )
            .reset_index()
        )

        # 2+3. Event + dynamic data in long form.
        if subject_ids:
            events_df = self._filter_col_inclusion(self.events_df, {"subject_id": subject_ids})
            event_ids = list(events_df["event_id"])
            dynamic_measurements_df = self._filter_col_inclusion(
                self.dynamic_measurements_df, {"event_id": event_ids}
            )
        else:
            events_df = self.events_df
            dynamic_measurements_df = self.dynamic_measurements_df

        event_long = self._melt_df(events_df, ["subject_id", "timestamp", "event_id"], event_measures)
        dynamic_ids = ["event_id", "measurement_id"] if do_sort_outputs else ["event_id"]
        dynamic_long = self._melt_df(dynamic_measurements_df, dynamic_ids, dynamic_measures)
        if do_sort_outputs:
            dynamic_long = dynamic_long.sort_values(["event_id", "measurement_id"])

        long = pd.concat([event_long, dynamic_long], ignore_index=True, sort=False)

        # Group measurements per event. This is ETL hot loop #3 (SURVEY §3.1);
        # a groupby with Python-lambda aggregators costs ~300µs/event, so the
        # ragged grouping is done with a stable sort + np.unique/np.split
        # instead — identical output (same group order, same within-group
        # order), linear numpy cost. Timestamps/subjects come straight from
        # events_df (every event_id in `long` originates there).
        long = long.sort_values("event_id", kind="stable")
        ev_ids = long["event_id"].to_numpy()
        uniq_ev, ev_starts = np.unique(ev_ids, return_index=True)
        split_at = ev_starts[1:]
        per_event = pd.DataFrame(
            {
                "event_id": uniq_ev,
                "dynamic_measurement_indices": np.split(
                    long["measurement_index"].to_numpy(), split_at
                ),
                "dynamic_indices": np.split(long["index"].to_numpy(), split_at),
                "dynamic_values": np.split(long["value"].to_numpy(), split_at),
            }
        )
        for c in ("dynamic_measurement_indices", "dynamic_indices", "dynamic_values"):
            per_event[c] = per_event[c].map(np.ndarray.tolist)
        ev_meta = events_df.set_index("event_id")[["timestamp", "subject_id"]]
        per_event["timestamp"] = per_event["event_id"].map(ev_meta["timestamp"])
        per_event["subject_id"] = per_event["event_id"].map(ev_meta["subject_id"])

        per_event = per_event.sort_values(["subject_id", "timestamp"]).reset_index(drop=True)

        # Same vectorized grouping per subject: rows are sorted by
        # (subject_id, timestamp), so each subject's first timestamp is its
        # min and slices preserve time order.
        sub_ids = per_event["subject_id"].to_numpy()
        uniq_sub, sub_starts = np.unique(sub_ids, return_index=True)
        counts = np.diff(np.append(sub_starts, len(sub_ids)))
        ts = per_event["timestamp"].to_numpy(dtype="datetime64[ns]")
        start_ts = ts[sub_starts]
        rel_min = (ts - np.repeat(start_ts, counts)) / np.timedelta64(1, "m")
        sub_split = sub_starts[1:]
        event_data = pd.DataFrame(
            {
                "subject_id": uniq_sub,
                "start_time": start_ts,
                "time": [a.tolist() for a in np.split(rel_min, sub_split)],
                "dynamic_measurement_indices": np.split(
                    per_event["dynamic_measurement_indices"].to_numpy(), sub_split
                ),
                "dynamic_indices": np.split(per_event["dynamic_indices"].to_numpy(), sub_split),
                "dynamic_values": np.split(per_event["dynamic_values"].to_numpy(), sub_split),
            }
        )
        for c in ("dynamic_measurement_indices", "dynamic_indices", "dynamic_values"):
            event_data[c] = event_data[c].map(np.ndarray.tolist)

        out = static_data.merge(event_data, on="subject_id", how="outer")
        if do_sort_outputs:
            out = out.sort_values("subject_id").reset_index(drop=True)
        return out

    def _denormalize(self, events_df: DF_T, col: str) -> DF_T:
        """Reference ``dataset_polars.py:1391``."""
        if self.config.normalizer_config is None:
            return events_df
        elif self.config.normalizer_config["cls"] != "standard_scaler":
            raise ValueError(f"De-normalizing from {self.config.normalizer_config} not yet supported!")

        config = self.measurement_configs[col]
        if config.modality != DataModality.UNIVARIATE_REGRESSION:
            raise ValueError(f"De-normalizing {config.modality} is not currently supported.")

        normalizer_params = config.measurement_metadata["normalizer"]
        events_df = events_df.copy()
        events_df[col] = (
            events_df[col] * normalizer_params["std_"] + normalizer_params["mean_"]
        )
        return events_df


