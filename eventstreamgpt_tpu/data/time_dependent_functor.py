"""Functional time-dependent measurements (age, time-of-day).

TPU-native rebuild of ``/root/reference/EventStream/data/time_dependent_functor.py``.
Each functor is dual-implemented:

1. ``compute(events_df, static_df)`` — a **pandas** evaluation used during ETL
   (the reference uses a Polars expression, ``time_dependent_functor.py:62``;
   Polars is unavailable in this environment, and ETL is host-side anyway).
2. ``update_from_prior_timepoint`` — a pure **jnp** update used inside the
   jitted generation loop (the reference uses torch,
   ``time_dependent_functor.py:149,262``). Static Python scalars (vocab
   indices, normalizer params) are baked in at trace time; array arguments are
   traced, so the update is ``lax.scan``-safe.
"""

from __future__ import annotations

import abc
from datetime import datetime
from typing import Any

import jax.numpy as jnp
import numpy as np
import pandas as pd

from .types import DataModality
from .vocabulary import Vocabulary

MINUTES_PER_YEAR = 60 * 24 * 365.25


class TimeDependentFunctor(abc.ABC):
    """ABC for measurements that are analytic functions of time + static data.

    Reference contract: ``time_dependent_functor.py:23-113``.
    """

    OUTPUT_MODALITY: DataModality = DataModality.DROPPED

    def __init__(self, **fn_params):
        for k, val in fn_params.items():
            setattr(self, k, val)
        self.link_static_cols: list[str] = []

    def to_dict(self) -> dict[str, Any]:
        return {
            "class": self.__class__.__name__,
            "params": {k: v for k, v in vars(self).items() if k != "link_static_cols"},
        }

    @classmethod
    def from_dict(cls, in_dict: dict[str, Any]) -> "TimeDependentFunctor":
        return cls(**in_dict["params"])

    def __eq__(self, other) -> bool:
        return isinstance(other, TimeDependentFunctor) and self.to_dict() == other.to_dict()

    @abc.abstractmethod
    def compute(self, timestamps: pd.Series, static_row_df: pd.DataFrame) -> pd.Series:
        """Evaluates the functor for each event.

        Args:
            timestamps: Event timestamps (datetime series), aligned with
                ``static_row_df`` rows (one static row per event).
            static_row_df: Per-event static data (already joined onto events).

        Returns:
            A series of measurement values (float or categorical string).
        """
        raise NotImplementedError("Must be implemented in subclass!")

    @abc.abstractmethod
    def update_from_prior_timepoint(
        self,
        prior_indices,
        prior_values,
        new_delta,
        new_time,
        vocab: Vocabulary | None,
        measurement_metadata: pd.Series | None,
    ):
        """jnp update used in the generation loop; see class docstring."""
        raise NotImplementedError("Must be implemented in subclass!")


class AgeFunctor(TimeDependentFunctor):
    """The subject's age, in fixed-length (365.25-day) years.

    Reference: ``time_dependent_functor.py:116-225``.

    Examples:
        >>> import pandas as pd
        >>> from datetime import datetime
        >>> f = AgeFunctor(dob_col="birth_date")
        >>> ts = pd.Series([datetime(2020, 1, 1), datetime(2021, 1, 1)])
        >>> st = pd.DataFrame({"birth_date": [datetime(1990, 1, 1), datetime(1995, 1, 1)]})
        >>> [round(v, 4) for v in f.compute(ts, st).tolist()]
        [29.9986, 26.0014]
    """

    OUTPUT_MODALITY: DataModality = DataModality.UNIVARIATE_REGRESSION

    def __init__(self, dob_col: str):
        self.dob_col = dob_col
        self.link_static_cols = [dob_col]

    def compute(self, timestamps: pd.Series, static_row_df: pd.DataFrame) -> pd.Series:
        dob = pd.to_datetime(static_row_df[self.dob_col])
        ts = pd.to_datetime(timestamps)
        delta_s = (ts.values - dob.values).astype("timedelta64[us]").astype(np.int64) / 1e6
        return pd.Series(delta_s / (60 * 60 * 24 * 365.25), index=timestamps.index)

    def update_from_prior_timepoint(
        self,
        prior_indices,
        prior_values,
        new_delta,
        new_time,
        vocab: Vocabulary | None,
        measurement_metadata: pd.Series | None,
    ):
        """De-normalizes the prior age, advances it by ``new_delta``, re-normalizes.

        Out-of-bounds new ages (per the fit outlier thresholds) become NaN,
        matching the reference's torch update
        (``time_dependent_functor.py:149-225``).
        """
        mean = float(measurement_metadata["normalizer"]["mean_"])
        std = float(measurement_metadata["normalizer"]["std_"])
        thresh_large = measurement_metadata["outlier_model"]["thresh_large_"]
        thresh_small = measurement_metadata["outlier_model"]["thresh_small_"]

        prior_age = prior_values * std + mean
        new_age = prior_age + new_delta / MINUTES_PER_YEAR

        oob = jnp.zeros_like(new_age, dtype=bool)
        if thresh_large is not None and not pd.isna(thresh_large):
            oob = oob | (new_age > float(thresh_large))
        if thresh_small is not None and not pd.isna(thresh_small):
            oob = oob | (new_age < float(thresh_small))
        new_age = jnp.where(oob, jnp.nan, new_age)

        return prior_indices, (new_age - mean) / std


class TimeOfDayFunctor(TimeDependentFunctor):
    """Categorizes the event time into EARLY_AM / AM / PM / LATE_PM.

    Reference: ``time_dependent_functor.py:228-332``. Buckets: hour < 6 →
    EARLY_AM, < 12 → AM, < 21 → PM, else LATE_PM.

    Examples:
        >>> import pandas as pd
        >>> from datetime import datetime
        >>> f = TimeOfDayFunctor()
        >>> ts = pd.Series([datetime(2020, 1, 1, 0), datetime(2020, 1, 1, 6),
        ...                 datetime(2020, 1, 1, 12), datetime(2020, 1, 1, 23)])
        >>> f.compute(ts, None).tolist()
        ['EARLY_AM', 'AM', 'PM', 'LATE_PM']
    """

    OUTPUT_MODALITY: DataModality = DataModality.SINGLE_LABEL_CLASSIFICATION

    def compute(self, timestamps: pd.Series, static_row_df: pd.DataFrame | None) -> pd.Series:
        hours = pd.to_datetime(timestamps).dt.hour
        return pd.Series(
            np.select(
                [hours < 6, hours < 12, hours < 21],
                ["EARLY_AM", "AM", "PM"],
                default="LATE_PM",
            ),
            index=timestamps.index,
        )

    def update_from_prior_timepoint(
        self,
        prior_indices,
        prior_values,
        new_delta,
        new_time,
        vocab: Vocabulary | None,
        measurement_metadata: pd.Series | None,
    ):
        """Maps new absolute times (minutes since epoch) to time-of-day indices."""
        hrs_local_at_midnight_epoch = datetime(1970, 1, 1).timestamp() / 60 / 60

        new_hour_utc = new_time / 60
        new_hour_local = (new_hour_utc - hrs_local_at_midnight_epoch) % 24

        early_am = vocab.idxmap.get("EARLY_AM", 0)
        am = vocab.idxmap.get("AM", 0)
        pm = vocab.idxmap.get("PM", 0)
        late_pm = vocab.idxmap.get("LATE_PM", 0)

        new_indices = jnp.where(
            new_hour_local < 6,
            early_am,
            jnp.where(new_hour_local < 12, am, jnp.where(new_hour_local < 21, pm, late_pm)),
        )
        return new_indices, jnp.nan * prior_values
