"""Device-resident dataset: CSR arrays in HBM, collation on device.

Why this exists (round-5 headline fix): the padded-epoch metric of record was
~7x below the measured device step rate, and a feed-path breakdown
(``scripts/probe_feed.py``) showed the sink is neither host collation
(~8 ms/batch) nor compute (~13.5 ms/step) but the per-batch ``device_put``
of ~2.6 MB through a ~80 MB/s, ~90 ms-RTT tunnel (~30+ ms/batch, serialized
on the data plane). Caching *host* collation — the obvious fix — would not
touch that wire cost.

The TPU-native design instead moves the whole dataset to the device once and
re-derives every batch there:

* `DeviceDataset` uploads the `JaxDataset`'s flattened CSR arrays
  (values + offsets; tens of MB for tutorial-scale cohorts) to HBM a single
  time per training run.
* Each step sends only a `BatchPlan` — subject indices, crop starts, and the
  fill-row validity mask, ~100 bytes — and a jitted collate kernel rebuilds
  the static-shape ``(B, L, M)`` batch with pure gathers on the TPU, where
  gathers at these shapes cost microseconds.
* The plan stream (`JaxDataset.plan_batches`) consumes the identical rng
  stream host collation uses, so device- and host-collated epochs are
  bit-identical (tested) and the ``skip_batches`` mid-epoch-resume contract
  is unchanged.

The reference's analog is the DataLoader worker pool re-padding per item per
epoch (``/root/reference/EventStream/data/pytorch_dataset.py:568-683``);
there is no reference analog of device-side collation — it is only possible
because the CSR redesign made collation a fixed set of dense gathers.

Light per-subject fields (``subject_id``, ``start_time``, subsequence
bounds, ``stream_labels``) stay host-computed from the plan: they are O(B)
bytes, and keeping them on the host preserves bit-exact parity with host
collation for free.
"""

from __future__ import annotations

from functools import partial
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .config import SeqPaddingSide
from .jax_dataset import BatchPlan, JaxDataset
from .types import EventStreamBatch

__all__ = ["DeviceDataset", "padded_collate_kernel", "packed_collate_kernel"]

# Dense per-event tables shipped to HBM, in kernel argument order. The CSR
# representation the host uses is re-materialized into dense ``(n_events, M)``
# tables at upload time: collation then needs NO element-level gathers — TPU
# gathers at (B, L, M) element granularity measured ~1.6 ms each on this
# chip, while the dynamic-slice/row-gather formulations over dense tables run
# the whole collate in ~0.25 ms (scripts/probe_feed.py). The dense tables
# cost ``M / avg_fill`` more HBM than CSR (~1.6x on the bench cohort); both
# representations stop fitting HBM at roughly the same cohort scale, which is
# what the residency gate is for.
_RESIDENT_FIELDS = (
    "subject_event_offsets",  # (n_subjects + 1,) int32
    "time_delta",  # (L + n_events + L,) float32, zero-padded both sides
    "dynamic_indices",  # (L + n_events + L, M) int32, 0 in empty slots
    "dynamic_measurement_indices",  # same layout
    "dynamic_values",  # same layout, float32, 0 where unobserved
    "dynamic_values_obs",  # same layout, bool: slot filled AND observed
    "static_indices",  # (n_subjects, S) int32, 0 in empty slots
    "static_measurement_indices",  # (n_subjects, S) int32
)


def padded_collate_kernel(
    arrays: dict,
    subject_indices,
    starts,
    valid,
    *,
    L: int,
    M: int,
    S: int,
    pad_right: bool,
    do_static: bool,
) -> dict:
    """The on-device mirror of ``JaxDataset._collate_with_starts``.

    Every padded row is a CONTIGUOUS range of the event axis (``ev_lo + start
    + pos``), so the whole collate is a batch of ``lax.dynamic_slice``s over
    the dense per-event tables — no element gathers. The tables carry ``L``
    zero rows on both ends so slice starts stay in range for left padding
    (start can reach ``ev_lo - L``) and slice ends for short subjects
    (overrun reads zeros, which the event mask then zeroes anyway — matching
    host collation bit-for-bit).

    The fill-row convention also matches the host path: ``valid`` blanks only
    the two masks; sliced payloads of fill rows are left in place, exactly as
    host collation leaves them after its post-collation blanking.
    """
    offsets = arrays["subject_event_offsets"]
    ev_lo = offsets[subject_indices]
    seq_lens = offsets[subject_indices + 1] - ev_lo
    kept = jnp.minimum(seq_lens, L)

    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    if pad_right:
        event_mask = pos < kept[:, None]
        slice_starts = L + ev_lo + starts
    else:
        pad = L - kept
        event_mask = pos >= pad[:, None]
        slice_starts = L + ev_lo + starts - pad
    out = _slice_event_payload(arrays, slice_starts, event_mask, L)
    out["event_mask"] = event_mask & valid[:, None]
    out["dynamic_values_mask"] = out["dynamic_values_mask"] & valid[:, None, None]

    if do_static:
        # (B, S) row gathers over small dense per-subject tables.
        out["static_indices"] = arrays["static_indices"][subject_indices]
        out["static_measurement_indices"] = arrays["static_measurement_indices"][
            subject_indices
        ]
    return out


def _slice_event_payload(arrays: dict, slice_starts, event_mask, L: int) -> dict:
    """Contiguous per-row slices of the dense tables + host-parity masking."""

    def row(s):
        return tuple(
            jax.lax.dynamic_slice_in_dim(arrays[k], s, L)
            for k in (
                "time_delta",
                "dynamic_indices",
                "dynamic_measurement_indices",
                "dynamic_values",
                "dynamic_values_obs",
            )
        )

    td, di, dm, dv, dobs = jax.vmap(row)(slice_starts)
    return _mask_event_payload(td, di, dm, dv, dobs, event_mask)


def _mask_event_payload(td, di, dm, dv, dobs, event_mask) -> dict:
    """Applies the host path's exact zeroing: positions outside the event
    mask are zero in every payload field (empty slots inside valid events are
    already zero in the dense tables, as host ``np.where`` leaves them)."""
    m3 = event_mask[..., None]
    return {
        "time_delta": jnp.where(event_mask, td, 0.0),
        "dynamic_indices": jnp.where(m3, di, 0),
        "dynamic_measurement_indices": jnp.where(m3, dm, 0),
        "dynamic_values": jnp.where(m3, dv, 0.0),
        "dynamic_values_mask": dobs & m3,
    }


def packed_collate_kernel(
    arrays: dict, event_ids, event_mask, *, L_PAD: int, M: int
) -> dict:
    """On-device payload fetch for packed rows.

    Packed rows interleave several subjects, so the event axis is not one
    contiguous range; instead each ``(b, l)`` position row-gathers an M-wide
    row of the dense tables (~30x faster than element gathers on this chip).
    The host still runs the (cheap, sequential) first-fit packing and sends
    the ``(B, L)`` event-id/segment plan; the ``(B, L, M)`` payload — ~97% of
    the batch bytes — never crosses the wire.

    ``L_PAD`` is the dense tables' front zero-pad (the dataset's
    ``max_seq_len``); masked positions carry event id 0, which lands on a
    real row after the offset but is zeroed by the mask, as on the host.
    """
    eids = event_ids + L_PAD
    td = arrays["time_delta"][eids]
    di = arrays["dynamic_indices"][eids]
    dm = arrays["dynamic_measurement_indices"][eids]
    dv = arrays["dynamic_values"][eids]
    dobs = arrays["dynamic_values_obs"][eids]
    out = _mask_event_payload(td, di, dm, dv, dobs, event_mask)
    out["event_mask"] = event_mask
    return out


class DeviceDataset:
    """HBM-resident view of a `JaxDataset` with on-device collation.

    Args:
        dataset: the host dataset to mirror. Its CSR index arrays must be
            int32-narrow (`JaxDataset` shrinks them whenever sizes permit; a
            >2B-element cohort would not fit HBM anyway).
        mesh: optional device mesh. Resident arrays are replicated over it;
            collated batches come out sharded batch-dim-over-``data`` (and,
            with ``context_parallel``, event-dim-over-``context``) — the
            layouts ``shard_batch`` / ``shard_batch_cp`` would have produced.
        context_parallel: emit ring-attention input layout.
    """

    def __init__(
        self,
        dataset: JaxDataset,
        mesh: Mesh | None = None,
        context_parallel: bool = False,
    ):
        self.dataset = dataset
        self.mesh = mesh
        self.context_parallel = context_parallel
        d = dataset.data
        for name in ("subject_event_offsets", "event_data_offsets", "dynamic_indices"):
            if getattr(d, name).dtype == np.int64:
                raise ValueError(
                    f"JaxDataset.data.{name} did not narrow to int32 "
                    "(>2^31 elements); such a cohort cannot be device-resident."
                )

        host = self._build_dense_tables()
        self.nbytes = sum(a.nbytes for a in host.values())
        if mesh is not None:
            replicated = NamedSharding(mesh, P())
            self.arrays = {k: jax.device_put(v, replicated) for k, v in host.items()}
        else:
            self.arrays = {k: jnp.asarray(v) for k, v in host.items()}
        self._kernel_cache: dict = {}

    # Default HBM budget for auto-residency: conservative against a 16 GB
    # v5e chip that also holds params, optimizer state, and activations.
    DEFAULT_BUDGET_BYTES = 2 * 1024**3

    @staticmethod
    def estimate_nbytes(dataset: JaxDataset) -> int:
        """Predicted HBM footprint of residency, without building anything.

        Lets callers (``training.train`` in ``device_resident_data='auto'``
        mode) gate residency on an HBM budget before paying the host-side
        dense-table build.
        """
        n_rows = len(dataset.data.time_delta) + 2 * dataset.max_seq_len
        per_row = 4 + dataset.max_n_dynamic * (4 + 4 + 4 + 1)
        static = 2 * 4 * dataset.max_n_static * max(dataset.data.n_subjects, 1)
        return n_rows * per_row + static + dataset.data.subject_event_offsets.nbytes

    @classmethod
    def try_create(
        cls,
        dataset: JaxDataset,
        mesh: Mesh | None = None,
        context_parallel: bool = False,
        max_bytes: int | None = None,
    ) -> "DeviceDataset | None":
        """`DeviceDataset` when residency is eligible, else ``None``.

        The single auto-residency gate every harness shares: single-process
        runs only, estimated tables within ``max_bytes`` (default
        `DEFAULT_BUDGET_BYTES`), CSR arrays int32-narrow. Callers fall back
        to host collation on ``None``.
        """
        if jax.process_count() != 1:
            return None
        if cls.estimate_nbytes(dataset) > (max_bytes or cls.DEFAULT_BUDGET_BYTES):
            return None
        try:
            return cls(dataset, mesh=mesh, context_parallel=context_parallel)
        except ValueError:
            return None

    def _build_dense_tables(self) -> dict:
        """CSR → dense per-event tables (see `_RESIDENT_FIELDS` for why)."""
        ds = self.dataset
        d = ds.data
        L = ds.max_seq_len
        M = ds.max_n_dynamic
        n_events = len(d.time_delta)

        off = np.asarray(d.event_data_offsets, np.int64)
        counts = np.diff(off)
        # Clip slots beyond M (possible when config.max_n_dynamic caps below
        # the data's true max — host collation drops them the same way).
        slot = np.arange(off[-1], dtype=np.int64) - np.repeat(off[:-1], counts)
        keep = slot < M
        rows = np.repeat(np.arange(n_events), counts)[keep] + L
        cols = slot[keep]

        def dense(src, dtype):
            t = np.zeros((n_events + 2 * L, M), dtype)
            t[rows, cols] = np.asarray(src)[keep]
            return t

        td = np.zeros(n_events + 2 * L, np.float32)
        td[L : L + n_events] = d.time_delta

        S = ds.max_n_static
        n_subjects = d.n_subjects
        st_idx = np.zeros((max(n_subjects, 1), S), np.int32)
        st_meas = np.zeros((max(n_subjects, 1), S), np.int32)
        if ds.do_produce_static_data and n_subjects:
            st_off = np.asarray(d.static_offsets, np.int64)
            st_counts = np.diff(st_off)
            st_slot = np.arange(st_off[-1], dtype=np.int64) - np.repeat(st_off[:-1], st_counts)
            st_keep = st_slot < S
            st_rows = np.repeat(np.arange(n_subjects), st_counts)[st_keep]
            st_idx[st_rows, st_slot[st_keep]] = np.asarray(d.static_indices)[st_keep]
            st_meas[st_rows, st_slot[st_keep]] = np.asarray(d.static_measurement_indices)[
                st_keep
            ]

        return {
            "subject_event_offsets": np.asarray(d.subject_event_offsets, np.int32),
            "time_delta": td,
            "dynamic_indices": dense(d.dynamic_indices, np.int32),
            "dynamic_measurement_indices": dense(d.dynamic_measurement_indices, np.int32),
            "dynamic_values": dense(
                np.where(d.dynamic_values_observed, d.dynamic_values, 0.0), np.float32
            ),
            "dynamic_values_obs": dense(d.dynamic_values_observed, bool),
            "static_indices": st_idx,
            "static_measurement_indices": st_meas,
        }

    # ----------------------------------------------------------- shardings
    # Fields whose dim 1 is the event (sequence) axis — sharded over the
    # ``context`` mesh axis in ring-attention layouts (mirrors
    # ``training.pretrain._CP_SEQ_FIELDS`` for the heavy fields).
    _SEQ_FIELDS = frozenset(
        {
            "event_mask",
            "time_delta",
            "dynamic_indices",
            "dynamic_measurement_indices",
            "dynamic_values",
            "dynamic_values_mask",
            "segment_ids",
        }
    )

    def _out_sharding(self, ndim: int, seq_axis: bool):
        if self.mesh is None:
            return None
        if seq_axis and self.context_parallel and "context" in self.mesh.shape:
            return NamedSharding(self.mesh, P("data", "context", *([None] * (ndim - 2))))
        return NamedSharding(self.mesh, P("data", *([None] * (ndim - 1))))

    def constrain_fields(self, fields: dict) -> dict:
        """Applies mesh sharding constraints to collate outputs inside jit.

        The in-jit counterpart of the ``out_shardings`` the standalone
        kernels use — scanned train programs
        (``training.make_chunked_train_step``) call this so batches
        materialize in the same layout ``shard_batch`` / ``shard_batch_cp``
        would have produced.
        """
        if self.mesh is None:
            return fields
        return {
            k: jax.lax.with_sharding_constraint(
                v, self._out_sharding(v.ndim, k in self._SEQ_FIELDS)
            )
            for k, v in fields.items()
        }

    def padded_kernel(self):
        """The un-jitted padded collate kernel, bound to this dataset's
        shapes — the single source of the config→kernel mapping."""
        ds = self.dataset
        return partial(
            padded_collate_kernel,
            L=ds.max_seq_len,
            M=ds.max_n_dynamic,
            S=ds.max_n_static,
            pad_right=ds.seq_padding_side == SeqPaddingSide.RIGHT,
            do_static=ds.do_produce_static_data,
        )

    def packed_kernel(self):
        """The un-jitted packed collate kernel bound to this dataset."""
        return partial(
            packed_collate_kernel,
            L_PAD=self.dataset.max_seq_len,
            M=self.dataset.max_n_dynamic,
        )

    def _jit_kernel(self, key: tuple, kern) -> "jax.stages.Wrapped":
        if key not in self._kernel_cache:
            out_shardings = None
            if self.mesh is not None:
                # Shapes don't matter for sharding specs — evaluate on ndim.
                ndims = {
                    "event_mask": 2,
                    "time_delta": 2,
                    "dynamic_indices": 3,
                    "dynamic_measurement_indices": 3,
                    "dynamic_values": 3,
                    "dynamic_values_mask": 3,
                }
                if key[0] == "padded" and self.dataset.do_produce_static_data:
                    ndims["static_indices"] = 2
                    ndims["static_measurement_indices"] = 2
                out_shardings = {
                    k: self._out_sharding(nd, k in self._SEQ_FIELDS)
                    for k, nd in ndims.items()
                }
            self._kernel_cache[key] = jax.jit(kern, out_shardings=out_shardings)
        return self._kernel_cache[key]

    def _jit_padded(self, B: int):
        return self._jit_kernel(("padded", B), self.padded_kernel())

    def _jit_packed(self, B: int, L: int):
        return self._jit_kernel(("packed", B, L), self.packed_kernel())

    # ----------------------------------------------------------- collation
    def collate(self, plan: BatchPlan) -> EventStreamBatch:
        """Collates one `BatchPlan` on device → static-shape batch.

        Heavy ``(B, L[, M])`` fields are device arrays; light per-subject
        fields ride along as host arrays (transferred with the step's
        arguments, O(B) bytes).
        """
        ds = self.dataset
        B = len(plan.subject_indices)
        fields = self._jit_padded(B)(
            self.arrays, plan.subject_indices, plan.starts, plan.valid_mask
        )

        if ds.config.do_include_start_time_min:
            if plan.start_time is None:
                raise ValueError(
                    "do_include_start_time_min is set but the plan carries no "
                    "start_time — regenerate plans from this config."
                )
            fields["start_time"] = plan.start_time
        if ds.config.do_include_subsequence_indices:
            # int32, matching host _collate_with_starts (bit-identical incl.
            # dtype; the parity tests assert dtypes too).
            fields["start_idx"] = plan.starts
            fields["end_idx"] = plan.starts + plan.kept
        if ds.config.do_include_subject_id:
            fields["subject_id"] = np.asarray(
                [ds.subject_ids[i] for i in plan.subject_indices], dtype=np.int64
            )
        if ds.has_task:
            fields["stream_labels"] = {
                t: np.asarray(
                    ds.stream_labels[t][plan.subject_indices],
                    dtype=np.int64
                    if ds.task_types[t] == "multi_class_classification"
                    else np.float32,
                )
                for t in ds.tasks
            }
        fields["valid_mask"] = plan.valid_mask
        return EventStreamBatch(**fields)

    def batches(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed: int | None = None,
        drop_last: bool | None = None,
        skip_batches: int = 0,
        with_counts: bool = False,
    ) -> Iterator:
        """Device-collated mirror of `JaxDataset.batches` (same rng stream).

        With ``with_counts=True`` yields ``(batch, n_events)`` — the event
        count comes from the plan, so throughput accounting never syncs the
        device.
        """
        for plan in self.dataset.plan_batches(
            batch_size,
            shuffle=shuffle,
            seed=seed,
            drop_last=drop_last,
            skip_batches=skip_batches,
        ):
            b = self.collate(plan)
            yield (b, plan.n_events) if with_counts else b

    def packed_batches(
        self,
        batch_size: int,
        seq_len: int | None = None,
        shuffle: bool = True,
        seed: int | None = None,
        with_counts: bool = False,
    ) -> Iterator:
        """Device-collated mirror of `JaxDataset.packed_batches`.

        Packing order and row contents are identical to the host path (same
        ``_pack_rows`` call, same rng); the host ships the ``(B, L)``
        event-id plan (~KBs) and the device gathers the ``(B, L, M)``
        payload.
        """
        ds = self.dataset
        L = seq_len or ds.max_seq_len
        n = len(ds)
        rng = np.random.default_rng(seed)
        order = rng.permutation(n) if shuffle else np.arange(n)
        rows = ds._pack_rows(L, rng, order)

        for lo_idx in range(0, len(rows), batch_size):
            chunk = rows[lo_idx : lo_idx + batch_size]
            kernel = self._jit_packed(len(chunk), L)
            event_ids, seg, mask, n_events = ds.packed_row_plan(chunk, L)
            fields = kernel(self.arrays, event_ids.astype(np.int32), mask)
            batch = EventStreamBatch(
                segment_ids=seg, valid_mask=np.ones(len(chunk), dtype=bool), **fields
            )
            yield (batch, n_events) if with_counts else batch

    # ------------------------------------------------------- chunked plans
    def plan_chunks(
        self,
        batch_size: int,
        chunk_steps: int,
        shuffle: bool = True,
        seed: int | None = None,
        drop_last: bool | None = None,
        skip_batches: int = 0,
    ) -> Iterator[tuple[dict, int]]:
        """Yields ``(plans, n_events)`` with ``chunk_steps`` stacked plans.

        ``plans`` maps plan fields to ``(k, B)`` numpy arrays — the payload a
        scanned multi-step train program (``training.make_chunked_train_step``)
        consumes to run ``k`` collate+step iterations in ONE device program,
        amortizing per-dispatch tunnel overhead ``k``-fold. The final chunk
        may be shorter (``k < chunk_steps``); callers get one extra
        compilation for it at most.
        """
        buf: list[BatchPlan] = []
        for plan in self.dataset.plan_batches(
            batch_size,
            shuffle=shuffle,
            seed=seed,
            drop_last=drop_last,
            skip_batches=skip_batches,
        ):
            buf.append(plan)
            if len(buf) == chunk_steps:
                yield self._stack_plans(buf)
                buf = []
        if buf:
            yield self._stack_plans(buf)

    @staticmethod
    def _stack_plans(plans: list[BatchPlan]) -> tuple[dict, int]:
        return (
            {
                "subject_indices": np.stack([p.subject_indices for p in plans]),
                "starts": np.stack([p.starts for p in plans]),
                "valid_mask": np.stack([p.valid_mask for p in plans]),
            },
            sum(p.n_events for p in plans),
        )

    def packed_plan_chunks(
        self,
        batch_size: int,
        chunk_steps: int,
        seq_len: int | None = None,
        shuffle: bool = True,
        seed: int | None = None,
        skip_batches: int = 0,
        drop_short: bool = True,
    ) -> Iterator[tuple[dict, int]]:
        """Packed-row analog of `plan_chunks`: ``(k, B, L)`` event-id plans.

        ``drop_short`` skips the final under-filled packed batch (it would
        retrigger compilation — the training loop drops it too).
        """
        ds = self.dataset
        L = seq_len or ds.max_seq_len
        n = len(ds)
        rng = np.random.default_rng(seed)
        order = rng.permutation(n) if shuffle else np.arange(n)
        rows = ds._pack_rows(L, rng, order)

        buf: list[tuple] = []
        n_ev_buf = 0
        n_seen = 0
        for lo_idx in range(0, len(rows), batch_size):
            chunk = rows[lo_idx : lo_idx + batch_size]
            if drop_short and len(chunk) < batch_size:
                continue
            n_seen += 1
            if n_seen <= skip_batches:
                continue
            event_ids, seg, mask, n_events = self.dataset.packed_row_plan(chunk, L)
            buf.append((event_ids.astype(np.int32), seg.astype(np.int32), mask))
            n_ev_buf += n_events
            if len(buf) == chunk_steps:
                yield self._stack_packed(buf), n_ev_buf
                buf, n_ev_buf = [], 0
        if buf:
            yield self._stack_packed(buf), n_ev_buf

    @staticmethod
    def _stack_packed(buf: list[tuple]) -> dict:
        return {
            "event_ids": np.stack([e for e, _, _ in buf]),
            "segment_ids": np.stack([s for _, s, _ in buf]),
            "event_mask": np.stack([m for _, _, m in buf]),
        }
