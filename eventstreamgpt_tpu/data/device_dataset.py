"""Device-resident dataset: CSR arrays in HBM, collation on device.

Why this exists (round-5 headline fix): the padded-epoch metric of record was
~7x below the measured device step rate, and a feed-path breakdown
(``scripts/probe_feed.py``) showed the sink is neither host collation
(~8 ms/batch) nor compute (~13.5 ms/step) but the per-batch ``device_put``
of ~2.6 MB through a ~80 MB/s, ~90 ms-RTT tunnel (~30+ ms/batch, serialized
on the data plane). Caching *host* collation — the obvious fix — would not
touch that wire cost.

The TPU-native design instead moves the whole dataset to the device once and
re-derives every batch there:

* `DeviceDataset` uploads the `JaxDataset`'s flattened CSR arrays
  (values + offsets; tens of MB for tutorial-scale cohorts) to HBM a single
  time per training run.
* Each step sends only a `BatchPlan` — subject indices, crop starts, and the
  fill-row validity mask, ~100 bytes — and a jitted collate kernel rebuilds
  the static-shape ``(B, L, M)`` batch with pure gathers on the TPU, where
  gathers at these shapes cost microseconds.
* The plan stream (`JaxDataset.plan_batches`) consumes the identical rng
  stream host collation uses, so device- and host-collated epochs are
  bit-identical (tested) and the ``skip_batches`` mid-epoch-resume contract
  is unchanged.

The reference's analog is the DataLoader worker pool re-padding per item per
epoch (``/root/reference/EventStream/data/pytorch_dataset.py:568-683``);
there is no reference analog of device-side collation — it is only possible
because the CSR redesign made collation a fixed set of dense gathers.

Light per-subject fields (``subject_id``, ``start_time``, subsequence
bounds, ``stream_labels``) stay host-computed from the plan: they are O(B)
bytes, and keeping them on the host preserves bit-exact parity with host
collation for free.

Multi-host pods (``data_shards > 1``): the dense tables become ONE global
``jax.Array`` laid out over the mesh's ``data`` axis — subjects are
partitioned into per-shard pools (`JaxDataset.subject_shards`), each shard's
tables are stacked along a leading shard axis sharded ``P("data")``, and
each process materializes/uploads ONLY the shards its addressable devices
own (``jax.make_array_from_callback``). The plan stream
(`JaxDataset.plan_batches(n_shards=K)`) deals every batch shard-major —
``batch_size / K`` rows per pool — from one shared rng stream, so all
processes derive identical plans and every data-axis shard collates its own
rows with purely LOCAL gathers (a vmap over the shard axis; GSPMD inserts no
collectives). The ``skip_batches`` rng-exact resume contract carries over
unchanged. Single-process stays on the replicated layout and the historical
global plan stream, bit-for-bit.
"""

from __future__ import annotations

from functools import partial
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .config import SeqPaddingSide
from .jax_dataset import BatchPlan, JaxDataset
from .types import EventStreamBatch

__all__ = ["DeviceDataset", "padded_collate_kernel", "packed_collate_kernel"]

# Dense per-event tables shipped to HBM, in kernel argument order. The CSR
# representation the host uses is re-materialized into dense ``(n_events, M)``
# tables at upload time: collation then needs NO element-level gathers — TPU
# gathers at (B, L, M) element granularity measured ~1.6 ms each on this
# chip, while the dynamic-slice/row-gather formulations over dense tables run
# the whole collate in ~0.25 ms (scripts/probe_feed.py). The dense tables
# cost ``M / avg_fill`` more HBM than CSR (~1.6x on the bench cohort); both
# representations stop fitting HBM at roughly the same cohort scale, which is
# what the residency gate is for.
_RESIDENT_FIELDS = (
    "subject_event_offsets",  # (n_subjects + 1,) int32
    "time_delta",  # (L + n_events + L,) float32, zero-padded both sides
    "dynamic_indices",  # (L + n_events + L, M) int32, 0 in empty slots
    "dynamic_measurement_indices",  # same layout
    "dynamic_values",  # same layout, float32, 0 where unobserved
    "dynamic_values_obs",  # same layout, bool: slot filled AND observed
    "static_indices",  # (n_subjects, S) int32, 0 in empty slots
    "static_measurement_indices",  # (n_subjects, S) int32
)


def padded_collate_kernel(
    arrays: dict,
    subject_indices,
    starts,
    valid,
    *,
    L: int,
    M: int,
    S: int,
    pad_right: bool,
    do_static: bool,
) -> dict:
    """The on-device mirror of ``JaxDataset._collate_with_starts``.

    Every padded row is a CONTIGUOUS range of the event axis (``ev_lo + start
    + pos``), so the whole collate is a batch of ``lax.dynamic_slice``s over
    the dense per-event tables — no element gathers. The tables carry ``L``
    zero rows on both ends so slice starts stay in range for left padding
    (start can reach ``ev_lo - L``) and slice ends for short subjects
    (overrun reads zeros, which the event mask then zeroes anyway — matching
    host collation bit-for-bit).

    The fill-row convention also matches the host path: ``valid`` blanks only
    the two masks; sliced payloads of fill rows are left in place, exactly as
    host collation leaves them after its post-collation blanking.
    """
    offsets = arrays["subject_event_offsets"]
    ev_lo = offsets[subject_indices]
    seq_lens = offsets[subject_indices + 1] - ev_lo
    kept = jnp.minimum(seq_lens, L)

    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    if pad_right:
        event_mask = pos < kept[:, None]
        slice_starts = L + ev_lo + starts
    else:
        pad = L - kept
        event_mask = pos >= pad[:, None]
        slice_starts = L + ev_lo + starts - pad
    out = _slice_event_payload(arrays, slice_starts, event_mask, L)
    out["event_mask"] = event_mask & valid[:, None]
    out["dynamic_values_mask"] = out["dynamic_values_mask"] & valid[:, None, None]

    if do_static:
        # (B, S) row gathers over small dense per-subject tables.
        out["static_indices"] = arrays["static_indices"][subject_indices]
        out["static_measurement_indices"] = arrays["static_measurement_indices"][
            subject_indices
        ]
    return out


def _slice_event_payload(arrays: dict, slice_starts, event_mask, L: int) -> dict:
    """Contiguous per-row slices of the dense tables + host-parity masking."""

    def row(s):
        return tuple(
            jax.lax.dynamic_slice_in_dim(arrays[k], s, L)
            for k in (
                "time_delta",
                "dynamic_indices",
                "dynamic_measurement_indices",
                "dynamic_values",
                "dynamic_values_obs",
            )
        )

    td, di, dm, dv, dobs = jax.vmap(row)(slice_starts)
    return _mask_event_payload(td, di, dm, dv, dobs, event_mask)


def _mask_event_payload(td, di, dm, dv, dobs, event_mask) -> dict:
    """Applies the host path's exact zeroing: positions outside the event
    mask are zero in every payload field (empty slots inside valid events are
    already zero in the dense tables, as host ``np.where`` leaves them)."""
    m3 = event_mask[..., None]
    return {
        "time_delta": jnp.where(event_mask, td, 0.0),
        "dynamic_indices": jnp.where(m3, di, 0),
        "dynamic_measurement_indices": jnp.where(m3, dm, 0),
        "dynamic_values": jnp.where(m3, dv, 0.0),
        "dynamic_values_mask": dobs & m3,
    }


def packed_collate_kernel(
    arrays: dict, event_ids, event_mask, *, L_PAD: int, M: int
) -> dict:
    """On-device payload fetch for packed rows.

    Packed rows interleave several subjects, so the event axis is not one
    contiguous range; instead each ``(b, l)`` position row-gathers an M-wide
    row of the dense tables (~30x faster than element gathers on this chip).
    The host still runs the (cheap, sequential) first-fit packing and sends
    the ``(B, L)`` event-id/segment plan; the ``(B, L, M)`` payload — ~97% of
    the batch bytes — never crosses the wire.

    ``L_PAD`` is the dense tables' front zero-pad (the dataset's
    ``max_seq_len``); masked positions carry event id 0, which lands on a
    real row after the offset but is zeroed by the mask, as on the host.
    """
    eids = event_ids + L_PAD
    td = arrays["time_delta"][eids]
    di = arrays["dynamic_indices"][eids]
    dm = arrays["dynamic_measurement_indices"][eids]
    dv = arrays["dynamic_values"][eids]
    dobs = arrays["dynamic_values_obs"][eids]
    out = _mask_event_payload(td, di, dm, dv, dobs, event_mask)
    out["event_mask"] = event_mask
    return out


def _dense_pre_sliced(src, rows, cols, keep, n_rows: int, M: int, dtype) -> np.ndarray:
    """Dense-table scatter for a source array already sliced to the range."""
    t = np.zeros((n_rows, M), dtype)
    t[rows, cols] = np.asarray(src)[keep]
    return t


class DeviceDataset:
    """HBM-resident view of a `JaxDataset` with on-device collation.

    Args:
        dataset: the host dataset to mirror. Its CSR index arrays must be
            int32-narrow (`JaxDataset` shrinks them whenever sizes permit; a
            >2B-element cohort would not fit HBM anyway).
        mesh: optional device mesh. Resident arrays are replicated over it
            (``data_shards == 1``) or sharded over its ``data`` axis;
            collated batches come out sharded batch-dim-over-``data`` (and,
            with ``context_parallel``, event-dim-over-``context``) — the
            layouts ``shard_batch`` / ``shard_batch_cp`` would have produced.
        context_parallel: emit ring-attention input layout.
        data_shards: 1 for the replicated single-process layout; the mesh's
            ``data``-axis size for the sharded (pod) layout, where each
            data-axis device holds one subject-pool's tables and each process
            uploads only its addressable shards. Use `create` / `try_create`
            to pick this from the topology.
    """

    def __init__(
        self,
        dataset: JaxDataset,
        mesh: Mesh | None = None,
        context_parallel: bool = False,
        data_shards: int = 1,
    ):
        self.dataset = dataset
        self.mesh = mesh
        self.context_parallel = context_parallel
        self.data_shards = int(data_shards)
        d = dataset.data
        for name in ("subject_event_offsets", "event_data_offsets", "dynamic_indices"):
            if getattr(d, name).dtype == np.int64:
                raise ValueError(
                    f"JaxDataset.data.{name} did not narrow to int32 "
                    "(>2^31 elements); such a cohort cannot be device-resident."
                )
        # One host-side finiteness pass over the CSR arrays (values are
        # stored observed-masked, so any non-finite IS an observed value).
        # This is what lets resident zero-shot prompts skip the per-batch
        # device-side NaN readback without weakening the guarantee: a
        # poisoned DL cache fails loudly here, at table-build time.
        if not np.isfinite(d.time_delta).all():
            raise ValueError(
                "non-finite time_delta in the DL cache; refusing to build "
                "device-resident tables (resident batches skip per-batch NaN "
                "validation on the strength of this check)."
            )
        if not np.isfinite(d.dynamic_values).all():
            raise ValueError(
                "non-finite observed dynamic_values in the DL cache; refusing "
                "to build device-resident tables (resident batches skip "
                "per-batch NaN validation on the strength of this check)."
            )

        if self.data_shards > 1:
            if mesh is None or "data" not in mesh.shape:
                raise ValueError(
                    "data_shards > 1 requires a mesh with a 'data' axis to lay "
                    "the shard axis over."
                )
            if int(mesh.shape["data"]) != self.data_shards:
                raise ValueError(
                    f"data_shards ({self.data_shards}) must equal the mesh's "
                    f"'data' axis size ({int(mesh.shape['data'])}): the sharded "
                    "layout places exactly one subject-pool per data-axis row."
                )
            self.arrays = self._build_and_upload_sharded()
        else:
            if jax.process_count() > 1:
                raise ValueError(
                    "replicated resident tables cannot span processes — on "
                    f"{jax.process_count()} processes use the sharded layout "
                    "(DeviceDataset.create picks data_shards from the mesh), "
                    "or set trainer_config.device_resident_data='auto'/false."
                )
            host = self._build_dense_tables()
            self.nbytes = sum(a.nbytes for a in host.values())
            if mesh is not None:
                replicated = NamedSharding(mesh, P())
                self.arrays = {k: jax.device_put(v, replicated) for k, v in host.items()}
            else:
                self.arrays = {k: jnp.asarray(v) for k, v in host.items()}
        self._kernel_cache: dict = {}

    # Default HBM budget for auto-residency: conservative against a 16 GB
    # v5e chip that also holds params, optimizer state, and activations.
    DEFAULT_BUDGET_BYTES = 2 * 1024**3

    @staticmethod
    def estimate_nbytes(dataset: JaxDataset) -> int:
        """Predicted HBM footprint of residency, without building anything.

        Lets callers (``training.train`` in ``device_resident_data='auto'``
        mode) gate residency on an HBM budget before paying the host-side
        dense-table build.
        """
        n_rows = len(dataset.data.time_delta) + 2 * dataset.max_seq_len
        per_row = 4 + dataset.max_n_dynamic * (4 + 4 + 4 + 1)
        static = 2 * 4 * dataset.max_n_static * max(dataset.data.n_subjects, 1)
        return n_rows * per_row + static + dataset.data.subject_event_offsets.nbytes

    @staticmethod
    def estimate_sharded_nbytes(dataset: JaxDataset, n_shards: int) -> int:
        """Predicted GLOBAL footprint of the sharded layout, without building.

        Not ``estimate_nbytes``: every shard pads to the largest pool (plus
        its own 2L slice guard), so a skewed cohort — one subject holding
        most events — can cost up to ``n_shards ×`` the unsharded estimate.
        Raises ``ValueError`` when the cohort cannot shard ``n_shards`` ways.
        """
        bounds = dataset.subject_shards(n_shards)
        ev = np.asarray(dataset.data.subject_event_offsets, np.int64)[bounds]
        n_rows = int(np.diff(ev).max()) + 2 * dataset.max_seq_len
        n_subj_rows = int(np.diff(bounds).max())
        per_row = 4 + dataset.max_n_dynamic * (4 + 4 + 4 + 1)
        static = 2 * 4 * dataset.max_n_static * n_subj_rows
        return n_shards * (n_rows * per_row + static + (n_subj_rows + 1) * 4 + 8)

    @classmethod
    def create(
        cls,
        dataset: JaxDataset,
        mesh: Mesh | None = None,
        context_parallel: bool = False,
        batch_sizes: tuple[int, ...] = (),
    ) -> "DeviceDataset":
        """Topology-aware constructor (no budget gate).

        Single-process → the replicated layout. Multi-process → the sharded
        layout over the mesh's ``data`` axis (one subject pool per data-axis
        row; each process uploads only its addressable shards). Raises
        ``ValueError`` with an actionable message on unsupported topologies
        (no mesh / no ``data`` axis / fewer subjects than shards) instead of
        silently misbehaving — this is the path explicit
        ``device_resident_data: true`` configs take. ``batch_sizes`` (every
        size the caller will stream, train AND eval) is validated against
        the shard count HERE, at startup — the alternative is a full epoch
        of pod time before the first dealt eval stream raises.
        """
        if jax.process_count() == 1:
            return cls(dataset, mesh=mesh, context_parallel=context_parallel)
        if mesh is None or "data" not in mesh.shape:
            raise ValueError(
                f"device-resident data on {jax.process_count()} processes "
                "requires a device mesh with a 'data' axis (the dense tables "
                "shard over it); this caller passed "
                f"mesh={'None' if mesh is None else tuple(mesh.shape.items())}."
            )
        n_shards = int(mesh.shape["data"])
        bad = [int(b) for b in batch_sizes if int(b) % n_shards]
        if bad:
            raise ValueError(
                f"device-resident data shards the plan stream {n_shards} ways, so "
                f"every streamed batch size must be divisible by {n_shards}; got "
                f"{bad}. Adjust the batch/validation batch size or disable "
                "device_resident_data."
            )
        return cls(
            dataset,
            mesh=mesh,
            context_parallel=context_parallel,
            data_shards=n_shards,
        )

    @classmethod
    def try_create(
        cls,
        dataset: JaxDataset,
        mesh: Mesh | None = None,
        context_parallel: bool = False,
        max_bytes: int | None = None,
        batch_sizes: tuple[int, ...] = (),
    ) -> "DeviceDataset | None":
        """`DeviceDataset` when residency is eligible, else ``None``.

        The single auto-residency gate every harness shares: estimated tables
        within ``max_bytes`` (default `DEFAULT_BUDGET_BYTES`), CSR arrays
        int32-narrow, finite values. Multi-process topologies take the
        sharded layout (each process uploads ~1/P of the tables, so the
        budget applies to the per-process share) and additionally need a
        mesh with a ``data`` axis, plus every batch size the caller will
        stream (``batch_sizes``) divisible by the shard count — checked HERE
        so an ineligible eval batch size falls back to host collation at
        startup instead of killing the run at its first dealt stream.
        Callers fall back to host collation on ``None``.
        """
        budget = max_bytes or cls.DEFAULT_BUDGET_BYTES
        n_proc = jax.process_count()
        if n_proc == 1:
            if cls.estimate_nbytes(dataset) > budget:
                return None
            try:
                return cls(dataset, mesh=mesh, context_parallel=context_parallel)
            except ValueError:
                return None
        if mesh is None or "data" not in mesh.shape:
            return None
        if any(int(b) % int(mesh.shape["data"]) for b in batch_sizes):
            return None
        try:
            # The sharded estimate, not estimate_nbytes // K: shards pad to
            # the largest pool, so skewed cohorts cost more than total/K —
            # the budget must bound what a process will actually upload.
            global_bytes = cls.estimate_sharded_nbytes(dataset, int(mesh.shape["data"]))
            if global_bytes // n_proc > budget:
                return None
            return cls.create(dataset, mesh=mesh, context_parallel=context_parallel)
        except ValueError:
            return None

    def _build_dense_tables(self) -> dict:
        """CSR → dense per-event tables (see `_RESIDENT_FIELDS` for why)."""
        return self._dense_tables_for_subjects(0, self.dataset.data.n_subjects)

    def _dense_tables_for_subjects(
        self,
        s_lo: int,
        s_hi: int,
        n_rows_pad: int | None = None,
        n_subj_pad: int | None = None,
    ) -> dict:
        """Dense tables for the subject range ``[s_lo, s_hi)``, with all
        offsets LOCAL to the range (event row 0 = the range's first event).

        The full-range call is the replicated layout; the sharded layout
        builds one range per shard, padded (``n_rows_pad`` event rows,
        ``n_subj_pad`` subject rows) so every shard stacks to one uniform
        global array. Padding subject rows repeat the final offset (zero-
        length subjects that dealing never references); padding event rows
        are zeros, indistinguishable from the slice-guard pad.
        """
        ds = self.dataset
        d = ds.data
        L = ds.max_seq_len
        M = ds.max_n_dynamic
        ev_lo = int(d.subject_event_offsets[s_lo])
        ev_hi = int(d.subject_event_offsets[s_hi])
        n_events = ev_hi - ev_lo
        n_rows = n_rows_pad if n_rows_pad is not None else n_events + 2 * L

        off = np.asarray(d.event_data_offsets[ev_lo : ev_hi + 1], np.int64)
        counts = np.diff(off)
        el_lo, el_hi = int(off[0]), int(off[-1])
        # Clip slots beyond M (possible when config.max_n_dynamic caps below
        # the data's true max — host collation drops them the same way).
        slot = np.arange(el_hi - el_lo, dtype=np.int64) - np.repeat(off[:-1] - el_lo, counts)
        keep = slot < M
        rows = np.repeat(np.arange(n_events), counts)[keep] + L
        cols = slot[keep]

        def dense(src, dtype):
            return _dense_pre_sliced(src[el_lo:el_hi], rows, cols, keep, n_rows, M, dtype)

        td = np.zeros(n_rows, np.float32)
        td[L : L + n_events] = d.time_delta[ev_lo:ev_hi]

        S = ds.max_n_static
        n_subjects = s_hi - s_lo
        n_subj_rows = n_subj_pad if n_subj_pad is not None else max(n_subjects, 1)
        st_idx = np.zeros((n_subj_rows, S), np.int32)
        st_meas = np.zeros((n_subj_rows, S), np.int32)
        if ds.do_produce_static_data and n_subjects:
            st_off = np.asarray(d.static_offsets[s_lo : s_hi + 1], np.int64)
            st_counts = np.diff(st_off)
            st_el_lo, st_el_hi = int(st_off[0]), int(st_off[-1])
            st_slot = np.arange(st_el_hi - st_el_lo, dtype=np.int64) - np.repeat(
                st_off[:-1] - st_el_lo, st_counts
            )
            st_keep = st_slot < S
            st_rows = np.repeat(np.arange(n_subjects), st_counts)[st_keep]
            st_idx[st_rows, st_slot[st_keep]] = np.asarray(
                d.static_indices[st_el_lo:st_el_hi]
            )[st_keep]
            st_meas[st_rows, st_slot[st_keep]] = np.asarray(
                d.static_measurement_indices[st_el_lo:st_el_hi]
            )[st_keep]

        offsets = np.asarray(d.subject_event_offsets[s_lo : s_hi + 1], np.int64) - ev_lo
        if n_subj_pad is not None and len(offsets) < n_subj_pad + 1:
            offsets = np.concatenate(
                [offsets, np.full(n_subj_pad + 1 - len(offsets), offsets[-1], np.int64)]
            )

        vals = np.where(
            d.dynamic_values_observed[el_lo:el_hi], d.dynamic_values[el_lo:el_hi], 0.0
        )
        return {
            "subject_event_offsets": offsets.astype(np.int32),
            "time_delta": td,
            "dynamic_indices": dense(d.dynamic_indices, np.int32),
            "dynamic_measurement_indices": dense(d.dynamic_measurement_indices, np.int32),
            "dynamic_values": _dense_pre_sliced(vals, rows, cols, keep, n_rows, M, np.float32),
            "dynamic_values_obs": dense(d.dynamic_values_observed, bool),
            "static_indices": st_idx,
            "static_measurement_indices": st_meas,
        }

    # ----------------------------------------------------- sharded layout
    def _shard_layout(self) -> tuple[np.ndarray, int, int]:
        """``(bounds, n_rows, n_subj_rows)`` for the stacked shard tables.

        ``bounds`` are the subject-pool boundaries; every shard's event table
        pads to ``n_rows`` (largest shard + the 2L slice guard) and its
        subject axes to ``n_subj_rows`` so the stack is one uniform global
        array.
        """
        ds = self.dataset
        bounds = ds.subject_shards(self.data_shards)
        ev = np.asarray(ds.data.subject_event_offsets, np.int64)[bounds]
        n_rows = int(np.diff(ev).max()) + 2 * ds.max_seq_len
        n_subj_rows = int(np.diff(bounds).max())
        return bounds, n_rows, n_subj_rows

    def _build_and_upload_sharded(self) -> dict:
        """Stacked per-shard tables as global arrays sharded over ``data``.

        Each process materializes ONLY the shards its addressable devices
        hold (``jax.make_array_from_callback`` requests exactly those global
        slices), which is what makes pod-scale residency per-host-bounded:
        host RAM and HBM per process scale with its subject share, not the
        cohort.
        """
        ds = self.dataset
        K = self.data_shards
        bounds, n_rows, n_subj_rows = self._shard_layout()
        ev_base = np.asarray(ds.data.subject_event_offsets, np.int64)[bounds[:-1]]

        shard_cache: dict[int, dict] = {}

        def shard_tables(k: int) -> dict:
            if k not in shard_cache:
                shard_cache[k] = self._dense_tables_for_subjects(
                    int(bounds[k]), int(bounds[k + 1]),
                    n_rows_pad=n_rows, n_subj_pad=n_subj_rows,
                )
            return shard_cache[k]

        M, S = ds.max_n_dynamic, ds.max_n_static
        field_shapes: dict[str, tuple] = {
            "subject_event_offsets": (K, n_subj_rows + 1),
            "time_delta": (K, n_rows),
            "dynamic_indices": (K, n_rows, M),
            "dynamic_measurement_indices": (K, n_rows, M),
            "dynamic_values": (K, n_rows, M),
            "dynamic_values_obs": (K, n_rows, M),
            "static_indices": (K, n_subj_rows, S),
            "static_measurement_indices": (K, n_subj_rows, S),
        }
        bases = {
            "shard_subject_base": bounds[:-1].astype(np.int32),
            "shard_event_base": ev_base.astype(np.int32),
        }

        arrays: dict = {}
        self.nbytes = 0
        for name, shape in field_shapes.items():
            sharding = NamedSharding(self.mesh, P("data", *([None] * (len(shape) - 1))))

            def cb(index, name=name):
                ks = range(*index[0].indices(K))
                return np.stack([shard_tables(k)[name] for k in ks])

            arrays[name] = jax.make_array_from_callback(shape, sharding, cb)
            self.nbytes += int(np.prod(shape)) * arrays[name].dtype.itemsize
        for name, host in bases.items():
            sharding = NamedSharding(self.mesh, P("data"))
            arrays[name] = jax.make_array_from_callback(
                (K,), sharding, lambda index, host=host: host[index[0]]
            )
            self.nbytes += host.nbytes
        shard_cache.clear()
        return arrays

    # ----------------------------------------------------------- shardings
    # Fields whose dim 1 is the event (sequence) axis — sharded over the
    # ``context`` mesh axis in ring-attention layouts (mirrors
    # ``training.pretrain._CP_SEQ_FIELDS`` for the heavy fields).
    _SEQ_FIELDS = frozenset(
        {
            "event_mask",
            "time_delta",
            "dynamic_indices",
            "dynamic_measurement_indices",
            "dynamic_values",
            "dynamic_values_mask",
            "segment_ids",
        }
    )

    def _out_sharding(self, ndim: int, seq_axis: bool):
        if self.mesh is None:
            return None
        if seq_axis and self.context_parallel and "context" in self.mesh.shape:
            return NamedSharding(self.mesh, P("data", "context", *([None] * (ndim - 2))))
        return NamedSharding(self.mesh, P("data", *([None] * (ndim - 1))))

    def constrain_fields(self, fields: dict) -> dict:
        """Applies mesh sharding constraints to collate outputs inside jit.

        The in-jit counterpart of the ``out_shardings`` the standalone
        kernels use — scanned train programs
        (``training.make_chunked_train_step``) call this so batches
        materialize in the same layout ``shard_batch`` / ``shard_batch_cp``
        would have produced.
        """
        if self.mesh is None:
            return fields
        return {
            k: jax.lax.with_sharding_constraint(
                v, self._out_sharding(v.ndim, k in self._SEQ_FIELDS)
            )
            for k, v in fields.items()
        }

    def padded_kernel(self):
        """The un-jitted padded collate kernel, bound to this dataset's
        shapes — the single source of the config→kernel mapping.

        Sharded layouts wrap the same per-shard kernel in a vmap over the
        shard axis: plan indices (global, dealt shard-major) rebase to each
        pool's local subject axis, every lane gathers ONLY its own table
        shard (no cross-shard collectives under GSPMD — the batched gather's
        leading axis matches the tables' ``data`` sharding), and the outputs
        merge back to the plain ``(B, ...)`` global batch the train step
        already consumes.
        """
        ds = self.dataset
        base = partial(
            padded_collate_kernel,
            L=ds.max_seq_len,
            M=ds.max_n_dynamic,
            S=ds.max_n_static,
            pad_right=ds.seq_padding_side == SeqPaddingSide.RIGHT,
            do_static=ds.do_produce_static_data,
        )
        if self.data_shards == 1:
            return base
        K = self.data_shards

        def sharded(arrays, subject_indices, starts, valid):
            B = subject_indices.shape[0]
            bl = B // K
            tables = {k: arrays[k] for k in _RESIDENT_FIELDS}

            def lane(tab, subj_base, si, st, va):
                return base(tab, si - subj_base, st, va)

            out = jax.vmap(lane)(
                tables,
                arrays["shard_subject_base"],
                jnp.asarray(subject_indices).reshape(K, bl),
                jnp.asarray(starts).reshape(K, bl),
                jnp.asarray(valid).reshape(K, bl),
            )
            return {k: v.reshape((B,) + v.shape[2:]) for k, v in out.items()}

        return sharded

    def packed_kernel(self):
        """The un-jitted packed collate kernel bound to this dataset.

        Sharded layouts mirror `padded_kernel`: global event ids rebase to
        each shard's local event axis (masked slots carry global id 0, which
        goes negative after rebasing — clamped to 0 and zeroed by the mask,
        exactly the host convention) and the row gathers stay shard-local.
        """
        base = partial(
            packed_collate_kernel,
            L_PAD=self.dataset.max_seq_len,
            M=self.dataset.max_n_dynamic,
        )
        if self.data_shards == 1:
            return base
        K = self.data_shards

        def sharded(arrays, event_ids, event_mask):
            B, L = event_ids.shape
            bl = B // K
            tables = {k: arrays[k] for k in _RESIDENT_FIELDS}

            def lane(tab, ev_base, eids, mask):
                return base(tab, jnp.maximum(eids - ev_base, 0), mask)

            out = jax.vmap(lane)(
                tables,
                arrays["shard_event_base"],
                jnp.asarray(event_ids).reshape(K, bl, L),
                jnp.asarray(event_mask).reshape(K, bl, L),
            )
            return {k: v.reshape((B,) + v.shape[2:]) for k, v in out.items()}

        return sharded

    def _jit_kernel(self, key: tuple, kern) -> "jax.stages.Wrapped":
        if key not in self._kernel_cache:
            out_shardings = None
            if self.mesh is not None:
                # Shapes don't matter for sharding specs — evaluate on ndim.
                ndims = {
                    "event_mask": 2,
                    "time_delta": 2,
                    "dynamic_indices": 3,
                    "dynamic_measurement_indices": 3,
                    "dynamic_values": 3,
                    "dynamic_values_mask": 3,
                }
                if key[0] == "padded" and self.dataset.do_produce_static_data:
                    ndims["static_indices"] = 2
                    ndims["static_measurement_indices"] = 2
                out_shardings = {
                    k: self._out_sharding(nd, k in self._SEQ_FIELDS)
                    for k, nd in ndims.items()
                }
            self._kernel_cache[key] = jax.jit(kern, out_shardings=out_shardings)
        return self._kernel_cache[key]

    def _jit_padded(self, B: int):
        return self._jit_kernel(("padded", B), self.padded_kernel())

    def _jit_packed(self, B: int, L: int):
        return self._jit_kernel(("packed", B, L), self.packed_kernel())

    # ----------------------------------------------------------- collation
    def collate(self, plan: BatchPlan) -> EventStreamBatch:
        """Collates one `BatchPlan` on device → static-shape batch.

        Heavy ``(B, L[, M])`` fields are device arrays; light per-subject
        fields ride along as host arrays (transferred with the step's
        arguments, O(B) bytes).
        """
        ds = self.dataset
        B = len(plan.subject_indices)
        fields = self._jit_padded(B)(
            self.arrays, plan.subject_indices, plan.starts, plan.valid_mask
        )

        if ds.config.do_include_start_time_min:
            if plan.start_time is None:
                raise ValueError(
                    "do_include_start_time_min is set but the plan carries no "
                    "start_time — regenerate plans from this config."
                )
            fields["start_time"] = plan.start_time
        if ds.config.do_include_subsequence_indices:
            # int32, matching host _collate_with_starts (bit-identical incl.
            # dtype; the parity tests assert dtypes too).
            fields["start_idx"] = plan.starts
            fields["end_idx"] = plan.starts + plan.kept
        if ds.config.do_include_subject_id:
            fields["subject_id"] = np.asarray(
                [ds.subject_ids[i] for i in plan.subject_indices], dtype=np.int64
            )
        if ds.has_task:
            fields["stream_labels"] = {
                t: np.asarray(
                    ds.stream_labels[t][plan.subject_indices],
                    dtype=np.int64
                    if ds.task_types[t] == "multi_class_classification"
                    else np.float32,
                )
                for t in ds.tasks
            }
        fields["valid_mask"] = plan.valid_mask
        return EventStreamBatch(**fields)

    def batches(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed: int | None = None,
        drop_last: bool | None = None,
        skip_batches: int = 0,
        with_counts: bool = False,
    ) -> Iterator:
        """Device-collated mirror of `JaxDataset.batches` (same rng stream).

        With ``with_counts=True`` yields ``(batch, n_events)`` — the event
        count comes from the plan, so throughput accounting never syncs the
        device.
        """
        for plan in self.dataset.plan_batches(
            batch_size,
            shuffle=shuffle,
            seed=seed,
            drop_last=drop_last,
            skip_batches=skip_batches,
            n_shards=self.data_shards,
        ):
            b = self.collate(plan)
            yield (b, plan.n_events) if with_counts else b

    def packed_batches(
        self,
        batch_size: int,
        seq_len: int | None = None,
        shuffle: bool = True,
        seed: int | None = None,
        with_counts: bool = False,
    ) -> Iterator:
        """Device-collated mirror of `JaxDataset.packed_batches`.

        Packing order and row contents are identical to the host path (same
        ``_pack_rows`` call, same rng); the host ships the ``(B, L)``
        event-id plan (~KBs) and the device gathers the ``(B, L, M)``
        payload.
        """
        ds = self.dataset
        L = seq_len or ds.max_seq_len
        rows = ds.packed_rows_dealt(
            batch_size, seq_len=L, shuffle=shuffle, seed=seed, n_shards=self.data_shards
        )

        for lo_idx in range(0, len(rows), batch_size):
            chunk = rows[lo_idx : lo_idx + batch_size]
            kernel = self._jit_packed(len(chunk), L)
            event_ids, seg, mask, n_events = ds.packed_row_plan(chunk, L)
            fields = kernel(self.arrays, event_ids.astype(np.int32), mask)
            batch = EventStreamBatch(
                segment_ids=seg, valid_mask=np.ones(len(chunk), dtype=bool), **fields
            )
            yield (batch, n_events) if with_counts else batch

    # ------------------------------------------------------- chunked plans
    def plan_chunks(
        self,
        batch_size: int,
        chunk_steps: int,
        shuffle: bool = True,
        seed: int | None = None,
        drop_last: bool | None = None,
        skip_batches: int = 0,
    ) -> Iterator[tuple[dict, int]]:
        """Yields ``(plans, n_events)`` with ``chunk_steps`` stacked plans.

        ``plans`` maps plan fields to ``(k, B)`` numpy arrays — the payload a
        scanned multi-step train program (``training.make_chunked_train_step``)
        consumes to run ``k`` collate+step iterations in ONE device program,
        amortizing per-dispatch tunnel overhead ``k``-fold. The final chunk
        may be shorter (``k < chunk_steps``); callers get one extra
        compilation for it at most.
        """
        buf: list[BatchPlan] = []
        for plan in self.dataset.plan_batches(
            batch_size,
            shuffle=shuffle,
            seed=seed,
            drop_last=drop_last,
            skip_batches=skip_batches,
            n_shards=self.data_shards,
        ):
            buf.append(plan)
            if len(buf) == chunk_steps:
                yield self._stack_plans(buf)
                buf = []
        if buf:
            yield self._stack_plans(buf)

    @staticmethod
    def _stack_plans(plans: list[BatchPlan]) -> tuple[dict, int]:
        return (
            {
                "subject_indices": np.stack([p.subject_indices for p in plans]),
                "starts": np.stack([p.starts for p in plans]),
                "valid_mask": np.stack([p.valid_mask for p in plans]),
            },
            sum(p.n_events for p in plans),
        )

    def packed_plan_chunks(
        self,
        batch_size: int,
        chunk_steps: int,
        seq_len: int | None = None,
        shuffle: bool = True,
        seed: int | None = None,
        skip_batches: int = 0,
        drop_short: bool = True,
    ) -> Iterator[tuple[dict, int]]:
        """Packed-row analog of `plan_chunks`: ``(k, B, L)`` event-id plans.

        ``drop_short`` skips the final under-filled packed batch (it would
        retrigger compilation — the training loop drops it too).
        """
        ds = self.dataset
        L = seq_len or ds.max_seq_len
        rows = ds.packed_rows_dealt(
            batch_size, seq_len=L, shuffle=shuffle, seed=seed, n_shards=self.data_shards
        )

        buf: list[tuple] = []
        n_ev_buf = 0
        n_seen = 0
        for lo_idx in range(0, len(rows), batch_size):
            chunk = rows[lo_idx : lo_idx + batch_size]
            if drop_short and len(chunk) < batch_size:
                continue
            n_seen += 1
            if n_seen <= skip_batches:
                continue
            event_ids, seg, mask, n_events = self.dataset.packed_row_plan(chunk, L)
            buf.append((event_ids.astype(np.int32), seg.astype(np.int32), mask))
            n_ev_buf += n_events
            if len(buf) == chunk_steps:
                yield self._stack_packed(buf), n_ev_buf
                buf, n_ev_buf = [], 0
        if buf:
            yield self._stack_packed(buf), n_ev_buf

    @staticmethod
    def _stack_packed(buf: list[tuple]) -> dict:
        return {
            "event_ids": np.stack([e for e, _, _ in buf]),
            "segment_ids": np.stack([s for _, s, _ in buf]),
            "event_mask": np.stack([m for _, _, m in buf]),
        }
