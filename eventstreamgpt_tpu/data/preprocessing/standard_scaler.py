"""Pre-processor that normalizes data to have zero mean and unit variance.

Rebuild of ``/root/reference/EventStream/data/preprocessing/standard_scaler.py:8``
(numpy instead of Polars expressions; same params schema and semantics,
including the sample standard deviation ``ddof=1``).
"""

from __future__ import annotations

import numpy as np

from .preprocessor import Preprocessor


class StandardScaler(Preprocessor):
    """Normalizes data to have zero mean and unit variance.

    Examples:
        >>> import numpy as np
        >>> S = StandardScaler()
        >>> params = S.fit(np.asarray([1., 2., 3., 4., 5.]))
        >>> params["mean_"], round(params["std_"], 6)
        (3.0, 1.581139)
        >>> per_row = {k: np.full(5, v) for k, v in params.items()}
        >>> np.round(S.predict(np.asarray([1., 2., 3., 4., 5.]), per_row), 6).tolist()
        [-1.264911, -0.632456, 0.0, 0.632456, 1.264911]
    """

    @classmethod
    def params_schema(cls) -> dict[str, type]:
        return {"mean_": float, "std_": float}

    def fit(self, column: np.ndarray) -> dict[str, float]:
        column = np.asarray(column, dtype=np.float64)
        return {
            "mean_": float(np.mean(column)),
            "std_": float(np.std(column, ddof=1)) if len(column) > 1 else float("nan"),
        }

    def fit_grouped(self, values, keys):
        """All keys fit in one grouped aggregation (pandas ``std`` is the
        sample std, ddof=1, and is NaN for singleton groups — exactly
        ``fit``'s convention).

        Examples:
            >>> import pandas as pd
            >>> out = StandardScaler().fit_grouped(
            ...     pd.Series([1., 2., 3., 7.]), pd.Series(list("aaab")))
            >>> out["a"] == {"mean_": 2.0, "std_": 1.0}
            True
            >>> out["b"]["mean_"], str(out["b"]["std_"])
            (7.0, 'nan')
        """
        import pandas as pd

        agg = values.astype(np.float64).groupby(keys).agg(["mean", "std"])
        agg.columns = ["mean_", "std_"]
        return pd.Series(agg.to_dict("index"), dtype=object).reindex(agg.index)

    def params_from_stats(self, stats: dict[str, float]) -> dict[str, float]:
        """Scaler params from (merged) sufficient statistics.

        Examples:
            >>> S = StandardScaler()
            >>> a = S.sufficient_stats([1., 2., 3.])
            >>> b = S.sufficient_stats([4., 5.])
            >>> p = S.params_from_stats(S.merge_stats(a, b))
            >>> p["mean_"], round(p["std_"], 6)
            (3.0, 1.581139)
        """
        mean, std = self._moments_from_stats(stats)
        return {"mean_": mean, "std_": std}

    @classmethod
    def predict(cls, column: np.ndarray, model_params: dict[str, np.ndarray]) -> np.ndarray:
        return (np.asarray(column, dtype=np.float64) - model_params["mean_"]) / model_params["std_"]
