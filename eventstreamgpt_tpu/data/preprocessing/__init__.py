"""Numerical pre-processor plugins (reference ``EventStream/data/preprocessing/``)."""

from .preprocessor import Preprocessor
from .standard_scaler import StandardScaler
from .stddev_cutoff import StddevCutoffOutlierDetector

__all__ = ["Preprocessor", "StandardScaler", "StddevCutoffOutlierDetector"]
