"""The plugin API for numerical-measurement pre-processors.

Rebuild of ``/root/reference/EventStream/data/preprocessing/preprocessor.py:13``.
The reference expresses fit/predict as unmaterialized Polars expressions;
Polars is not available in this image, so the same contract is expressed over
numpy arrays: ``fit`` maps a vector of raw observations to a params dict (one
struct per vocabulary key, fit under a host-side groupby), and ``predict``
maps values + per-row param columns to outputs, fully vectorized. Fit params
live in the measurement-metadata dataframes as plain dicts, which keeps the
reference's on-disk artifact format (dict-valued ``outlier_model`` /
``normalizer`` cells) byte-compatible.
"""

from __future__ import annotations

from abc import ABC, abstractmethod

import numpy as np


class Preprocessor(ABC):
    """sklearn-like fit/predict over numpy arrays, grouped by vocabulary key.

    Subclasses declare ``params_schema`` (field names of the fit-params
    struct), ``fit`` (observations → params dict), and ``predict`` (values +
    per-row param arrays → outputs).
    """

    @classmethod
    @abstractmethod
    def params_schema(cls) -> dict[str, type]:
        """Field names → dtypes of the fit-params struct."""
        raise NotImplementedError("Subclass must implement abstract method")

    @abstractmethod
    def fit(self, column: np.ndarray) -> dict[str, float]:
        """Fits the pre-processing model over raw observations ``column``."""
        raise NotImplementedError("Subclass must implement abstract method")

    def fit_grouped(self, values, keys):
        """Fits one params struct per vocabulary key: ``values`` grouped by
        ``keys`` (aligned pandas Series) → object Series of params dicts
        indexed by key.

        The default loops `fit` over groups — correct for any plugin. The
        shipped plugins override it with one grouped aggregation: the ETL
        fit path is O(rows) vectorized work, not O(keys) Python calls
        (mirrors the reference's grouped Polars expressions,
        ``/root/reference/EventStream/data/dataset_polars.py:899-1035``).
        """
        import pandas as pd

        return pd.Series(
            {k: self.fit(g.to_numpy()) for k, g in values.groupby(keys)}, dtype=object
        )

    # ------------------------------------------------- incremental-fit API
    # The streaming/append path (``Dataset.append_subjects``) never re-reads
    # old observations: each fit persists (count, sum, sum-of-squares) per
    # vocabulary key in the cache metadata, new shards contribute their own
    # stats, and the merged params come from `params_from_stats`. Any
    # moment-based preprocessor gets this for free; a plugin whose params
    # are not derivable from these moments must override all three hooks.

    @staticmethod
    def sufficient_stats(column) -> dict[str, float]:
        """(count, sum, sum-of-squares) of one key's raw observations."""
        column = np.asarray(column, dtype=np.float64)
        return {
            "count": int(len(column)),
            "sum": float(np.sum(column)),
            "sumsq": float(np.sum(column * column)),
        }

    @classmethod
    def sufficient_stats_grouped(cls, values, keys) -> dict[str, dict[str, float]]:
        """Per-key sufficient statistics in one grouped aggregation.

        Keys are STRINGIFIED: the stats persist through a JSON sidecar
        (whose object keys are strings), so normalizing here keeps the
        in-session and round-tripped spellings identical.

        Examples:
            >>> import pandas as pd
            >>> class P(Preprocessor):
            ...     @classmethod
            ...     def params_schema(cls): return {}
            ...     def fit(self, column): return {}
            ...     @classmethod
            ...     def predict(cls, column, model_params): return column
            >>> P.sufficient_stats_grouped(
            ...     pd.Series([1., 2., 4.]), pd.Series(list("aab")))
            {'a': {'count': 2, 'sum': 3.0, 'sumsq': 5.0}, 'b': {'count': 1, 'sum': 4.0, 'sumsq': 16.0}}
        """
        import pandas as pd

        vals = values.astype(np.float64)
        agg = pd.DataFrame({"v": vals, "v2": vals * vals}).groupby(keys.to_numpy()).agg(
            count=("v", "size"), sum=("v", "sum"), sumsq=("v2", "sum")
        )
        return {
            str(k): {
                "count": int(r["count"]),
                "sum": float(r["sum"]),
                "sumsq": float(r["sumsq"]),
            }
            for k, r in agg.iterrows()
        }

    @staticmethod
    def merge_stats(a: dict[str, float] | None, b: dict[str, float] | None) -> dict[str, float]:
        """Adds two sufficient-statistic structs (either side may be None).

        Examples:
            >>> Preprocessor.merge_stats(
            ...     {"count": 2, "sum": 3.0, "sumsq": 5.0},
            ...     {"count": 1, "sum": 4.0, "sumsq": 16.0})
            {'count': 3, 'sum': 7.0, 'sumsq': 21.0}
        """
        if a is None:
            return dict(b)
        if b is None:
            return dict(a)
        return {
            "count": int(a["count"]) + int(b["count"]),
            "sum": float(a["sum"]) + float(b["sum"]),
            "sumsq": float(a["sumsq"]) + float(b["sumsq"]),
        }

    @staticmethod
    def _moments_from_stats(stats: dict[str, float]) -> tuple[float, float]:
        """(mean, sample std ddof=1) from sufficient statistics; the std is
        NaN for fewer than two observations, matching ``fit``'s convention."""
        n = int(stats["count"])
        if n == 0:
            return float("nan"), float("nan")
        mean = stats["sum"] / n
        if n < 2:
            return mean, float("nan")
        var = max(stats["sumsq"] - n * mean * mean, 0.0) / (n - 1)
        return mean, float(np.sqrt(var))

    def params_from_stats(self, stats: dict[str, float]) -> dict[str, float]:
        """Fit params derived from (merged) sufficient statistics.

        NOTE: floating-point accumulation differs from a direct re-fit on
        the concatenated raw data, so incrementally updated params may
        drift by last-ulp amounts from a from-scratch fit (documented,
        pinned by the append-subjects drift test).
        """
        raise NotImplementedError(
            f"{type(self).__name__} does not support incremental fitting from "
            "sufficient statistics"
        )

    @classmethod
    @abstractmethod
    def predict(cls, column: np.ndarray, model_params: dict[str, np.ndarray]) -> np.ndarray:
        """Predicts for ``column`` given per-row fit parameters ``model_params``.

        ``model_params`` maps each schema field to an array aligned with
        ``column`` (rows inherit the params of their vocabulary key).
        """
        raise NotImplementedError("Subclass must implement abstract method")
