"""The plugin API for numerical-measurement pre-processors.

Rebuild of ``/root/reference/EventStream/data/preprocessing/preprocessor.py:13``.
The reference expresses fit/predict as unmaterialized Polars expressions;
Polars is not available in this image, so the same contract is expressed over
numpy arrays: ``fit`` maps a vector of raw observations to a params dict (one
struct per vocabulary key, fit under a host-side groupby), and ``predict``
maps values + per-row param columns to outputs, fully vectorized. Fit params
live in the measurement-metadata dataframes as plain dicts, which keeps the
reference's on-disk artifact format (dict-valued ``outlier_model`` /
``normalizer`` cells) byte-compatible.
"""

from __future__ import annotations

from abc import ABC, abstractmethod

import numpy as np


class Preprocessor(ABC):
    """sklearn-like fit/predict over numpy arrays, grouped by vocabulary key.

    Subclasses declare ``params_schema`` (field names of the fit-params
    struct), ``fit`` (observations → params dict), and ``predict`` (values +
    per-row param arrays → outputs).
    """

    @classmethod
    @abstractmethod
    def params_schema(cls) -> dict[str, type]:
        """Field names → dtypes of the fit-params struct."""
        raise NotImplementedError("Subclass must implement abstract method")

    @abstractmethod
    def fit(self, column: np.ndarray) -> dict[str, float]:
        """Fits the pre-processing model over raw observations ``column``."""
        raise NotImplementedError("Subclass must implement abstract method")

    def fit_grouped(self, values, keys):
        """Fits one params struct per vocabulary key: ``values`` grouped by
        ``keys`` (aligned pandas Series) → object Series of params dicts
        indexed by key.

        The default loops `fit` over groups — correct for any plugin. The
        shipped plugins override it with one grouped aggregation: the ETL
        fit path is O(rows) vectorized work, not O(keys) Python calls
        (mirrors the reference's grouped Polars expressions,
        ``/root/reference/EventStream/data/dataset_polars.py:899-1035``).
        """
        import pandas as pd

        return pd.Series(
            {k: self.fit(g.to_numpy()) for k, g in values.groupby(keys)}, dtype=object
        )

    @classmethod
    @abstractmethod
    def predict(cls, column: np.ndarray, model_params: dict[str, np.ndarray]) -> np.ndarray:
        """Predicts for ``column`` given per-row fit parameters ``model_params``.

        ``model_params`` maps each schema field to an array aligned with
        ``column`` (rows inherit the params of their vocabulary key).
        """
        raise NotImplementedError("Subclass must implement abstract method")
