"""Pre-processor flagging values beyond K standard deviations from the mean.

Rebuild of ``/root/reference/EventStream/data/preprocessing/stddev_cutoff.py:9``
(numpy instead of Polars expressions; same params schema and semantics,
default cutoff 5.0, sample standard deviation ``ddof=1``).
"""

from __future__ import annotations

import numpy as np

from .preprocessor import Preprocessor


class StddevCutoffOutlierDetector(Preprocessor):
    """Flags data elements outside ``stddev_cutoff`` standard deviations.

    Examples:
        >>> import numpy as np
        >>> S = StddevCutoffOutlierDetector(stddev_cutoff=1.0)
        >>> params = S.fit(np.asarray([1., 2., 3., 4., 5.]))
        >>> round(params["thresh_large_"], 6), round(params["thresh_small_"], 6)
        (4.581139, 1.418861)
        >>> per_row = {k: np.full(5, v) for k, v in params.items()}
        >>> S.predict(np.asarray([1., 2., 3., 4., 5.]), per_row).tolist()
        [True, False, False, False, True]
    """

    def __init__(self, stddev_cutoff: float = 5.0):
        self.stddev_cutoff = stddev_cutoff

    @classmethod
    def params_schema(cls) -> dict[str, type]:
        return {"thresh_large_": float, "thresh_small_": float}

    def fit(self, column: np.ndarray) -> dict[str, float]:
        column = np.asarray(column, dtype=np.float64)
        mean = float(np.mean(column))
        std = float(np.std(column, ddof=1)) if len(column) > 1 else float("nan")
        return {
            "thresh_large_": mean + self.stddev_cutoff * std,
            "thresh_small_": mean - self.stddev_cutoff * std,
        }

    def fit_grouped(self, values, keys):
        """All keys' thresholds in one grouped aggregation (sample std,
        NaN for singleton groups, like ``fit``).

        Examples:
            >>> import pandas as pd
            >>> S = StddevCutoffOutlierDetector(stddev_cutoff=1.0)
            >>> out = S.fit_grouped(pd.Series([1., 3.]), pd.Series(["a", "a"]))
            >>> out["a"] == {"thresh_large_": 2.0 + 1.4142135623730951,
            ...              "thresh_small_": 2.0 - 1.4142135623730951}
            True
        """
        import pandas as pd

        agg = values.astype(np.float64).groupby(keys).agg(["mean", "std"])
        out = pd.DataFrame(
            {
                "thresh_large_": agg["mean"] + self.stddev_cutoff * agg["std"],
                "thresh_small_": agg["mean"] - self.stddev_cutoff * agg["std"],
            }
        )
        return pd.Series(out.to_dict("index"), dtype=object).reindex(out.index)

    def params_from_stats(self, stats: dict[str, float]) -> dict[str, float]:
        """Thresholds from (merged) sufficient statistics.

        Examples:
            >>> S = StddevCutoffOutlierDetector(stddev_cutoff=1.0)
            >>> p = S.params_from_stats(S.sufficient_stats([1., 3.]))
            >>> p == {"thresh_large_": 2.0 + 1.4142135623730951,
            ...       "thresh_small_": 2.0 - 1.4142135623730951}
            True
        """
        mean, std = self._moments_from_stats(stats)
        return {
            "thresh_large_": mean + self.stddev_cutoff * std,
            "thresh_small_": mean - self.stddev_cutoff * std,
        }

    @classmethod
    def predict(cls, column: np.ndarray, model_params: dict[str, np.ndarray]) -> np.ndarray:
        column = np.asarray(column, dtype=np.float64)
        return (column > model_params["thresh_large_"]) | (column < model_params["thresh_small_"])
