"""Asynchronous host→device input pipeline.

The r02 benchmark showed ~14× between the compute-only ceiling and the
system number — lost to synchronous host collation (VERDICT r02 weak #3;
SURVEY §7 "the host must not bottleneck — double-buffer to device"). This
module closes that gap: a background thread drains the host batch generator,
computes any host-side statistics, and issues ``jax.device_put`` ahead of
need so a depth-``depth`` buffer of device-resident batches is always ready
when the training loop asks for the next one.

The reference has no analog (its DataLoader workers feed a synchronous
Lightning loop); this is TPU-native design: ``device_put`` is asynchronous,
so the transfer of batch N+1 overlaps the compute of batch N, and collation
of batch N+2 overlaps both.

Resume semantics are untouched: prefetching wraps the generator without
changing its rng stream, so the ``skip_batches`` mid-epoch resume contract of
`JaxDataset.batches` holds bit-for-bit.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator

_SENTINEL = object()


class DevicePrefetcher:
    """Iterates ``(device_batch, host_stats)`` with background collation.

    Args:
        batches: host batch iterable (e.g. ``JaxDataset.batches(...)``).
        place_fn: host batch → device batch (e.g. ``shard_batch(b, mesh)``);
            called in the worker thread. ``jax.device_put`` is async, so this
            only *enqueues* the transfer.
        host_stats_fn: optional host batch → picklable stats, computed in the
            worker **before** transfer so the training loop never syncs the
            device to read e.g. the event count.
        depth: number of device batches buffered ahead (2 = double buffering).

    The iterator re-raises worker exceptions at the consuming site and stops
    its thread on `close` (also called on destruction and generator exit).
    """

    def __init__(
        self,
        batches: Iterable,
        place_fn: Callable[[Any], Any],
        host_stats_fn: Callable[[Any], Any] | None = None,
        depth: int = 2,
    ):
        # State used by close() is assigned before any validation so a
        # failed construction still destructs cleanly via __del__.
        self._stop = threading.Event()
        self._thread = None
        # Streaming sources (e.g. the sharded-ETL feed) may expose close();
        # held so close() can tell a stalled source to stop producing
        # instead of abandoning the worker mid-`__next__` every time.
        self._source = batches
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1; got {depth}")
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._thread = threading.Thread(
            target=self._worker,
            args=(iter(batches), place_fn, host_stats_fn),
            daemon=True,
        )
        self._thread.start()

    def _worker(self, it: Iterator, place_fn, host_stats_fn) -> None:
        try:
            for host_batch in it:
                if self._stop.is_set():
                    return
                stats = host_stats_fn(host_batch) if host_stats_fn is not None else None
                device_batch = place_fn(host_batch)
                self._put((device_batch, stats))
            self._put(_SENTINEL)
        except BaseException as e:  # noqa: BLE001 — must surface in consumer
            self._put(e)

    def _put(self, item) -> None:
        """Blocking put that wakes on close() instead of deadlocking."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self):
        return self

    def __next__(self):
        # A closed (or exhausted) prefetcher terminates iteration instead of
        # blocking forever on an empty queue; the timeout loop also covers a
        # close() racing a blocked get().
        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is _SENTINEL:
                self.close()
                raise StopIteration
            if isinstance(item, BaseException):
                self.close()
                raise item
            return item
        raise StopIteration

    def close(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        if getattr(self, "_queue", None) is None:
            return
        # A streaming source with its own lifecycle (shard workers, file
        # handles) gets told to stop FIRST: a worker blocked inside the
        # source's __next__ can't see the stop flag, so without this the
        # bounded join below would always burn its full timeout on a
        # stalled shard. Generators refuse cross-thread close() while
        # executing — that (or any source-side failure) must not break
        # teardown, so errors are swallowed and the bounded join still
        # guarantees close() returns.
        src_close = getattr(getattr(self, "_source", None), "close", None)
        if src_close is not None:
            try:
                src_close()
            except Exception:
                pass
        # Drain so a blocked worker put() can observe the stop flag.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        # Join the worker (bounded): teardown must not leave a thread racing
        # a live device_put against e.g. pytest's fixture cleanup or the
        # preemption drain. The worker polls the stop flag every 0.1s, so a
        # healthy thread exits well inside the timeout; a wedged device_put
        # is abandoned as a daemon rather than hanging the process.
        t = getattr(self, "_thread", None)
        if t is not None and t is not threading.current_thread() and t.is_alive():
            t.join(timeout=join_timeout)
        # The worker may have completed one last put() between the first
        # drain and its stop-flag check — including the case where it
        # already exited before the liveness check above — so the final
        # drain is unconditional: no device buffers may linger in the dead
        # queue.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass

    def __del__(self):
        self.close(join_timeout=1.0)


def prefetch_to_device(
    batches: Iterable,
    place_fn: Callable[[Any], Any],
    host_stats_fn: Callable[[Any], Any] | None = None,
    depth: int = 2,
) -> DevicePrefetcher:
    """Convenience constructor; see `DevicePrefetcher`."""
    return DevicePrefetcher(batches, place_fn, host_stats_fn=host_stats_fn, depth=depth)
