from .config import (
    DatasetConfig,
    DatasetSchema,
    InputDFSchema,
    MeasurementConfig,
    PytorchDatasetConfig,
    SeqPaddingSide,
    SubsequenceSamplingStrategy,
    VocabularyConfig,
)
from .dataset_base import DatasetBase
from .dataset_pandas import Dataset, Query
from .device_dataset import DeviceDataset
from .jax_dataset import BatchPlan, JaxDataset
from .prefetch import DevicePrefetcher, prefetch_to_device
from .time_dependent_functor import AgeFunctor, TimeDependentFunctor, TimeOfDayFunctor
from .types import (
    DataModality,
    EventStreamBatch,
    InputDataType,
    InputDFType,
    NumericDataModalitySubtype,
    TemporalityType,
    de_pad,
)
from .vocabulary import Vocabulary

__all__ = [
    "AgeFunctor",
    "DataModality",
    "Dataset",
    "DatasetBase",
    "BatchPlan",
    "DatasetConfig",
    "DatasetSchema",
    "DeviceDataset",
    "DevicePrefetcher",
    "prefetch_to_device",
    "Query",
    "EventStreamBatch",
    "InputDataType",
    "InputDFSchema",
    "InputDFType",
    "JaxDataset",
    "MeasurementConfig",
    "NumericDataModalitySubtype",
    "PytorchDatasetConfig",
    "SeqPaddingSide",
    "SubsequenceSamplingStrategy",
    "TemporalityType",
    "TimeDependentFunctor",
    "TimeOfDayFunctor",
    "Vocabulary",
    "VocabularyConfig",
    "de_pad",
]
