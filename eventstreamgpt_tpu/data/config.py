"""Dataset, measurement, and input-schema configuration objects.

TPU-native rebuild of ``/root/reference/EventStream/data/config.py`` (1615
LoC). Public surface and on-disk JSON contracts are preserved — the reference's
``config.json`` / ``inferred_measurement_configs.json`` /
``vocabulary_config.json`` artifacts parse into these classes unchanged — but
the implementation is independent and pandas-based (the reference uses Polars
for measurement metadata; Polars is absent here and measurement metadata are
tiny host-side tables).

Classes (reference anchors):
* ``DatasetSchema`` (``config.py:51``) / ``InputDFSchema`` (``config.py:138``)
* ``VocabularyConfig`` (``config.py:557``)
* ``SeqPaddingSide`` / ``SubsequenceSamplingStrategy`` (``config.py:607,623``)
* ``PytorchDatasetConfig`` (``config.py:646``) — name kept for API parity;
  here it configures the host→device batch pipeline feeding JAX.
* ``MeasurementConfig`` (``config.py:795``)
* ``DatasetConfig`` (``config.py:1372``)
"""

from __future__ import annotations

import dataclasses
import enum
import random
from collections import OrderedDict
from pathlib import Path
from typing import Any, Hashable, Union

import pandas as pd

from ..utils import (
    COUNT_OR_PROPORTION,
    JSONableMixin,
    StrEnum,
    config_dataclass,
    count_or_proportion,
)
from .time_dependent_functor import AgeFunctor, TimeDependentFunctor, TimeOfDayFunctor
from .types import DataModality, InputDataType, InputDFType, TemporalityType
from .vocabulary import Vocabulary

PROPORTION = float
DF_COL = Union[str, list[str]]
INPUT_COL_T = Union[InputDataType, tuple[InputDataType, str]]
DF_SCHEMA = Union[dict, list, tuple]


@dataclasses.dataclass
class InputDFSchema(JSONableMixin):
    """Schema for extracting one input dataframe (static, event, or range).

    Validation and unified-schema semantics follow the reference
    (``config.py:259-554``): static sources need ``subject_id_col`` and no
    timestamps; event sources need ``ts_col`` + a string ``event_type``; range
    sources need start/end timestamp columns and expand a string event type
    ``X`` into ``(X, X_START, X_END)``.
    """

    input_df: Any | None = None
    type: InputDFType | None = None
    event_type: str | tuple[str, str, str] | None = None

    subject_id_col: str | None = None
    ts_col: DF_COL | None = None
    start_ts_col: DF_COL | None = None
    end_ts_col: DF_COL | None = None
    ts_format: str | None = None
    start_ts_format: str | None = None
    end_ts_format: str | None = None

    data_schema: DF_SCHEMA | list[DF_SCHEMA] | None = None
    start_data_schema: DF_SCHEMA | list[DF_SCHEMA] | None = None
    end_data_schema: DF_SCHEMA | list[DF_SCHEMA] | None = None

    must_have: list = dataclasses.field(default_factory=list)

    @property
    def is_static(self) -> bool:
        return self.type == InputDFType.STATIC

    def __post_init__(self):
        if self.input_df is None:
            raise ValueError("Missing mandatory parameter input_df!")
        if self.type is None:
            raise ValueError("Missing mandatory parameter type!")
        if self.type is not None and not isinstance(self.type, InputDFType):
            self.type = InputDFType(self.type)
        for attr in ("data_schema", "start_data_schema", "end_data_schema"):
            v = getattr(self, attr)
            if v is not None and type(v) is not list:
                setattr(self, attr, [v])

        self.filter_on = {}
        for filter_col in self.must_have:
            match filter_col:
                case str():
                    self.filter_on[filter_col] = True
                case (str() as col, list() as vals) | [str() as col, list() as vals]:
                    self.filter_on[col] = vals
                case _:
                    raise ValueError(f"Malformed filter: {filter_col}")

        match self.type:
            case InputDFType.STATIC:
                if self.subject_id_col is None:
                    raise ValueError("Must set subject_id_col for static source!")
                if self.event_type is not None:
                    raise ValueError("Event_type can't be set if type == 'static'!")
                for param in ("ts_col", "start_ts_col", "end_ts_col"):
                    if getattr(self, param) is not None:
                        raise ValueError(f"Set invalid param {param} for static source!")
            case InputDFType.EVENT:
                if self.ts_col is None:
                    raise ValueError("Missing mandatory event parameter ts_col!")
                match self.event_type:
                    case None:
                        raise ValueError("Missing mandatory event parameter event_type!")
                    case str():
                        pass
                    case _:
                        raise TypeError(f"event_type must be a string for events. Got {self.event_type}")
                if self.subject_id_col is not None:
                    raise ValueError("subject_id_col should be None for non-static types!")
                for param in (
                    "start_ts_col",
                    "end_ts_col",
                    "start_ts_format",
                    "end_ts_format",
                    "start_data_schema",
                    "end_data_schema",
                ):
                    if getattr(self, param) is not None:
                        raise ValueError(
                            f"{param} should be None for {self.type} schema: Got {getattr(self, param)}"
                        )
            case InputDFType.RANGE:
                match self.event_type:
                    case None:
                        raise ValueError("Missing mandatory range parameter event_type!")
                    case (str(), str(), str()) | [str(), str(), str()]:
                        self.event_type = tuple(self.event_type)
                    case str():
                        self.event_type = (
                            self.event_type,
                            f"{self.event_type}_START",
                            f"{self.event_type}_END",
                        )
                    case _:
                        raise TypeError(
                            "event_type must be a string or a 3-element tuple (eq_type, st_type, "
                            f"end_type) for ranges. Got {self.event_type}."
                        )
                if self.data_schema is not None:
                    for param in ("start_data_schema", "end_data_schema"):
                        if getattr(self, param) is not None:
                            raise ValueError(
                                f"{param} can't be simultaneously set with `self.data_schema`! "
                                f"Got {getattr(self, param)}"
                            )
                    self.start_data_schema = self.data_schema
                    self.end_data_schema = self.data_schema
                if self.start_ts_col is None:
                    raise ValueError("Missing mandatory range parameter start_ts_col!")
                if self.end_ts_col is None:
                    raise ValueError("Missing mandatory range parameter end_ts_col!")
                if self.ts_col is not None:
                    raise ValueError(f"ts_col should be `None` for {self.type} schemas! Got: {self.ts_col}.")
                if self.subject_id_col is not None:
                    raise ValueError("subject_id_col should be None for non-static types!")
                if self.start_ts_format is not None:
                    if self.end_ts_format is None:
                        raise ValueError(
                            "If start_ts_format is specified, end_ts_format must also be specified!"
                        )
                    if self.ts_format is not None:
                        raise ValueError("If start_ts_format is specified, ts_format must be `None`!")
                else:
                    if self.end_ts_format is not None:
                        raise ValueError(
                            "If end_ts_format is specified, start_ts_format must also be specified!"
                        )
                    self.start_ts_format = self.ts_format
                    self.end_ts_format = self.ts_format
                    self.ts_format = None

        self.columns_to_load  # noqa: B018 — property access validates the schema.

    @property
    def columns_to_load(self) -> list[tuple[str, INPUT_COL_T]]:
        """All (input column, dtype) pairs to read, including timestamp columns."""
        columns_to_load: dict[str, Any] = {}
        match self.type:
            case InputDFType.EVENT | InputDFType.STATIC:
                for in_col, (out_col, dt) in self.unified_schema.items():
                    if in_col in columns_to_load:
                        raise ValueError(f"Duplicate column {in_col}!")
                    columns_to_load[in_col] = dt
            case InputDFType.RANGE:
                for unified_schema in self.unified_schema:
                    for in_col, (out_col, dt) in unified_schema.items():
                        if in_col in columns_to_load:
                            if dt != columns_to_load[in_col]:
                                raise ValueError(f"Duplicate column {in_col} with differing dts!")
                        else:
                            columns_to_load[in_col] = dt
            case _:
                raise ValueError(f"Unrecognized type {self.type}!")

        out = list(columns_to_load.items())
        for param, fmt_attr in [
            ("start_ts_col", "start_ts_format"),
            ("end_ts_col", "end_ts_format"),
            ("ts_col", "ts_format"),
        ]:
            val = getattr(self, param)
            fmt_param = getattr(self, fmt_attr)
            fmt = InputDataType.TIMESTAMP if fmt_param is None else (InputDataType.TIMESTAMP, fmt_param)
            match val:
                case list():
                    out.extend([(c, fmt) for c in val])
                case str():
                    out.append((val, fmt))
                case None:
                    pass
                case _:
                    raise ValueError(f"Can't parse timestamp {param}, {fmt_param}, {val}")
        return out

    @property
    def unified_schema(self):
        match self.type:
            case InputDFType.EVENT | InputDFType.STATIC:
                return self.unified_event_schema
            case InputDFType.RANGE:
                return [self.unified_eq_schema, self.unified_start_schema, self.unified_end_schema]
            case _:
                raise ValueError(f"Unrecognized type {self.type}!")

    @property
    def unified_event_schema(self) -> dict[str, tuple[str, INPUT_COL_T]]:
        return self._unify_schema(self.data_schema)

    @property
    def unified_start_schema(self) -> dict[str, tuple[str, INPUT_COL_T]]:
        if self.type != InputDFType.RANGE:
            raise ValueError(f"Start schema is invalid for {self.type}")
        return self._unify_schema(self.start_data_schema or self.data_schema)

    @property
    def unified_end_schema(self) -> dict[str, tuple[str, INPUT_COL_T]]:
        if self.type != InputDFType.RANGE:
            raise ValueError(f"End schema is invalid for {self.type}")
        return self._unify_schema(self.end_data_schema or self.data_schema)

    @property
    def unified_eq_schema(self) -> dict[str, tuple[str, INPUT_COL_T]]:
        if self.type != InputDFType.RANGE:
            raise ValueError(f"Start=End schema is invalid for {self.type}")
        if self.start_data_schema is None and self.end_data_schema is None:
            return self._unify_schema(self.data_schema)
        ds: list = []
        for sub in (self.start_data_schema, self.end_data_schema):
            if sub is not None:
                ds.extend(sub if type(sub) is list else [sub])
        return self._unify_schema(ds)

    @classmethod
    def __add_to_schema(cls, container, in_col, dt, out_col=None):
        if out_col is None:
            out_col = in_col
        if type(in_col) is not str or type(out_col) is not str:
            raise ValueError(f"Column names must be strings! Got {in_col}, {out_col}")
        if in_col in container and container[in_col] != (out_col, dt):
            raise ValueError(
                f"Column {in_col} is repeated in schema with different value!\n"
                f"Existing: {container[in_col]}\nNew: ({out_col}, {dt})"
            )
        container[in_col] = (out_col, dt)

    @classmethod
    def _unify_schema(cls, data_schema) -> dict[str, tuple[str, INPUT_COL_T]]:
        """Resolves a (possibly list-of-)schema spec into ``{in_col: (out_col, dtype)}``.

        Accepts the same spellings as the reference (``config.py:519-554``):
        ``(col, dtype)``, ``([cols], dtype)``, ``{in_col: dtype}``,
        ``{in_col: (out_col, dtype)}``, ``({in: out}, dtype)``; timestamps may
        be ``(TIMESTAMP, fmt)`` pairs.
        """
        if data_schema is None:
            return {}

        def is_dt(x) -> bool:
            if isinstance(x, InputDataType) or (isinstance(x, str) and x in InputDataType.values()):
                return True
            if isinstance(x, (tuple, list)) and len(x) == 2:
                dt0, fmt = x
                return (
                    (isinstance(dt0, InputDataType) and dt0 == InputDataType.TIMESTAMP)
                    or dt0 == "timestamp"
                ) and isinstance(fmt, str)
            return False

        unified_schema: dict[str, tuple[str, INPUT_COL_T]] = {}
        for schema in data_schema:
            match schema:
                case (str() as col, dt) if is_dt(dt):
                    cls.__add_to_schema(unified_schema, in_col=col, dt=dt)
                case (list() as cols, dt) if is_dt(dt):
                    for c in cols:
                        cls.__add_to_schema(unified_schema, in_col=c, dt=dt)
                case (dict() as col_names_map, dt) if is_dt(dt):
                    for in_col, out_col in col_names_map.items():
                        cls.__add_to_schema(unified_schema, in_col=in_col, dt=dt, out_col=out_col)
                case dict():
                    for in_col, schema_info in schema.items():
                        match schema_info:
                            case (str() as out_col, dt) if is_dt(dt):
                                cls.__add_to_schema(unified_schema, in_col=in_col, dt=dt, out_col=out_col)
                            case [str() as out_col, dt] if is_dt(dt):
                                cls.__add_to_schema(unified_schema, in_col=in_col, dt=dt, out_col=out_col)
                            case dt if is_dt(dt):
                                cls.__add_to_schema(unified_schema, in_col=in_col, dt=dt)
                            case _:
                                raise ValueError(f"Schema Unprocessable!\n{schema_info}")
                case _:
                    raise ValueError(f"Schema Unprocessable!\n{schema}")
        return unified_schema

    def to_dict(self) -> dict:
        as_dict = dataclasses.asdict(self)
        if not isinstance(self.input_df, str):
            as_dict["input_df"] = str(self.input_df)
        as_dict["type"] = str(self.type) if self.type is not None else None
        return as_dict


@dataclasses.dataclass
class DatasetSchema(JSONableMixin):
    """One static schema plus 1+ dynamic schemas (reference ``config.py:51``)."""

    static: dict[str, Any] | InputDFSchema | None = None
    dynamic: list[InputDFSchema | dict[str, Any]] | None = dataclasses.field(default_factory=list)

    def __post_init__(self):
        if self.static is None:
            raise ValueError("Must specify a static schema!")
        if isinstance(self.static, dict):
            self.static = InputDFSchema(**self.static)
        if not self.static.is_static:
            raise ValueError("Must pass a static schema config for static.")
        if not self.dynamic:
            raise ValueError("Must pass dynamic schemas in self.dynamic!")
        self.dynamic = [InputDFSchema(**s) if isinstance(s, dict) else s for s in self.dynamic]
        for s in self.dynamic:
            if s.is_static:
                raise ValueError("Must pass dynamic schemas in self.dynamic!")
            if s.subject_id_col is None:
                s.subject_id_col = self.static.subject_id_col

    @property
    def dynamic_by_df(self) -> dict[str, list[InputDFSchema]]:
        out: dict[str, list[InputDFSchema]] = {}
        for s in self.dynamic:
            out.setdefault(str(s.input_df), []).append(s)
        return out


@dataclasses.dataclass
class VocabularyConfig(JSONableMixin):
    """Describes the learned unified vocabulary of a dataset.

    Matches the reference's serialized ``vocabulary_config.json``
    (``config.py:557-605``) byte-for-byte in structure.

    Examples:
        >>> config = VocabularyConfig(
        ...     vocab_sizes_by_measurement={"m1": 10, "m2": 3},
        ...     vocab_offsets_by_measurement={"m1": 5, "m2": 15, "m3": 18})
        >>> config.total_vocab_size
        19
    """

    vocab_sizes_by_measurement: dict[str, int] | None = None
    vocab_offsets_by_measurement: dict[str, int] | None = None
    measurements_idxmap: dict[str, dict[Hashable, int]] | None = None
    measurements_per_generative_mode: dict[DataModality, list[str]] | None = None
    event_types_idxmap: dict[str, int] | None = None

    @property
    def total_vocab_size(self) -> int:
        return (
            sum(self.vocab_sizes_by_measurement.values())
            + min(self.vocab_offsets_by_measurement.values())
            + (len(self.vocab_offsets_by_measurement) - len(self.vocab_sizes_by_measurement))
        )


class SeqPaddingSide(StrEnum):
    """Which side of the sequence gets padding in collated batches."""

    RIGHT = enum.auto()
    LEFT = enum.auto()


class SubsequenceSamplingStrategy(StrEnum):
    """How to sample a subsequence when a subject has more events than fit."""

    TO_END = enum.auto()
    FROM_START = enum.auto()
    RANDOM = enum.auto()


@config_dataclass
class PytorchDatasetConfig(JSONableMixin):
    """Configures the host-side dataset → device batch pipeline.

    Name kept from the reference (``config.py:646``) for checkpoint-directory
    and YAML compatibility, though batches here are numpy→jnp, not torch. Two
    TPU-specific knobs are added (both optional, defaulted to reference
    behavior): ``max_n_dynamic`` / ``max_n_static`` pin the data-element axes
    to static sizes so XLA never recompiles on batch shape.
    """

    save_dir: Path | None = None

    max_seq_len: int = 256
    min_seq_len: int = 2
    seq_padding_side: SeqPaddingSide = SeqPaddingSide.RIGHT
    subsequence_sampling_strategy: SubsequenceSamplingStrategy = SubsequenceSamplingStrategy.RANDOM

    train_subset_size: int | float | str = "FULL"
    train_subset_seed: int | None = None

    task_df_name: str | None = None

    do_include_subsequence_indices: bool = False
    do_include_subject_id: bool = False
    do_include_start_time_min: bool = False

    # TPU-native additions: static data-element axis sizes (None → inferred
    # from the cached data once, then frozen).
    max_n_dynamic: int | None = None
    max_n_static: int | None = None

    def __post_init__(self):
        if isinstance(self.seq_padding_side, str):
            self.seq_padding_side = SeqPaddingSide(self.seq_padding_side)
        if isinstance(self.subsequence_sampling_strategy, str):
            self.subsequence_sampling_strategy = SubsequenceSamplingStrategy(
                self.subsequence_sampling_strategy
            )
        if self.seq_padding_side not in SeqPaddingSide.values():
            raise ValueError(f"seq_padding_side invalid! Got {self.seq_padding_side}")
        if self.min_seq_len is None or self.min_seq_len < 0:
            raise ValueError(f"min_seq_len must be non-negative! Got {self.min_seq_len}")
        if self.max_seq_len is None or self.max_seq_len < self.min_seq_len:
            raise ValueError(
                f"max_seq_len must be >= min_seq_len! Got {self.max_seq_len} < {self.min_seq_len}"
            )
        if self.save_dir is not None and not isinstance(self.save_dir, Path):
            self.save_dir = Path(self.save_dir)

        match self.train_subset_size:
            case None | "FULL":
                pass
            case int() as n if n < 0:
                raise ValueError(f"If integral, train_subset_size must be positive! Got {n}")
            case float() as frac if frac <= 0 or frac >= 1:
                raise ValueError(f"If float, train_subset_size must be in (0, 1)! Got {frac}")
            case int() | float():
                pass
            case _:
                raise TypeError(
                    f"train_subset_size is of unrecognized type {type(self.train_subset_size)}."
                )

        if self.train_subset_size in (None, "FULL"):
            if self.train_subset_seed is not None:
                raise ValueError(
                    f"train_subset_seed {self.train_subset_seed} should be None "
                    "if train_subset_size is FULL."
                )
        elif self.train_subset_seed is None:
            self.train_subset_seed = int(random.randint(1, int(1e6)))

    def to_dict(self) -> dict:
        as_dict = dataclasses.asdict(self)
        as_dict["save_dir"] = str(as_dict["save_dir"]) if as_dict["save_dir"] is not None else None
        as_dict["seq_padding_side"] = str(self.seq_padding_side)
        as_dict["subsequence_sampling_strategy"] = str(self.subsequence_sampling_strategy)
        return as_dict

    @classmethod
    def from_dict(cls, as_dict: dict) -> "PytorchDatasetConfig":
        as_dict = dict(as_dict)
        if as_dict.get("save_dir") is not None:
            as_dict["save_dir"] = Path(as_dict["save_dir"])
        return cls(**as_dict)


@dataclasses.dataclass
class MeasurementConfig(JSONableMixin):
    """Configuration (pre- and post-fit) of a single measurement.

    Reference: ``config.py:795-1370``. Numerical measurement metadata are kept
    as pandas objects: a ``DataFrame`` indexed by vocabulary key for
    multivariate regression, a ``Series`` for univariate regression /
    functional time-dependent numeric measures. Metadata can be cached to /
    lazily re-read from CSV (``cache_measurement_metadata``), preserving the
    reference's ``inferred_measurement_metadata/*.csv`` artifact layout.
    """

    FUNCTORS = {
        "AgeFunctor": AgeFunctor,
        "TimeOfDayFunctor": TimeOfDayFunctor,
    }

    PREPROCESSING_METADATA_COLUMNS = OrderedDict(
        {"value_type": str, "outlier_model": object, "normalizer": object}
    )

    name: str | None = None
    temporality: TemporalityType | None = None
    modality: DataModality | None = None
    observation_frequency: float | None = None

    functor: TimeDependentFunctor | None = None

    vocabulary: Vocabulary | None = None

    values_column: str | None = None
    _measurement_metadata: pd.DataFrame | pd.Series | str | Path | None = None

    def __post_init__(self):
        if isinstance(self.temporality, str):
            self.temporality = TemporalityType(self.temporality)
        if isinstance(self.modality, str):
            self.modality = DataModality(self.modality)
        if isinstance(self.functor, dict):
            self.functor = self.FUNCTORS[self.functor["class"]].from_dict(self.functor)
        self._validate()

    def _validate(self):
        match self.temporality:
            case TemporalityType.STATIC:
                if self.functor is not None:
                    raise ValueError(
                        f"functor should be None for {self.temporality} measurements! Got {self.functor}"
                    )
                if self.is_numeric:
                    raise NotImplementedError(
                        f"Numeric data modalities like {self.modality} not yet supported on static measures."
                    )
            case TemporalityType.DYNAMIC:
                if self.functor is not None:
                    raise ValueError(
                        f"functor should be None for {self.temporality} measurements! Got {self.functor}"
                    )
                if self.modality == DataModality.SINGLE_LABEL_CLASSIFICATION:
                    raise ValueError(
                        f"{self.modality} on {self.temporality} measurements is not currently supported, as "
                        "event aggregation can turn single-label tasks into multi-label tasks in a manner "
                        "that is not currently automatically detected or compensated for."
                    )
            case TemporalityType.FUNCTIONAL_TIME_DEPENDENT:
                if self.functor is None:
                    raise ValueError(f"functor must be set for {self.temporality} measurements!")
                if self.modality is None:
                    self.modality = self.functor.OUTPUT_MODALITY
                elif self.modality not in (DataModality.DROPPED, self.functor.OUTPUT_MODALITY):
                    raise ValueError(
                        "self.modality must either be DataModality.DROPPED or "
                        f"{self.functor.OUTPUT_MODALITY} for {self.temporality} measures; "
                        f"got {self.modality}"
                    )
            case _:
                raise ValueError(
                    f"`self.temporality = {self.temporality}` Invalid! Must be in "
                    f"{', '.join(TemporalityType.values())}"
                )

        err_strings = []
        match self.modality:
            case DataModality.MULTIVARIATE_REGRESSION:
                if self.values_column is None:
                    err_strings.append(f"values_column must be set on a {self.modality} MeasurementConfig")
                if (self._measurement_metadata is not None) and not isinstance(
                    self._measurement_metadata, (pd.DataFrame, str, Path)
                ):
                    err_strings.append(
                        f"If set, measurement_metadata must be a DataFrame on a {self.modality} "
                        f"MeasurementConfig. Got {type(self._measurement_metadata)}"
                    )
            case DataModality.UNIVARIATE_REGRESSION:
                if self.values_column is not None:
                    err_strings.append(
                        f"values_column must be None on a {self.modality} MeasurementConfig. "
                        f"Got {self.values_column}"
                    )
                if (self._measurement_metadata is not None) and not isinstance(
                    self._measurement_metadata, (pd.Series, str, Path)
                ):
                    err_strings.append(
                        f"If set, measurement_metadata must be a Series on a {self.modality} "
                        f"MeasurementConfig. Got {type(self._measurement_metadata)}"
                    )
            case DataModality.SINGLE_LABEL_CLASSIFICATION | DataModality.MULTI_LABEL_CLASSIFICATION:
                if self.values_column is not None:
                    err_strings.append(
                        f"values_column must be None on a {self.modality} MeasurementConfig. "
                        f"Got {self.values_column}"
                    )
                if self._measurement_metadata is not None:
                    err_strings.append(
                        f"measurement_metadata must be None on a {self.modality} MeasurementConfig. "
                        f"Got {type(self._measurement_metadata)}"
                    )
            case DataModality.DROPPED | None:
                pass
            case _:
                raise ValueError(f"`self.modality = {self.modality}` Invalid!")
        if err_strings:
            raise ValueError("\n".join(err_strings))

    def drop(self):
        """Marks this measurement as dropped."""
        self.modality = DataModality.DROPPED
        self._measurement_metadata = None
        self.vocabulary = None

    @property
    def is_dropped(self) -> bool:
        return self.modality == DataModality.DROPPED

    @property
    def is_numeric(self) -> bool:
        return self.modality in (
            DataModality.MULTIVARIATE_REGRESSION,
            DataModality.UNIVARIATE_REGRESSION,
        )

    @property
    def measurement_metadata(self) -> pd.DataFrame | pd.Series | None:
        """The numerical-fit metadata, reading through a CSV cache if set."""
        match self._measurement_metadata:
            case None | pd.DataFrame() | pd.Series():
                return self._measurement_metadata
            case (Path() | str()) as fp:
                out = pd.read_csv(fp, index_col=0)
                if self.modality == DataModality.UNIVARIATE_REGRESSION:
                    if out.shape[1] != 1:
                        raise ValueError(
                            f"Expected a single-column dataframe for univariate regression; got {out}"
                        )
                    # object dtype so dict-valued cells can be assigned (the
                    # default arrow-backed string dtype rejects them).
                    out = out.iloc[:, 0].astype(object)
                    for col in ("outlier_model", "normalizer"):
                        if col in out.index and isinstance(out[col], str):
                            out[col] = eval(out[col])  # noqa: S307 — own artifact round-trip.
                else:
                    for col in ("outlier_model", "normalizer"):
                        if col in out.columns:
                            out[col] = out[col].apply(lambda x: eval(x) if isinstance(x, str) else x)  # noqa: S307
                return out
            case _:
                raise ValueError(f"_measurement_metadata is invalid! Got {self._measurement_metadata}")

    @measurement_metadata.setter
    def measurement_metadata(self, new_metadata: pd.DataFrame | pd.Series | None):
        if new_metadata is None:
            self._measurement_metadata = None
            return
        if isinstance(self._measurement_metadata, (str, Path)):
            new_metadata.to_csv(self._measurement_metadata)
        else:
            self._measurement_metadata = new_metadata

    def cache_measurement_metadata(self, fp: Path):
        """Writes metadata to ``fp`` and converts the in-memory copy to a pointer."""
        fp = Path(fp)
        if isinstance(self._measurement_metadata, (str, Path)):
            if str(fp) != str(self._measurement_metadata):
                raise ValueError(f"Caching is already enabled at {self._measurement_metadata} != {fp}")
            return
        if self.measurement_metadata is None:
            return
        fp.parent.mkdir(exist_ok=True, parents=True)
        self.measurement_metadata.to_csv(fp)
        self._measurement_metadata = str(fp.resolve())

    def uncache_measurement_metadata(self):
        """Re-materializes metadata in memory, dropping the CSV pointer."""
        if self._measurement_metadata is None:
            return
        if not isinstance(self._measurement_metadata, (str, Path)):
            raise ValueError("Caching is not enabled, can't uncache!")
        self._measurement_metadata = self.measurement_metadata

    def add_empty_metadata(self):
        """Initializes empty fit metadata of the modality-appropriate type."""
        if self.measurement_metadata is not None:
            raise ValueError(f"Can't add empty metadata; already set to {self.measurement_metadata}")
        match self.modality:
            case DataModality.UNIVARIATE_REGRESSION:
                self._measurement_metadata = pd.Series(
                    [None] * len(self.PREPROCESSING_METADATA_COLUMNS),
                    index=list(self.PREPROCESSING_METADATA_COLUMNS.keys()),
                    dtype=object,
                )
            case DataModality.MULTIVARIATE_REGRESSION:
                self._measurement_metadata = pd.DataFrame(
                    {c: pd.Series([], dtype=t) for c, t in self.PREPROCESSING_METADATA_COLUMNS.items()},
                    index=pd.Index([], name=self.name),
                )
            case _:
                raise ValueError(f"Can't add metadata to a {self.modality} measure!")

    def add_missing_mandatory_metadata_cols(self):
        if not self.is_numeric:
            raise ValueError("Only numeric measures can have measurement metadata")
        match self.measurement_metadata:
            case None:
                self.add_empty_metadata()
            case pd.DataFrame() as df:
                for col, dtype in self.PREPROCESSING_METADATA_COLUMNS.items():
                    if col not in df.columns:
                        df[col] = pd.Series([None] * len(df), dtype=dtype)
                if df.index.names == [None]:
                    df.index.names = [self.name]
                self.measurement_metadata = df
            case pd.Series() as s:
                for col in self.PREPROCESSING_METADATA_COLUMNS:
                    if col not in s.index:
                        s[col] = None
                self.measurement_metadata = s

    def to_dict(self) -> dict:
        as_dict = {
            "name": self.name,
            "temporality": str(self.temporality) if self.temporality is not None else None,
            "modality": str(self.modality) if self.modality is not None else None,
            "observation_frequency": self.observation_frequency,
            "functor": self.functor.to_dict() if self.functor is not None else None,
            "vocabulary": (
                {
                    "vocabulary": self.vocabulary.vocabulary,
                    "obs_frequencies": [float(f) for f in self.vocabulary.obs_frequencies],
                }
                if self.vocabulary is not None
                else None
            ),
            "values_column": self.values_column,
        }
        match self._measurement_metadata:
            case pd.DataFrame():
                as_dict["_measurement_metadata"] = self.measurement_metadata.to_dict(orient="tight")
            case pd.Series():
                as_dict["_measurement_metadata"] = self.measurement_metadata.to_dict(into=OrderedDict)
            case Path() | str():
                as_dict["_measurement_metadata"] = str(self._measurement_metadata)
            case None:
                as_dict["_measurement_metadata"] = None
        return as_dict

    @classmethod
    def from_dict(cls, as_dict: dict, base_dir: Path | None = None) -> "MeasurementConfig":
        as_dict = dict(as_dict)
        if as_dict.get("vocabulary") is not None:
            as_dict["vocabulary"] = Vocabulary(**as_dict["vocabulary"])

        mm = as_dict.get("_measurement_metadata")
        modality = as_dict.get("modality")
        if mm is not None:
            match mm:
                case str() | Path():
                    fp = Path(mm)
                    if base_dir is not None and not fp.is_absolute():
                        fp = base_dir / fp
                    elif base_dir is not None and not fp.exists():
                        # Artifacts produced elsewhere carry absolute paths
                        # from the producing machine; re-root them at the
                        # local dataset directory's metadata cache.
                        local = Path(base_dir) / "inferred_measurement_metadata" / fp.name
                        if local.exists():
                            fp = local
                    as_dict["_measurement_metadata"] = fp
                case dict() if modality == str(DataModality.MULTIVARIATE_REGRESSION):
                    as_dict["_measurement_metadata"] = pd.DataFrame.from_dict(mm, orient="tight")
                case dict():
                    as_dict["_measurement_metadata"] = pd.Series(mm)
        return cls(**as_dict)

    def __eq__(self, other) -> bool:
        if not isinstance(other, MeasurementConfig):
            return False
        self_d, other_d = self.to_dict(), other.to_dict()
        return self_d == other_d

    def describe(self, line_width: int = 60, wrap_lines: bool = False, stream=None) -> int | None:
        """Text summary: modality line, value types, vocabulary sparkline."""
        lines = []
        lines.append(
            f"{self.name}: {self.temporality}, {self.modality} "
            f"observed {100 * (self.observation_frequency or 0):.1f}%"
        )
        match self.modality:
            case DataModality.UNIVARIATE_REGRESSION:
                if self.measurement_metadata is not None:
                    lines.append(f"Value is a {self.measurement_metadata['value_type']}")
            case DataModality.MULTIVARIATE_REGRESSION:
                lines.append("Value Types:")
                if self.measurement_metadata is not None:
                    for t, cnt in self.measurement_metadata.value_type.value_counts().items():
                        lines.append(f"  {cnt} {t}")
        if self.vocabulary is not None:
            from io import StringIO

            sio = StringIO()
            self.vocabulary.describe(line_width=line_width - 2, stream=sio, wrap_lines=wrap_lines)
            lines.append("Vocabulary:")
            lines.extend(f"  {line}" for line in sio.getvalue().split("\n"))
        desc = "\n".join(lines)
        if stream is None:
            print(desc)
            return None
        return stream.write(desc)


@dataclasses.dataclass
class DatasetConfig(JSONableMixin):
    """Dataset-level ETL configuration (reference ``config.py:1372-1615``)."""

    measurement_configs: dict[str, MeasurementConfig] = dataclasses.field(default_factory=dict)

    min_events_per_subject: int | None = None

    agg_by_time_scale: str | None = "1h"

    min_valid_column_observations: COUNT_OR_PROPORTION | None = None
    min_valid_vocab_element_observations: COUNT_OR_PROPORTION | None = None
    min_true_float_frequency: PROPORTION | None = None
    min_unique_numerical_observations: COUNT_OR_PROPORTION | None = None

    outlier_detector_config: dict[str, Any] | None = None
    normalizer_config: dict[str, Any] | None = None

    save_dir: Path | None = None

    def __post_init__(self):
        for name, cfg in self.measurement_configs.items():
            if cfg.name is None:
                cfg.name = name
            elif cfg.name != name:
                raise ValueError(f"Measurement config {name} has name {cfg.name} which differs from dict key!")

        for var in ("min_valid_column_observations", "min_valid_vocab_element_observations",
                    "min_unique_numerical_observations"):
            val = getattr(self, var)
            if val is not None:
                match val:
                    case bool():
                        raise TypeError(f"{var} must be a fraction or count; got bool")
                    case float() if 0 < val < 1:
                        pass
                    case int() if val > 1:
                        pass
                    case float() | int():
                        raise ValueError(f"{var} must be a fraction in (0,1) or a count > 1; got {val}")
                    case _:
                        raise TypeError(
                            f"{var} must either be a fraction (float between 0 and 1) or count "
                            f"(int > 1). Got {type(val)} of {val}"
                        )

        if self.min_true_float_frequency is not None:
            if not isinstance(self.min_true_float_frequency, float) or not (
                0 < self.min_true_float_frequency < 1
            ):
                raise TypeError(
                    f"min_true_float_frequency must be a fraction in (0,1); got {self.min_true_float_frequency}"
                )

        for var in ("outlier_detector_config", "normalizer_config"):
            val = getattr(self, var)
            if val is not None and (not isinstance(val, dict) or "cls" not in val):
                raise ValueError(f"{var} must be a dictionary with 'cls' key! Got {val}")

        for k, v in self.measurement_configs.items():
            try:
                v._validate()
            except Exception as e:
                raise ValueError(f"Measurement config {k} invalid!") from e

        if self.save_dir is not None and not isinstance(self.save_dir, Path):
            self.save_dir = Path(self.save_dir)

    def to_dict(self) -> dict:
        as_dict = {
            "measurement_configs": {k: v.to_dict() for k, v in self.measurement_configs.items()},
            "min_events_per_subject": self.min_events_per_subject,
            "agg_by_time_scale": self.agg_by_time_scale,
            "min_valid_column_observations": self.min_valid_column_observations,
            "min_valid_vocab_element_observations": self.min_valid_vocab_element_observations,
            "min_true_float_frequency": self.min_true_float_frequency,
            "min_unique_numerical_observations": self.min_unique_numerical_observations,
            "outlier_detector_config": self.outlier_detector_config,
            "normalizer_config": self.normalizer_config,
            "save_dir": str(self.save_dir) if self.save_dir is not None else None,
        }
        return as_dict

    @classmethod
    def from_dict(cls, as_dict: dict, base_dir: Path | None = None) -> "DatasetConfig":
        as_dict = dict(as_dict)
        as_dict["measurement_configs"] = {
            k: MeasurementConfig.from_dict(v, base_dir=base_dir)
            for k, v in as_dict.get("measurement_configs", {}).items()
        }
        if as_dict.get("save_dir") is not None:
            as_dict["save_dir"] = Path(as_dict["save_dir"])
        return cls(**as_dict)

    def __eq__(self, other) -> bool:
        return isinstance(other, DatasetConfig) and self.to_dict() == other.to_dict()
