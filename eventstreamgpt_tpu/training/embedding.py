"""Embedding extraction: encoder-only inference over all splits.

Rebuild of
``/root/reference/EventStream/transformer/lightning_modules/embedding.py:19-155``:
an encoder-only model (pretrained weights grafted from a generative
checkpoint) pooled per subject (``last``/``max``/``mean``/``none``), written
per split to ``{load_from_model_dir}/embeddings/{task_df_name}/
{split}_embeddings.npy`` (numpy instead of torch.save — the consumer surface
is numpy arrays either way). Fill rows in short final batches are dropped via
``valid_mask`` so every subject appears exactly once.
"""

from __future__ import annotations

from pathlib import Path

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..data.jax_dataset import JaxDataset
from ..data.prefetch import prefetch_to_device
from ..models.config import StructuredEventProcessingMode, StructuredTransformerConfig
from ..models.transformer import (
    ConditionallyIndependentPointProcessTransformer,
    NestedAttentionPointProcessTransformer,
)
from ..ops.tensor_ops import safe_masked_max, safe_weighted_avg
from .fine_tuning import FinetuneConfig, init_from_pretrained_encoder
from .pretrain import data_parallel_mesh, replicate, shard_batch


class EmbeddingsOnlyModel(nn.Module):
    """Encoder-only wrapper (reference ``embedding.py:19``)."""

    config: StructuredTransformerConfig

    @nn.compact
    def __call__(self, batch, **kwargs):
        cfg = self.config
        if cfg.structured_event_processing_mode == StructuredEventProcessingMode.NESTED_ATTENTION:
            encoder = NestedAttentionPointProcessTransformer(cfg, name="encoder")
        else:
            encoder = ConditionallyIndependentPointProcessTransformer(cfg, name="encoder")
        return encoder(batch, **kwargs)


def embed_batch(model, params, config, batch, pooling_method: str):
    """Pooled per-subject embeddings for one batch (reference ``predict_step``)."""
    encoded = model.apply(params, batch).last_hidden_state
    uses_dep_graph = (
        config.structured_event_processing_mode == StructuredEventProcessingMode.NESTED_ATTENTION
    )
    event_encoded = encoded[:, :, -1, :] if uses_dep_graph else encoded

    if pooling_method == "last":
        B, L, _ = event_encoded.shape
        positions = jnp.arange(L)[None, :]
        last_idx = jnp.max(jnp.where(batch.event_mask, positions, 0), axis=1)
        return event_encoded[jnp.arange(B), last_idx]
    if pooling_method == "max":
        return safe_masked_max(jnp.swapaxes(event_encoded, 1, 2), batch.event_mask)
    if pooling_method == "mean":
        return safe_weighted_avg(jnp.swapaxes(event_encoded, 1, 2), batch.event_mask)[0]
    if pooling_method == "none":
        return event_encoded
    raise ValueError(f"{pooling_method} is not a supported pooling method.")


def get_embeddings(cfg: FinetuneConfig) -> dict[str, Path]:
    """Extracts + writes embeddings for train/tuning/held_out (reference ``:89-155``).

    Returns the written file paths per split.
    """
    config = cfg.config
    oc = cfg.optimization_config

    train_pyd = JaxDataset(cfg.data_config, split="train")
    config.set_to_dataset(train_pyd)

    pooling_method = (config.task_specific_params or {}).get("pooling_method", "last")

    model = EmbeddingsOnlyModel(config)
    init_batch = next(
        train_pyd.batches(min(oc.validation_batch_size, len(train_pyd)), shuffle=False)
    )
    template = model.init(jax.random.PRNGKey(0), init_batch)
    # The generative checkpoint also carries output-layer params; graft just
    # the encoder subtree into the encoder-only template.
    params = init_from_pretrained_encoder(template, cfg.pretrained_weights_fp)

    embed_step = jax.jit(
        lambda params, batch: embed_batch(model, params, config, batch, pooling_method)
    )

    # Batch-shard extraction over a data mesh (replicated params): the
    # encoder forward runs on every chip (VERDICT r02 missing #1).
    mesh = data_parallel_mesh(oc.validation_batch_size)
    params = replicate(params, mesh)

    out_dir = Path(cfg.load_from_model_dir) / "embeddings" / (cfg.task_df_name or "all")
    written: dict[str, Path] = {}
    from ..data.device_dataset import DeviceDataset

    for sp in ("train", "tuning", "held_out"):
        dataset = train_pyd if sp == "train" else JaxDataset(cfg.data_config, split=sp)
        chunks = []
        # Device-resident batches when the split fits HBM (r05 feed-path
        # redesign: no per-batch wire transfer); host prefetch otherwise.
        # valid_mask is a host array either way, so reading it costs no
        # device sync.
        # Multi-process topologies take the sharded resident layout, whose
        # dealt stream interleaves subject pools — but the saved .npy
        # contract is dataset row order; extraction is a one-shot job, so
        # take the ordered host path there WITHOUT first paying the sharded
        # table build + HBM upload that try_create would do.
        dd = (
            DeviceDataset.try_create(
                dataset, mesh=mesh, batch_sizes=(oc.validation_batch_size,)
            )
            if jax.process_count() == 1
            else None
        )
        if dd is not None:
            batch_iter = (
                (b, np.asarray(b.valid_mask) if b.valid_mask is not None else None)  # graftcheck: allow GC001 -- valid_mask is a host array on device batches, no sync
                for b in dd.batches(
                    oc.validation_batch_size, shuffle=False, drop_last=False, seed=0
                )
            )
        else:
            batch_iter = prefetch_to_device(
                dataset.batches(oc.validation_batch_size, shuffle=False, drop_last=False, seed=0),
                lambda b: shard_batch(b, mesh),
                host_stats_fn=lambda b: (
                    np.asarray(b.valid_mask) if b.valid_mask is not None else None  # graftcheck: allow GC001 -- runs in the prefetch worker on the host batch
                ),
            )
        try:
            for batch, valid in batch_iter:
                emb = np.asarray(embed_step(params, batch))  # graftcheck: allow GC001 -- extraction readback IS the job (embeddings stream to .npy)
                if valid is not None:
                    emb = emb[valid]
                chunks.append(emb)
        finally:
            batch_iter.close()
        embeddings = np.concatenate(chunks, axis=0)

        embeddings_fp = out_dir / f"{sp}_embeddings.npy"
        if jax.process_index() == 0:
            if embeddings_fp.is_file() and not cfg.do_overwrite:
                print(
                    f"Embeddings already exist at {embeddings_fp}. To overwrite, set "
                    "`do_overwrite=True`."
                )
            else:
                embeddings_fp.parent.mkdir(parents=True, exist_ok=True)
                print(f"Saving {sp} embeddings to {embeddings_fp}.")
                np.save(embeddings_fp, embeddings)
        written[sp] = embeddings_fp
    return written
