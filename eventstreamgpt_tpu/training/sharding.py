"""Parameter sharding rules: data-parallel, tensor-parallel, and FSDP layouts.

The reference's distributed story is data-parallel only (Lightning DDP;
SURVEY §2.10). TPU-native scaling adds two parameter-sharding axes:

* a ``model`` mesh axis with Megatron-style tensor parallelism where it pays
  at event-stream scale: the unified vocabulary embedding table and
  classification head are the widest matrices in the model (vocab can be
  ~10k+; SURVEY §2.10 names the vocab-sharded ``ClassificationLayer`` as the
  first TP candidate) — both sharded over the vocab dimension; MLP blocks
  split column-then-row (``c_fc`` columns, ``c_proj`` rows) and attention
  splits by heads (``q/k/v`` columns, ``out_proj`` rows), so each pair needs
  a single all-reduce inserted by XLA GSPMD;

* an ``fsdp`` mesh axis (r10 scale-up round, per the pjit/TPUv4 playbook in
  PAPERS.md): EVERY parameter — and, via `shard_state`, its Adam moments —
  shards its largest eligible dimension over the axis, and the batch shards
  over ``(data, fsdp)`` jointly, so XLA GSPMD inserts the FSDP schedule
  automatically: all-gather each (layer's) weights on use in forward and
  backward, reduce-scatter the gradients, and update each optimizer shard
  locally. Per-chip parameter+optimizer HBM drops by the fsdp factor, which
  is what lets widths the replicated layout cannot fit (the bench width
  ladder's 4096 rung) compile at all. Stacked scan-over-layers parameters
  (``h_scan`` scopes, leading ``(L/p,)`` layer axis — models/transformer.py)
  shard a *within-layer* dimension, never the layer axis, so each scan step
  gathers exactly one layer's shards.

Rules are regex → ``PartitionSpec`` over flattened parameter paths for TP,
plus the generic largest-divisible-dim rule for FSDP; unmatched leaves
replicate. No explicit collectives anywhere — layouts are declared, XLA
inserts the psums/gathers over ICI/DCN.
"""

from __future__ import annotations

import re
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

TP_RULES: list[tuple[str, tuple]] = [
    (r".*/embed_table$", ("model", None)),
    (r".*/ClassificationLayer/kernel$", (None, "model")),
    (r".*/ClassificationLayer/bias$", ("model",)),
    (r".*/mlp/c_fc/kernel$", (None, "model")),
    (r".*/mlp/c_fc/bias$", ("model",)),
    (r".*/mlp/c_proj/kernel$", ("model", None)),
    (r".*/attention/[qkv]_proj/kernel$", (None, "model")),
    (r".*/attention/out_proj/kernel$", ("model", None)),
]

# Scanned layer stacks carry a leading (L/p,) layer axis that FSDP must not
# shard: the scan gathers one layer per step, so sharding the stack axis
# would turn every step's gather into a cross-layer collective.
_SCAN_SCOPE_RE = re.compile(r"(^|/)h_scan(/|$)")


def make_mesh(n_data: int, n_model: int = 1, n_fsdp: int = 1, devices=None) -> Mesh:
    """A ``(data[, fsdp], model)`` mesh over the first ``n_data·n_fsdp·n_model``
    devices. The historical 2-D ``(data, model)`` shape is preserved when
    ``n_fsdp == 1`` so existing layouts (and their committed collective
    budgets) are unchanged; ``fsdp`` slots between ``data`` and ``model`` —
    parameter all-gathers ride higher-bandwidth links than the gradient
    sweep, but the per-layer TP all-reduces keep the innermost axis."""
    if devices is None:
        devices = jax.devices()
    n = n_data * n_fsdp * n_model
    if len(devices) < n:
        raise ValueError(
            f"Need {n} devices for a {n_data}x{n_fsdp}x{n_model} mesh; have {len(devices)}"
        )
    if n_fsdp == 1:
        return Mesh(np.asarray(devices[:n]).reshape(n_data, n_model), ("data", "model"))
    return Mesh(
        np.asarray(devices[:n]).reshape(n_data, n_fsdp, n_model),
        ("data", "fsdp", "model"),
    )


def batch_partition_axes(mesh: Mesh) -> tuple[str, ...]:
    """The mesh axes the batch dimension shards over: ``data`` plus (when
    present) ``fsdp`` — FSDP is data parallelism with sharded state, so the
    batch splits over both jointly."""
    return tuple(
        a for a in ("data", "fsdp") if a in mesh.axis_names and mesh.shape.get(a, 1) >= 1
    )


def _leaf_path(path) -> str:
    return "/".join(str(getattr(p, "key", p)) for p in path)


def _fsdp_dim(path_str: str, shape: tuple, spec: list, n_fsdp: int) -> int | None:
    """The dimension FSDP shards: the largest dim divisible by ``n_fsdp``
    that no other axis already occupies, excluding a scanned stack's leading
    layer axis. ``None`` when no dimension qualifies (the leaf replicates
    over ``fsdp`` and is reported by `make_param_shardings`)."""
    stacked = bool(_SCAN_SCOPE_RE.search(path_str))
    candidates = [
        d
        for d in range(len(shape))
        if spec[d] is None
        and shape[d] % n_fsdp == 0
        and shape[d] > 0
        and not (stacked and d == 0)
    ]
    if not candidates:
        return None
    return max(candidates, key=lambda d: (shape[d], -d))


def make_param_shardings(
    params: Any,
    mesh: Mesh,
    strict: bool = False,
    max_replicated_frac: float = 0.5,
    verbose: bool = True,
) -> Any:
    """NamedSharding tree for ``params``: TP rules + FSDP + replicated fallback.

    ``verbose=False`` suppresses the replication warnings (strict-mode
    errors still raise) — the serve-time TP path (`serving/engine.py`)
    builds a layout per engine replica with ``strict=True, verbose=False``:
    a fleet would otherwise print the same small-leaf report once per
    replica, but a layout that replicates most parameter bytes still
    raises at engine construction instead of OOMing at admit.

    Tensor-parallel rules apply first (``model`` axis; dimensions that don't
    divide the axis evenly are left unsharded for that rule — GSPMD would
    handle uneven shards, but even splits keep layouts predictable), then
    the ``fsdp`` axis shards each leaf's largest remaining divisible
    dimension (`_fsdp_dim`). Leaves no rule touches replicate.

    Every replicated-despite-a-requested-axis leaf is reported by path with
    its shape, and ``strict=True`` upgrades the report to an error when more
    than ``max_replicated_frac`` of the parameter *bytes* stay replicated —
    a sharding layout that silently replicates the big tables is an HBM
    budget lie, not a warning.
    """
    has_model = "model" in mesh.axis_names and mesh.shape.get("model", 1) > 1
    has_fsdp = "fsdp" in mesh.axis_names and mesh.shape.get("fsdp", 1) > 1
    n_model = mesh.shape.get("model", 1)
    n_fsdp = mesh.shape.get("fsdp", 1)

    n_sharded = 0
    tp_skipped: list[str] = []
    replicated: list[str] = []
    replicated_bytes = 0
    total_bytes = 0

    def rule_for(path, leaf):
        nonlocal n_sharded, replicated_bytes, total_bytes
        nbytes = int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        total_bytes += nbytes
        p_str = _leaf_path(path)
        spec = [None] * leaf.ndim
        stacked = bool(_SCAN_SCOPE_RE.search(p_str))
        if has_model:
            for pattern, tp_spec in TP_RULES:
                if re.match(pattern, p_str):
                    # Stacked scan params carry a leading layer axis on top of
                    # the rule's rank; the rule then applies to the trailing
                    # within-layer dims.
                    offset = 1 if stacked and len(tp_spec) + 1 == leaf.ndim else 0
                    if len(tp_spec) + offset == leaf.ndim and all(
                        axis is None or leaf.shape[d + offset] % n_model == 0
                        for d, axis in enumerate(tp_spec)
                    ):
                        for d, axis in enumerate(tp_spec):
                            spec[d + offset] = axis
                    else:
                        tp_skipped.append(f"{p_str} {tuple(leaf.shape)}")
                    break
        if has_fsdp:
            d = _fsdp_dim(p_str, tuple(leaf.shape), spec, n_fsdp)
            if d is not None:
                spec[d] = "fsdp"
        if any(axis is not None for axis in spec):
            n_sharded += 1
            # Normalized spec (no trailing Nones): jit's propagated output
            # shardings drop them, and a donated step whose inputs compare
            # structurally unequal to its outputs re-compiles once.
            while spec and spec[-1] is None:
                spec.pop()
            return NamedSharding(mesh, P(*spec))
        if has_model or has_fsdp:
            replicated.append(f"{p_str} {tuple(leaf.shape)}")
            replicated_bytes += nbytes
        # P() — not P(None, ..., None): the specs are semantically equal but
        # compare unequal, and a donated step whose input shardings differ
        # structurally from its propagated outputs re-compiles every other
        # dispatch (the CompileGuard suite pins this).
        return NamedSharding(mesh, P())

    out = jax.tree_util.tree_map_with_path(rule_for, params)
    if has_model and tp_skipped and verbose:
        # Partial failures matter most when the widest matrices (embedding /
        # classification head — the motivation for TP) are the ones skipped.
        print(
            f"WARNING: {len(tp_skipped)} TP-eligible parameter(s) have dims not divisible by "
            f"the model axis ({n_model}) and stay replicated for that rule: "
            + "; ".join(tp_skipped[:5])
            + ("; ..." if len(tp_skipped) > 5 else "")
        )
    if (has_model or has_fsdp) and replicated:
        frac = replicated_bytes / max(total_bytes, 1)
        axes = "/".join(
            n for n, on in (("model", has_model), ("fsdp", has_fsdp)) if on
        )
        msg = (
            f"{len(replicated)} parameter(s) ({replicated_bytes} bytes, "
            f"{100.0 * frac:.1f}% of parameter bytes) matched no {axes} sharding rule "
            "and stay replicated: " + "; ".join(replicated[:8])
            + ("; ..." if len(replicated) > 8 else "")
        )
        if strict and frac > max_replicated_frac:
            raise ValueError(
                f"strict sharding: {msg} — exceeds max_replicated_frac="
                f"{max_replicated_frac}. Check that hidden/vocab dims divide the "
                "requested shard counts."
            )
        if verbose:
            print(f"WARNING: {msg}")
    if (has_model or has_fsdp) and n_sharded == 0:
        msg = (
            "a parameter-sharding mesh axis was requested but NO parameter is "
            "sharded — all parameters are replicated. Check that hidden/vocab "
            "dims divide the shard counts."
        )
        if strict:
            raise ValueError(f"strict sharding: {msg}")
        if verbose:
            print(f"WARNING: {msg}")
    return out


def shard_params(params: Any, mesh: Mesh, strict: bool = False) -> Any:
    """Device-puts parameters per `make_param_shardings`."""
    return jax.device_put(params, make_param_shardings(params, mesh, strict=strict))


def make_state_shardings(state: Any, mesh: Mesh, strict: bool = False) -> Any:
    """Sharding tree for a `TrainState` (or its ``jax.eval_shape``): params
    per `make_param_shardings`, optimizer moments alongside their
    parameters, scalars replicated.

    Optimizer moments (adamw ``mu``/``nu``, possibly nested under MultiSteps)
    are param-structured subtrees; they are detected by tree structure and
    given the parameter shardings so each moment lives beside its parameter
    shard — under ``fsdp`` this is exactly the ZeRO-style sharded optimizer
    state (each chip updates only its own parameter shard).

    Accepting ``eval_shape`` output is what makes big-model init honest:
    ``jax.jit(init_fn, out_shardings=make_state_shardings(shapes, mesh))``
    materializes each parameter (and moment) directly into its shard —
    at the width-ladder 4096 rung the replicated tree this avoids would not
    fit one chip's HBM at all (`train_state_bytes`).
    """
    param_sh = make_param_shardings(state.params, mesh, strict=strict)
    param_treedef = jax.tree_util.tree_structure(state.params)
    replicated = NamedSharding(mesh, P())

    def is_param_tree(x) -> bool:
        try:
            return jax.tree_util.tree_structure(x) == param_treedef
        except Exception:
            return False

    def sh(node):
        if is_param_tree(node):
            return param_sh
        return jax.tree_util.tree_map(lambda _: replicated, node)

    return type(state)(
        step=replicated,
        params=param_sh,
        opt_state=jax.tree_util.tree_map(sh, state.opt_state, is_leaf=is_param_tree),
    )


def shard_state(state: Any, mesh: Mesh, strict: bool = False) -> Any:
    """Device-puts a materialized `TrainState` per `make_state_shardings`."""
    return jax.device_put(state, make_state_shardings(state, mesh, strict=strict))


def train_state_bytes(n_params: int, adam_moments: int = 2, grad_bytes: int = 4) -> int:
    """Analytic steady-state training footprint of ``n_params`` parameters:
    fp32 params + fp32 Adam ``mu``/``nu`` + one transient fp32 gradient tree
    (activations excluded — they scale with batch/remat policy, not width
    alone). The bench width ladder holds this against the documented
    16 GB/chip HBM budget to decide which rungs fit replicated and which
    are FSDP-only."""
    return int(n_params) * (4 * (1 + adam_moments) + grad_bytes)


# ------------------------------------------------- graftcheck Tier C census
def _census_programs():
    """The training subsystem's compiled-program fleet for the Tier C
    census: every canonical pretrain layout this module's meshes/shardings
    can produce, plus the fine-tune steps. The builders are Tier B's
    canonical constructions (same toy shapes, so the committed COLLECTIVES
    budgets re-apply); the donated argument is always the train state."""
    from ..analysis import program_checks as pc
    from ..analysis.program_census import CensusProgram

    specs = [
        # (label, COLLECTIVES.json budget key, builder)
        ("pretrain:dp8", "dp8", lambda: pc.canonical_pretrain_step(8, 1)),
        ("pretrain:dp4_tp2", "dp4_tp2", lambda: pc.canonical_pretrain_step(4, 2)),
        (
            "pretrain:dp8_health",
            "dp8",
            lambda: pc.canonical_pretrain_step(8, 1, with_health=True),
        ),
        ("pretrain:na_dp8", "na_dp8", lambda: pc.canonical_pretrain_step(8, 1, na=True)),
        (
            "pretrain:na_pallas_dp8",
            "na_pallas_dp8",
            lambda: pc.canonical_pretrain_step(8, 1, na=True, na_impl="pallas_interpret"),
        ),
        ("pretrain:scan_dp8", "scan_dp8", lambda: pc.canonical_pretrain_step(8, 1, scan=True)),
        (
            "pretrain:fsdp8",
            "fsdp8",
            lambda: pc.canonical_pretrain_step(1, 1, scan=True, n_fsdp=8),
        ),
        ("finetune:dp8", None, lambda: pc.canonical_finetune_step(8)),
        (
            "finetune:dp8_health",
            None,
            lambda: pc.canonical_finetune_step(8, with_health=True),
        ),
    ]
    out = {}
    for label, budget_key, build in specs:
        fn, args = build()
        out[label] = CensusProgram(
            label, fn, args, donate_argnums=(0,), budget_key=budget_key
        )
    return out


def _register_census() -> None:
    from ..analysis.program_census import register_aot_provider

    register_aot_provider("training", _census_programs)


_register_census()
