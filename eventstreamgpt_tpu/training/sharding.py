"""Parameter sharding rules: data-parallel + tensor-parallel layouts.

The reference's distributed story is data-parallel only (Lightning DDP;
SURVEY §2.10). TPU-native scaling adds a ``model`` mesh axis with
Megatron-style tensor parallelism where it pays at event-stream scale:

* the unified vocabulary embedding table and classification head are the
  widest matrices in the model (vocab can be ~10k+; SURVEY §2.10 names the
  vocab-sharded ``ClassificationLayer`` as the first TP candidate) — both are
  sharded over the vocab dimension;
* MLP blocks split column-then-row (``c_fc`` columns, ``c_proj`` rows) and
  attention splits by heads (``q/k/v`` columns, ``out_proj`` rows), so each
  pair needs a single all-reduce inserted by XLA GSPMD.

Everything else stays replicated. Rules are regex → ``PartitionSpec`` over
flattened parameter paths; unmatched leaves replicate. No explicit
collectives anywhere — layouts are declared, XLA inserts the psums over
ICI/DCN.
"""

from __future__ import annotations

import re
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

TP_RULES: list[tuple[str, tuple]] = [
    (r".*/embed_table$", ("model", None)),
    (r".*/ClassificationLayer/kernel$", (None, "model")),
    (r".*/ClassificationLayer/bias$", ("model",)),
    (r".*/mlp/c_fc/kernel$", (None, "model")),
    (r".*/mlp/c_fc/bias$", ("model",)),
    (r".*/mlp/c_proj/kernel$", ("model", None)),
    (r".*/attention/[qkv]_proj/kernel$", (None, "model")),
    (r".*/attention/out_proj/kernel$", ("model", None)),
]


def make_mesh(n_data: int, n_model: int = 1, devices=None) -> Mesh:
    """A 2-D ``(data, model)`` mesh over the first ``n_data·n_model`` devices."""
    if devices is None:
        devices = jax.devices()
    n = n_data * n_model
    if len(devices) < n:
        raise ValueError(f"Need {n} devices for a {n_data}x{n_model} mesh; have {len(devices)}")
    return Mesh(np.asarray(devices[:n]).reshape(n_data, n_model), ("data", "model"))


def _leaf_path(path) -> str:
    return "/".join(str(getattr(p, "key", p)) for p in path)


def make_param_shardings(params: Any, mesh: Mesh) -> Any:
    """NamedSharding tree for ``params``: TP rules + replicated fallback.

    Dimensions that don't divide the ``model`` axis evenly are left
    unsharded for that rule (GSPMD would handle uneven shards, but even
    splits keep layouts predictable).
    """
    has_model = "model" in mesh.axis_names and mesh.shape.get("model", 1) > 1
    n_model = mesh.shape.get("model", 1)

    n_sharded = 0
    skipped: list[str] = []

    def rule_for(path, leaf):
        nonlocal n_sharded
        if has_model:
            p_str = _leaf_path(path)
            for pattern, spec in TP_RULES:
                if re.match(pattern, p_str):
                    # Rank must match before indexing shape for divisibility.
                    if len(spec) == leaf.ndim and all(
                        axis is None or leaf.shape[d] % n_model == 0
                        for d, axis in enumerate(spec)
                    ):
                        n_sharded += 1
                        return NamedSharding(mesh, P(*spec))
                    skipped.append(f"{p_str} {tuple(leaf.shape)}")
                    break
        return NamedSharding(mesh, P())

    out = jax.tree_util.tree_map_with_path(rule_for, params)
    if has_model and skipped:
        # Partial failures matter most when the widest matrices (embedding /
        # classification head — the motivation for TP) are the ones skipped.
        print(
            f"WARNING: {len(skipped)} TP-eligible parameter(s) have dims not divisible by "
            f"the model axis ({n_model}) and stay replicated: "
            + "; ".join(skipped[:5])
            + ("; ..." if len(skipped) > 5 else "")
        )
    if has_model and n_sharded == 0:
        print(
            "WARNING: a 'model' mesh axis was requested but no parameter is sharded — "
            "all parameters are replicated. Check that hidden/vocab dims divide the "
            "tensor-parallel shard count."
        )
    return out


def shard_params(params: Any, mesh: Mesh) -> Any:
    """Device-puts parameters per `make_param_shardings`."""
    return jax.device_put(params, make_param_shardings(params, mesh))


def shard_state(state: Any, mesh: Mesh) -> Any:
    """Shards a `TrainState`: params + optimizer moments follow the same
    layout, scalars replicate.

    Optimizer moments (adamw ``mu``/``nu``, possibly nested under MultiSteps)
    are param-structured subtrees; they are detected by tree structure and
    given the parameter shardings so each moment lives beside its parameter
    shard.
    """
    param_sh = make_param_shardings(state.params, mesh)
    param_treedef = jax.tree_util.tree_structure(state.params)
    replicated = NamedSharding(mesh, P())

    def is_param_tree(x) -> bool:
        try:
            return jax.tree_util.tree_structure(x) == param_treedef
        except Exception:
            return False

    def put(node):
        if is_param_tree(node):
            return jax.device_put(node, param_sh)
        return jax.device_put(node, replicated)

    return type(state)(
        step=jax.device_put(state.step, replicated),
        params=jax.device_put(state.params, param_sh),
        opt_state=jax.tree_util.tree_map(put, state.opt_state, is_leaf=is_param_tree),
    )
