"""Per-measurement generative metric collection, gated by ``MetricsConfig``.

Rebuild of the reference Lightning module's metric zoo + logging
(``/root/reference/EventStream/transformer/lightning_modules/generative_modeling.py:117-432``):
``build_metrics`` instantiates one accumulator per measurement × modality ×
metric × averaging that the config admits on any split; ``update`` consumes a
``GenerativeSequenceModelOutput`` exactly the way ``log_metrics`` does
(distribution sampling for TTE/regression, masked slicing, indexed-regression
expansion); ``compute`` returns ``{split}_{measurement}_{metric}`` → value.

Losses are tracked per subject: each component loss in this codebase is a
macro-average over the batch's subject axis with zero contributions from
blanked fill rows, so re-weighting the batch mean by ``batch_size /
n_valid`` recovers the exact per-valid-subject average (this is how eval
avoids double-counting wrap-around fill subjects; see
``JaxDataset.batches``).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from ..data.types import DataModality
from ..models.config import (
    Averaging,
    MetricCategories,
    Metrics,
    MetricsConfig,
    Split,
    StructuredTransformerConfig,
)
from .metrics import (
    ExplainedVariance,
    MeanMetric,
    MeanSquaredError,
    MeanSquaredLogError,
    MulticlassAccuracy,
    MulticlassAUROC,
    MulticlassAveragePrecision,
    MultilabelAccuracy,
    MultilabelAUROC,
    MultilabelAveragePrecision,
)

CLASSIFICATION_MODALITIES = {
    DataModality.SINGLE_LABEL_CLASSIFICATION,
    DataModality.MULTI_LABEL_CLASSIFICATION,
}


def expand_indexed_regression_np(x: np.ndarray, idx: np.ndarray, vocab_size: int) -> np.ndarray:
    """Scatter sparse per-key values into dense vocab space (host-side twin of
    ``ops.tensor_ops.expand_indexed_regression``)."""
    out = np.zeros((*x.shape[:-1], vocab_size), dtype=x.dtype)
    np.put_along_axis(out, idx.astype(np.int64), x, axis=-1)
    return out


class GenerativeMetrics:
    """Accumulates loss + quality metrics for one split of generative eval."""

    def __init__(
        self,
        config: StructuredTransformerConfig,
        metrics_config: MetricsConfig,
        split: str = Split.TUNING,
    ):
        self.config = config
        self.metrics_config = metrics_config
        self.split = split

        self.loss = MeanMetric()
        self.loss_parts: dict[str, MeanMetric] = {}

        n_thresh = metrics_config.n_auc_thresholds or 50

        # TTE metrics (reference ``build_metrics`` :124-130).
        self.tte_metrics: dict[str, Any] = {}
        if metrics_config.do_log(split, MetricCategories.TTE):
            for name, m in (
                ("MSE", MeanSquaredError),
                ("MSLE", MeanSquaredLogError),
                ("explained_variance", ExplainedVariance),
            ):
                if metrics_config.do_log(split, MetricCategories.TTE, name):
                    self.tte_metrics[name] = m()

        # Per-measurement zoo (reference :132-228).
        self.metrics: dict[str, dict[str, dict[str, Any]]] = {}
        for task_type, measurements in config.measurements_per_generative_mode.items():
            for measurement in measurements:
                vocab_size = config.vocab_sizes_by_measurement.get(measurement, 1)
                per_meas = self.metrics.setdefault(measurement, {}).setdefault(task_type, {})

                if task_type == DataModality.SINGLE_LABEL_CLASSIFICATION:
                    cat = MetricCategories.CLASSIFICATION
                    zoo = {
                        Metrics.ACCURACY: (
                            lambda avg: MulticlassAccuracy(vocab_size, average=avg, ignore_index=0),
                            [Averaging.MACRO, Averaging.WEIGHTED, Averaging.MICRO],
                        ),
                        Metrics.AUROC: (
                            lambda avg: MulticlassAUROC(
                                vocab_size, thresholds=n_thresh, average=avg, ignore_index=0
                            ),
                            [Averaging.MACRO, Averaging.WEIGHTED],
                        ),
                        Metrics.AUPRC: (
                            lambda avg: MulticlassAveragePrecision(
                                vocab_size, thresholds=n_thresh, average=avg, ignore_index=0
                            ),
                            [Averaging.MACRO, Averaging.WEIGHTED],
                        ),
                    }
                elif task_type == DataModality.MULTI_LABEL_CLASSIFICATION:
                    cat = MetricCategories.CLASSIFICATION
                    zoo = {
                        Metrics.ACCURACY: (
                            lambda avg: MultilabelAccuracy(vocab_size, average=avg),
                            [Averaging.MACRO, Averaging.WEIGHTED, Averaging.MICRO],
                        ),
                        Metrics.AUROC: (
                            lambda avg: MultilabelAUROC(vocab_size, thresholds=n_thresh, average=avg),
                            [Averaging.MACRO, Averaging.WEIGHTED, Averaging.MICRO],
                        ),
                        Metrics.AUPRC: (
                            lambda avg: MultilabelAveragePrecision(
                                vocab_size, thresholds=n_thresh, average=avg
                            ),
                            [Averaging.MACRO, Averaging.WEIGHTED, Averaging.MICRO],
                        ),
                    }
                elif task_type == DataModality.UNIVARIATE_REGRESSION:
                    cat = MetricCategories.REGRESSION
                    zoo = {
                        Metrics.MSE: (lambda avg: MeanSquaredError(), [None]),
                        Metrics.EXPLAINED_VARIANCE: (lambda avg: ExplainedVariance(), [None]),
                    }
                elif task_type == DataModality.MULTIVARIATE_REGRESSION:
                    cat = MetricCategories.REGRESSION
                    zoo = {
                        Metrics.MSE: (lambda avg: MeanSquaredError(), [None]),
                        Metrics.EXPLAINED_VARIANCE: (
                            lambda avg: ExplainedVariance(
                                multioutput="uniform_average"
                                if avg == Averaging.MACRO
                                else "variance_weighted"
                            ),
                            [Averaging.MACRO, Averaging.WEIGHTED],
                        ),
                    }
                else:
                    raise ValueError(f"Unrecognized modality {task_type}!")

                for metric, (factory, averagings) in zoo.items():
                    for averaging in averagings:
                        metric_name = str(metric) if averaging is None else f"{averaging}_{metric}"
                        if metrics_config.do_log(split, cat, metric_name):
                            per_meas[metric_name] = factory(averaging)

    # ------------------------------------------------------------------ update
    def update(self, out, key: jax.Array | None = None, n_valid: int | None = None) -> None:
        """Accumulates one batch's ``GenerativeSequenceModelOutput``.

        ``n_valid`` is the count of non-fill subjects (``valid_mask.sum()``);
        ``key`` drives distribution sampling for TTE/regression metrics and is
        only needed when those categories are enabled.
        """
        mc = self.metrics_config
        split = self.split

        event_mask = np.asarray(out.event_mask)
        B = event_mask.shape[0]
        if n_valid is None:
            n_valid = B

        # Loss (+ parts). Denominator semantics differ per part: cls/reg parts
        # go through ``weighted_loss`` (mean over *non-empty* subjects — fill
        # rows are excluded already, no rescale), while the TTE part averages
        # over all B subjects (``TTE_LL_per_patient.mean()``) with zero
        # contribution from fill rows → rescale by B/n_valid. The total is
        # reconstructed from the parts on short batches so each term gets its
        # own correction.
        tte_scale = B / max(n_valid, 1)
        parts: dict[str, float] = {}
        if out.losses is not None:
            if out.losses.classification:
                parts.update(
                    {f"{k}_cls_NLL": float(v) for k, v in out.losses.classification.items()}
                )
            if out.losses.regression:
                parts.update(
                    {f"{k}_reg_NLL": float(v) for k, v in out.losses.regression.items()}
                )
            if out.losses.time_to_event is not None:
                parts["TTE_reg_NLL"] = float(out.losses.time_to_event) * tte_scale
        if out.loss is not None:
            if n_valid == B or not parts:
                loss_val = float(out.loss)
            else:
                loss_val = sum(parts.values())
            self.loss.update(loss_val, weight=n_valid)
        if mc.do_log(split, MetricCategories.LOSS_PARTS):
            for name, v in parts.items():
                acc = self.loss_parts.setdefault(name, MeanMetric())
                acc.update(v, weight=n_valid)

        if mc.do_log_only_loss(split):
            return

        # TTE metrics (reference ``log_tte_metrics`` :279-305): sample the
        # distribution, keep interior intra-event times whose next event is
        # observed.
        if self.tte_metrics and out.preds is not None and out.preds.time_to_event is not None:
            key, sub = jax.random.split(key)
            tte_preds = np.asarray(out.preds.time_to_event.sample(sub))
            sel = event_mask[:, 1:]
            tte_preds = tte_preds[:, :-1][sel]
            tte_labels = np.asarray(out.labels.time_to_event)[sel]
            for acc in self.tte_metrics.values():
                acc.update(tte_preds, tte_labels)

        values_mask = np.asarray(out.dynamic_values_mask) if out.dynamic_values_mask is not None else None

        for measurement, by_task in self.metrics.items():
            mask = event_mask
            if not mask.any():
                continue
            for task_type, metric_dict in by_task.items():
                if not metric_dict:
                    continue
                if task_type in CLASSIFICATION_MODALITIES:
                    # preds = logits of the sample distribution at observed events.
                    _, sample_dist = out.preds.classification[measurement]
                    preds = np.asarray(sample_dist.logits)[mask]
                    labels = np.asarray(out.labels.classification[measurement])[mask]
                    for acc in metric_dict.values():
                        acc.update(preds, labels.astype(np.int64) if labels.ndim == 1 else labels)
                elif task_type == DataModality.MULTIVARIATE_REGRESSION:
                    vocab_size = self.config.vocab_sizes_by_measurement[measurement]
                    _, dist = out.preds.regression[measurement]
                    key, sub = jax.random.split(key)
                    preds = np.asarray(dist.sample(sub))[mask]
                    labels = np.asarray(out.labels.regression[measurement])[mask]
                    preds_indices = np.asarray(out.preds.regression_indices[measurement])[mask]
                    labels_indices = np.asarray(out.labels.regression_indices[measurement])[mask]
                    data_el_mask = values_mask[mask]
                    preds = preds[data_el_mask]
                    labels = labels[data_el_mask]
                    preds_indices = preds_indices[data_el_mask]
                    labels_indices = labels_indices[data_el_mask]
                    preds_expanded = expand_indexed_regression_np(
                        preds[..., None], preds_indices[..., None], vocab_size
                    )
                    labels_expanded = expand_indexed_regression_np(
                        labels[..., None], labels_indices[..., None], vocab_size
                    )
                    for acc in metric_dict.values():
                        acc.update(preds_expanded, labels_expanded)
                elif task_type == DataModality.UNIVARIATE_REGRESSION:
                    _, dist = out.preds.regression[measurement]
                    key, sub = jax.random.split(key)
                    preds = np.asarray(dist.sample(sub))[mask]
                    labels = np.asarray(out.labels.regression[measurement])[mask]
                    for acc in metric_dict.values():
                        acc.update(preds, labels)

    # ----------------------------------------------------------------- compute
    def compute(self) -> dict[str, float]:
        """Returns ``{split}_...``-named metric values, NaNs dropped."""
        split = self.split
        result = {f"{split}_loss": self.loss.compute()}
        for name, acc in self.loss_parts.items():
            result[f"{split}_{name}"] = acc.compute()
        for name, acc in self.tte_metrics.items():
            result[f"{split}_TTE_{name}"] = acc.compute()
        for measurement, by_task in self.metrics.items():
            for metric_dict in by_task.values():
                for metric_name, acc in metric_dict.items():
                    result[f"{split}_{measurement}_{metric_name}"] = acc.compute()
        return {k: v for k, v in result.items() if not (isinstance(v, float) and np.isnan(v))}
