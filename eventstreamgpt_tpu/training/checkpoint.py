"""Model serialization: the ``save_pretrained`` directory contract + resume.

The reference bootstraps every downstream stage (fine-tune, zero-shot,
embeddings, trajectory generation) from a pretrain ``save_dir`` containing
``config.json``, ``data_config.json``, ``optimization_config.json``, and HF
``save_pretrained`` weights under ``pretrained_weights``
(``/root/reference/EventStream/transformer/lightning_modules/generative_modeling.py:113-115,576-596,670``;
``fine_tuning.py:329-372``). This module reproduces that contract with orbax
as the array store, and adds what the reference lacks (SURVEY §5.3/§5.4):
**step-level, preemption-safe resume checkpoints** via
``orbax.CheckpointManager`` (atomic finalization, keeps the most recent K,
restores latest on restart — what TPU-pod preemption requires).
"""

from __future__ import annotations

import json
import warnings
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

from ..models.config import StructuredTransformerConfig
from ..utils.misc import atomic_write_json

PRETRAINED_WEIGHTS_DIR = "pretrained_weights"


def _abs(path: Path | str) -> Path:
    return Path(path).expanduser().resolve()


def save_pretrained(save_dir: Path | str, params: Any, config: StructuredTransformerConfig | None = None) -> Path:
    """Writes model parameters (and optionally the config) under ``save_dir``.

    Mirrors ``LM.save_pretrained`` + the rank-0 config dump: weights go to
    ``save_dir/pretrained_weights``, config to ``save_dir/config.json`` (only
    when given — the pretrain driver writes configs up front).
    """
    save_dir = _abs(save_dir)
    save_dir.mkdir(parents=True, exist_ok=True)
    weights_fp = save_dir / PRETRAINED_WEIGHTS_DIR
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(weights_fp, params, force=True)
    if config is not None:
        config.to_json_file(save_dir / "config.json", do_overwrite=True)
    return weights_fp


def load_pretrained(
    save_dir: Path | str, params_template: Any | None = None
) -> tuple[Any, StructuredTransformerConfig]:
    """Loads ``(params, config)`` from a ``save_pretrained`` directory.

    ``params_template`` (a pytree of like-shaped arrays, e.g. from
    ``model.init``) restores with matching dtypes/structure; without it the
    stored tree structure is returned as saved.
    """
    save_dir = _abs(save_dir)
    config = StructuredTransformerConfig.from_json_file(save_dir / "config.json")
    ckptr = ocp.PyTreeCheckpointer()
    weights_fp = save_dir / PRETRAINED_WEIGHTS_DIR
    if params_template is not None:
        params = ckptr.restore(weights_fp, item=params_template)
    else:
        params = ckptr.restore(weights_fp)
    return params, config


class TrainCheckpointManager:
    """Step-level train-state checkpointing with preemption-safe resume.

    Wraps ``orbax.CheckpointManager``: atomic commits, ``max_to_keep`` most
    recent steps retained, ``latest_step`` discovery for auto-resume. The
    train state is whatever pytree the training loop passes (params +
    opt_state + step + rng); scalars ride alongside as JSON metadata.
    """

    def __init__(self, ckpt_dir: Path | str, max_to_keep: int = 2, save_interval_steps: int = 1):
        self.ckpt_dir = _abs(ckpt_dir)
        self.ckpt_dir.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.ckpt_dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
            ),
        )

    def save(self, step: int, state: Any, metadata: dict | None = None) -> bool:
        saved = self._mgr.save(step, args=ocp.args.PyTreeSave(state))
        # Metadata rides next to the manager root; small, human-readable. It
        # is (re)written even when the array save was skipped because the step
        # already exists — e.g. the epoch-end save landing on the same step as
        # an in-loop save must still upgrade the metadata to epoch_complete.
        if (
            metadata is not None
            and jax.process_index() == 0
            and (saved or step in self._mgr.all_steps())
        ):
            # Atomic publish (tmp + fsync + rename): a kill mid-write must
            # never leave a truncated sidecar that poisons the next resume.
            # Sidecars live on shared storage, so only process 0 writes them
            # (every process would write identical bytes; racing renames and
            # prunes are pure downside).
            atomic_write_json(self.ckpt_dir / f"metadata_{step}.json", metadata)
        if saved:
            self._prune_metadata()
        return saved

    def _prune_metadata(self) -> None:
        """Drops sidecars (metadata, integrity manifests, stranded tmp
        files) whose checkpoint the manager has deleted. Process 0 only —
        sidecar files are shared across a pod."""
        if jax.process_index() != 0:
            return
        live = set(self._mgr.all_steps())
        for pattern in ("metadata_*.json", "manifest_*.json"):
            for fp in self.ckpt_dir.glob(pattern):
                try:
                    step = int(fp.stem.split("_")[-1])
                except ValueError:
                    continue
                if step not in live:
                    fp.unlink(missing_ok=True)
        # Stranded tmps from killed writers (both the legacy fixed name and
        # the per-pid unique names). Only process 0 ever writes sidecars, so
        # no live writer's tmp can be swept here.
        for pattern in ("*.json.tmp", "*.json.*.tmp"):
            for fp in self.ckpt_dir.glob(pattern):
                fp.unlink(missing_ok=True)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        """All committed checkpoint steps, ascending."""
        return sorted(self._mgr.all_steps())

    def restore(self, state_template: Any, step: int | None = None) -> tuple[Any, int]:
        """Restores ``(state, step)`` at ``step`` (default: latest)."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"No checkpoints found under {self.ckpt_dir}")
        state = self._mgr.restore(step, args=ocp.args.PyTreeRestore(state_template))
        return state, step

    def metadata(self, step: int) -> dict | None:
        fp = self.ckpt_dir / f"metadata_{step}.json"
        if fp.exists():
            try:
                with open(fp) as f:
                    return json.load(f)
            except (json.JSONDecodeError, UnicodeDecodeError, OSError) as e:
                # A sidecar predating the atomic-write fix (or rotted on
                # disk) must degrade the resume, not crash it: callers treat
                # None as "no metadata" and fall back to epoch-boundary
                # semantics.
                warnings.warn(
                    f"undecodable checkpoint metadata sidecar {fp}: {e}; ignoring it",
                    RuntimeWarning,
                    stacklevel=2,
                )
                return None
        return None

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
