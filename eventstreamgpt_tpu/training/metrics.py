"""Streaming metric accumulators + the per-measurement generative metric zoo.

TPU-native replacement for the reference's ``torchmetrics`` usage
(``/root/reference/EventStream/transformer/lightning_modules/generative_modeling.py:117-432``).
Device code produces model outputs; metric state lives on host as plain numpy
(eval metric accumulation is not the hot path), with AUROC/AUPRC computed on a
fixed threshold grid (``MetricsConfig.n_auc_thresholds``) exactly like the
reference's binned ``torchmetrics`` configuration, so memory stays bounded at
MIMIC scale.

Averaging semantics follow ``torchmetrics``:

* multiclass accuracy: per-class recall; ``macro`` averages classes with
  support, ``micro``/``weighted`` collapse to overall correct/N.
* multilabel accuracy: per-label binary accuracy at a 0.5 threshold.
* AUROC: trapezoidal area over the binned (FPR, TPR) curve.
* AUPRC / average precision: step-interpolated sum over the binned PR curve.
* explained variance: ``1 - Var[y - yhat]/Var[y]``, per output dim, combined
  by ``uniform_average`` or ``variance_weighted``.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "BinaryAccuracy",
    "BinaryAUROC",
    "BinaryAveragePrecision",
    "MeanMetric",
    "MulticlassAccuracy",
    "MultilabelAccuracy",
    "MulticlassAUROC",
    "MultilabelAUROC",
    "MulticlassAveragePrecision",
    "MultilabelAveragePrecision",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "ExplainedVariance",
]


class MeanMetric:
    """Weighted running mean (the ``self.log`` aggregation in the reference)."""

    def __init__(self):
        self.total = 0.0
        self.weight = 0.0

    def update(self, value: float, weight: float = 1.0) -> None:
        if not np.isfinite(value):
            return
        self.total += float(value) * float(weight)
        self.weight += float(weight)

    def compute(self) -> float:
        return self.total / self.weight if self.weight > 0 else float("nan")


def _as_probs_multiclass(preds: np.ndarray) -> np.ndarray:
    """Logits → probabilities if needed (torchmetrics auto-detection)."""
    if preds.size and (preds.min() < 0 or preds.max() > 1):
        z = preds - preds.max(axis=-1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=-1, keepdims=True)
    return preds


def _as_probs_binary(preds: np.ndarray) -> np.ndarray:
    if preds.size and (preds.min() < 0 or preds.max() > 1):
        return 1.0 / (1.0 + np.exp(-preds))
    return preds


class MulticlassAccuracy:
    """Multiclass accuracy over ``(N, C)`` preds and ``(N,)`` int labels.

    ``macro`` = mean per-class recall over classes with support; ``micro`` and
    ``weighted`` = overall fraction correct (they coincide for accuracy).
    """

    def __init__(self, num_classes: int, average: str = "micro", ignore_index: int | None = None):
        self.num_classes = num_classes
        self.average = average
        self.ignore_index = ignore_index
        self.correct = np.zeros(num_classes, dtype=np.int64)
        self.support = np.zeros(num_classes, dtype=np.int64)

    def update(self, preds: np.ndarray, labels: np.ndarray) -> None:
        preds = np.asarray(preds)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        if preds.ndim == labels.ndim + 1:
            preds = preds.reshape(-1, preds.shape[-1]).argmax(axis=-1)
        else:
            preds = preds.reshape(-1)
        if self.ignore_index is not None:
            keep = labels != self.ignore_index
            preds, labels = preds[keep], labels[keep]
        if labels.size == 0:
            return
        self.support += np.bincount(labels, minlength=self.num_classes)
        hits = labels[preds == labels]
        self.correct += np.bincount(hits, minlength=self.num_classes)

    def compute(self) -> float:
        if self.average == "macro":
            has = self.support > 0
            if not has.any():
                return float("nan")
            return float((self.correct[has] / self.support[has]).mean())
        total = self.support.sum()
        return float(self.correct.sum() / total) if total else float("nan")


class MultilabelAccuracy:
    """Multilabel accuracy over ``(N, L)`` preds (logits or probs) and 0/1 labels."""

    def __init__(self, num_labels: int, average: str = "macro", threshold: float = 0.5):
        self.num_labels = num_labels
        self.average = average
        self.threshold = threshold
        self.correct = np.zeros(num_labels, dtype=np.int64)
        self.count = np.zeros(num_labels, dtype=np.int64)
        self.positives = np.zeros(num_labels, dtype=np.int64)

    def update(self, preds: np.ndarray, labels: np.ndarray) -> None:
        preds = _as_probs_binary(np.asarray(preds, dtype=np.float64)).reshape(-1, self.num_labels)
        labels = np.asarray(labels).reshape(-1, self.num_labels) > 0.5
        hard = preds >= self.threshold
        self.correct += (hard == labels).sum(axis=0)
        self.count += labels.shape[0]
        self.positives += labels.sum(axis=0)

    def compute(self) -> float:
        if not self.count.any():
            return float("nan")
        per_label = self.correct / np.maximum(self.count, 1)
        if self.average == "micro":
            return float(self.correct.sum() / self.count.sum())
        if self.average == "weighted":
            w = self.positives.astype(np.float64)
            if w.sum() == 0:
                return float("nan")
            return float((per_label * w).sum() / w.sum())
        return float(per_label.mean())


class _BinnedCurve:
    """Shared thresholded confusion state for AUROC / average precision.

    State per label/class: TP and FP counts at each threshold on a uniform
    [0, 1] grid, plus positive/negative totals — the same bounded-memory
    scheme ``torchmetrics`` uses when ``thresholds`` is an int.
    """

    def __init__(self, n_series: int, thresholds: int):
        self.n_series = n_series
        self.thresholds = np.linspace(0.0, 1.0, int(thresholds))
        self.tp = np.zeros((n_series, len(self.thresholds)), dtype=np.int64)
        self.fp = np.zeros((n_series, len(self.thresholds)), dtype=np.int64)
        self.pos = np.zeros(n_series, dtype=np.int64)
        self.neg = np.zeros(n_series, dtype=np.int64)

    def _update_series(self, s: int, probs: np.ndarray, targets: np.ndarray) -> None:
        """probs (M,), targets bool (M,)."""
        above = probs[:, None] >= self.thresholds[None, :]
        self.tp[s] += (above & targets[:, None]).sum(axis=0)
        self.fp[s] += (above & ~targets[:, None]).sum(axis=0)
        self.pos[s] += int(targets.sum())
        self.neg[s] += int((~targets).sum())

    def _auroc_series(self, s: int) -> float:
        if self.pos[s] == 0 or self.neg[s] == 0:
            return float("nan")
        tpr = self.tp[s] / self.pos[s]
        fpr = self.fp[s] / self.neg[s]
        # Thresholds ascend → rates descend; integrate over increasing FPR.
        order = np.argsort(fpr, kind="stable")
        return float(np.trapezoid(tpr[order], fpr[order]))

    def _ap_series(self, s: int) -> float:
        if self.pos[s] == 0:
            return float("nan")
        recall = self.tp[s] / self.pos[s]
        denom = self.tp[s] + self.fp[s]
        precision = np.where(denom > 0, self.tp[s] / np.maximum(denom, 1), 1.0)
        # Thresholds ascending → recall descending. AP = Σ (R_t − R_{t+1})·P_t
        # with R after the last threshold pinned to 0.
        r = np.concatenate([recall, [0.0]])
        return float(np.sum((r[:-1] - r[1:]) * precision))

    def _average(self, per_series: np.ndarray, average: str, micro_fn=None) -> float:
        if average == "micro" and micro_fn is not None:
            return micro_fn()
        valid = ~np.isnan(per_series)
        if not valid.any():
            return float("nan")
        if average == "weighted":
            w = self.pos.astype(np.float64)
            w[~valid] = 0.0
            if w.sum() == 0:
                return float("nan")
            return float(np.nansum(per_series * w) / w.sum())
        # macro (and micro fallback when no micro_fn is meaningful)
        return float(per_series[valid].mean())


class MulticlassAUROC(_BinnedCurve):
    """One-vs-rest binned AUROC over ``(N, C)`` preds, ``(N,)`` int labels."""

    def __init__(
        self,
        num_classes: int,
        thresholds: int = 50,
        average: str = "macro",
        ignore_index: int | None = None,
    ):
        super().__init__(num_classes, thresholds)
        self.average = average
        self.ignore_index = ignore_index

    def update(self, preds: np.ndarray, labels: np.ndarray) -> None:
        preds = np.asarray(preds, dtype=np.float64).reshape(-1, self.n_series)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        if self.ignore_index is not None:
            keep = labels != self.ignore_index
            preds, labels = preds[keep], labels[keep]
        if labels.size == 0:
            return
        probs = _as_probs_multiclass(preds)
        for c in range(self.n_series):
            self._update_series(c, probs[:, c], labels == c)

    def compute(self) -> float:
        per = np.array([self._auroc_series(c) for c in range(self.n_series)])
        return self._average(per, self.average)


class MultilabelAUROC(_BinnedCurve):
    """Per-label binned AUROC over ``(N, L)`` preds and 0/1 labels."""

    def __init__(self, num_labels: int, thresholds: int = 50, average: str = "macro"):
        # One extra series accumulates the flattened micro curve.
        super().__init__(num_labels + 1, thresholds)
        self.num_labels = num_labels
        self.average = average

    def update(self, preds: np.ndarray, labels: np.ndarray) -> None:
        preds = np.asarray(preds, dtype=np.float64).reshape(-1, self.num_labels)
        labels = np.asarray(labels).reshape(-1, self.num_labels) > 0.5
        probs = _as_probs_binary(preds)
        for c in range(self.num_labels):
            self._update_series(c, probs[:, c], labels[:, c])
        self._update_series(self.num_labels, probs.reshape(-1), labels.reshape(-1))

    def compute(self) -> float:
        if self.average == "micro":
            return self._auroc_series(self.num_labels)
        per = np.array([self._auroc_series(c) for c in range(self.num_labels)])
        saved = self.pos
        self.pos = self.pos[: self.num_labels]
        try:
            return self._average(per, self.average)
        finally:
            self.pos = saved


class MulticlassAveragePrecision(MulticlassAUROC):
    def compute(self) -> float:
        per = np.array([self._ap_series(c) for c in range(self.n_series)])
        return self._average(per, self.average)


class MultilabelAveragePrecision(MultilabelAUROC):
    def compute(self) -> float:
        if self.average == "micro":
            return self._ap_series(self.num_labels)
        per = np.array([self._ap_series(c) for c in range(self.num_labels)])
        saved = self.pos
        self.pos = self.pos[: self.num_labels]
        try:
            return self._average(per, self.average)
        finally:
            self.pos = saved


class BinaryAccuracy:
    """Binary accuracy over ``(N,)`` preds (logits or probs) and 0/1 labels."""

    def __init__(self, threshold: float = 0.5):
        self.inner = MultilabelAccuracy(1, average="micro", threshold=threshold)

    def update(self, preds: np.ndarray, labels: np.ndarray) -> None:
        self.inner.update(np.asarray(preds).reshape(-1, 1), np.asarray(labels).reshape(-1, 1))

    def compute(self) -> float:
        return self.inner.compute()


class BinaryAUROC:
    """Binned AUROC over ``(N,)`` preds (logits or probs) and 0/1 labels."""

    def __init__(self, thresholds: int = 50):
        self.inner = MultilabelAUROC(1, thresholds=thresholds, average="macro")

    def update(self, preds: np.ndarray, labels: np.ndarray) -> None:
        self.inner.update(np.asarray(preds).reshape(-1, 1), np.asarray(labels).reshape(-1, 1))

    def compute(self) -> float:
        return self.inner.compute()


class BinaryAveragePrecision:
    """Binned average precision over ``(N,)`` preds and 0/1 labels."""

    def __init__(self, thresholds: int = 50):
        self.inner = MultilabelAveragePrecision(1, thresholds=thresholds, average="macro")

    def update(self, preds: np.ndarray, labels: np.ndarray) -> None:
        self.inner.update(np.asarray(preds).reshape(-1, 1), np.asarray(labels).reshape(-1, 1))

    def compute(self) -> float:
        return self.inner.compute()


class MeanSquaredError:
    def __init__(self):
        self.total = 0.0
        self.count = 0

    def update(self, preds: np.ndarray, labels: np.ndarray) -> None:
        preds = np.asarray(preds, dtype=np.float64).reshape(-1)
        labels = np.asarray(labels, dtype=np.float64).reshape(-1)
        self.total += float(((preds - labels) ** 2).sum())
        self.count += preds.size

    def compute(self) -> float:
        return self.total / self.count if self.count else float("nan")


class MeanSquaredLogError:
    """mean((log1p(pred) − log1p(label))²); inputs must be ≥ −1."""

    def __init__(self):
        self.total = 0.0
        self.count = 0

    def update(self, preds: np.ndarray, labels: np.ndarray) -> None:
        preds = np.asarray(preds, dtype=np.float64).reshape(-1)
        labels = np.asarray(labels, dtype=np.float64).reshape(-1)
        with np.errstate(invalid="ignore"):
            err = np.log1p(np.maximum(preds, -1.0)) - np.log1p(np.maximum(labels, -1.0))
        self.total += float(np.nansum(err**2))
        self.count += preds.size

    def compute(self) -> float:
        return self.total / self.count if self.count else float("nan")


class ExplainedVariance:
    """``1 − Var[y − ŷ]/Var[y]`` per output dim, then averaged.

    ``multioutput``: ``uniform_average`` (reference ``macro``) or
    ``variance_weighted`` (reference ``weighted``); scalar streams use a
    single output dim.
    """

    def __init__(self, multioutput: str = "uniform_average"):
        self.multioutput = multioutput
        self._n = None

    def _init_state(self, d: int) -> None:
        self._n = np.zeros(d)
        self._sum_y = np.zeros(d)
        self._sum_y2 = np.zeros(d)
        self._sum_e = np.zeros(d)
        self._sum_e2 = np.zeros(d)

    def update(self, preds: np.ndarray, labels: np.ndarray) -> None:
        preds = np.asarray(preds, dtype=np.float64)
        labels = np.asarray(labels, dtype=np.float64)
        if preds.ndim <= 1:
            preds = preds.reshape(-1, 1)
            labels = labels.reshape(-1, 1)
        else:
            preds = preds.reshape(-1, preds.shape[-1])
            labels = labels.reshape(-1, labels.shape[-1])
        if self._n is None:
            self._init_state(preds.shape[-1])
        err = labels - preds
        self._n += preds.shape[0]
        self._sum_y += labels.sum(axis=0)
        self._sum_y2 += (labels**2).sum(axis=0)
        self._sum_e += err.sum(axis=0)
        self._sum_e2 += (err**2).sum(axis=0)

    def compute(self) -> float:
        if self._n is None or not self._n.any():
            return float("nan")
        n = np.maximum(self._n, 1)
        var_y = self._sum_y2 / n - (self._sum_y / n) ** 2
        var_e = self._sum_e2 / n - (self._sum_e / n) ** 2
        with np.errstate(divide="ignore", invalid="ignore"):
            ev = 1.0 - var_e / var_y
        ev = np.where(var_y > 0, ev, 0.0)
        if self.multioutput == "variance_weighted":
            denom = var_y.sum()
            return float((ev * var_y).sum() / denom) if denom > 0 else float("nan")
        return float(ev.mean())
