"""The fine-tuning harness for stream classification.

Rebuild of ``/root/reference/EventStream/transformer/lightning_modules/fine_tuning.py``:

* ``FinetuneConfig`` (``:270-381``): bootstraps from a pretrain ``save_dir``
  — loads ``config.json`` + ``data_config.json``, applies overrides, sets
  the task dataframe, and derives few-shot save dirs for train subsets.
* the stream-classification metric sets (``:97-150``): binary /
  multiclass / multilabel accuracy + AUROC + AUPRC.
* ``train`` (``:384-514``): datasets → ``set_to_dataset`` → config dumps →
  model (optionally warm-started from pretrained encoder weights) → fit with
  tuning eval + early stopping → final tuning/held-out metric JSONs.

The train loop itself reuses the pretraining harness machinery (mesh,
jitted donated step, orbax checkpoints) — only the model/loss and metric
collection differ.
"""

from __future__ import annotations

import dataclasses
import json
import random
import time
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from ..data.config import PytorchDatasetConfig
from ..data.jax_dataset import JaxDataset
from ..data.prefetch import prefetch_to_device
from ..models.config import OptimizationConfig, Split, StructuredTransformerConfig
from ..models.fine_tuning_model import ESTForStreamClassification
from ..utils import config_dataclass
from .checkpoint import load_pretrained, save_pretrained
from .metrics import (
    BinaryAccuracy,
    BinaryAUROC,
    BinaryAveragePrecision,
    MeanMetric,
    MulticlassAccuracy,
    MulticlassAUROC,
    MulticlassAveragePrecision,
    MultilabelAccuracy,
    MultilabelAUROC,
    MultilabelAveragePrecision,
)
from .optimizer import build_optimizer
from .pretrain import TrainState, data_parallel_mesh, make_train_step, replicate, shard_batch

# ---------------------------------------------------------------- metrics
class StreamClassificationMetrics:
    """Binary/multiclass/multilabel metric set (reference ``:97-150``)."""

    def __init__(self, config: StructuredTransformerConfig, split: str, n_thresholds: int = 50):
        self.split = split
        self.loss = MeanMetric()
        problem = config.problem_type
        n = config.num_labels

        if problem == "single_label_classification" and n > 2:
            kw = {"num_classes": n}
            self.metrics = {
                "macro_AUROC": MulticlassAUROC(**kw, thresholds=n_thresholds, average="macro"),
                "weighted_AUROC": MulticlassAUROC(**kw, thresholds=n_thresholds, average="weighted"),
                "macro_accuracy": MulticlassAccuracy(**kw, average="macro"),
                "weighted_accuracy": MulticlassAccuracy(**kw, average="weighted"),
                "micro_accuracy": MulticlassAccuracy(**kw, average="micro"),
                "macro_AUPRC": MulticlassAveragePrecision(
                    **kw, thresholds=n_thresholds, average="macro"
                ),
                "weighted_AUPRC": MulticlassAveragePrecision(
                    **kw, thresholds=n_thresholds, average="weighted"
                ),
            }
        elif problem == "single_label_classification" and n == 2:
            self.metrics = {
                "AUROC": BinaryAUROC(thresholds=n_thresholds),
                "accuracy": BinaryAccuracy(),
                "AUPRC": BinaryAveragePrecision(thresholds=n_thresholds),
            }
        elif problem == "multi_label_classification":
            kw = {"num_labels": n}
            self.metrics = {
                "macro_AUROC": MultilabelAUROC(**kw, thresholds=n_thresholds, average="macro"),
                "weighted_AUROC": MultilabelAUROC(**kw, thresholds=n_thresholds, average="weighted"),
                "micro_AUROC": MultilabelAUROC(**kw, thresholds=n_thresholds, average="micro"),
                "macro_accuracy": MultilabelAccuracy(**kw, average="macro"),
                "weighted_accuracy": MultilabelAccuracy(**kw, average="weighted"),
                "micro_accuracy": MultilabelAccuracy(**kw, average="micro"),
                "macro_AUPRC": MultilabelAveragePrecision(
                    **kw, thresholds=n_thresholds, average="macro"
                ),
                "weighted_AUPRC": MultilabelAveragePrecision(
                    **kw, thresholds=n_thresholds, average="weighted"
                ),
                "micro_AUPRC": MultilabelAveragePrecision(
                    **kw, thresholds=n_thresholds, average="micro"
                ),
            }
        else:
            raise ValueError(f"{problem} not valid")

    def update(
        self, out, n_valid: int | None = None, valid_mask=None, skip_metrics=()
    ) -> None:
        preds = np.asarray(out.preds)
        labels = np.asarray(out.labels)
        B = len(labels)
        # Fill rows are blanked subjects — drop them. The dealt (sharded)
        # plan stream can leave fill rows MID-batch (one run per exhausted
        # pool), so a boolean mask is authoritative; ``n_valid`` keeps the
        # historical trailing-fill prefix convention for callers without one.
        if valid_mask is None:
            valid_mask = np.arange(B) < (B if n_valid is None else n_valid)
        else:
            valid_mask = np.asarray(valid_mask, bool)
        preds, labels = preds[valid_mask], labels[valid_mask]
        self.loss.update(float(out.loss), weight=int(valid_mask.sum()))
        for name, metric in self.metrics.items():
            if any(s in name for s in skip_metrics):
                continue
            metric.update(preds, labels)

    def compute(self) -> dict[str, float]:
        out = {f"{self.split}_loss": self.loss.compute()}
        for name, metric in self.metrics.items():
            v = metric.compute()
            if not (isinstance(v, float) and np.isnan(v)):
                out[f"{self.split}_{name}"] = v
        return out


# ----------------------------------------------------------------- config
@config_dataclass
class FinetuneConfig:
    """Fine-tuning driver config (reference ``FinetuneConfig`` :270-381)."""

    load_from_model_dir: str | Path | None = None
    seed: int = 1

    pretrained_weights_fp: str | Path | None = None
    save_dir: str | Path | None = None

    do_overwrite: bool = False
    # Debug mode: NaN provenance via ``jax_debug_nans`` (see PretrainConfig).
    do_detect_anomaly: bool = False

    optimization_config: OptimizationConfig = dataclasses.field(default_factory=OptimizationConfig)

    task_df_name: str | None = None

    data_config_overrides: dict[str, Any] = dataclasses.field(
        default_factory=lambda: {
            "subsequence_sampling_strategy": "to_end",
            "seq_padding_side": "right",
        }
    )

    trainer_config: dict[str, Any] = dataclasses.field(
        default_factory=lambda: {
            "log_every_n_steps": 10,
            "checkpoint_every_n_steps": 100,
            "max_checkpoints_to_keep": 2,
        }
    )

    task_specific_params: dict[str, Any] = dataclasses.field(
        default_factory=lambda: {"pooling_method": "last", "num_samples": None}
    )

    config_overrides: dict[str, Any] = dataclasses.field(default_factory=dict)

    do_final_validation_on_metrics: bool = True
    # Auto-resume parity with pretrain: restore the newest verifiable
    # train-state checkpoint under save_dir and (for a mid-epoch one) skip
    # the batches already trained on — same key, same semantics.
    do_resume_from_checkpoint: bool = True

    def __post_init__(self):
        if isinstance(self.save_dir, str):
            self.save_dir = Path(self.save_dir)

        if self.load_from_model_dir is None:
            self.data_config = None
            self.config = None
            return

        self.load_from_model_dir = Path(self.load_from_model_dir)
        if self.task_df_name is None:
            raise ValueError("Missing mandatory parameter task_df_name!")

        if self.pretrained_weights_fp is None:
            self.pretrained_weights_fp = self.load_from_model_dir
        if self.save_dir is None:
            subset_size = self.data_config_overrides.get("train_subset_size", None)
            if subset_size in (None, "FULL"):
                self.save_dir = self.load_from_model_dir / "finetuning" / self.task_df_name
            else:
                if self.data_config_overrides.get("train_subset_seed", None) is None:
                    self.data_config_overrides["train_subset_seed"] = int(
                        random.randint(1, int(1e6))
                    )
                    print(
                        f"WARNING: train_subset_size={subset_size} but seed is unset. Setting to "
                        f"{self.data_config_overrides['train_subset_seed']}"
                    )
                self.save_dir = (
                    self.load_from_model_dir
                    / "finetuning"
                    / f"subset_size_{subset_size}"
                    / f"subset_seed_{self.data_config_overrides['train_subset_seed']}"
                    / self.task_df_name
                )

        data_config_fp = self.load_from_model_dir / "data_config.json"
        print(f"Loading data_config from {data_config_fp}")
        self.data_config = PytorchDatasetConfig.from_json_file(data_config_fp)
        self.data_config.task_df_name = self.task_df_name

        for param, val in (self.data_config_overrides or {}).items():
            if param == "task_df_name":
                print(
                    f"WARNING: task_df_name is set in data_config_overrides to {val}! "
                    f"Original is {self.task_df_name}. Ignoring data_config_overrides..."
                )
                continue
            print(f"Overwriting {param} in data_config from {getattr(self.data_config, param)} to {val}")
            setattr(self.data_config, param, val)

        config_fp = self.load_from_model_dir / "config.json"
        print(f"Loading config from {config_fp}")
        self.config = StructuredTransformerConfig.from_json_file(config_fp)

        if self.task_specific_params is not None:
            if self.config.task_specific_params is None:
                self.config.task_specific_params = {}
            self.config.task_specific_params.update(self.task_specific_params)

        for param, val in (self.config_overrides or {}).items():
            print(f"Overwriting {param} in config from {getattr(self.config, param)} to {val}")
            setattr(self.config, param, val)


# --------------------------------------------------------- pretrained graft
def init_from_pretrained_encoder(
    ft_params: Any, pretrained_dir: Path | str
) -> Any:
    """Grafts pretrained generative-model encoder weights into fresh
    fine-tuning params (HF ``from_pretrained`` partial-load semantics: only
    the encoder subtree transfers; pooling/logit layers stay fresh)."""
    pretrained, _ = load_pretrained(pretrained_dir)
    pre_encoder = pretrained["params"]["encoder"]
    ft_sd = serialization.to_state_dict(ft_params)
    ft_encoder = ft_sd["params"]["encoder"]

    def graft(dst: dict, src: dict, path=""):
        out = {}
        for k, v in dst.items():
            if k in src and isinstance(v, dict) and isinstance(src[k], dict):
                out[k] = graft(v, src[k], f"{path}/{k}")
            elif k in src and not isinstance(v, dict):
                sv = np.asarray(src[k])
                if sv.shape == np.asarray(v).shape:
                    out[k] = sv
                else:
                    print(f"WARNING: shape mismatch at {path}/{k}; keeping fresh init")
                    out[k] = v
            else:
                print(f"WARNING: {path}/{k} missing from pretrained weights; keeping fresh init")
                out[k] = v
        return out

    ft_sd["params"]["encoder"] = graft(ft_encoder, pre_encoder)
    return serialization.from_state_dict(ft_params, ft_sd)


# ------------------------------------------------------------------ driver
def train(cfg: FinetuneConfig) -> tuple[float | None, dict | None, dict | None]:
    """End-to-end fine-tuning (reference ``train`` :384-514)."""
    np.random.seed(cfg.seed)
    rng = jax.random.PRNGKey(cfg.seed)

    if getattr(cfg, "do_detect_anomaly", False):
        jax.config.update("jax_debug_nans", True)

    train_pyd = JaxDataset(cfg.data_config, split="train")
    tuning_pyd = JaxDataset(cfg.data_config, split="tuning")

    config = cfg.config
    data_config = cfg.data_config
    oc = cfg.optimization_config

    config.set_to_dataset(train_pyd)
    oc.set_to_dataset(train_pyd)

    save_dir = Path(cfg.save_dir)
    is_main = jax.process_index() == 0
    if is_main:
        save_dir.mkdir(parents=True, exist_ok=True)
        config_fp = save_dir / "config.json"
        # Same guard semantics as pretrain: resume waives the overwrite check
        # only when a checkpoint actually exists to resume from.
        has_resume_target = cfg.do_resume_from_checkpoint and any(
            p.name.isdigit() for p in (save_dir / "model_checkpoints").glob("*")
        )
        if config_fp.exists() and not cfg.do_overwrite and not has_resume_target:
            raise FileExistsError(f"{config_fp} already exists!")
        config.to_json_file(config_fp, do_overwrite=True)
        data_config.to_json_file(save_dir / "data_config.json", do_overwrite=True)
        oc.to_json_file(save_dir / "optimization_config.json", do_overwrite=True)

    model = ESTForStreamClassification(config)
    tx, lr_schedule = build_optimizer(oc)
    mesh = data_parallel_mesh(oc.batch_size, oc.validation_batch_size)

    if len(train_pyd) < oc.batch_size:
        raise ValueError(
            f"Train split has {len(train_pyd)} subjects but batch_size is {oc.batch_size}."
        )
    init_batch = next(train_pyd.batches(oc.batch_size, shuffle=True, seed=cfg.seed))
    rng, init_rng = jax.random.split(rng)
    params = model.init(init_rng, init_batch)
    if cfg.pretrained_weights_fp is not None:
        params = init_from_pretrained_encoder(params, cfg.pretrained_weights_fp)

    state = TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params))
    state = replicate(state, mesh)

    tc = dict(cfg.trainer_config or {})

    # Reliability subsystem (eventstreamgpt_tpu/reliability/): same wiring
    # as pretrain — hardened checkpoint I/O, divergence sentinel + bounded
    # rollback, graceful preemption, deterministic fault hooks.
    from ..reliability import faults
    from ..reliability.integrity import ReliableCheckpointManager, resume_training_state
    from ..reliability.preemption import GracefulShutdown
    from ..reliability.sentinel import (
        DivergenceSentinel,
        HealthMonitor,
        RollbackController,
        SentinelConfig,
        finish_epoch,
    )

    sentinel_cfg = SentinelConfig.from_trainer_config(tc)
    sentinel = DivergenceSentinel(sentinel_cfg) if sentinel_cfg is not None else None
    rollback_ctl = (
        RollbackController(
            sentinel_cfg.max_rollbacks, save_dir / "divergence_diagnostics.json"
        )
        if sentinel_cfg is not None
        else None
    )
    with_health = sentinel is not None

    # The step body is pretrain's, verbatim (same fold-in rng, same update
    # math) — fine-tuning only swaps the model/loss. with_health adds the
    # sentinel's [loss, grad_norm] device flags to the step outputs.
    train_step = make_train_step(model, tx, with_health=with_health)
    eval_step = jax.jit(lambda params, batch: model.apply(params, batch))

    # Device-resident batches (r05 feed-path redesign): collate on device
    # from ~100-byte plans — stream labels ride along as host arrays — with
    # the host prefetch pipeline as the oversized-cohort fallback. Few-shot
    # fine-tuning cohorts essentially always fit the budget.
    # device_resident_data=False opts out (config parity with pretrain —
    # also what batch-level fault injection needs, since plans collate on
    # device out of reach of the host poisoning hook).
    from ..data.device_dataset import DeviceDataset

    resident_mode = tc.get("device_resident_data", "auto")
    if resident_mode is True:
        # Explicit opt-in fails loudly on unsupported topologies (pretrain
        # parity) instead of silently falling back to the host path.
        device_train = DeviceDataset.create(
            train_pyd, mesh=mesh, batch_sizes=(oc.batch_size, oc.validation_batch_size)
        )
    elif resident_mode is False:
        device_train = None
    else:
        device_train = DeviceDataset.try_create(
            train_pyd, mesh=mesh, batch_sizes=(oc.batch_size, oc.validation_batch_size)
        )
    _device_eval_cache: dict[int, "DeviceDataset | None"] = {}

    def evaluate(params, dataset, split) -> dict[str, float]:
        metrics = StreamClassificationMetrics(config, split)
        # seed=0 pins random subsequence crops: eval passes must be comparable.
        if id(dataset) not in _device_eval_cache:
            _device_eval_cache[id(dataset)] = DeviceDataset.try_create(
                dataset, mesh=mesh, batch_sizes=(oc.validation_batch_size,)
            )
        dd = _device_eval_cache[id(dataset)]
        if dd is not None:
            for batch in dd.batches(
                oc.validation_batch_size, shuffle=False, drop_last=False, seed=0
            ):
                out = eval_step(params, batch)
                metrics.update(
                    out,
                    valid_mask=(
                        np.asarray(batch.valid_mask) if batch.valid_mask is not None else None  # graftcheck: allow GC001 -- valid_mask is a host array on device batches, no sync
                    ),
                )
            return metrics.compute()
        batch_iter = prefetch_to_device(
            dataset.batches(oc.validation_batch_size, shuffle=False, drop_last=False, seed=0),
            lambda b: shard_batch(b, mesh),
            host_stats_fn=lambda b: (
                np.asarray(b.valid_mask) if b.valid_mask is not None else None
            ),
        )
        try:
            for batch, valid_mask in batch_iter:
                out = eval_step(params, batch)
                metrics.update(out, valid_mask=valid_mask)
        finally:
            batch_iter.close()
        return metrics.compute()

    log_every = int(tc.get("log_every_n_steps") or 10)
    ckpt_every = int(tc.get("checkpoint_every_n_steps") or 100)
    keep = int(tc.get("max_checkpoints_to_keep") or 2)
    ckpt_mgr = ReliableCheckpointManager(
        save_dir / "model_checkpoints",
        max_to_keep=keep,
        retries=int(tc.get("ckpt_retries", 3)),
        backoff_base=float(tc.get("ckpt_backoff_base", 0.5)),
    )

    log_fp = save_dir / "train_log.jsonl" if is_main else None

    def log_record(rec: dict):
        if log_fp is not None:
            with open(log_fp, "a") as f:
                f.write(json.dumps(rec) + "\n")

    accum = oc.gradient_accumulation or 1
    best_tuning_loss = float("inf")
    epochs_since_best = 0
    global_step = 0
    stop = False
    tuning_metrics = None

    # Auto-resume (pretrain parity): restore the newest verifiable
    # train-state checkpoint; a mid-epoch one re-enters its epoch and skips
    # the batches already trained on (batch order is deterministic per
    # cfg.seed + epoch, so the skip is rng-exact).
    start_epoch = 0
    skip_batches = 0
    if cfg.do_resume_from_checkpoint and ckpt_mgr.latest_step() is not None:
        # Shared auto-resume (reliability/integrity.py; pretrain parity).
        state, resumed_step, start_epoch, skip_batches = resume_training_state(
            ckpt_mgr, state, lambda s: replicate(s, mesh)
        )
        global_step = resumed_step

    shutdown = GracefulShutdown()
    resume_epoch, resume_skip = start_epoch, skip_batches
    epoch = start_epoch
    with shutdown:
        while epoch < oc.max_epochs:
            epoch_t0 = time.perf_counter()
            window_losses = []
            epoch_skip = resume_skip if epoch == resume_epoch else 0
            if rollback_ctl is not None:
                epoch_skip = rollback_ctl.epoch_skip(epoch, epoch_skip)
            epoch_progress = epoch_skip
            # Shared health buffer + inspection gate (reliability/sentinel.py):
            # record per step without readback, inspect only at the flush
            # cadence — no host sync in the dispatch loop (see pretrain).
            health_mon = HealthMonitor(sentinel)
            if device_train is not None:
                batch_iter = (
                    (b, None)
                    for b in device_train.batches(
                        oc.batch_size,
                        shuffle=True,
                        seed=cfg.seed + epoch,
                        skip_batches=epoch_skip,
                    )
                )
            else:
                batch_iter = prefetch_to_device(
                    faults.wrap_batches(
                        train_pyd.batches(
                            oc.batch_size,
                            shuffle=True,
                            seed=cfg.seed + epoch,
                            skip_batches=epoch_skip,
                        ),
                        epoch=epoch,
                        first_index=epoch_skip,
                    ),
                    lambda b: shard_batch(b, mesh),
                )
            # Window records buffer their losses as device arrays and flush at
            # checkpoint cadence / epoch end — a float() per window here would
            # stall the dispatch pipeline on a host readback (GC001), exactly
            # the bug class graftcheck lints for.
            pending_logs: list[dict] = []

            def flush_pending() -> None:
                for rec in pending_logs:
                    rec["train_loss"] = float(jnp.mean(jnp.stack(rec.pop("_losses"))))  # graftcheck: allow GC001 -- flush runs only after the pipeline drains (ckpt/epoch end)
                    rec["lr"] = float(lr_schedule(rec["step"] // accum))  # graftcheck: allow GC001 -- flush runs only after the pipeline drains (ckpt/epoch end)
                    log_record(rec)
                pending_logs.clear()

            try:
                for step_in_epoch, (batch, _) in enumerate(batch_iter, start=epoch_skip):
                    if with_health:
                        state, (loss, health) = train_step(state, batch, rng)  # graftcheck: allow GC003 -- step body folds rng with state.step; constant base key is the dropout-stream contract
                        health_mon.record(health)
                    else:
                        state, loss = train_step(state, batch, rng)  # graftcheck: allow GC003 -- step body folds rng with state.step; constant base key is the dropout-stream contract
                    global_step += 1
                    epoch_progress = step_in_epoch + 1
                    faults.maybe_sigterm(global_step, shutdown)
                    window_losses.append(loss)
                    if global_step % log_every == 0:
                        pending_logs.append(
                            {
                                "split": str(Split.TRAIN),
                                "epoch": epoch,
                                "step": global_step,
                                "_losses": list(window_losses),
                            }
                        )
                        window_losses = []
                    if global_step % ckpt_every == 0:
                        # Shared inspect-then-save gate (see pretrain): the
                        # save commits only when THIS window vetted healthy.
                        if health_mon.vetted_save(
                            ckpt_mgr,
                            global_step,
                            lambda: serialization.to_state_dict(jax.device_get(state)),  # graftcheck: allow GC001 -- checkpoint readback + sentinel inspection, cadence-bounded
                            {
                                "epoch": epoch,
                                "epoch_complete": False,
                                "step_in_epoch": epoch_progress,
                            },
                            epoch=epoch,
                            progress=epoch_progress,
                        ):
                            # device_get drained the pipeline: persisting the
                            # window records here is sync-free and bounds
                            # preemption loss.
                            flush_pending()
                    if (
                        oc.max_training_steps is not None
                        and global_step // accum >= oc.max_training_steps
                    ):
                        stop = True
                        break
                    if shutdown.requested:
                        break
                    if health_mon.rollback_requested:
                        break
            finally:
                batch_iter.close()
                # Flush in the finally so a mid-epoch failure still writes the
                # loss trajectory leading up to it.
                flush_pending()

            # Post-epoch recovery tail — shared verbatim with pretrain
            # (reliability/sentinel.py finish_epoch): tail vetting, pending
            # rollback, or preemption drain (raises Preempted).
            outcome = finish_epoch(
                health_mon=health_mon,
                rollback_ctl=rollback_ctl,
                ckpt_mgr=ckpt_mgr,
                shutdown=shutdown,
                state=state,
                place_state=lambda s: replicate(s, mesh),
                log_record=log_record,
                epoch=epoch,
                epoch_progress=epoch_progress,
                global_step=global_step,
                accum=accum,
                max_training_steps=oc.max_training_steps,
                label="fine-tuning",
            )
            if outcome.action == "rollback":
                state = outcome.state
                global_step = outcome.global_step
                resume_epoch, resume_skip = outcome.resume_epoch, outcome.resume_skip
                stop = outcome.stop
                epoch = resume_epoch
                continue
            tail_healthy = outcome.tail_healthy

            tuning_metrics = evaluate(state.params, tuning_pyd, Split.TUNING)
            tuning_loss = tuning_metrics.get("tuning_loss", float("nan"))
            log_record(
                {
                    "split": str(Split.TUNING),
                    "epoch": epoch,
                    "step": global_step,
                    **tuning_metrics,
                    "epoch_time_s": time.perf_counter() - epoch_t0,
                }
            )
            print(f"finetune epoch {epoch}: tuning_loss={tuning_loss:.4f}")
            if tail_healthy:
                ckpt_mgr.save(
                    global_step,
                    serialization.to_state_dict(jax.device_get(state)),  # graftcheck: allow GC001 -- epoch-end checkpoint readback, pipeline already drained by eval
                    metadata={"epoch": epoch, "epoch_complete": True},
                )

            if np.isfinite(tuning_loss) and tuning_loss < best_tuning_loss - 1e-12:
                best_tuning_loss = tuning_loss
                epochs_since_best = 0
            else:
                epochs_since_best += 1
                if oc.patience is not None and epochs_since_best >= max(oc.patience, 1):
                    print(f"Early stopping at epoch {epoch} (patience {oc.patience})")
                    break
            if stop:
                break
            epoch += 1

    ckpt_mgr.wait_until_finished()
    params_host = jax.device_get(state.params)
    if is_main:
        save_pretrained(save_dir, params_host)

    if not cfg.do_final_validation_on_metrics:
        ckpt_mgr.close()
        return None, None, None

    held_out_pyd = JaxDataset(cfg.data_config, split="held_out")
    # The last epoch's tuning eval ran at these exact params with pinned eval
    # crops, so reuse it rather than paying a second pass.
    final_tuning = tuning_metrics
    if final_tuning is None:
        final_tuning = evaluate(state.params, tuning_pyd, Split.TUNING)
    final_held_out = evaluate(state.params, held_out_pyd, Split.HELD_OUT)

    if is_main:
        print("Saving final metrics...")
        with open(save_dir / "tuning_metrics.json", "w") as f:
            json.dump(final_tuning, f)
        with open(save_dir / "held_out_metrics.json", "w") as f:
            json.dump(final_held_out, f)

    ckpt_mgr.close()
    return final_tuning.get("tuning_loss"), final_tuning, final_held_out
