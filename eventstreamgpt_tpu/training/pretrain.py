"""The generative pretraining harness: sharded train step, epoch loop, driver.

TPU-native rebuild of the reference Lightning pretraining stack
(``/root/reference/EventStream/transformer/lightning_modules/generative_modeling.py:45-698``):

* ``ESTForGenerativeSequenceModelingLM.configure_optimizers`` → ``build_optimizer``
  (AdamW + polynomial decay w/ warmup, optax).
* Lightning DDP (``devices="auto"``) → a 1-D ``data`` mesh over
  ``jax.devices()``; the batch is sharded over the mesh, parameters are
  replicated, and gradient all-reduce is inserted by XLA under ``jit`` — no
  explicit collectives (SURVEY §2.10/§5.8).
* ``Trainer.fit`` + callbacks → an explicit epoch loop with tuning eval,
  early stopping on ``tuning_loss`` (``EarlyStopping`` ≡
  ``OptimizationConfig.patience``), LR logging (``LearningRateMonitor``),
  and step-level orbax checkpoints with preemption-safe auto-resume (a
  capability the reference lacks; SURVEY §5.3 calls it out as a must-add).
* ``train()`` keeps the reference contract: seeds, builds train/tuning
  datasets, ``set_to_dataset``, dumps the five config JSONs, fits, calls
  ``save_pretrained``, then runs final tuning/held-out validation with the
  full metrics config and writes ``tuning_metrics.json`` /
  ``held_out_metrics.json``, returning ``tuning_loss``.

W&B is replaced by a local JSONL train log (``train_log.jsonl`` in
``save_dir``) — same information, no external service.
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
from pathlib import Path
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import serialization, struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.config import PytorchDatasetConfig
from ..data.device_dataset import DeviceDataset
from ..data.jax_dataset import JaxDataset
from ..data.prefetch import prefetch_to_device
from ..data.types import EventStreamBatch
from ..models.ci_model import CIPPTForGenerativeSequenceModeling
from ..models.config import (
    MetricsConfig,
    OptimizationConfig,
    Split,
    StructuredEventProcessingMode,
    StructuredTransformerConfig,
)
from ..models.na_model import NAPPTForGenerativeSequenceModeling
from ..utils import config_dataclass
from .checkpoint import TrainCheckpointManager, save_pretrained
from .generative_metrics import GenerativeMetrics
from .optimizer import build_optimizer

SKIP_CFG_PARAMS = {"seq_attention_layers", "dep_graph_attention_layers"}


# --------------------------------------------------------------------- state
@struct.dataclass
class TrainState:
    """Replicated training state — a pytree moved whole through ``jit``."""

    step: jnp.ndarray  # scalar int32, counts optimizer steps
    params: Any
    opt_state: Any


def build_model(config: StructuredTransformerConfig):
    """CI vs NA model choice (reference ``generative_modeling.py:98-106``)."""
    mode = config.structured_event_processing_mode
    if mode == StructuredEventProcessingMode.NESTED_ATTENTION:
        return NAPPTForGenerativeSequenceModeling(config)
    if mode == StructuredEventProcessingMode.CONDITIONALLY_INDEPENDENT:
        return CIPPTForGenerativeSequenceModeling(config)
    raise ValueError(f"Unsupported structured event processing mode: {mode}")


# ------------------------------------------------------------------ sharding
def _fit_data_axis(n_data: int, *batch_sizes: int, multiplier: int = 1) -> int:
    """Largest data-axis size ≤ ``n_data`` such that ``n_data·multiplier``
    divides every batch size.

    The shared fallback rule of every mesh builder: shrink the data axis
    (rather than fail) so e.g. a batch of 6 on 4 chips runs 2-way
    data-parallel. ``multiplier`` is the batch-sharding factor the other
    axes contribute (the ``fsdp`` axis shards the batch too).
    """
    while n_data > 1 and any(bs % (n_data * multiplier) != 0 for bs in batch_sizes):
        n_data -= 1
    return max(n_data, 1)


def parallel_mesh(*batch_sizes: int, n_cp: int = 1, n_tp: int = 1, n_fsdp: int = 1) -> Mesh:
    """The training mesh for any ``data × fsdp × context × model`` layout.

    Axes of size 1 are omitted, so the degenerate layouts collapse to the
    1-D ``data`` mesh, ``data × model`` (tensor parallel), ``data × context``
    (ring attention), or ``data × fsdp`` (sharded parameters/optimizer —
    training/sharding.py). Axis order puts ``model`` innermost (the
    highest-bandwidth links carry the per-layer TP all-reduces), ``context``
    next (ring kv rotations), ``fsdp`` next (per-layer weight all-gathers /
    gradient reduce-scatters), ``data`` outermost. The data axis shrinks
    until ``data × fsdp`` divides every batch size (`_fit_data_axis` — the
    batch shards over both axes jointly).
    """
    devices = jax.devices()
    n_devices = len(devices)
    if n_fsdp > 1 and n_cp > 1:
        raise ValueError(
            "fsdp_shards and context_parallel_shards cannot be combined (the "
            "batch's event axis and the parameter shards would contend for the "
            "same links); pick one of the two memory axes."
        )
    per_data = n_cp * n_tp * n_fsdp
    if n_devices % per_data != 0:
        raise ValueError(
            f"fsdp x context x tensor parallel shards ({n_fsdp}x{n_cp}x{n_tp}) must "
            f"divide the device count ({n_devices}); a silent partial mesh would "
            "waste devices."
        )
    if n_fsdp > 1 and any(bs % n_fsdp != 0 for bs in batch_sizes):
        raise ValueError(
            f"every batch size {batch_sizes} must divide by fsdp_shards ({n_fsdp}): "
            "the batch shards over the fsdp axis jointly with data."
        )
    n_data = _fit_data_axis(n_devices // per_data, *batch_sizes, multiplier=n_fsdp)
    # The pure data-parallel shrink is documented quiet fallback behavior
    # (data_parallel_mesh); only explicitly-requested TP/CP/FSDP layouts warn
    # about wasted devices.
    if per_data > 1 and n_data * per_data < n_devices:
        print(
            f"WARNING: batch sizes {batch_sizes} shrink the data axis to {n_data}; "
            f"using {n_data * per_data} of {n_devices} devices."
        )
    dims = [("data", n_data)]
    if n_fsdp > 1:
        dims.append(("fsdp", n_fsdp))
    if n_cp > 1:
        dims.append(("context", n_cp))
    if n_tp > 1:
        dims.append(("model", n_tp))
    return Mesh(
        np.asarray(devices[: n_data * per_data]).reshape([s for _, s in dims]),
        tuple(n for n, _ in dims),
    )


def data_parallel_mesh(*batch_sizes: int) -> Mesh:
    """A 1-D ``data`` mesh over the most devices that divide every batch size.

    Falls back to fewer devices (largest common divisor) rather than failing —
    a batch of 6 on 4 chips runs 2-way data-parallel. Passing both the train
    and validation batch sizes yields one mesh usable for the whole run.
    """
    return parallel_mesh(*batch_sizes)


def shard_batch(batch: EventStreamBatch, mesh: Mesh) -> EventStreamBatch:
    """Device-puts a host batch sharded over the mesh's batch axes —
    ``data``, joined by ``fsdp`` when that axis exists (FSDP is data
    parallelism with sharded parameters, so the batch splits over both)."""
    from .sharding import batch_partition_axes

    axes = batch_partition_axes(mesh)
    dim0 = axes if len(axes) > 1 else axes[0]

    def put(x):
        x = np.asarray(x)
        sharding = NamedSharding(mesh, P(dim0, *([None] * (x.ndim - 1))))
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(put, batch)


def context_parallel_mesh(n_cp: int, *batch_sizes: int) -> Mesh:
    """A ``data × context`` mesh: sequence axis sharded ``n_cp``-way.

    The data axis takes the remaining devices, shrinking (like
    `data_parallel_mesh`) until it divides every batch size.
    """
    return parallel_mesh(*batch_sizes, n_cp=n_cp)


# Batch fields whose dim 1 is the event (sequence) axis; statics, labels,
# and per-subject scalars stay data-sharded only.
_CP_SEQ_FIELDS = frozenset(
    {
        "event_mask",
        "time_delta",
        "time",
        "dynamic_indices",
        "dynamic_measurement_indices",
        "dynamic_values",
        "dynamic_values_mask",
        "segment_ids",
    }
)


def shard_batch_cp(batch: EventStreamBatch, mesh: Mesh) -> EventStreamBatch:
    """Device-puts a batch with the batch dim on ``data`` and the sequence
    (event) dim on ``context`` — the layout ring attention consumes.

    Arrays whose event axis does not divide the ``context`` axis (e.g. padded
    eval batches at the dataset's own cap) fall back to data-only sharding;
    GSPMD reshards them at the first trace-enforced boundary instead.
    """
    n_ctx = int(mesh.shape["context"])

    def put(x, seq_sharded: bool):
        x = np.asarray(x)
        if seq_sharded and x.ndim >= 2 and x.shape[1] % n_ctx == 0:
            spec = P("data", "context", *([None] * (x.ndim - 2)))
        else:
            spec = P("data", *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    updates = {}
    for field in dataclasses.fields(batch):
        val = getattr(batch, field.name)
        if val is None:
            continue
        if isinstance(val, dict):  # stream_labels: per-subject arrays
            updates[field.name] = {k: put(v, False) for k, v in val.items()}
        else:
            updates[field.name] = put(val, field.name in _CP_SEQ_FIELDS)
    return batch.replace(**updates)


def replicate(tree: Any, mesh: Mesh) -> Any:
    return jax.device_put(tree, NamedSharding(mesh, P()))


# ----------------------------------------------------------------- train step
def _train_step_body(model, tx, with_health: bool = False) -> Callable:
    """The un-jitted ``(state, batch, rng) -> (state, loss)`` step body.

    Shared verbatim by the per-batch step (`make_train_step`) and the
    scanned multi-step program (`make_chunked_train_step`), so both paths
    have identical numerics: same per-step dropout rng (``fold_in`` on the
    step counter), same gradient, same optimizer update.

    ``with_health=True`` switches the output to ``(state, (loss, health))``
    where ``health`` is the divergence sentinel's device-resident flag
    vector ``[loss, grad_global_norm]`` (f32). It is computed from values
    the step already has in registers — no extra host traffic, no change to
    the parameter/loss numerics — and is read back only at the training
    loop's existing flush cadence (``reliability/sentinel.py``).
    """

    def train_step(state: TrainState, batch: EventStreamBatch, rng: jax.Array):
        dropout_rng = jax.random.fold_in(rng, state.step)

        def loss_fn(params):
            out = model.apply(params, batch, rngs={"dropout": dropout_rng})
            return out.loss

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(step=state.step + 1, params=new_params, opt_state=new_opt_state)
        if with_health:
            health = jnp.stack([loss, optax.global_norm(grads)]).astype(jnp.float32)
            return new_state, (loss, health)
        return new_state, loss

    return train_step


def make_train_step(
    model, tx, with_health: bool = False, out_state_shardings=None
) -> Callable:
    """A jitted ``(state, batch, rng) -> (state, loss)`` step.

    Gradients reduce across the ``data`` axis automatically (XLA inserts the
    psum for replicated-param/sharded-batch layouts). The state is donated so
    parameters update in place on device. ``with_health`` swaps the output
    for ``(state, (loss, health))`` (see `_train_step_body`).

    ``out_state_shardings`` (a `TrainState` sharding tree, i.e.
    `make_state_shardings` output) pins the output state to the input
    layout. Without the pin, GSPMD's sharding propagation may choose a
    DIFFERENT layout for updated parameters than the caller declared on the
    inputs — on tensor-parallel meshes it reshards the small replicated
    leaves (layer norms, biases) over ``model`` — which silently drops
    their donation (input/output layouts no longer match, so the buffers
    cannot alias: the graftcheck Tier C donation audit caught 48 such
    leaves on dp4_tp2) and makes the second dispatch reshard or recompile.
    Pass it whenever the state carries a parameter-sharding axis (tp/fsdp);
    pure data-parallel layouts propagate P() unchanged and don't need it.
    The loss (and health) outputs replicate — they are cross-replica
    reductions already.
    """
    step = _train_step_body(model, tx, with_health=with_health)
    if out_state_shardings is None:
        return jax.jit(step, donate_argnums=(0,))
    mesh = jax.tree_util.tree_leaves(out_state_shardings)[0].mesh
    replicated = NamedSharding(mesh, P())
    # (state, loss) or (state, (loss, health)): `replicated` is a tree
    # prefix covering the whole auxiliary output.
    return jax.jit(
        step,
        donate_argnums=(0,),
        out_shardings=(out_state_shardings, replicated),
    )


def make_chunked_train_step(
    model,
    tx,
    device_data,
    packed: bool = False,
    with_health: bool = False,
    out_state_shardings=None,
) -> Callable:
    """A jitted ``(state, arrays, plans, rng) -> (state, losses)`` program
    that runs ``k`` collate+train steps in ONE dispatch.

    The round-5 feed-path redesign (``data/device_dataset.py``): with the
    dataset HBM-resident, a ``lax.scan`` over ``k`` stacked `BatchPlan`s
    collates each batch on device and steps the optimizer, so per-step wire
    traffic is ~100 bytes and per-dispatch tunnel overhead (~10-20 ms on the
    bench tunnel) is amortized ``k``-fold. Numerics are identical to ``k``
    calls of `make_train_step` on the same plan stream (shared step body,
    same fold-in rng; tested in ``tests/training/test_resident_training.py``).

    ``plans`` comes from `DeviceDataset.plan_chunks` (padded rows) or
    `DeviceDataset.packed_plan_chunks` (``packed=True``); ``arrays`` is
    ``device_data.arrays``. Pretraining ignores per-subject light fields
    (labels, subject ids), which is why the scanned batch carries none.
    ``with_health`` stacks the per-step sentinel health vectors alongside
    the losses: the output becomes ``(state, (losses, healths))``.
    """
    body = _train_step_body(model, tx, with_health=with_health)

    if packed:
        kern = device_data.packed_kernel()

        def collate(arrays, plan):
            fields = kern(arrays, plan["event_ids"], plan["event_mask"])
            fields["segment_ids"] = plan["segment_ids"]
            fields = device_data.constrain_fields(fields)
            B = plan["event_ids"].shape[0]
            return EventStreamBatch(valid_mask=jnp.ones(B, bool), **fields)

    else:
        kern = device_data.padded_kernel()

        def collate(arrays, plan):
            fields = kern(
                arrays, plan["subject_indices"], plan["starts"], plan["valid_mask"]
            )
            fields = device_data.constrain_fields(fields)
            return EventStreamBatch(valid_mask=plan["valid_mask"], **fields)

    def chunk_step(state: TrainState, arrays: dict, plans: dict, rng: jax.Array):
        def scan_body(st, plan):
            st, out = body(st, collate(arrays, plan), rng)
            return st, out

        return jax.lax.scan(scan_body, state, plans)

    if out_state_shardings is None:
        return jax.jit(chunk_step, donate_argnums=(0,))
    # Same output-layout pin as make_train_step: on parameter-sharding
    # meshes, unpinned GSPMD propagation reshards the small replicated
    # leaves over `model` on output, silently dropping their donation.
    mesh = jax.tree_util.tree_leaves(out_state_shardings)[0].mesh
    replicated = NamedSharding(mesh, P())
    return jax.jit(
        chunk_step,
        donate_argnums=(0,),
        out_shardings=(out_state_shardings, replicated),
    )


def _plan_event_count(plans: dict, dataset: JaxDataset) -> int:
    """Exact real-event count of a (possibly sliced) stacked plan chunk.

    Used when ``max_training_steps`` truncates a chunk: the chunk-level count
    from ``plan_chunks`` includes the dropped plans' events, which would
    inflate the final logging window's events_per_sec.
    """
    if "event_mask" in plans:  # packed plans carry the mask directly
        return int(np.asarray(plans["event_mask"]).sum())
    off = np.asarray(dataset.data.subject_event_offsets, np.int64)
    idx = np.asarray(plans["subject_indices"], np.int64)
    kept = np.minimum(off[idx + 1] - off[idx], dataset.max_seq_len)
    return int(kept[np.asarray(plans["valid_mask"])].sum())


def make_eval_step(model) -> Callable:
    def eval_step(params, batch: EventStreamBatch):
        return model.apply(params, batch)

    return jax.jit(eval_step)


# ------------------------------------------------------------------ eval loop
def evaluate(
    eval_step: Callable,
    params: Any,
    dataset: JaxDataset,
    batch_size: int,
    config: StructuredTransformerConfig,
    metrics_config: MetricsConfig,
    split: str,
    mesh: Mesh | None = None,
    key: jax.Array | None = None,
    place_batch: Callable[[EventStreamBatch, Mesh], EventStreamBatch] | None = None,
    device_data: "DeviceDataset | None" = None,
) -> dict[str, float]:
    """Runs one full-split eval pass, returning ``{split}_...`` metrics.

    Fill rows in the final short batch are blanked + flagged by
    ``valid_mask``; loss parts re-weight by the valid count so no subject is
    double-counted (VERDICT weak #5). ``place_batch`` overrides the default
    data-sharded placement — context-parallel callers pass ``shard_batch_cp``
    so the event axis lands on the ``context`` mesh axis up front instead of
    being resharded at every ring-attention boundary. ``device_data`` (a
    `DeviceDataset` over the same split) switches to device-resident
    collation — identical batches, no per-batch wire transfer.
    """
    metrics = GenerativeMetrics(config, metrics_config, split=split)
    if key is None:
        key = jax.random.PRNGKey(0)
    # seed=0 pins the (otherwise random) subsequence crops so every eval pass
    # scores identical data — epoch-to-epoch tuning losses must be comparable
    # for early stopping, and the final validation must match the last epoch.
    if device_data is not None:
        # Device-resident eval: batches collate on device from ~100-byte
        # plans (bit-identical to host collation), so no transfer thread is
        # needed; collate and eval dispatches pipeline asynchronously.
        # valid_mask is a host array on device batches — reading it costs no
        # device sync.
        for batch in device_data.batches(
            batch_size, shuffle=False, drop_last=False, seed=0
        ):
            out = eval_step(params, batch)
            key, sub = jax.random.split(key)
            metrics.update(out, key=sub, n_valid=int(np.asarray(batch.valid_mask).sum()))
        return metrics.compute()
    placer = place_batch if place_batch is not None else shard_batch
    place = (lambda b: placer(b, mesh)) if mesh is not None else (lambda b: b)
    batch_iter = prefetch_to_device(
        dataset.batches(batch_size, shuffle=False, drop_last=False, seed=0),
        place,
        host_stats_fn=lambda b: int(np.asarray(b.valid_mask).sum()) if b.valid_mask is not None else None,
    )
    try:
        for batch, n_valid in batch_iter:
            out = eval_step(params, batch)
            key, sub = jax.random.split(key)
            metrics.update(out, key=sub, n_valid=n_valid)
    finally:
        batch_iter.close()
    return metrics.compute()


# --------------------------------------------------------------------- config
@config_dataclass
class PretrainConfig:
    """Pretraining driver config (reference ``PretrainConfig`` :491-552).

    ``config`` holds ``StructuredTransformerConfig`` kwargs as a dict (the
    reference's hydra ``_target_`` pattern; a ``_target_`` key is accepted
    and ignored). ``save_dir`` supports ``${...}`` interpolation via
    ``utils.config_tool``.
    """

    do_overwrite: bool = False
    seed: int = 1
    # Debug mode (reference ``PretrainConfig.do_detect_anomaly`` / Lightning
    # ``detect_anomaly``; SURVEY §5.2): enables ``jax_debug_nans``, which
    # re-runs any jitted computation that produces a NaN in op-by-op mode and
    # raises with the originating primitive — NaN provenance for the whole
    # forward/backward, not just the generation boundary.
    do_detect_anomaly: bool = False

    config: dict[str, Any] = dataclasses.field(default_factory=dict)
    optimization_config: OptimizationConfig = dataclasses.field(default_factory=OptimizationConfig)
    data_config: PytorchDatasetConfig = dataclasses.field(default_factory=PytorchDatasetConfig)
    pretraining_metrics_config: MetricsConfig = dataclasses.field(
        default_factory=lambda: MetricsConfig(do_skip_all_metrics=True)
    )
    final_validation_metrics_config: MetricsConfig = dataclasses.field(
        default_factory=lambda: MetricsConfig(do_skip_all_metrics=False)
    )

    trainer_config: dict[str, Any] = dataclasses.field(
        default_factory=lambda: {
            "log_every_n_steps": 10,
            "checkpoint_every_n_steps": 100,
            "max_checkpoints_to_keep": 2,
            "profile_dir": None,
        }
    )

    experiment_dir: str = "./experiments"
    save_dir: str = "${experiment_dir}/pretrain"

    do_final_validation_on_metrics: bool = True
    do_resume_from_checkpoint: bool = True

    def __post_init__(self):
        if "max_epochs" in self.trainer_config:
            raise ValueError("Max epochs is set in the optimization_config, not the trainer config!")

    def build_model_config(self) -> StructuredTransformerConfig:
        kwargs = {k: v for k, v in self.config.items() if k not in SKIP_CFG_PARAMS and k != "_target_"}
        return StructuredTransformerConfig(**kwargs)


# --------------------------------------------------------------------- driver
def train(
    cfg: PretrainConfig,
    model_config: StructuredTransformerConfig | None = None,
) -> tuple[float | None, dict | None, dict | None]:
    """End-to-end pretraining (reference ``train`` :555-698).

    Returns ``(tuning_loss, tuning_metrics, held_out_metrics)`` when final
    validation runs, else ``(None, None, None)``.

    Fault tolerance (docs/reliability.md): raises
    ``reliability.Preempted`` after a graceful SIGTERM/SIGINT drain (final
    mid-epoch checkpoint written; script entry points convert this to
    ``EXIT_PREEMPTED``), and ``reliability.DivergenceError`` when the
    divergence sentinel exhausts its rollback budget (diagnostic dump in
    ``save_dir/divergence_diagnostics.json``).
    """
    np.random.seed(cfg.seed)
    rng = jax.random.PRNGKey(cfg.seed)

    if getattr(cfg, "do_detect_anomaly", False):
        jax.config.update("jax_debug_nans", True)

    train_pyd = JaxDataset(cfg.data_config, split="train")
    tuning_pyd = JaxDataset(cfg.data_config, split="tuning")

    config = model_config if model_config is not None else cfg.build_model_config()
    optimization_config = cfg.optimization_config
    data_config = cfg.data_config

    # set_to_dataset overwrites max_seq_len with the dataset's per-subject
    # cap; the constructor-set value is the user's intended *model* context
    # length, which packed-row training must honor (packed rows hold several
    # subjects, so their length legitimately exceeds the per-subject cap).
    configured_max_seq_len = config.max_seq_len
    config.set_to_dataset(train_pyd)

    oc = optimization_config
    tc = dict(cfg.trainer_config or {})
    # Optional tensor parallelism: trainer_config.tensor_parallel_shards > 1
    # carves a ``model`` axis out of the device set (vocab-sharded embedding
    # + classification head etc.; see training/sharding.py) with the
    # remaining devices data-parallel. The data axis shrinks until it divides
    # both batch sizes, mirroring data_parallel_mesh's fallback.
    n_tp = int(tc.get("tensor_parallel_shards") or 1)
    # Optional FSDP (r10 scale-up round): trainer_config.fsdp_shards > 1
    # carves an ``fsdp`` mesh axis; every parameter and its Adam moments
    # shard their largest divisible dimension over it and the batch shards
    # over (data, fsdp) jointly, so GSPMD inserts gather-on-use /
    # reduce-scatter-on-grad — the layout that fits widths the replicated
    # path cannot (training/sharding.py, docs/scaling.md).
    # trainer_config.strict_sharding upgrades the replicated-fallback
    # warning to an error when most parameter bytes miss the rules.
    n_fsdp = int(tc.get("fsdp_shards") or 1)
    # Optional sequence (context) parallelism: packed long-context batches
    # shard their event axis over a ``context`` mesh axis and attention runs
    # as a ring (parallel/ring_attention.py). Requires packed batches and the
    # ring attention implementation. ``use_packed_batches`` alone trains on
    # packed rows without sequence sharding; ``packed_seq_len`` overrides the
    # packed row length (default: config.max_seq_len).
    n_cp = int(tc.get("context_parallel_shards") or 1)
    use_packed = bool(tc.get("use_packed_batches")) or n_cp > 1
    # Default packed row length: the larger of the configured model context
    # and the dataset's per-subject cap — a model max_seq_len left at its
    # class default must not shrink packed rows below the data cap, and an
    # explicitly longer model context must be honored. packed_seq_len
    # overrides outright.
    packed_L = int(tc.get("packed_seq_len") or max(configured_max_seq_len, train_pyd.max_seq_len))
    if use_packed:
        # The saved config must reflect the true context length trained at
        # (downstream generation budgets read config.max_seq_len).
        config.max_seq_len = packed_L
    if n_cp > 1:
        if config.attention_implementation != "ring":
            raise ValueError(
                "context_parallel_shards > 1 requires config.attention_implementation='ring' "
                "(otherwise the sharded sequence axis is all-gathered for attention)."
            )
        if float(config.attention_dropout) != 0.0:
            raise ValueError(
                "context_parallel_shards > 1 requires attention_dropout=0 (the ring path, "
                "like the Pallas kernels, has no attention dropout)."
            )
        if packed_L % n_cp != 0:
            raise ValueError(
                f"the packed row length ({packed_L}) must be divisible by "
                f"context_parallel_shards ({n_cp})."
            )

    # Packed rows hold several subjects, so the packed stream has a
    # packing-factor fewer batches per epoch than the padded count — the LR
    # schedule and step budget must see that count, not the padded one.
    # Epoch 0's packing (packing only, no collation) sets the nominal
    # horizon; later epochs repack under a different shuffle and may differ
    # by a row or two, exactly like Lightning's estimated steps when a
    # dataloader's length drifts.
    steps_per_epoch = (
        train_pyd.packed_batch_count(oc.batch_size, seq_len=packed_L, seed=cfg.seed)
        if use_packed
        else None
    )
    optimization_config.set_to_dataset(train_pyd, steps_per_epoch=steps_per_epoch)
    if steps_per_epoch is None:
        steps_per_epoch = len(train_pyd) // oc.batch_size

    save_dir = Path(cfg.save_dir)
    is_main = jax.process_index() == 0
    if is_main:
        save_dir.mkdir(parents=True, exist_ok=True)
        config_fp = save_dir / "config.json"
        # Resume waives the overwrite guard only when there is actually a
        # checkpoint to resume from — resume-enabled-but-fresh reruns into a
        # foreign results dir must still fail loudly instead of clobbering.
        has_resume_target = cfg.do_resume_from_checkpoint and any(
            p.name.isdigit() for p in (save_dir / "model_checkpoints").glob("*")
        )
        if config_fp.exists() and not cfg.do_overwrite and not has_resume_target:
            raise FileExistsError(f"{config_fp} already exists!")
        config.to_json_file(config_fp, do_overwrite=True)
        data_config.to_json_file(save_dir / "data_config.json", do_overwrite=True)
        optimization_config.to_json_file(save_dir / "optimization_config.json", do_overwrite=True)
        cfg.pretraining_metrics_config.to_json_file(
            save_dir / "pretraining_metrics_config.json", do_overwrite=True
        )
        cfg.final_validation_metrics_config.to_json_file(
            save_dir / "final_validation_metrics_config.json", do_overwrite=True
        )

    model = build_model(config)
    tx, lr_schedule = build_optimizer(optimization_config)

    # One mesh for every layout: data-parallel by default; a ``model`` axis
    # for Megatron tensor parallelism; a ``context`` axis for ring-attention
    # sequence parallelism; all three composed when both shard counts are set
    # (the axes are orthogonal — each model shard rings its local heads' kv
    # blocks over ``context``; parallel/ring_attention.py ``head_axis``).
    mesh = parallel_mesh(
        oc.batch_size, oc.validation_batch_size, n_cp=n_cp, n_tp=n_tp, n_fsdp=n_fsdp
    )
    state_shardings = None  # set by the first place_state on tp/fsdp layouts
    if n_tp > 1 or n_fsdp > 1:
        from .sharding import make_state_shardings

        strict_sharding = bool(tc.get("strict_sharding", False))

        def place_state(s):
            nonlocal state_shardings
            state_shardings = make_state_shardings(s, mesh, strict=strict_sharding)
            return jax.device_put(s, state_shardings)

    else:
        place_state = lambda s: replicate(s, mesh)  # noqa: E731
    place_batch = shard_batch_cp if n_cp > 1 else shard_batch

    def train_batches(epoch: int, skip: int):
        """The epoch's training batch stream (padded or packed)."""
        if not use_packed:
            return train_pyd.batches(
                oc.batch_size, shuffle=True, seed=cfg.seed + epoch, skip_batches=skip
            )
        import itertools

        packed = (
            b
            for b in train_pyd.packed_batches(
                oc.batch_size, seq_len=packed_L, seed=cfg.seed + epoch
            )
            # A short final packed batch would retrigger compilation.
            if b.event_mask.shape[0] == oc.batch_size
        )
        # Packing is deterministic per seed, so mid-epoch resume re-derives
        # and discards the first `skip` batches (collation cost only).
        return itertools.islice(packed, skip, None)

    # Initialize from the first training batch's shapes.
    if len(train_pyd) < oc.batch_size:
        raise ValueError(
            f"Train split has {len(train_pyd)} subjects but batch_size is "
            f"{oc.batch_size}; training batches drop the last short batch, so "
            "no batch can be formed. Lower optimization_config.batch_size."
        )
    init_iter = train_batches(epoch=0, skip=0)
    try:
        init_batch = next(init_iter)
    except StopIteration:
        raise ValueError(
            "No full training batch could be formed; lower optimization_config.batch_size."
        ) from None
    rng, init_rng = jax.random.split(rng)
    params = model.init(init_rng, init_batch)
    state = TrainState(
        step=jnp.zeros((), dtype=jnp.int32), params=params, opt_state=tx.init(params)
    )
    state = place_state(state)

    log_every = int(tc.get("log_every_n_steps") or 10)
    ckpt_every = int(tc.get("checkpoint_every_n_steps") or 100)
    keep = int(tc.get("max_checkpoints_to_keep") or 2)
    profile_dir = tc.get("profile_dir")

    # Reliability subsystem (eventstreamgpt_tpu/reliability/): hardened
    # checkpoint I/O (retry/backoff + checksum manifests + walk-back),
    # the divergence sentinel with bounded rollback, graceful preemption,
    # and the deterministic fault hooks CI drives all of it with. Imported
    # lazily (like CompileGuard) so the module graph stays cycle-free.
    from ..reliability import faults
    from ..reliability.integrity import ReliableCheckpointManager, resume_training_state
    from ..reliability.preemption import GracefulShutdown
    from ..reliability.sentinel import (
        DivergenceSentinel,
        HealthMonitor,
        RollbackController,
        SentinelConfig,
        finish_epoch,
    )

    sentinel_cfg = SentinelConfig.from_trainer_config(tc)
    sentinel = DivergenceSentinel(sentinel_cfg) if sentinel_cfg is not None else None
    rollback_ctl = (
        RollbackController(
            sentinel_cfg.max_rollbacks, save_dir / "divergence_diagnostics.json"
        )
        if sentinel_cfg is not None
        else None
    )
    with_health = sentinel is not None

    ckpt_mgr = ReliableCheckpointManager(
        save_dir / "model_checkpoints",
        max_to_keep=keep,
        save_interval_steps=1,
        retries=int(tc.get("ckpt_retries", 3)),
        backoff_base=float(tc.get("ckpt_backoff_base", 0.5)),
    )
    start_epoch = 0
    skip_batches = 0
    if cfg.do_resume_from_checkpoint and ckpt_mgr.latest_step() is not None:
        # Shared auto-resume (reliability/integrity.py): walk-back restore of
        # the newest verifiable checkpoint with readable resume metadata — a
        # corrupt or partially-written latest step degrades the relaunch
        # instead of crashing it, and a mid-epoch (preemption) checkpoint
        # re-enters its epoch past the batches already trained on (batch
        # order is deterministic per cfg.seed + epoch: the skip is rng-exact).
        state, _, start_epoch, skip_batches = resume_training_state(
            ckpt_mgr, state, place_state
        )

    # tp/fsdp layouts pin the output state to the input layout (see
    # make_train_step: unpinned propagation reshards replicated leaves over
    # `model`, silently dropping their donation).
    train_step = make_train_step(
        model, tx, with_health=with_health, out_state_shardings=state_shardings
    )
    eval_step = make_eval_step(model)

    # Device-resident data (round-5 feed-path redesign; data/device_dataset.py):
    # keep the dataset in HBM and run k on-device-collate + train steps per
    # dispatch. 'auto' enables it when the tables fit a conservative HBM
    # budget: single-process runs use the replicated layout, multi-process
    # runs the sharded layout (each process uploads its subject-pool shard
    # over the mesh's data axis and the plan stream is dealt shard-major —
    # see DeviceDataset.create). Numerics are bit-identical to host collation
    # of the same plan stream (tested), so this is purely a throughput
    # decision.
    resident_mode = tc.get("device_resident_data", "auto")
    resident_budget = int(
        tc.get("device_resident_max_bytes") or DeviceDataset.DEFAULT_BUDGET_BYTES
    )
    device_train = device_tuning = None
    if n_fsdp > 1:
        # The resident tables shard over the `data` axis and deal plans per
        # data shard; an fsdp axis splits the batch dimension further than
        # the plan stream deals. Host collation + shard_batch handles the
        # (data, fsdp) layout; the resident fast path is an open follow-up.
        if resident_mode is True:
            raise ValueError(
                "device_resident_data: true is not supported with fsdp_shards > 1; "
                "use 'auto' (host collation) for FSDP runs."
            )
        resident_mode = False
    if resident_mode is True:
        # Explicit opt-in: unsupported topologies (and shard-indivisible
        # batch sizes) raise a clear error here instead of a full epoch in.
        device_train = DeviceDataset.create(
            train_pyd, mesh=mesh, context_parallel=n_cp > 1,
            batch_sizes=(oc.batch_size, oc.validation_batch_size),
        )
        device_tuning = DeviceDataset.create(
            tuning_pyd, mesh=mesh, context_parallel=n_cp > 1,
            batch_sizes=(oc.validation_batch_size,),
        )
    elif resident_mode == "auto":
        device_train = DeviceDataset.try_create(
            train_pyd, mesh=mesh, context_parallel=n_cp > 1, max_bytes=resident_budget,
            batch_sizes=(oc.batch_size, oc.validation_batch_size),
        )
        if device_train is not None:
            device_tuning = DeviceDataset.try_create(
                tuning_pyd, mesh=mesh, context_parallel=n_cp > 1, max_bytes=resident_budget,
                batch_sizes=(oc.validation_batch_size,),
            )
    chunk_steps = tc.get("steps_per_execution") or "auto"
    if chunk_steps == "auto":
        # Align with the logging cadence so windowed records keep their
        # meaning; 16 steps/dispatch already amortizes dispatch overhead to
        # a few percent.
        chunk_steps = max(min(log_every, ckpt_every, 16), 1)
    chunk_steps = int(chunk_steps)
    chunked_step = (
        make_chunked_train_step(
            model,
            tx,
            device_train,
            packed=use_packed,
            with_health=with_health,
            out_state_shardings=state_shardings,
        )
        if device_train is not None
        else None
    )

    # Recompilation sentinel (analysis/compile_guard.py): every steady-state
    # shape is seen during the first in-process epoch, so from the second
    # epoch on the active step function must dispatch cached executables
    # only. Armed per epoch, checked after every full-shape dispatch
    # (handle_window); a mid-epoch recompile — drifting batch shape, weak
    # type — fails the run immediately instead of silently training at
    # compile speed. trainer_config.guard_recompiles=False opts out.
    step_guard = None
    if bool(tc.get("guard_recompiles", True)):
        from ..analysis.compile_guard import CompileGuard

        step_guard = CompileGuard(
            watch=[chunked_step if chunked_step is not None else train_step],
            label="pretrain step (mid-epoch)",
        )

    def train_plan_chunks(epoch: int, skip: int):
        if use_packed:
            return device_train.packed_plan_chunks(
                oc.batch_size,
                chunk_steps,
                seq_len=packed_L,
                seed=cfg.seed + epoch,
                skip_batches=skip,
            )
        return device_train.plan_chunks(
            oc.batch_size, chunk_steps, shuffle=True, seed=cfg.seed + epoch, skip_batches=skip
        )

    log_fp = save_dir / "train_log.jsonl" if is_main else None

    def log_record(rec: dict) -> None:
        if log_fp is not None:
            with open(log_fp, "a") as f:
                f.write(json.dumps(rec) + "\n")

    best_tuning_loss = float("inf")
    epochs_since_best = 0
    global_step = int(jax.device_get(state.step))
    # max_training_steps counts *optimizer* steps (what the LR schedule sees);
    # with gradient accumulation each optimizer step spans `accum` loop steps.
    accum = oc.gradient_accumulation or 1
    stop = False
    profiling = False

    # Context parallelism: ring attention engages whenever the config asks
    # for it AND a ring context is active during tracing. Activating it for
    # the whole fit (incl. tuning eval) keeps train and eval numerics on the
    # same path; it is tracing-time (thread-local) state only, restored on
    # exit — also on error — so subsequent in-process runs (ASHA rungs)
    # start clean.
    import contextlib

    ring_cm = contextlib.nullcontext()
    if n_cp > 1:
        from ..parallel import ring_context

        ring_cm = ring_context(mesh)

    # The guard arms only after a FULL in-process epoch: a resumed partial
    # epoch (skip_batches) can consist solely of a short tail chunk, which
    # would leave the full-chunk executable uncompiled until the next epoch —
    # a legitimate compile that must not trip the sentinel.
    full_epoch_completed_in_process = False
    shutdown = GracefulShutdown()
    # A while-loop, not a range: divergence rollback rewinds the walker —
    # restoring the last good checkpoint may re-enter the same epoch (or an
    # earlier one) with a fresh skip point past the poisoned window.
    resume_epoch, resume_skip = start_epoch, skip_batches
    epoch = start_epoch
    with ring_cm, shutdown:
        while epoch < oc.max_epochs:
            if step_guard is not None:
                if full_epoch_completed_in_process:
                    step_guard.arm()
                else:
                    step_guard.disarm()  # warm-up: compiles are expected
            epoch_t0 = time.perf_counter()
            window_t0, window_events, window_n = time.perf_counter(), 0, 0
            window_losses: list = []
            epoch_skip = resume_skip if epoch == resume_epoch else 0
            if rollback_ctl is not None:
                # Excise any window a previous rollback marked poisoned: the
                # epoch's batch order is deterministic, so a data-caused
                # fault would simply re-fire if these batches were retrained.
                epoch_skip = rollback_ctl.epoch_skip(epoch, epoch_skip)
            epoch_progress = epoch_skip  # epoch-order batches consumed so far
            preempt_requested = False
            # The shared health buffer + inspection gate (reliability/
            # sentinel.py): dispatches `record` their device flags without
            # readback; `inspect` runs only at the existing flush cadence
            # (checkpoint saves, epoch end) where the pipeline drains anyway,
            # so the sentinel adds no host sync to the dispatch loop.
            health_mon = HealthMonitor(sentinel)

            def flush_window() -> dict:
                """Closes the current logging window into a record whose
                losses stay device arrays (`finalize_record` converts)."""
                nonlocal window_t0, window_events, window_n, window_losses
                dt = time.perf_counter() - window_t0
                rec = {
                    "split": str(Split.TRAIN),
                    "epoch": epoch,
                    "step": global_step,
                    "_losses": [jnp.atleast_1d(l) for l in window_losses],
                    "events_per_sec": window_events / dt if dt > 0 else None,
                    "step_time_ms": 1000.0 * dt / max(window_n, 1),
                }
                window_t0, window_events, window_n = time.perf_counter(), 0, 0
                window_losses = []
                return rec

            def finalize_record(rec: dict) -> None:
                """Epoch-end flush: the only place window losses (and the lr
                schedule, a tiny eager jnp computation) touch the host."""
                rec["train_loss"] = float(jnp.mean(jnp.concatenate(rec.pop("_losses"))))  # graftcheck: allow GC001 -- epoch-end flush, dispatch loop already drained
                rec["lr"] = float(lr_schedule(rec["step"] // accum))  # graftcheck: allow GC001 -- epoch-end flush, dispatch loop already drained
                log_record(rec)

            def handle_window(step_in_epoch: int, stepped: int, pending: list):
                """Shared per-dispatch bookkeeping: logs, checkpoints, stop.

                ``stepped`` is how many optimizer-loop steps the last dispatch
                advanced (1 for the per-batch path, k for a scanned chunk) —
                cadences fire when the counter crosses a multiple. Window
                records buffer their losses as device arrays in ``pending``
                for an epoch-end flush (a float() here would block the
                dispatch pipeline on a data-plane round trip every window;
                GC001).
                """
                nonlocal stop, preempt_requested
                if global_step % log_every < stepped:
                    pending.append(flush_window())
                if global_step % ckpt_every < stepped:
                    # Shared inspect-then-save gate (HealthMonitor.vetted_save):
                    # sentinel inspection rides the checkpoint cadence and the
                    # save commits only when THIS window vetted healthy — a
                    # bad-but-below-streak window must never become a poisoned
                    # rollback target. Checkpointing IS a host readback; the
                    # cadence (ckpt_every) bounds how often the pipeline
                    # drains.
                    if health_mon.vetted_save(
                        ckpt_mgr,
                        global_step,
                        lambda: serialization.to_state_dict(jax.device_get(state)),  # graftcheck: allow GC001 -- checkpoint readback + sentinel inspection, cadence-bounded
                        {
                            "epoch": epoch,
                            "epoch_complete": False,
                            "step_in_epoch": step_in_epoch,
                        },
                        epoch=epoch,
                        progress=step_in_epoch,
                    ):
                        # The device_get above already drained the pipeline, so
                        # persisting the buffered window records here costs no
                        # extra sync — and bounds what a SIGKILL-style preemption
                        # can lose from train_log.jsonl to ckpt_every steps.
                        for rec in pending:
                            finalize_record(rec)
                        pending.clear()
                if step_guard is not None and step_guard.armed:
                    if chunked_step is None or stepped == chunk_steps:
                        # Steady state: the watched step function must not
                        # have grown a new executable.
                        step_guard.check()
                    elif step_guard.compiles > 0:
                        # A short tail chunk legitimately owns its shape (and
                        # repacking can shift its length between epochs):
                        # absorb its compile by re-baselining rather than
                        # tripping on the next full-shape dispatch. Clean
                        # short dispatches leave the baseline untouched so
                        # full-shape checks keep their bite.
                        step_guard.arm()
                if (
                    oc.max_training_steps is not None
                    and global_step // accum >= oc.max_training_steps
                ):
                    stop = True
                if shutdown.requested:
                    # Graceful preemption: this chunk boundary is the drain
                    # point; the final checkpoint is written once the
                    # dispatch loops unwind (reliability/preemption.py).
                    preempt_requested = True

            # Window records buffer device losses and flush once the dispatch
            # loop exits — in a finally, so a mid-epoch failure (step error,
            # RecompileError, preemption-triggered teardown) still writes the
            # trajectory leading up to it instead of losing the epoch's log.
            pending_logs: list[dict] = []
            try:
                if chunked_step is not None:
                    # Device-resident scanned training: k collate+step
                    # iterations per dispatch, ~100-byte plans on the wire
                    # (the production fast path; bit-identical numerics to
                    # the branch below).
                    step_in_epoch = epoch_skip
                    for plans, n_events in train_plan_chunks(epoch, epoch_skip):
                        k = int(next(iter(plans.values())).shape[0])
                        if oc.max_training_steps is not None:
                            remaining = oc.max_training_steps * accum - global_step
                            if remaining < k:
                                plans = {key_: v[:remaining] for key_, v in plans.items()}
                                k = remaining
                                # Recount from the kept plans only — the chunk's
                                # n_events includes the dropped plans' events.
                                n_events = _plan_event_count(plans, train_pyd) if k > 0 else 0
                        if k <= 0:
                            break
                        # Profile the dispatch(es) overlapping steps [10, 20),
                        # once — same window as the per-batch path.
                        if (
                            profile_dir and not profiling
                            and global_step < 20 and global_step + k > 10
                        ):
                            jax.profiler.start_trace(str(profile_dir))
                            profiling = True
                        if with_health:
                            state, (losses, healths) = chunked_step(state, device_train.arrays, plans, rng)  # graftcheck: allow GC003 -- step body folds rng with state.step; constant base key is the dropout-stream contract
                            health_mon.record(healths)
                        else:
                            state, losses = chunked_step(state, device_train.arrays, plans, rng)  # graftcheck: allow GC003 -- step body folds rng with state.step; constant base key is the dropout-stream contract
                        global_step += k
                        step_in_epoch += k
                        epoch_progress = step_in_epoch
                        faults.maybe_sigterm(global_step, shutdown)
                        window_events += n_events
                        window_losses.append(losses)
                        window_n += k
                        if profiling and global_step >= 20:
                            jax.profiler.stop_trace()
                            profiling = False
                        handle_window(step_in_epoch, k, pending_logs)
                        if stop or health_mon.rollback_requested or preempt_requested:
                            break
                else:
                    # Asynchronous host input pipeline: collation + device_put
                    # run in a background thread with a depth-2 device buffer,
                    # so the host path overlaps the previous step's compute
                    # (VERDICT r02 #2). Event counts are computed host-side in
                    # the worker — reading them here would otherwise force a
                    # device sync every step.
                    batch_iter = prefetch_to_device(
                        # Fault injection (reliability/faults.py): a no-op
                        # pass-through unless a plan scripts a poisoned batch
                        # for this epoch's deterministic order.
                        faults.wrap_batches(
                            train_batches(epoch, epoch_skip),
                            epoch=epoch,
                            first_index=epoch_skip,
                        ),
                        lambda b: place_batch(b, mesh),
                        host_stats_fn=lambda b: int(b.event_mask.sum()),
                    )
                    try:
                        for step_in_epoch, (batch, n_events) in enumerate(
                            batch_iter, start=epoch_skip
                        ):
                            if profile_dir and not profiling and 10 <= global_step < 20:
                                jax.profiler.start_trace(str(profile_dir))
                                profiling = True
                            if with_health:
                                state, (loss, health) = train_step(state, batch, rng)  # graftcheck: allow GC003 -- step body folds rng with state.step; constant base key is the dropout-stream contract
                                health_mon.record(health)
                            else:
                                state, loss = train_step(state, batch, rng)  # graftcheck: allow GC003 -- step body folds rng with state.step; constant base key is the dropout-stream contract
                            global_step += 1
                            epoch_progress = step_in_epoch + 1
                            faults.maybe_sigterm(global_step, shutdown)
                            window_events += n_events
                            # Keep the loss on device: converting every step
                            # would sync the host with the device and serialize
                            # collation with compute.
                            window_losses.append(loss)
                            window_n += 1
                            if profiling and global_step >= 20:
                                jax.profiler.stop_trace()
                                profiling = False
                            handle_window(step_in_epoch + 1, 1, pending_logs)
                            if stop or health_mon.rollback_requested or preempt_requested:
                                break
                    finally:
                        batch_iter.close()
            finally:
                for rec in pending_logs:
                    finalize_record(rec)
            if profiling:
                jax.profiler.stop_trace()
                profiling = False

            # Post-epoch recovery tail (reliability/sentinel.py finish_epoch,
            # shared verbatim with fine-tuning): vets the tail window,
            # executes a pending rollback, or drains a pending preemption
            # (raising Preempted after the tail-gated final checkpoint). The
            # returned verdict gates the epoch-end checkpoint below.
            outcome = finish_epoch(
                health_mon=health_mon,
                rollback_ctl=rollback_ctl,
                ckpt_mgr=ckpt_mgr,
                shutdown=shutdown,
                state=state,
                place_state=place_state,
                log_record=log_record,
                epoch=epoch,
                epoch_progress=epoch_progress,
                global_step=global_step,
                accum=accum,
                max_training_steps=oc.max_training_steps,
                label="pretraining",
            )
            if outcome.action == "rollback":
                state = outcome.state
                global_step = outcome.global_step
                resume_epoch, resume_skip = outcome.resume_epoch, outcome.resume_skip
                stop = outcome.stop
                epoch = resume_epoch
                continue
            tail_healthy = outcome.tail_healthy

            if epoch_skip == 0:
                full_epoch_completed_in_process = True

            # Tuning eval (loss-only under the default pretraining metrics config).
            rng, eval_key = jax.random.split(rng)  # graftcheck: allow GC003 -- train consumptions above only fold_in; this split advances the base stream
            tuning_metrics = evaluate(
                eval_step,
                state.params,
                tuning_pyd,
                oc.validation_batch_size,
                config,
                cfg.pretraining_metrics_config,
                Split.TUNING,
                mesh=mesh,
                key=eval_key,
                place_batch=place_batch,
                device_data=device_tuning,
            )
            tuning_loss = tuning_metrics.get("tuning_loss", float("nan"))
            log_record(
                {
                    "split": str(Split.TUNING),
                    "epoch": epoch,
                    "step": global_step,
                    **tuning_metrics,
                    "epoch_time_s": time.perf_counter() - epoch_t0,
                }
            )
            print(
                f"epoch {epoch}: opt step {global_step // accum}/"
                f"{oc.max_training_steps or steps_per_epoch * oc.max_epochs}"
                f" tuning_loss={tuning_loss:.4f}"
            )

            if tail_healthy:
                ckpt_mgr.save(
                    global_step,
                    serialization.to_state_dict(jax.device_get(state)),  # graftcheck: allow GC001 -- epoch-end checkpoint readback, pipeline already drained by eval
                    metadata={"epoch": epoch, "epoch_complete": True},
                )

            # Early stopping (reference EarlyStopping(monitor="tuning_loss")).
            if np.isfinite(tuning_loss) and tuning_loss < best_tuning_loss - 1e-12:
                best_tuning_loss = tuning_loss
                epochs_since_best = 0
            else:
                epochs_since_best += 1
                # Lightning EarlyStopping semantics: stop once the wait count
                # reaches patience (the Nth consecutive non-improving epoch).
                if oc.patience is not None and epochs_since_best >= max(oc.patience, 1):
                    print(f"Early stopping at epoch {epoch} (patience {oc.patience})")
                    break
            if stop:
                break
            epoch += 1

    ckpt_mgr.wait_until_finished()
    params_host = jax.device_get(state.params)
    if is_main:
        save_pretrained(save_dir, params_host)

    if not cfg.do_final_validation_on_metrics:
        ckpt_mgr.close()
        return None, None, None

    held_out_pyd = JaxDataset(cfg.data_config, split="held_out")
    device_held_out = (
        DeviceDataset.try_create(
            held_out_pyd, mesh=mesh, context_parallel=n_cp > 1, max_bytes=resident_budget,
            batch_sizes=(oc.validation_batch_size,),
        )
        if device_train is not None
        else None
    )
    rng, k1, k2 = jax.random.split(rng, 3)
    final_tuning = evaluate(
        eval_step,
        state.params,
        tuning_pyd,
        oc.validation_batch_size,
        config,
        cfg.final_validation_metrics_config,
        Split.TUNING,
        mesh=mesh,
        key=k1,
        place_batch=place_batch,
        device_data=device_tuning,
    )
    final_held_out = evaluate(
        eval_step,
        state.params,
        held_out_pyd,
        oc.validation_batch_size,
        config,
        cfg.final_validation_metrics_config,
        Split.HELD_OUT,
        mesh=mesh,
        key=k2,
        place_batch=place_batch,
        device_data=device_held_out,
    )

    if is_main:
        print("Saving final metrics...")
        with open(save_dir / "tuning_metrics.json", "w") as f:
            json.dump(final_tuning, f)
        with open(save_dir / "held_out_metrics.json", "w") as f:
            json.dump(final_held_out, f)

    ckpt_mgr.close()
    return final_tuning.get("tuning_loss"), final_tuning, final_held_out
