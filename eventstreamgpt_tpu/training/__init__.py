"""Training harnesses: pretraining driver, optimizer, checkpointing, metrics.

TPU-native replacement for the reference's Lightning modules
(``/root/reference/EventStream/transformer/lightning_modules/``).
"""

from .checkpoint import TrainCheckpointManager, load_pretrained, save_pretrained
from .fine_tuning import (
    FinetuneConfig,
    StreamClassificationMetrics,
    init_from_pretrained_encoder,
)
from .fine_tuning import train as finetune
from .generative_metrics import GenerativeMetrics
from .optimizer import build_optimizer, polynomial_decay_with_warmup
from .sharding import (
    batch_partition_axes,
    make_mesh,
    make_param_shardings,
    make_state_shardings,
    shard_params,
    shard_state,
    train_state_bytes,
)
from .pretrain import (
    PretrainConfig,
    TrainState,
    build_model,
    data_parallel_mesh,
    evaluate,
    make_chunked_train_step,
    make_eval_step,
    make_train_step,
    parallel_mesh,
    replicate,
    shard_batch,
    train,
)

__all__ = [
    "FinetuneConfig",
    "GenerativeMetrics",
    "PretrainConfig",
    "StreamClassificationMetrics",
    "finetune",
    "init_from_pretrained_encoder",
    "TrainCheckpointManager",
    "TrainState",
    "batch_partition_axes",
    "build_model",
    "build_optimizer",
    "data_parallel_mesh",
    "evaluate",
    "load_pretrained",
    "make_chunked_train_step",
    "make_eval_step",
    "make_mesh",
    "make_param_shardings",
    "make_state_shardings",
    "make_train_step",
    "parallel_mesh",
    "polynomial_decay_with_warmup",
    "replicate",
    "shard_params",
    "shard_state",
    "save_pretrained",
    "shard_batch",
    "train",
    "train_state_bytes",
]
