"""Optimizer construction from ``OptimizationConfig``.

Rebuild of the reference's ``configure_optimizers``
(``/root/reference/EventStream/transformer/lightning_modules/generative_modeling.py:460-485``):
AdamW with configurable weight decay, LR warming up linearly from 0 to
``init_lr`` then decaying polynomially to ``end_lr`` — the exact schedule of
HuggingFace's ``get_polynomial_decay_schedule_with_warmup``. Gradient
accumulation (``accumulate_grad_batches`` in Lightning) is ``optax.MultiSteps``.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from ..models.config import OptimizationConfig


def polynomial_decay_with_warmup(
    init_lr: float,
    end_lr: float,
    num_warmup_steps: int,
    num_training_steps: int,
    power: float = 1.0,
) -> optax.Schedule:
    """LR schedule matching HF ``get_polynomial_decay_schedule_with_warmup``.

    step < warmup:  init_lr · step / warmup
    step ≥ total:   end_lr
    otherwise:      end_lr + (init_lr − end_lr) · (1 − (step − warmup)/(total − warmup))^power
    """
    if init_lr <= end_lr:
        raise ValueError(f"end_lr ({end_lr}) must be smaller than init_lr ({init_lr})")

    def schedule(step):
        step = jnp.asarray(step, dtype=jnp.float32)
        warmup = init_lr * step / jnp.maximum(num_warmup_steps, 1)
        remaining = 1.0 - (step - num_warmup_steps) / jnp.maximum(
            num_training_steps - num_warmup_steps, 1
        )
        decay = (init_lr - end_lr) * remaining**power + end_lr
        lr = jnp.where(step < num_warmup_steps, warmup, decay)
        return jnp.where(step >= num_training_steps, end_lr, lr)

    return schedule


def build_optimizer(
    optimization_config: OptimizationConfig,
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """AdamW + warmup/polynomial-decay schedule (+ MultiSteps accumulation).

    Returns ``(tx, schedule)``; the schedule is also returned standalone so
    training loops can log the current LR (the reference's
    ``LearningRateMonitor``).
    """
    oc = optimization_config
    if oc.max_training_steps is None or oc.lr_num_warmup_steps is None:
        raise ValueError(
            "OptimizationConfig.max_training_steps / lr_num_warmup_steps are unset; "
            "call optimization_config.set_to_dataset(train_dataset) first."
        )
    schedule = polynomial_decay_with_warmup(
        init_lr=oc.init_lr,
        end_lr=oc.end_lr,
        num_warmup_steps=oc.lr_num_warmup_steps,
        num_training_steps=oc.max_training_steps,
        power=oc.lr_decay_power,
    )
    tx = optax.adamw(learning_rate=schedule, weight_decay=oc.weight_decay)
    if oc.gradient_accumulation is not None and oc.gradient_accumulation > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=oc.gradient_accumulation)
    return tx, schedule
